"""The examples/ quickstart scripts are the full-lifecycle integration
proofs (app → import → build → train → deploy → query → undeploy through
the real CLI and subprocesses); keep them runnable."""

import json
import os
import socket
import subprocess

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_quickstart(script: str, workdir, marker: str) -> str:
    """Launch one quickstart script the way a user would; returns stdout."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["QUICKSTART_PORT"] = str(_free_port())
    env.pop("PIO_FS_BASEDIR", None)  # storage isolated to the workdir
    out = subprocess.run(
        ["bash", script, str(workdir)],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=540,
    )
    assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-2000:]
    assert marker in out.stdout, out.stdout[-2000:]
    return out.stdout


def test_quickstart_runs_end_to_end(tmp_path):
    stdout = _run_quickstart(
        "examples/movielens_quickstart/run.sh", tmp_path,
        "QUICKSTART COMPLETE",
    )
    # the two cohorts' top lists must come from opposite item parities
    lines = [ln for ln in stdout.splitlines() if ln.startswith('{"itemScores"')]
    assert len(lines) == 2, stdout[-2000:]
    tops = [
        [int(r["item"][1:]) % 2 for r in json.loads(ln)["itemScores"]]
        for ln in lines
    ]
    assert sum(tops[0]) <= 1, tops  # u0 (even): nearly all even items
    assert sum(tops[1]) >= 4, tops  # u1 (odd): nearly all odd items


def test_classification_quickstart_runs_end_to_end(tmp_path):
    stdout = _run_quickstart(
        "examples/classification_quickstart/run.sh", tmp_path,
        "CLASSIFICATION QUICKSTART COMPLETE",
    )
    labels = [
        json.loads(ln)["label"]
        for ln in stdout.splitlines()
        if ln.startswith('{"label"')
    ]
    assert labels == [1.0, 0.0], stdout[-1500:]


def test_similarproduct_quickstart_runs_end_to_end(tmp_path):
    stdout = _run_quickstart(
        "examples/similarproduct_quickstart/run.sh", tmp_path,
        "SIMILARPRODUCT QUICKSTART COMPLETE",
    )
    # reference wire shape (camelCase) and cluster structure
    lines = [ln for ln in stdout.splitlines() if ln.startswith('{"itemScores"')]
    assert len(lines) == 2, stdout[-2000:]
    for ln, parity in zip(lines, (0, 1)):
        items = [r["item"] for r in json.loads(ln)["itemScores"]]
        assert len(items) >= 3, (items, parity)  # empty results must fail
        wrong = [it for it in items if int(it[1:]) % 2 != parity]
        assert len(wrong) <= 1, (items, parity)


def test_ecommerce_quickstart_runs_end_to_end(tmp_path):
    stdout = _run_quickstart(
        "examples/ecommerce_quickstart/run.sh", tmp_path,
        "ECOMMERCE QUICKSTART COMPLETE",
    )
    # the script itself asserts the live filters dropped the bought and
    # unavailable items; confirm that verification line ran
    assert "live filters verified" in stdout, stdout[-2000:]


def test_sequencerec_quickstart_runs_end_to_end(tmp_path):
    stdout = _run_quickstart(
        "examples/sequencerec_quickstart/run.sh", tmp_path,
        "SEQUENCEREC QUICKSTART COMPLETE",
    )
    lines = [ln for ln in stdout.splitlines() if ln.startswith('{"itemScores"')]
    assert len(lines) == 2, stdout[-2000:]
    tops = [json.loads(ln)["itemScores"][0]["item"] for ln in lines]
    # the cycle rule: [i3,i4,i5] -> i6; u0's history ends at i11 -> i0
    assert tops == ["i6", "i0"], (tops, stdout[-1500:])
