"""The examples/movielens_quickstart script is the full-lifecycle
integration proof (app → import → build → train → deploy → query →
undeploy through the real CLI and subprocesses); keep it runnable."""

import json
import os
import socket
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_quickstart_runs_end_to_end(tmp_path):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["QUICKSTART_PORT"] = str(_free_port())
    env.pop("PIO_FS_BASEDIR", None)
    out = subprocess.run(
        ["bash", "examples/movielens_quickstart/run.sh", str(tmp_path)],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=540,
    )
    assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-2000:]
    assert "QUICKSTART COMPLETE" in out.stdout
    # the two cohorts' top lists must come from opposite item parities
    lines = [ln for ln in out.stdout.splitlines() if ln.startswith('{"itemScores"')]
    assert len(lines) == 2, out.stdout[-2000:]
    tops = [
        [int(r["item"][1:]) % 2 for r in json.loads(ln)["itemScores"]]
        for ln in lines
    ]
    assert sum(tops[0]) <= 1, tops  # u0 (even): nearly all even items
    assert sum(tops[1]) >= 4, tops  # u1 (odd): nearly all odd items


if __name__ == "__main__":
    sys.exit(0)
