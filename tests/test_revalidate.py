"""Unit tests for the TPU revalidation queue's recording logic.

The queue runs unattended in the rare hardware window; its parsing must
convert every subprocess outcome — good JSON, garbage, crashes,
timeouts — into an appended record without killing the chain. These
tests stub ``subprocess.run`` so no device (or bench) is involved.
"""

import json
import subprocess
import types

import pytest

from predictionio_tpu.tools import tpu_revalidate as tr


@pytest.fixture(autouse=True)
def evidence_file(tmp_path, monkeypatch):
    out = tmp_path / "ev.jsonl"
    monkeypatch.setattr(tr, "OUT", str(out))
    return out


def _records(path):
    return [json.loads(l) for l in path.read_text().splitlines() if l]


def _stub(monkeypatch, stdout="", stderr="", rc=0, raise_timeout=False):
    def fake_run(*a, **kw):
        if raise_timeout:
            raise subprocess.TimeoutExpired(cmd=a[0], timeout=1)
        return types.SimpleNamespace(
            stdout=stdout, stderr=stderr, returncode=rc
        )

    monkeypatch.setattr(tr.subprocess, "run", fake_run)


class TestRunBench:
    def test_good_json_recorded_with_step(self, monkeypatch, evidence_file):
        _stub(monkeypatch, stdout='noise\n{"value": 17.8, "holdout_rmse": 0.53}\n')
        rec = tr.run_bench("baseline_f32", {})
        assert rec["value"] == 17.8 and rec["step"] == "baseline_f32"
        assert _records(evidence_file)[0]["step"] == "baseline_f32"

    def test_malformed_json_recorded_not_raised(self, monkeypatch,
                                                evidence_file):
        _stub(monkeypatch, stdout='{"truncated": ', rc=1)
        rec = tr.run_bench("baseline_f32", {})
        assert "malformed" in rec["error"]
        assert _records(evidence_file)[0]["rc"] == 1

    def test_timeout_recorded_and_chain_continues(self, monkeypatch,
                                                  evidence_file):
        _stub(monkeypatch, raise_timeout=True)
        rec = tr.run_bench("bf16_gather", {}, timeout_s=1)
        assert rec["rc"] == -1 and "timed out" in rec["error"]

    def test_fallback_marked_invalid(self, monkeypatch, evidence_file):
        _stub(monkeypatch,
              stdout='{"value": 12.0, "fallback": "cpu-fallback"}\n')
        rec = tr.run_bench("baseline_f32", {})
        assert "DEVICE FELL BACK" in rec["note"]


class TestRunStep:
    def test_inner_step_name_normalized(self, monkeypatch, evidence_file):
        # _reval_steps subcommand names differ from their records' own
        # step names; the file must use ONE name per logical step
        _stub(monkeypatch,
              stdout='{"step": "fused_kernel_compiled", "ok": true}\n')
        rec = tr.run_step("fused_smoke")
        assert rec["step"] == "fused_smoke"
        assert rec["inner_step"] == "fused_kernel_compiled"
        assert rec["ok"] is True

    def test_crash_with_no_json_records_stderr_tail(self, monkeypatch,
                                                    evidence_file):
        _stub(monkeypatch, stdout="", stderr="Trace\nRuntimeError: boom",
              rc=1)
        rec = tr.run_step("mesh_pallas")
        assert rec["error"] == "RuntimeError: boom"
        assert rec["rc"] == 1

    def test_malformed_json_guarded(self, monkeypatch, evidence_file):
        _stub(monkeypatch, stdout='{"ok": tru')
        rec = tr.run_step("dispatch_bench")
        assert "malformed" in rec["error"]
