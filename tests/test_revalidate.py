"""Unit tests for the TPU revalidation queue's recording logic.

The queue runs unattended in the rare hardware window; its parsing must
convert every subprocess outcome — good JSON, garbage, crashes,
timeouts — into an appended record without killing the chain. These
tests stub ``subprocess.run`` so no device (or bench) is involved.
"""

import json
import subprocess
import types

import pytest

from predictionio_tpu.tools import tpu_revalidate as tr


@pytest.fixture(autouse=True)
def evidence_file(tmp_path, monkeypatch):
    out = tmp_path / "ev.jsonl"
    monkeypatch.setattr(tr, "OUT", str(out))
    return out


def _records(path):
    return [json.loads(l) for l in path.read_text().splitlines() if l]


def _stub(monkeypatch, stdout="", stderr="", rc=0, raise_timeout=False):
    def fake_run(*a, **kw):
        if raise_timeout:
            raise subprocess.TimeoutExpired(cmd=a[0], timeout=1)
        return types.SimpleNamespace(
            stdout=stdout, stderr=stderr, returncode=rc
        )

    monkeypatch.setattr(tr.subprocess, "run", fake_run)


class TestRunBench:
    def test_good_json_recorded_with_step(self, monkeypatch, evidence_file):
        _stub(monkeypatch, stdout='noise\n{"value": 17.8, "holdout_rmse": 0.53}\n')
        rec = tr.run_bench("baseline_f32", {})
        assert rec["value"] == 17.8 and rec["step"] == "baseline_f32"
        assert _records(evidence_file)[0]["step"] == "baseline_f32"

    def test_malformed_json_recorded_not_raised(self, monkeypatch,
                                                evidence_file):
        _stub(monkeypatch, stdout='{"truncated": ', rc=1)
        rec = tr.run_bench("baseline_f32", {})
        assert "malformed" in rec["error"]
        assert _records(evidence_file)[0]["rc"] == 1

    def test_timeout_recorded_and_chain_continues(self, monkeypatch,
                                                  evidence_file):
        _stub(monkeypatch, raise_timeout=True)
        rec = tr.run_bench("bf16_gather", {}, timeout_s=1)
        assert rec["rc"] == -1 and "timed out" in rec["error"]

    def test_fallback_marked_invalid(self, monkeypatch, evidence_file):
        _stub(monkeypatch,
              stdout='{"value": 12.0, "fallback": "cpu-fallback"}\n')
        rec = tr.run_bench("baseline_f32", {})
        assert "DEVICE FELL BACK" in rec["note"]


class TestRunStep:
    def test_inner_step_name_normalized(self, monkeypatch, evidence_file):
        # _reval_steps subcommand names differ from their records' own
        # step names; the file must use ONE name per logical step
        _stub(monkeypatch,
              stdout='{"step": "fused_kernel_compiled", "ok": true}\n')
        rec = tr.run_step("fused_smoke")
        assert rec["step"] == "fused_smoke"
        assert rec["inner_step"] == "fused_kernel_compiled"
        assert rec["ok"] is True

    def test_crash_with_no_json_records_stderr_tail(self, monkeypatch,
                                                    evidence_file):
        _stub(monkeypatch, stdout="", stderr="Trace\nRuntimeError: boom",
              rc=1)
        rec = tr.run_step("mesh_pallas")
        assert rec["error"] == "RuntimeError: boom"
        assert rec["rc"] == 1

    def test_malformed_json_guarded(self, monkeypatch, evidence_file):
        _stub(monkeypatch, stdout='{"ok": tru')
        rec = tr.run_step("dispatch_bench")
        assert "malformed" in rec["error"]


class TestRecent:
    def test_append_stamps_and_recent_finds(self, evidence_file):
        tr.append({"step": "baseline_f32", "value": 17.0})
        rec = tr._recent("baseline_f32")
        assert rec["value"] == 17.0 and "t_unix" in rec

    def test_old_record_not_reused(self, evidence_file):
        import time

        tr.append({"step": "baseline_f32", "value": 17.0,
                   "t_unix": time.time() - 7 * 3600})
        assert tr._recent("baseline_f32") is None

    def test_unstamped_pre_tier_record_ignored(self, evidence_file):
        evidence_file.write_text('{"step": "baseline_f32", "value": 1}\n')
        assert tr._recent("baseline_f32") is None

    def test_newest_record_wins(self, evidence_file):
        tr.append({"step": "fused_smoke", "ok": False})
        tr.append({"step": "fused_smoke", "ok": True})
        assert tr._recent("fused_smoke")["ok"] is True

    def test_missing_file_is_none(self, evidence_file):
        assert tr._recent("anything") is None

    def test_cpu_sourced_record_not_reused(self, evidence_file):
        # a CPU-env invocation (or mid-window fallback) must never become
        # the RMSE gate or Mosaic verdict for a real TPU window
        tr.append({"step": "baseline_f32", "rc": 0, "value": 9.0,
                   "holdout_rmse": 0.53, "device": "TFRT_CPU_0"})
        tr.append({"step": "fused_smoke", "rc": 0, "ok": True,
                   "backend": "cpu"})
        assert tr._recent("baseline_f32") is None
        assert tr._recent("fused_smoke") is None


class TestTiers:
    """Tier A runs exactly the golden-window records; tier B reuses
    fresh tier-A records instead of re-spending device time."""

    @pytest.fixture
    def harness(self, monkeypatch, evidence_file):
        calls = []

        def fake_bench(step, env, timeout_s=1800):
            calls.append(("bench", step))
            rec = {"step": step, "rc": 0, "value": 17.0,
                   "holdout_rmse": 0.53, "iteration_s": [1.0, 0.4],
                   "bucketize_stage_s": 2.0}
            tr.append(dict(rec))
            return rec

        def fake_step(step, timeout_s=900, env_extra=None):
            calls.append(("step", step))
            rec = {"step": step, "rc": 0, "ok": True}
            if env_extra:
                rec["lever"] = dict(env_extra)
            tr.append(dict(rec))
            return rec

        monkeypatch.setattr(tr, "run_bench", fake_bench)
        monkeypatch.setattr(tr, "run_step", fake_step)
        monkeypatch.setenv("PIO_JAX_CACHE_DIR", "")  # hermetic
        monkeypatch.delenv("BENCH_SCALE", raising=False)
        monkeypatch.delenv("BENCH_ITERATIONS", raising=False)
        import bench

        monkeypatch.setattr(bench, "probe_device", lambda timeout_s: "ok")
        return calls

    def _main(self, monkeypatch, argv):
        import sys as _sys

        monkeypatch.setattr(_sys, "argv", ["tpu_revalidate"] + argv)
        return tr.main()

    def test_tier_a_runs_only_golden_records(self, harness, monkeypatch):
        rc = self._main(monkeypatch, ["--tier", "a"])
        assert rc == 0
        assert harness == [("bench", "baseline_f32"),
                           ("step", "fused_smoke"),
                           ("step", "mesh_pallas")]

    def test_tier_b_reuses_fresh_tier_a_records(self, harness, monkeypatch):
        tr.append({"step": "baseline_f32", "rc": 0, "value": 17.0,
                   "holdout_rmse": 0.53, "iteration_s": [1.0, 0.4],
                   "bucketize_stage_s": 2.0, "scale": 1.0,
                   "iterations": 10})
        tr.append({"step": "fused_smoke", "rc": 0, "ok": True})
        tr.append({"step": "mesh_pallas", "rc": 0, "ok": True})
        rc = self._main(monkeypatch, ["--tier", "b", "--repeats", "1",
                                      "--skip-loadgen"])
        assert rc == 0
        bench_steps = [s for kind, s in harness if kind == "bench"]
        step_steps = [s for kind, s in harness if kind == "step"]
        assert "baseline_f32" not in bench_steps
        assert set(bench_steps) == {"bf16_gather", "sort_gather",
                                    "bf16_plus_sort", "fused_gather",
                                    "fused_plus_bf16"}
        # fused_smoke/mesh_pallas reused from the file, not re-run;
        # implicit_gate runs because bf16+sort passed their explicit gates
        assert step_steps == ["dispatch_bench", "flash_pallas",
                              "profile_trace", "implicit_gate"]

    def test_tier_b_rejects_config_mismatched_baseline(self, harness,
                                                       monkeypatch):
        # a baseline measured at a different scale/iterations must not
        # become this run's RMSE gate (review finding)
        tr.append({"step": "baseline_f32", "rc": 0, "value": 17.0,
                   "holdout_rmse": 0.53, "iteration_s": [1.0, 0.4],
                   "bucketize_stage_s": 2.0, "scale": 0.01,
                   "iterations": 10})
        rc = self._main(monkeypatch, ["--tier", "b", "--repeats", "1",
                                      "--skip-loadgen"])
        assert rc == 0
        bench_steps = [s for kind, s in harness if kind == "bench"]
        assert bench_steps[0] == "baseline_f32"  # re-measured, not reused

    def test_tier_b_rc1_when_a_step_times_out(self, harness, monkeypatch):
        # a window that wedges mid-tier-B must NOT report complete: rc=1
        # keeps the watcher alive for another window (review finding)
        def timing_out_step(step, timeout_s=900, env_extra=None):
            rec = {"step": step, "rc": -1, "error": "timed out"}
            tr.append(dict(rec))
            return rec

        monkeypatch.setattr(tr, "run_step", timing_out_step)
        rc = self._main(monkeypatch, ["--tier", "b", "--repeats", "1",
                                      "--skip-loadgen"])
        assert rc == 1

    def test_failed_tier_a_step_record_not_reused(self, harness,
                                                  monkeypatch):
        # tier A's smoke timed out as the window closed; tier B must give
        # it a fresh chance, not inherit the failure (review finding)
        tr.append({"step": "baseline_f32", "rc": 0, "value": 17.0,
                   "holdout_rmse": 0.53, "iteration_s": [1.0, 0.4],
                   "bucketize_stage_s": 2.0, "scale": 1.0,
                   "iterations": 10})
        tr.append({"step": "fused_smoke", "rc": -1, "error": "timed out"})
        rc = self._main(monkeypatch, ["--tier", "b", "--repeats", "1",
                                      "--skip-loadgen"])
        assert rc == 0
        step_steps = [s for kind, s in harness if kind == "step"]
        assert "fused_smoke" in step_steps  # re-run, not reused

    def test_tier_b_standalone_runs_baseline_itself(self, harness,
                                                    monkeypatch):
        rc = self._main(monkeypatch, ["--tier", "b", "--repeats", "1",
                                      "--skip-loadgen"])
        assert rc == 0
        bench_steps = [s for kind, s in harness if kind == "bench"]
        assert bench_steps[0] == "baseline_f32"
        step_steps = [s for kind, s in harness if kind == "step"]
        assert "fused_smoke" in step_steps and "mesh_pallas" in step_steps
