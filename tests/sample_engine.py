"""Deterministic toy DASE components for engine/workflow tests.

The analogue of the reference's fake-engine fixture
(``core/src/test/scala/io/prediction/controller/SampleEngine.scala``):
components carry integer ids so tests assert exact dataflow composition, and
class-level invocation counters back the FastEvalEngine memoization tests
(``FastEvalEngineTest.scala:30-146``).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import List, Optional, Tuple

from predictionio_tpu.controller import (
    RETRAIN,
    Algorithm,
    DataSource,
    Params,
    PersistentModel,
    Preparator,
    Serving,
)


# -- data carriers ----------------------------------------------------------
@dataclasses.dataclass(frozen=True)
class TrainingData:
    id: int
    error: bool = False

    def sanity_check(self):
        if self.error:
            raise ValueError(f"TrainingData {self.id} failed sanity check")


@dataclasses.dataclass(frozen=True)
class EvalInfo:
    id: int


@dataclasses.dataclass(frozen=True)
class PreparedData:
    id: int
    td_id: int


@dataclasses.dataclass(frozen=True)
class SampleModel:
    algo_id: int
    pd_id: int


@dataclasses.dataclass(frozen=True)
class Query:
    id: int


@dataclasses.dataclass(frozen=True)
class Prediction:
    algo_id: int
    model: SampleModel
    query: Query
    combined: Tuple[int, ...] = ()


@dataclasses.dataclass(frozen=True)
class Actual:
    id: int


# -- params -----------------------------------------------------------------
@dataclasses.dataclass(frozen=True)
class IdParams(Params):
    id: int = 0


@dataclasses.dataclass(frozen=True)
class DSParams(Params):
    id: int = 0
    n_eval_sets: int = 0
    error: bool = False


# -- components -------------------------------------------------------------
class CountingMixin:
    """Class-level invocation counters (FastEvalEngineTest's count asserts).
    Lock-guarded so parallel-sweep tests count exactly."""

    _count_lock = threading.Lock()

    @classmethod
    def reset_count(cls):
        with CountingMixin._count_lock:
            cls.count = 0

    @classmethod
    def bump(cls):
        with CountingMixin._count_lock:
            cls.count = getattr(cls, "count", 0) + 1


class DataSource0(DataSource, CountingMixin):
    params_class = DSParams
    count = 0

    def __init__(self, params: DSParams = DSParams()):
        self.params = params

    def read_training(self, ctx) -> TrainingData:
        type(self).bump()
        return TrainingData(id=self.params.id, error=self.params.error)

    def read_eval(self, ctx):
        type(self).bump()
        sets = []
        for i in range(self.params.n_eval_sets):
            td = TrainingData(id=self.params.id + i)
            ei = EvalInfo(id=self.params.id + i)
            qa = [(Query(id=q), Actual(id=q)) for q in range(2)]
            sets.append((td, ei, qa))
        return sets


class Preparator0(Preparator, CountingMixin):
    params_class = IdParams
    count = 0

    def __init__(self, params: IdParams = IdParams()):
        self.params = params

    def prepare(self, ctx, td: TrainingData) -> PreparedData:
        type(self).bump()
        return PreparedData(id=self.params.id, td_id=td.id)


class Algo0(Algorithm, CountingMixin):
    params_class = IdParams
    count = 0

    def __init__(self, params: IdParams = IdParams()):
        self.params = params

    def train(self, ctx, pd: PreparedData) -> SampleModel:
        type(self).bump()
        return SampleModel(algo_id=self.params.id, pd_id=pd.id)

    def predict(self, model: SampleModel, query: Query) -> Prediction:
        return Prediction(algo_id=self.params.id, model=model, query=query)


class Algo1(Algo0):
    """Second algorithm family for multi-algo engines."""

    count = 0


class Serving0(Serving, CountingMixin):
    params_class = IdParams
    count = 0

    def __init__(self, params: IdParams = IdParams()):
        self.params = params

    def serve(self, query: Query, predictions) -> Prediction:
        type(self).bump()
        first = predictions[0]
        return dataclasses.replace(
            first, combined=tuple(p.algo_id for p in predictions)
        )


# -- persistence variants ---------------------------------------------------
_saved_store = {}


@dataclasses.dataclass(frozen=True)
class PersistableModel(PersistentModel):
    algo_id: int
    pd_id: int

    def save(self, instance_id, params, ctx) -> bool:
        _saved_store[(instance_id, self.algo_id)] = self
        return True

    @classmethod
    def load(cls, instance_id, params, ctx):
        return _saved_store[(instance_id, params.id)]


class PersistentAlgo(Algo0):
    """Algorithm with a self-persisting model (IPersistentModel analogue)."""

    count = 0

    def train(self, ctx, pd: PreparedData):
        type(self).bump()
        return PersistableModel(algo_id=self.params.id, pd_id=pd.id)

    def predict(self, model, query):
        return Prediction(algo_id=self.params.id, model=model, query=query)


class NonPersistentAlgo(Algo0):
    """Model opts out of persistence → deploy retrains (PAlgorithm w/o
    IPersistentModel)."""

    count = 0

    def make_persistent(self, instance_id, model, ctx):
        return RETRAIN


def reset_all_counts():
    for cls in (DataSource0, Preparator0, Algo0, Algo1, Serving0,
                PersistentAlgo, NonPersistentAlgo):
        cls.reset_count()
    _saved_store.clear()
