"""Sharded ALS trainer (ops/als_sharded.py): shard-count invariance,
density balancing, tri-state resolution, and the loud-conflict surface.

CI budget (the ISSUE-15 guard): conftest.py already forces 8 virtual CPU
devices BEFORE the first jax import (the pre-jax-import fixture — no
per-test subprocess is spawned, every shard count runs in-process on the
same device pool), and every equivalence case reads ONE module-level
train-once sweep over the smallest ALS recipe, so the whole file costs
five small trainings + one implicit pair.
"""

import os

import numpy as np
import pytest

from predictionio_tpu.ops.als import ALSConfig, ALSFactors, als_train_coo, rmse
from predictionio_tpu.ops.als_sharded import (
    SHARDS_ENV,
    als_train_sharded,
    assign_rows_balanced,
    plan_side,
    resolve_shards,
    row_solve_flops,
)

#: the PR-12 equivalence tolerances (ROUND7_NOTES contract): sharding
#: reorders float accumulation (per-shard sorted gathers in permuted id
#: space, psum'd Gramians), never the per-row math
RTOL, ATOL, RMSE_TOL = 1e-3, 1e-4, 1e-3


def _recipe():
    rng = np.random.default_rng(7)
    nnz, n_u, n_i = 6_000, 240, 100
    w = 1.0 / np.arange(1, n_u + 1) ** 0.8  # zipf users: skewed degrees
    u = rng.choice(n_u, size=nnz, p=w / w.sum()).astype(np.int32)
    i = rng.integers(0, n_i, nnz).astype(np.int32)
    v = rng.integers(1, 6, nnz).astype(np.float32)
    return u, i, v, n_u, n_i


_CFG = ALSConfig(rank=8, iterations=3, lambda_=0.05, seed=2)
_SWEEP: dict = {}


def sweep(shards, implicit=False):
    """Factors for one (shard count, mode) over the shared recipe,
    trained at most once per session. ``shards=0`` is the single-device
    reference (``als_train_coo``)."""
    key = (shards, implicit)
    if key not in _SWEEP:
        u, i, v, n_u, n_i = _recipe()
        if implicit:
            cfg = ALSConfig(
                rank=6, iterations=2, lambda_=0.1,
                implicit_prefs=True, alpha=4.0, seed=2,
            )
            v = (v > 3).astype(np.float32)
        else:
            cfg = _CFG
        if shards == 0:
            f = als_train_coo(u, i, v, n_u, n_i, cfg)
        else:
            f = als_train_sharded(
                u, i, v, n_u, n_i, cfg, shards=shards
            )
        _SWEEP[key] = (
            np.asarray(f.user_factors), np.asarray(f.item_factors)
        )
    return _SWEEP[key]


class TestShardCountInvariance:
    """The CI-runnable ALX proof: 1/2/4/8 virtual-device shards produce
    the single-device trainer's factors within the reassociation
    tolerances and its holdout RMSE within 1e-3 — sharding is a layout,
    not a model change."""

    @pytest.mark.parametrize("shards", [1, 2, 4, 8])
    def test_factors_match_single_device(self, shards):
        ref_u, ref_i = sweep(0)
        got_u, got_i = sweep(shards)
        np.testing.assert_allclose(got_u, ref_u, rtol=RTOL, atol=ATOL)
        np.testing.assert_allclose(got_i, ref_i, rtol=RTOL, atol=ATOL)

    @pytest.mark.parametrize("shards", [2, 8])
    def test_rmse_matches_single_device(self, shards):
        u, i, v, _, _ = _recipe()
        ref = rmse(ALSFactors(*sweep(0), rank=_CFG.rank), u, i, v)
        got = rmse(ALSFactors(*sweep(shards), rank=_CFG.rank), u, i, v)
        assert abs(ref - got) < RMSE_TOL, (ref, got)

    def test_implicit_psum_gramian_matches_single_device(self):
        """Implicit mode builds YᵀY as a psum of per-shard Gramians —
        the collective path the explicit sweep never touches."""
        ref_u, ref_i = sweep(0, implicit=True)
        got_u, got_i = sweep(4, implicit=True)
        np.testing.assert_allclose(got_u, ref_u, rtol=2e-3, atol=2e-4)
        np.testing.assert_allclose(got_i, ref_i, rtol=2e-3, atol=2e-4)


class TestDensityBalancing:
    """Rows are dealt to shards by padded solve-FLOP weight, widest
    class first — a deliberately skewed degree histogram still splits
    within a pinned imbalance bound, and the plan surfaces the evidence
    (``profile["shard_plan"]``) the hardware-day drive prints."""

    def test_skewed_histogram_splits_within_bound(self):
        # 8 heavy rows (pad to 2048), 60 medium (128), 600 light (32):
        # a power-law histogram a naive row-count split would skew badly
        degrees = np.concatenate([
            np.full(8, 1_500), np.full(60, 90), np.full(600, 10),
        ])
        plan = plan_side(degrees, shards=4, rank=16)
        assert plan.flop_imbalance <= 1.15, plan.per_shard_flops
        # every shard got its fair share of the heavy class
        heavy = np.nonzero(degrees == 1_500)[0]
        per_shard = np.bincount(plan.assign[heavy], minlength=4)
        assert per_shard.tolist() == [2, 2, 2, 2]

    def test_assignment_is_deterministic(self):
        degrees = np.random.default_rng(3).integers(0, 200, 500)
        a = assign_rows_balanced(degrees, 4, rank=8)
        b = assign_rows_balanced(degrees, 4, rank=8)
        np.testing.assert_array_equal(a, b)

    def test_zero_degree_rows_even_out_table_caps(self):
        # zero-degree rows carry no FLOPs but size the per-shard table
        # cap: they must spread, not pile onto shard 0
        degrees = np.concatenate([np.full(10, 50), np.zeros(90)])
        plan = plan_side(degrees, shards=4, rank=8)
        counts = np.bincount(plan.assign, minlength=4)
        assert counts.max() - counts.min() <= 1, counts.tolist()
        assert plan.cap == int(counts.max())

    def test_row_flops_matches_iteration_accounting(self):
        # the balancing weight is the estimate_iteration_flops per-row
        # arithmetic — hand-pinned so the two can never drift apart
        rank, k = 16, 128
        assert row_solve_flops(k, rank) == (
            k * (2 * rank * rank + 2 * rank) + rank**3 / 3 + 2 * rank * rank
        )


class TestShardsResolution:
    """The tri-state (PR-12 lever discipline): explicit wins, env
    (``pio train --shards``) next, default 1 — and the 1-shard path IS
    the single-device trainer, byte-identical config resolution."""

    def test_default_resolves_one(self, monkeypatch):
        monkeypatch.delenv(SHARDS_ENV, raising=False)
        assert resolve_shards(None) == 1

    def test_env_resolves(self, monkeypatch):
        monkeypatch.setenv(SHARDS_ENV, "4")
        assert resolve_shards(None) == 4

    def test_explicit_beats_env(self, monkeypatch):
        monkeypatch.setenv(SHARDS_ENV, "4")
        assert resolve_shards(2) == 2

    def test_invalid_values_fail_loudly(self, monkeypatch):
        with pytest.raises(ValueError):
            resolve_shards(0)
        monkeypatch.setenv(SHARDS_ENV, "zero")
        with pytest.raises(ValueError):
            resolve_shards(None)
        monkeypatch.setenv(SHARDS_ENV, "-1")
        with pytest.raises(ValueError):
            resolve_shards(None)

    def test_degenerate_one_shard_is_byte_identical(self, monkeypatch):
        """Explicit ``shards=1`` == tri-state None (no env): the same
        delegation to ``als_train``, so factors are BIT-identical and
        the resolved profile dicts agree on every non-timing field."""
        monkeypatch.delenv(SHARDS_ENV, raising=False)
        rng = np.random.default_rng(1)
        u = rng.integers(0, 30, 300).astype(np.int32)
        i = rng.integers(0, 20, 300).astype(np.int32)
        v = np.ones(300, dtype=np.float32)
        cfg = ALSConfig(rank=4, iterations=1, seed=0)
        p_explicit: dict = {}
        f_explicit = als_train_sharded(
            u, i, v, 30, 20, cfg, shards=1, profile=p_explicit
        )
        p_tristate: dict = {}
        f_tristate = als_train_sharded(
            u, i, v, 30, 20, cfg, shards=None, profile=p_tristate
        )
        np.testing.assert_array_equal(
            np.asarray(f_explicit.user_factors),
            np.asarray(f_tristate.user_factors),
        )
        timing = {"stage_s", "iteration_s"}
        cfg_fields = {
            k: v for k, v in p_explicit.items() if k not in timing
        }
        assert cfg_fields == {
            k: v for k, v in p_tristate.items() if k not in timing
        }
        assert p_explicit["shards"] == 1
        # the degenerate path resolves the SAME levers today's trainer
        # records — shards=1 is not a separate trainer
        assert p_explicit["solve_mode"] == "chunked"
        assert p_explicit["sort_gather"] is True
        assert p_explicit["fused_gather"] is False


class TestLoudConflicts:
    """A silently ignored flag would corrupt the hardware A/B — every
    unsupported combination raises before any device work."""

    def _tiny(self):
        return (
            np.array([0, 1, 2], dtype=np.int32),
            np.array([0, 1, 0], dtype=np.int32),
            np.ones(3, dtype=np.float32),
        )

    def test_more_shards_than_devices(self):
        u, i, v = self._tiny()
        with pytest.raises(ValueError, match="devices"):
            als_train_sharded(
                u, i, v, 3, 2,
                ALSConfig(rank=4, iterations=1), shards=16,
            )

    def test_explicit_pallas_solve_mode(self):
        u, i, v = self._tiny()
        with pytest.raises(ValueError, match="solve_mode"):
            als_train_sharded(
                u, i, v, 3, 2,
                ALSConfig(rank=4, iterations=1, solve_mode="pallas"),
                shards=2,
            )

    def test_explicit_fused_gather(self):
        u, i, v = self._tiny()
        with pytest.raises(ValueError, match="fused_gather"):
            als_train_sharded(
                u, i, v, 3, 2,
                ALSConfig(
                    rank=4, iterations=1, solve_mode="chunked",
                    fused_gather=True,
                ),
                shards=2,
            )

    def test_unknown_gather_dtype(self):
        u, i, v = self._tiny()
        with pytest.raises(ValueError, match="gather_dtype"):
            als_train_sharded(
                u, i, v, 3, 2,
                ALSConfig(rank=4, iterations=1, gather_dtype="f16"),
                shards=2,
            )

    def test_algorithm_params_conflicts(self):
        from predictionio_tpu.models.recommendation import (
            ALSAlgorithm,
            ALSAlgorithmParams,
            PreparedData,
        )
        from predictionio_tpu.storage import BiMap

        u, i, v = self._tiny()
        pd = PreparedData(
            user_map=BiMap({"a": 0, "b": 1, "c": 2}),
            item_map=BiMap({"x": 0, "y": 1}),
            users=u, items=i, ratings=v,
        )
        with pytest.raises(ValueError, match="distributed"):
            ALSAlgorithm(
                ALSAlgorithmParams(
                    rank=2, num_iterations=1, shards=2, distributed=True
                )
            ).train(None, pd)
        # checkpoint_every + shards is SUPPORTED since ISSUE 20 (the
        # sharded trainer snapshots canonical factors); without a
        # workflow checkpoint store it simply trains uncheckpointed
        model = ALSAlgorithm(
            ALSAlgorithmParams(
                rank=2, num_iterations=1, shards=2, checkpoint_every=1
            )
        ).train(None, pd)
        assert model.user_factors.shape[0] == 3

    def test_negative_checkpoint_every(self):
        u, i, v = self._tiny()
        with pytest.raises(ValueError, match="checkpoint_every"):
            als_train_sharded(
                u, i, v, 3, 2,
                ALSConfig(rank=4, iterations=1),
                shards=2, checkpoint_every=-1,
            )

    def test_checkpoint_cadence_without_store(self):
        u, i, v = self._tiny()
        with pytest.raises(ValueError, match="checkpoint"):
            als_train_sharded(
                u, i, v, 3, 2,
                ALSConfig(rank=4, iterations=1),
                shards=2, checkpoint=None, checkpoint_every=1,
            )


class TestProfileEvidence:
    """The resolved-lever + balance evidence the bench/ledger and the
    hardware-day drive read (docs/performance.md#levers)."""

    def test_profile_records_resolved_levers_and_plan(self):
        u, i, v, n_u, n_i = _recipe()
        profile: dict = {}
        # rides the sweep's 2-shard cache only for factors; this train
        # is the one extra profiled run the evidence test needs
        f = als_train_sharded(
            u[:1500], i[:1500], v[:1500], n_u, n_i,
            ALSConfig(rank=4, iterations=1, seed=2),
            shards=2, profile=profile,
        )
        assert np.isfinite(np.asarray(f.user_factors)).all()
        assert profile["shards"] == 2
        assert profile["solve_mode"] == "chunked"
        assert profile["fused_gather"] is False
        assert profile["sort_gather"] is True
        plan = profile["shard_plan"]
        assert plan["shards"] == 2
        assert len(plan["perShardFlops"]["user"]) == 2
        assert plan["flopImbalance"]["user"] >= 1.0
        assert len(profile["iteration_s"]) == 1
        assert profile["flops_per_iteration"] > 0


class TestCLISurface:
    """``pio train --shards`` rides the env tri-state end to end (the
    flag sets PIO_TRAIN_SHARDS; the algorithm's None resolves from
    it)."""

    def test_run_workflow_parser_accepts_shards(self):
        from predictionio_tpu.tools.run_workflow import build_parser

        args = build_parser().parse_args(["--shards", "4"])
        assert args.shards == 4

    def test_console_forwards_shards(self):
        import argparse

        from predictionio_tpu.tools.console import _workflow_argv

        ns = argparse.Namespace(
            engine_dir=".", engine_variant="engine.json", batch="",
            engine_params_key=None, verbose=False,
            skip_sanity_check=False, stop_after_read=False,
            stop_after_prepare=False, eval_parallelism=0, shards=4,
        )
        argv = _workflow_argv(ns)
        assert argv[-2:] == ["--shards", "4"]
        # an explicit 0 forwards too (it must FAIL LOUDLY in
        # resolve_shards, never silently train single-device)
        ns.shards = 0
        assert _workflow_argv(ns)[-2:] == ["--shards", "0"]

    def test_env_zero_fails_loudly(self, monkeypatch):
        monkeypatch.setenv(SHARDS_ENV, "0")
        with pytest.raises(ValueError):
            resolve_shards(None)

    def test_sharded_ledger_records_key_by_shard_count(self):
        from predictionio_tpu.obs import perfledger

        bench = {
            "shardedTrain": {
                "ok": True,
                "counts": {
                    "1": {"trainS": 10.0, "rmse": 0.9, "device": "cpu"},
                    "4": {"trainS": 4.0, "rmse": 0.9, "device": "cpu"},
                },
            }
        }
        records = perfledger.sharded_records(bench)
        assert [r["metric"] for r in records] == ["train_sharded_s"] * 2
        assert [r["scale"] for r in records] == [1, 4]
        assert all(r["unit"] == "s" for r in records)
        assert all(r["noise_band"] == 0.5 for r in records)
        # shard counts never share a comparable group: `pio perf diff`
        # can never gate a 4-shard run against the 1-shard trajectory
        keys = {perfledger.comparable_key(r) for r in records}
        assert len(keys) == 2
        # a failed drive records nothing
        assert perfledger.sharded_records(
            {"shardedTrain": {"ok": False, "counts": {}}}
        ) == []
