"""Engine/controller tests — the analogue of ``EngineTest.scala``,
``EngineWorkflowTest``, ``EvaluationWorkflowTest`` and
``FastEvalEngineTest.scala`` (memoization-count asserts)."""

import dataclasses
import pickle

import pytest

from predictionio_tpu.controller import (
    RETRAIN,
    EmptyParams,
    Engine,
    EngineParams,
    EngineParamsGenerator,
    Evaluation,
    FastEvalEngine,
    FirstServing,
    IdentityPreparator,
    Metric,
    MetricEvaluator,
    ParamsError,
    PersistentModelManifest,
    StopAfterPrepareInterruption,
    StopAfterReadInterruption,
    WorkflowParams,
    extract_params,
)
from predictionio_tpu.workflow.context import WorkflowContext

from sample_engine import (
    Algo0,
    Algo1,
    Actual,
    DataSource0,
    DSParams,
    IdParams,
    NonPersistentAlgo,
    PersistentAlgo,
    PersistableModel,
    Prediction,
    Preparator0,
    Query,
    SampleModel,
    Serving0,
    reset_all_counts,
)


@pytest.fixture(autouse=True)
def _reset():
    reset_all_counts()


@pytest.fixture()
def ctx():
    return WorkflowContext(mode="Training", batch="test")


def make_engine(algo_map=None):
    return Engine(
        {"": DataSource0},
        {"": Preparator0},
        algo_map or {"": Algo0},
        {"": Serving0},
    )


def make_params(ds_id=3, prep_id=7, algo_ids=(11,), n_eval_sets=2):
    return EngineParams(
        data_source_params=("", DSParams(id=ds_id, n_eval_sets=n_eval_sets)),
        preparator_params=("", IdParams(id=prep_id)),
        algorithm_params_list=[("", IdParams(id=a)) for a in algo_ids],
        serving_params=("", IdParams(id=0)),
    )


class TestTrain:
    def test_dataflow_composition(self, ctx):
        engine = make_engine()
        models = engine.train(ctx, make_params(ds_id=3, prep_id=7, algo_ids=(11, 13)))
        assert models == [
            SampleModel(algo_id=11, pd_id=7),
            SampleModel(algo_id=13, pd_id=7),
        ]

    def test_read_error_wrapped(self, ctx):
        engine = make_engine()
        params = make_params()

        class BoomDS(DataSource0):
            def read_training(self, c):
                raise IOError("backend down")

        eng = Engine({"": BoomDS}, {"": Preparator0}, {"": Algo0}, {"": Serving0})
        with pytest.raises(RuntimeError, match="Data is incomplete"):
            eng.train(ctx, params)

    def test_sanity_check_failure_propagates(self, ctx):
        engine = make_engine()
        params = make_params()
        params = params.copy(
            data_source_params=("", DSParams(id=1, error=True))
        )
        with pytest.raises(ValueError, match="sanity check"):
            engine.train(ctx, params)
        # --skip-sanity-check suppresses it (Engine.scala:526-543)
        models = engine.train(
            ctx, params, WorkflowParams(skip_sanity_check=True)
        )
        assert len(models) == 1

    def test_stop_after_read_and_prepare(self, ctx):
        engine = make_engine()
        with pytest.raises(StopAfterReadInterruption):
            engine.train(ctx, make_params(), WorkflowParams(stop_after_read=True))
        with pytest.raises(StopAfterPrepareInterruption):
            engine.train(ctx, make_params(), WorkflowParams(stop_after_prepare=True))

    def test_unknown_component_name(self, ctx):
        engine = make_engine()
        bad = make_params().copy(data_source_params=("nope", EmptyParams()))
        with pytest.raises(KeyError):
            engine.train(ctx, bad)


class TestPersistence:
    def test_plain_model_passthrough_pickle(self, ctx):
        engine = make_engine()
        params = make_params()
        models = engine.train(ctx, params)
        persisted = engine.make_serializable_models(ctx, params, "I1", models)
        roundtrip = pickle.loads(pickle.dumps(persisted))
        live = engine.prepare_deploy(ctx, params, "I1", roundtrip)
        assert live == models

    def test_persistent_model_manifest(self, ctx):
        engine = Engine(
            {"": DataSource0}, {"": Preparator0}, {"": PersistentAlgo}, {"": Serving0}
        )
        params = make_params(algo_ids=(5,))
        models = engine.train(ctx, params)
        persisted = engine.make_serializable_models(ctx, params, "I2", models)
        assert isinstance(persisted[0], PersistentModelManifest)
        live = engine.prepare_deploy(
            ctx, params, "I2", pickle.loads(pickle.dumps(persisted))
        )
        assert isinstance(live[0], PersistableModel)
        assert live[0].algo_id == 5

    def test_retrain_at_deploy(self, ctx):
        engine = Engine(
            {"": DataSource0}, {"": Preparator0}, {"": NonPersistentAlgo}, {"": Serving0}
        )
        params = make_params(algo_ids=(9,))
        models = engine.train(ctx, params)
        assert NonPersistentAlgo.count == 1
        persisted = engine.make_serializable_models(ctx, params, "I3", models)
        assert persisted[0] is RETRAIN
        # RETRAIN survives pickling as the same sentinel
        unpickled = pickle.loads(pickle.dumps(persisted))
        assert unpickled[0] is RETRAIN
        live = engine.prepare_deploy(ctx, params, "I3", unpickled)
        assert NonPersistentAlgo.count == 2  # retrained
        assert live[0] == SampleModel(algo_id=9, pd_id=7)

    def test_mixed_persistence(self, ctx):
        engine = Engine(
            {"": DataSource0},
            {"": Preparator0},
            {"plain": Algo0, "npa": NonPersistentAlgo, "pa": PersistentAlgo},
            {"": Serving0},
        )
        params = make_params().copy(
            algorithm_params_list=[
                ("plain", IdParams(id=1)),
                ("npa", IdParams(id=2)),
                ("pa", IdParams(id=3)),
            ]
        )
        models = engine.train(ctx, params)
        persisted = engine.make_serializable_models(ctx, params, "I4", models)
        live = engine.prepare_deploy(
            ctx, params, "I4", pickle.loads(pickle.dumps(persisted))
        )
        assert live[0] == SampleModel(algo_id=1, pd_id=7)
        assert live[1] == SampleModel(algo_id=2, pd_id=7)
        assert isinstance(live[2], PersistableModel)


class TestEval:
    def test_eval_dataflow(self, ctx):
        engine = make_engine({"a0": Algo0, "a1": Algo1})
        params = make_params(n_eval_sets=2).copy(
            algorithm_params_list=[("a0", IdParams(id=1)), ("a1", IdParams(id=2))]
        )
        results = engine.eval(ctx, params)
        assert len(results) == 2  # two folds
        ei, qpa = results[0]
        assert ei.id == 3
        assert len(qpa) == 2
        q, p, a = qpa[0]
        assert isinstance(q, Query) and isinstance(a, Actual)
        # serving combined both algos in order
        assert p.combined == (1, 2)
        assert p.algo_id == 1  # first algo's prediction is the base

    def test_batch_eval_returns_all_params(self, ctx):
        engine = make_engine()
        eps = [make_params(algo_ids=(i,)) for i in range(3)]
        results = engine.batch_eval(ctx, eps)
        assert [ep for ep, _ in results] == eps


class TestJsonToEngineParams:
    def test_full_variant(self):
        engine = make_engine({"a0": Algo0, "a1": Algo1})
        variant = {
            "id": "default",
            "engineFactory": "tests.Factory",
            "datasource": {"params": {"id": 4, "n_eval_sets": 1}},
            "preparator": {"params": {"id": 5}},
            "algorithms": [
                {"name": "a0", "params": {"id": 6}},
                {"name": "a1", "params": {"id": 7}},
            ],
            "serving": {"params": {"id": 8}},
        }
        ep = engine.json_to_engine_params(variant)
        assert ep.data_source_params == ("", DSParams(id=4, n_eval_sets=1))
        assert ep.preparator_params == ("", IdParams(id=5))
        assert ep.algorithm_params_list == (
            ("a0", IdParams(id=6)),
            ("a1", IdParams(id=7)),
        )
        assert ep.serving_params == ("", IdParams(id=8))

    def test_missing_fields_use_component_defaults(self):
        # An absent params block yields the component's declared default
        # Params (its params_class()), not EmptyParams — a component with
        # meaningful defaults (e.g. a preparator's seq_len) must still work
        # when the variant omits the block.
        engine = make_engine()
        ep = engine.json_to_engine_params({"engineFactory": "f"})
        assert ep.data_source_params == ("", DSParams())
        assert ep.algorithm_params_list == (("", IdParams()),)

    def test_unknown_algorithm_name_rejected(self):
        engine = make_engine()
        with pytest.raises(ParamsError):
            engine.json_to_engine_params(
                {"algorithms": [{"name": "ghost", "params": {}}]}
            )

    def test_params_extraction_errors(self):
        with pytest.raises(ParamsError, match="unknown fields"):
            extract_params(IdParams, {"id": 1, "bogus": 2})
        with pytest.raises(ParamsError, match="expected an integer"):
            extract_params(IdParams, {"id": "x"})

    def test_engine_instance_roundtrip(self):
        from predictionio_tpu.controller import serialize_engine_params

        engine = make_engine({"a0": Algo0})
        ep = make_params().copy(
            algorithm_params_list=[("a0", IdParams(id=42))]
        )
        cols = serialize_engine_params(ep)

        class FakeInstance:
            data_source_params = cols["data_source_params"]
            preparator_params = cols["preparator_params"]
            algorithms_params = cols["algorithms_params"]
            serving_params = cols["serving_params"]

        ep2 = engine.engine_instance_to_engine_params(FakeInstance())
        assert ep2 == ep


class IdSumMetric(Metric):
    """Sums prediction algo ids over all folds (deterministic check)."""

    def calculate(self, ctx, eval_data_set):
        return sum(
            p.algo_id for _, qpa in eval_data_set for _, p, _ in qpa
        )


class TestMetricEvaluator:
    def test_best_params_selection(self, ctx):
        engine = make_engine()
        eps = [make_params(algo_ids=(i,)) for i in (1, 5, 3)]
        data = engine.batch_eval(ctx, eps)
        result = MetricEvaluator(IdSumMetric()).evaluate_base(ctx, None, data)
        assert result.best_idx == 1
        assert result.best_engine_params == eps[1]
        assert result.best_score.score == 5 * 4  # 2 folds x 2 queries
        assert len(result.engine_params_scores) == 3

    def test_tie_keeps_earliest(self, ctx):
        engine = make_engine()
        eps = [make_params(algo_ids=(2,)), make_params(algo_ids=(2,))]
        data = engine.batch_eval(ctx, eps)
        result = MetricEvaluator(IdSumMetric()).evaluate_base(ctx, None, data)
        assert result.best_idx == 0

    def test_output_path_writes_variant(self, ctx, tmp_path):
        engine = make_engine()
        data = engine.batch_eval(ctx, [make_params(algo_ids=(4,))])
        out = tmp_path / "best.json"
        MetricEvaluator(IdSumMetric(), output_path=str(out)).evaluate_base(
            ctx, None, data
        )
        import json

        best = json.loads(out.read_text())
        assert best["algorithms"][0]["params"]["id"] == 4


class TestFastEvalMemoization:
    """FastEvalEngineTest.scala:30-146 — invocation-count asserts."""

    def fast_engine(self):
        return FastEvalEngine(
            {"": DataSource0}, {"": Preparator0}, {"": Algo0}, {"": Serving0}
        )

    def test_algo_sweep_reads_once(self, ctx):
        engine = self.fast_engine()
        eps = [make_params(algo_ids=(i,), n_eval_sets=1) for i in range(4)]
        results = engine.batch_eval(ctx, eps)
        assert len(results) == 4
        assert DataSource0.count == 1  # read once across the sweep
        assert Preparator0.count == 1  # prepared once
        assert Algo0.count == 4  # trained per algo params

    def test_ds_sweep_reads_per_params(self, ctx):
        engine = self.fast_engine()
        eps = [make_params(ds_id=i, n_eval_sets=1) for i in range(3)]
        engine.batch_eval(ctx, eps)
        assert DataSource0.count == 3
        assert Preparator0.count == 3

    def test_duplicate_params_fully_cached(self, ctx):
        engine = self.fast_engine()
        ep = make_params(n_eval_sets=1)
        engine.batch_eval(ctx, [ep, ep, ep])
        assert DataSource0.count == 1
        assert Algo0.count == 1
        assert Serving0.count == 2  # 1 fold x 2 queries, computed once

    def test_serving_sweep_caches_predictions(self, ctx):
        engine = self.fast_engine()
        base = make_params(n_eval_sets=1)
        eps = [
            base.copy(serving_params=("", IdParams(id=i))) for i in range(3)
        ]
        engine.batch_eval(ctx, eps)
        assert Algo0.count == 1  # predictions cached across serving sweep
        assert DataSource0.count == 1

    def test_non_value_eq_params_not_cached(self, ctx):
        """Params without value equality never hit the cache
        (FastEvalEngineTest.scala:146)."""

        class RawParams:  # not a dataclass: identity equality
            def __init__(self, id=0):
                self.id = id

        engine = self.fast_engine()
        eps = [
            make_params(n_eval_sets=1).copy(
                data_source_params=("", RawParams())
            )
            for _ in range(2)
        ]

        class RawDS(DataSource0):
            count = 0

            def __init__(self, params=None):
                self.params = params or DSParams()

            def read_eval(self, c):
                type(self).bump()
                from sample_engine import TrainingData, EvalInfo

                return [(TrainingData(id=1), EvalInfo(id=1), [(Query(0), Actual(0))])]

        eng = FastEvalEngine(
            {"": RawDS}, {"": Preparator0}, {"": Algo0}, {"": Serving0}
        )
        eng.batch_eval(ctx, eps)
        assert RawDS.count == 2  # two distinct instances, no cache hits


class TestEvaluationWiring:
    def test_evaluation_engine_metric(self, ctx):
        ev = Evaluation()
        ev.engine_metric = (make_engine(), IdSumMetric())
        engine, evaluator = ev.engine_evaluator
        assert isinstance(evaluator, MetricEvaluator)
        assert isinstance(evaluator.metric, IdSumMetric)

    def test_unset_evaluation_raises(self):
        with pytest.raises(ValueError):
            Evaluation().engine_evaluator

    def test_generator(self):
        g = EngineParamsGenerator()
        with pytest.raises(ValueError):
            g.engine_params_list
        g.engine_params_list = [make_params()]
        assert len(g.engine_params_list) == 1
