"""Benchmark: ALS rank-50 on a MovieLens-20M-shaped workload.

Prints ONE JSON line on stdout:
``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}``
(diagnostics go to stderr).

The north-star target (BASELINE.json) is MLlib ALS rank-50 on MovieLens-20M
training in < 60 s on a v5e-8 at RMSE parity. This bench runs on whatever
device is available (the driver provides one real TPU chip): it synthesizes a
20M-rating matrix with ML-20M's shape (138k users x 27k items, power-law
degrees, low-rank ground truth + noise), trains rank-50 for 10 iterations —
wall-clock includes bucketization, host→device staging and training — and
verifies holdout RMSE approaches the noise floor (quality gate; the run
fails loudly rather than reporting a fast-but-wrong number).

Bring-up: before committing to the full workload the bench probes the
device with a tiny op in a subprocess (a wedged accelerator tunnel would
otherwise hang or stack-trace the whole run). One retry, then a clean
fallback to the CPU backend at reduced scale — a measured number on a
fallback device beats a traceback.

``vs_baseline`` = 60 s / measured train seconds (>1 beats the 8-chip target
even on this single chip).

Env knobs: ``BENCH_SCALE`` (default 1.0) scales the rating count for quick
smoke runs; ``BENCH_ITERATIONS`` (default 10); ``BENCH_CPU_SCALE`` (default
0.01) is the scale used when falling back to CPU; ``BENCH_SYNTH_CACHE``
(off by default; the revalidation queue sets it) names a directory where
the deterministic synthetic dataset is cached across runs — cache files
are keyed by (generator version, scale, seed). Lever knobs
(``BENCH_SOLVE_MODE``/``BENCH_GATHER_DTYPE``/``BENCH_SORT_GATHER``/
``BENCH_FUSED_GATHER``) are documented at their ALSConfig fields; since
round 12 the fast paths default ON (sort-gather rides every run,
``BENCH_SORT_GATHER=0`` opts out; fused-gather resolves with the
solver, ``BENCH_FUSED_GATHER=0`` forces it off) and every round trains
a bf16-gather twin whose holdout RMSE must stay within
``BENCH_BF16_RMSE_GATE`` (default 0.01) of the f32 run —
``BENCH_BF16_GATE=0`` opts out, a drift fails the bench loudly. The
recorded lever flags are the RESOLVED values, and the gate's margin
rides the record (``bf16_gate``) into the perf ledger's ``extra``.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.abspath(__file__))

#: North-star wall-clock target (BASELINE.md): ML-20M rank-50 in < 60 s.
_BASELINE_S = 60.0

# The v5e reference peaks (98.5 TFLOP/s attainable f32, 819 GB/s HBM)
# live in predictionio_tpu.obs.profile.DEVICE_PEAKS — one home shared
# with `pio profile`'s roofline columns, so the two reports can never
# disagree about the same run.

#: Version of the synth_ml20m generation recipe — part of the cache key;
#: bump on ANY change to the sampling/ground-truth/noise code.
_SYNTH_VERSION = 1

_PROBE_SNIPPET = (
    "import jax, sys; "
    "d = jax.devices(); "
    "x = jax.numpy.ones((128, 128)) @ jax.numpy.ones((128, 128)); "
    "x.block_until_ready(); "
    "print('PROBE_OK', d[0].platform, len(d), file=sys.stderr)"
)


def probe_device(timeout_s: float = 240.0) -> str:
    """Run a tiny device op in a subprocess with a timeout. Returns "ok",
    "failed" (fast error — worth one retry), or "timeout" (unresponsive
    tunnel; killing the child may wedge it further, so the caller should
    go straight to fallback rather than re-probe)."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _PROBE_SNIPPET],
            timeout=timeout_s,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.PIPE,
        )
    except subprocess.TimeoutExpired:
        print(
            f"bench bring-up: device probe timed out after {timeout_s:.0f}s "
            "(accelerator tunnel unresponsive)",
            file=sys.stderr,
        )
        return "timeout"
    tail = proc.stderr.decode("utf-8", "replace").strip().splitlines()
    if proc.returncode == 0 and any("PROBE_OK" in ln for ln in tail):
        print(f"bench bring-up: {[l for l in tail if 'PROBE_OK' in l][0]}",
              file=sys.stderr)
        return "ok"
    last = tail[-1] if tail else "(no stderr)"
    print(
        f"bench bring-up: device probe failed rc={proc.returncode}: {last}",
        file=sys.stderr,
    )
    return "failed"


def _fallback_to_cpu(scale: float) -> int:
    """Re-exec this script hard-pinned to the CPU backend at reduced scale.
    The child's stdout (the JSON line) passes straight through."""
    sys.path.insert(0, _REPO_ROOT)
    from predictionio_tpu.utils.platform import force_cpu_env

    cpu_scale = min(scale, float(os.environ.get("BENCH_CPU_SCALE", "0.01")))
    env = force_cpu_env()
    env["_PIO_BENCH_CHILD"] = "cpu-fallback"
    env["BENCH_SCALE"] = str(cpu_scale)
    print(
        f"bench bring-up: falling back to CPU backend at scale {cpu_scale}",
        file=sys.stderr,
    )
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__)], env=env, cwd=_REPO_ROOT
    )
    return proc.returncode


def synth_ml20m(scale: float, seed: int = 0):
    """ML-20M-shaped synthetic ratings: power-law user/item degrees, rank-8
    ground truth, sd-0.5 observation noise.

    Deterministic in (scale, seed), so when ``BENCH_SYNTH_CACHE`` names a
    directory the triplets are saved there once and reloaded by later
    runs — the revalidation queue runs this bench ~8 times back to back
    and the ~minute of host-side generation per run comes straight out
    of the (historically scarce) hardware window."""
    cache_dir = os.environ.get("BENCH_SYNTH_CACHE")
    cache = None
    if cache_dir:
        os.makedirs(cache_dir, exist_ok=True)
        # _SYNTH_VERSION is part of the key: bump it with ANY change to
        # the generation code below, or a persistent cache dir would
        # silently serve the pre-change dataset as current evidence
        cache = os.path.join(
            cache_dir,
            f"synth_ml20m_v{_SYNTH_VERSION}_s{scale}_seed{seed}.npz",
        )
        if os.path.exists(cache):
            try:
                z = np.load(cache)
                return (
                    z["users"], z["items"], z["ratings"],
                    int(z["n_users"]), int(z["n_items"]),
                )
            except Exception as exc:  # torn write: regenerate
                print(f"bench: synth cache unreadable ({exc}); "
                      "regenerating", file=sys.stderr)
    rng = np.random.default_rng(seed)
    n_users = max(64, int(138_000 * min(1.0, scale)))
    n_items = max(32, int(27_000 * min(1.0, scale)))
    nnz = int(20_000_000 * scale)

    # power-law sampling via Zipf-ish inverse-rank weights
    u_w = 1.0 / np.arange(1, n_users + 1) ** 0.8
    i_w = 1.0 / np.arange(1, n_items + 1) ** 0.9
    users = rng.choice(n_users, size=nnz, p=u_w / u_w.sum()).astype(np.int64)
    items = rng.choice(n_items, size=nnz, p=i_w / i_w.sum()).astype(np.int64)

    gt_rank = 8
    x = rng.normal(size=(n_users, gt_rank)) / np.sqrt(gt_rank)
    y = rng.normal(size=(n_items, gt_rank)) / np.sqrt(gt_rank)
    ratings = (
        (x[users] * y[items]).sum(axis=1) + 3.5 + rng.normal(0, 0.5, nnz)
    ).astype(np.float32)
    if cache:
        # tmp name keeps the .npz suffix so np.savez writes it verbatim;
        # atomic rename = concurrent bench runs never see a torn file.
        # Sweep predecessors' orphans first: a bench killed mid-savez
        # (the tunnel-wedge timeout) leaves a ~400 MB tmp behind. Only
        # reap a tmp whose writer pid is gone — a concurrent bench's
        # live tmp must not vanish out from under its savez.
        import glob

        for orphan in glob.glob(f"{cache}.*.tmp.npz"):
            try:
                age_s = time.time() - os.path.getmtime(orphan)
            except OSError:
                continue  # vanished under us (another reaper won)
            if age_s < 6 * 3600.0:
                # young tmp: only reap if its writer pid is gone. Old
                # tmps are reaped regardless — a recycled pid must not
                # make a ~400 MB orphan permanent.
                try:
                    pid = int(os.path.basename(orphan).split(".")[-3])
                    os.kill(pid, 0)  # raises if no such process
                    continue  # writer still alive; leave its tmp alone
                except (ValueError, IndexError, ProcessLookupError):
                    pass  # unparseable name or dead writer: orphan
                except OSError:
                    continue  # exists but not signalable: assume alive
            try:
                os.remove(orphan)
            except OSError:
                pass
        tmp = f"{cache}.{os.getpid()}.tmp.npz"
        try:
            np.savez(tmp, users=users, items=items, ratings=ratings,
                     n_users=n_users, n_items=n_items)
            os.replace(tmp, cache)
        except BaseException:
            if os.path.exists(tmp):
                os.remove(tmp)
            raise
    return users, items, ratings, n_users, n_items


def holdout_mask(nnz: int) -> np.ndarray:
    """The bench's holdout split (5%, fixed seed). Shared with
    ``tools/prewarm_cache`` so the AOT-compiled programs keep the EXACT
    bench bucket shapes — any change here changes the compiled program
    and must flow to both users."""
    return np.random.default_rng(1).random(nnz) < 0.05


def _append_ledger(record: dict) -> None:
    """Durable perf-ledger append (``BENCH_LEDGER=path`` opts in —
    docs/performance.md#perf-ledger). Strictly additive: stdout stays
    the one-JSON-line contract, and a ledger failure never fails the
    bench."""
    path = os.environ.get("BENCH_LEDGER")
    if not path:
        return
    try:
        from predictionio_tpu.obs import perfledger

        perfledger.append_record(
            path,
            perfledger.bench_to_record(record),
        )
        # serving-fleet numbers (loadgen --replicas) gate alongside the
        # train time: p99 as a lower-is-better "s" record, QPS as a
        # trend-only record (docs/fleet.md, docs/performance.md)
        for fleet_record in perfledger.fleet_records(record):
            perfledger.append_record(path, fleet_record)
        # serve-from-memory numbers (loadgen --cached-hot-set): cached
        # p99 gated at its declared wide band, the step-function QPS
        # and hit-rate as trend records (docs/fleet.md#cache)
        for cache_record in perfledger.cache_records(record):
            perfledger.append_record(path, cache_record)
        # shared-tier numbers (loadgen --shared-cache-drill): the
        # hedged healthy-phase p99 gated at its declared wide band, the
        # fleet-wide hit rate as a trend record
        # (docs/fleet.md#shared-cache-tier)
        for shared_record in perfledger.shared_cache_records(record):
            perfledger.append_record(path, shared_record)
        # quantized-serving numbers (BENCH_QUANT block): the int8 table
        # byte count gated as a deterministic lower-is-better "bytes"
        # record, the top-k match rate as a trend record
        # (docs/quantization.md)
        for quant_record in perfledger.quant_records(record):
            perfledger.append_record(path, quant_record)
        # model-quality trajectory (score PSI / feedback hit-rate from
        # the feedback-stream drill) rides as trend-only records so
        # `pio perf trend` shows quality next to latency
        # (docs/observability.md#quality)
        for quality_record in perfledger.quality_records(record):
            perfledger.append_record(path, quality_record)
        # alert noisiness from the brownout drill, trend-only
        # (docs/slo.md): alert hygiene gets a trajectory too
        for alert_record in perfledger.alert_records(record):
            perfledger.append_record(path, alert_record)
        # ingest throughput per partition count, trend-only and keyed
        # by N via scale (docs/storage.md#partitioning): different
        # partition counts never gate each other
        for ingest_record in perfledger.ingest_records(record):
            perfledger.append_record(path, ingest_record)
        # sharded-train wall clock per shard count, keyed by N via scale
        # the same way (docs/distributed_training.md): each shard count
        # has its own gated trajectory, declared wide-band
        for sharded_record in perfledger.sharded_records(record):
            perfledger.append_record(path, sharded_record)
        # lint-sweep cold wall clock, trend-only (docs/lint.md#cache):
        # the warm time and cache byte-identity ride in extra
        for lint_record in perfledger.lint_records(record):
            perfledger.append_record(path, lint_record)
        # migration-drill wall + dual-write overhead, trend-only and
        # keyed by "N->M" via scale (docs/storage.md#live-migration):
        # an expansion and a merge never share a trajectory
        for migration_record in perfledger.migration_records(record):
            perfledger.append_record(path, migration_record)
        # checkpointing overhead ratio from the preemption drill,
        # trend-only (docs/checkpoint.md): the cost of never losing a
        # run gets a trajectory, never a gate
        for ckpt_record in perfledger.ckpt_records(record):
            perfledger.append_record(path, ckpt_record)
    except Exception as exc:
        print(f"bench: ledger append failed (ignored): {exc}",
              file=sys.stderr)


#: Child program for one sharded-train measurement. Runs in a SUBPROCESS
#: because the virtual device count must be pinned in XLA_FLAGS before
#: the first `import jax`; the recipe is deterministic in its seed so
#: every shard count trains the identical dataset (docs/
#: distributed_training.md — equivalence is pinned in tier-1, this
#: measures wall clock).
_SHARDED_SNIPPET = r"""
import json, sys, time
sys.path.insert(0, {repo!r})
import numpy as np
from predictionio_tpu.ops.als import ALSConfig, rmse
from predictionio_tpu.ops.als_sharded import als_train_sharded

shards = {shards}
rng = np.random.default_rng(7)
nnz, n_u, n_i = 60_000, 2_000, 600
w = 1.0 / np.arange(1, n_u + 1) ** 0.8
u = rng.choice(n_u, size=nnz, p=w / w.sum()).astype(np.int32)
i = rng.integers(0, n_i, nnz).astype(np.int32)
v = rng.integers(1, 6, nnz).astype(np.float32)
cfg = ALSConfig(rank=16, iterations=3, lambda_=0.05, seed=0)
profile = {{}}
t0 = time.monotonic()
factors = als_train_sharded(
    u, i, v, n_users=n_u, n_items=n_i, cfg=cfg, shards=shards,
    profile=profile,
)
np.asarray(factors.user_factors)
train_s = time.monotonic() - t0
import jax
out = {{
    "trainS": round(train_s, 3),
    "rmse": round(rmse(factors, u, i, v), 4),
    "shards": profile.get("shards"),
    "device": str(jax.devices()[0]),
    "nnz": nnz,
    "iterations": cfg.iterations,
    "solve_mode": profile.get("solve_mode", "chunked"),
    "gather_dtype": profile.get("gather_dtype", "f32"),
    "sort_gather": profile.get("sort_gather", True),
    "fused_gather": profile.get("fused_gather", False),
    "flopImbalance": (profile.get("shard_plan") or {{}}).get(
        "flopImbalance"
    ),
}}
print("SHARDED_JSON " + json.dumps(out))
"""


#: Child program for the preemption drill (docs/checkpoint.md). Two
#: modes in a SUBPROCESS each (virtual device count must be pinned
#: before the first `import jax`): "kill" trains with checkpointing and
#: SIGKILLs itself the instant the chosen step commits — a reclaimed VM,
#: not a clean shutdown — and "resume" picks the run back up at a
#: DIFFERENT shard count, compares against an uninterrupted in-process
#: twin within the PR-12 reassociation tolerances, and measures the
#: checkpointing overhead ratio on an untouched third run.
_CKPT_SNIPPET = r"""
import json, os, shutil, signal, sys, time
sys.path.insert(0, {repo!r})
import numpy as np
from predictionio_tpu.ckpt import CheckpointStore
from predictionio_tpu.ops.als import ALSConfig, rmse
from predictionio_tpu.ops.als_sharded import als_train_sharded

mode = {mode!r}
ckpt_dir = {ckpt_dir!r}
shards = {shards}
kill_step = {kill_step}

rng = np.random.default_rng(11)
nnz, n_u, n_i = 30_000, 1_000, 400
w = 1.0 / np.arange(1, n_u + 1) ** 0.8
u = rng.choice(n_u, size=nnz, p=w / w.sum()).astype(np.int32)
i = rng.integers(0, n_i, nnz).astype(np.int32)
v = rng.integers(1, 6, nnz).astype(np.float32)
cfg = ALSConfig(rank=8, iterations=3, lambda_=0.05, seed=3)

if mode == "kill":
    class KillingStore(CheckpointStore):
        def save(self, step, arrays, meta):
            out = super().save(step, arrays, meta)
            if step == kill_step:
                os.kill(os.getpid(), signal.SIGKILL)
            return out

    als_train_sharded(
        u, i, v, n_users=n_u, n_items=n_i, cfg=cfg, shards=shards,
        checkpoint=KillingStore(ckpt_dir), checkpoint_every=1,
    )
    print("CKPT_JSON " + json.dumps({{"error": "kill never fired"}}))
    sys.exit(3)

profile = {{}}
t0 = time.monotonic()
resumed = als_train_sharded(
    u, i, v, n_users=n_u, n_items=n_i, cfg=cfg, shards=shards,
    checkpoint=CheckpointStore(ckpt_dir), checkpoint_every=1,
    profile=profile,
)
ru = np.asarray(resumed.user_factors)
ri = np.asarray(resumed.item_factors)
resume_s = time.monotonic() - t0

t0 = time.monotonic()
plain = als_train_sharded(
    u, i, v, n_users=n_u, n_items=n_i, cfg=cfg, shards=shards,
)
plain_s = time.monotonic() - t0
pu = np.asarray(plain.user_factors)
pi = np.asarray(plain.item_factors)

fresh = ckpt_dir + ".overhead"
shutil.rmtree(fresh, ignore_errors=True)
t0 = time.monotonic()
als_train_sharded(
    u, i, v, n_users=n_u, n_items=n_i, cfg=cfg, shards=shards,
    checkpoint=CheckpointStore(fresh), checkpoint_every=1,
)
ckpt_s = time.monotonic() - t0
shutil.rmtree(fresh, ignore_errors=True)

import jax
ck = profile.get("ckpt") or {{}}
rmse_resumed = rmse(resumed, u, i, v)
rmse_plain = rmse(plain, u, i, v)
out = {{
    "resumedFrom": ck.get("resumedFrom"),
    "equivalent": bool(
        np.allclose(ru, pu, rtol=1e-3, atol=1e-4)
        and np.allclose(ri, pi, rtol=1e-3, atol=1e-4)
        and abs(rmse_resumed - rmse_plain) <= 1e-3
    ),
    "maxAbsDiff": round(float(max(
        np.max(np.abs(ru - pu)), np.max(np.abs(ri - pi))
    )), 6),
    "rmseResumed": round(float(rmse_resumed), 4),
    "rmsePlain": round(float(rmse_plain), 4),
    "resumeS": round(resume_s, 3),
    "plainS": round(plain_s, 3),
    "ckptS": round(ckpt_s, 3),
    "overheadRatio": (
        round(ckpt_s / plain_s, 4) if plain_s > 0 else None
    ),
    "snapshotS": ck.get("snapshotS"),
    "written": ck.get("written"),
    "dropped": ck.get("dropped"),
    "errors": ck.get("errors"),
    "device": str(jax.devices()[0]),
    "nnz": nnz,
    "iterations": cfg.iterations,
}}
print("CKPT_JSON " + json.dumps(out))
"""


def run_ckpt_resume(
    train_shards: int = 2, resume_shards: int = 4, timeout_s: float = 600.0
) -> dict:
    """The preemption drill (docs/checkpoint.md#preemption-drill):
    checkpointed training at N shards SIGKILLed the instant a chosen
    step commits, resumed at M shards, compared against an uninterrupted
    twin within the PR-12 tolerances. The overhead ratio (ckpt-on wall /
    plain wall) rides the ledger trend-only as
    ``train_ckpt_overhead_ratio``. Returns the ``ckptResume`` bench
    block (``ok`` only when the kill fired, the resume picked up the
    killed run's last committed step, and the factors match)."""
    import random
    import shutil
    import signal
    import tempfile

    from predictionio_tpu.utils.platform import force_cpu_env

    # a random kill point keeps the drill honest over bench history —
    # resume must work from ANY committed step, not a lucky one
    kill_step = random.choice((1, 2))
    ckpt_dir = tempfile.mkdtemp(prefix="bench_ckpt_")
    block: dict = {
        "trainShards": train_shards,
        "resumeShards": resume_shards,
        "killStep": kill_step,
        "ok": False,
    }

    def _child(mode: str, shards: int) -> subprocess.CompletedProcess:
        return subprocess.run(
            [
                sys.executable,
                "-c",
                _CKPT_SNIPPET.format(
                    repo=_REPO_ROOT, mode=mode, ckpt_dir=ckpt_dir,
                    shards=shards, kill_step=kill_step,
                ),
            ],
            env=force_cpu_env(n_devices=shards),
            cwd=_REPO_ROOT,
            timeout=timeout_s,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
        )

    try:
        kill = _child("kill", train_shards)
        if kill.returncode != -signal.SIGKILL:
            tail = kill.stderr.decode("utf-8", "replace").strip().splitlines()
            block["error"] = (
                f"kill child rc={kill.returncode}, expected SIGKILL: "
                f"{tail[-1] if tail else '(no stderr)'}"
            )
            return block
        proc = _child("resume", resume_shards)
        line = next(
            (
                ln[len("CKPT_JSON "):]
                for ln in proc.stdout.decode("utf-8", "replace").splitlines()
                if ln.startswith("CKPT_JSON ")
            ),
            None,
        )
        if proc.returncode != 0 or line is None:
            tail = proc.stderr.decode("utf-8", "replace").strip().splitlines()
            block["error"] = (
                f"resume child rc={proc.returncode}: "
                f"{tail[-1] if tail else '(no stderr)'}"
            )
            return block
        block.update(json.loads(line))
        if block.get("resumedFrom") != kill_step:
            block["error"] = (
                f"resumed from step {block.get('resumedFrom')}, "
                f"expected the killed run's last commit {kill_step}"
            )
        elif not block.get("equivalent"):
            block["error"] = (
                f"resumed factors drifted beyond tolerance "
                f"(maxAbsDiff {block.get('maxAbsDiff')})"
            )
        else:
            block["ok"] = True
        print(
            f"bench ckptResume: killed@{kill_step} "
            f"{train_shards}->{resume_shards} shards "
            f"ok={block['ok']} overhead {block.get('overheadRatio')}",
            file=sys.stderr,
        )
        return block
    except subprocess.TimeoutExpired:
        block["error"] = f"timed out after {timeout_s:.0f}s"
        return block
    finally:
        shutil.rmtree(ckpt_dir, ignore_errors=True)


def run_lint_sweep() -> dict:
    """Cold-vs-warm full-package lint sweep with a throwaway cache;
    returns the ``lintSweep`` bench block (``coldS``/``warmS``/
    ``files``/``identical``, ``ok`` only when both sweeps ran clean of
    engine errors AND the warm findings were byte-identical). The
    engine is stdlib-only, so this runs in-process on any box."""
    import tempfile

    from predictionio_tpu.lint import lint_paths, render_json

    package_dir = os.path.join(_REPO_ROOT, "predictionio_tpu")
    with tempfile.TemporaryDirectory() as tmp:
        cache = os.path.join(tmp, "lint_cache.json")
        t0 = time.perf_counter()
        cold = lint_paths([package_dir], cache_path=cache)
        cold_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        warm = lint_paths([package_dir], cache_path=cache)
        warm_s = time.perf_counter() - t0
    identical = render_json(cold) == render_json(warm)
    return {
        "coldS": cold_s,
        "warmS": warm_s,
        "files": cold.files,
        "findings": len(cold.findings),
        "identical": identical,
        "ok": bool(
            not cold.errors and not warm.errors and identical
        ),
    }


def run_quant_serve(user_factors, item_factors, k: int = 10) -> dict:
    """Quantize THIS round's trained item table and measure what the
    ledger wants to trend: the int8 serving footprint vs its f32 twin
    (serve_table_bytes, GATED — bytes are deterministic, so any
    compression regression trips the band) and the exactness-gate
    match rate (quant_topk_match_rate, trend-only — the id-identity
    margin the serve lever needs before it can turn on for this
    recipe). Uses the ungated constructor + gate probe directly: the
    bench MEASURES the gate margin, it does not refuse on it."""
    import jax

    from predictionio_tpu.quant import (
        default_probe_idx,
        estimate_table_bytes,
        quantize_table,
        top_k_quantized,
        topk_match_gate,
    )

    user_factors = np.asarray(user_factors, dtype=np.float32)
    item_factors = np.asarray(item_factors, dtype=np.float32)
    qtable = quantize_table(item_factors)
    probe = default_probe_idx(user_factors.shape[0])
    match_rate = topk_match_gate(
        user_factors, item_factors, qtable, probe, k
    )
    # quantized top-k wall over the probe batch (steady state: second
    # call, first one pays the jit)
    top_k_quantized(user_factors, qtable, probe, k)
    t0 = time.perf_counter()
    jax.block_until_ready(
        top_k_quantized(user_factors, qtable, probe, k)
    )
    topk_s = time.perf_counter() - t0
    return {
        "ok": True,
        "tableDtype": qtable.dtype,
        "tableBytes": qtable.table_bytes,
        "f32Bytes": qtable.f32_bytes,
        "ratio": round(qtable.compression_ratio, 3),
        "estTableBytes": estimate_table_bytes(
            qtable.n_rows, qtable.rank, qtable.dtype
        ),
        "matchRate": round(match_rate, 4),
        "probes": int(probe.size),
        "k": int(min(k, item_factors.shape[0])),
        "topkS": round(topk_s, 4),
        "rank": qtable.rank,
        "nItems": qtable.n_rows,
    }


def run_sharded_train(shard_counts=(1, 2, 4), timeout_s: float = 600.0) -> dict:
    """Train the small deterministic sharded recipe at each shard count
    in a forced-virtual-device subprocess; returns the ``shardedTrain``
    bench block (``counts`` keyed by N, ``ok`` only when every count
    measured)."""
    from predictionio_tpu.utils.platform import force_cpu_env

    counts: dict = {}
    ok = True
    for n in shard_counts:
        env = force_cpu_env(n_devices=n)
        try:
            proc = subprocess.run(
                [
                    sys.executable,
                    "-c",
                    _SHARDED_SNIPPET.format(repo=_REPO_ROOT, shards=n),
                ],
                env=env,
                cwd=_REPO_ROOT,
                timeout=timeout_s,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
            )
        except subprocess.TimeoutExpired:
            counts[str(n)] = {"error": f"timed out after {timeout_s:.0f}s"}
            ok = False
            continue
        line = next(
            (
                ln[len("SHARDED_JSON "):]
                for ln in proc.stdout.decode("utf-8", "replace").splitlines()
                if ln.startswith("SHARDED_JSON ")
            ),
            None,
        )
        if proc.returncode != 0 or line is None:
            tail = proc.stderr.decode("utf-8", "replace").strip().splitlines()
            counts[str(n)] = {
                "error": (
                    f"rc={proc.returncode}: "
                    f"{tail[-1] if tail else '(no stderr)'}"
                )
            }
            ok = False
            continue
        counts[str(n)] = json.loads(line)
        print(
            f"bench shardedTrain: shards={n} "
            f"train {counts[str(n)]['trainS']}s "
            f"rmse {counts[str(n)]['rmse']}",
            file=sys.stderr,
        )
    return {"counts": counts, "ok": ok}


def run_bench(scale: float, iterations: int, fallback: str) -> int:
    import jax

    from predictionio_tpu.obs.profile import default_telemetry
    from predictionio_tpu.ops.als import (
        ALSConfig,
        als_train,
        bucketize,
        rmse,
        stage,
    )

    jit_before = default_telemetry().snapshot()

    users, items, ratings, n_users, n_items = synth_ml20m(scale)
    nnz = len(ratings)

    # holdout split for the quality gate
    test = holdout_mask(nnz)
    tr = ~test

    solve_mode = os.environ.get("BENCH_SOLVE_MODE", "auto")
    gather_dtype = os.environ.get("BENCH_GATHER_DTYPE", "f32")
    # fast paths default ON (round 12): sort-gather is host-side and
    # proven equivalence-safe (ROUND7_NOTES), so it rides every run
    # unless BENCH_SORT_GATHER=0 opts out; fused_gather tri-states —
    # unset resolves WITH the solver (on exactly when solve_mode
    # resolves to pallas), "0"/"1" force it
    sort_gather = os.environ.get("BENCH_SORT_GATHER", "1") == "1"
    fused_env = os.environ.get("BENCH_FUSED_GATHER")
    fused_gather = None if fused_env is None else fused_env == "1"
    if fallback and fused_gather is not False:
        # the fused kernel's per-row DMA loops run in interpret mode off
        # TPU — hours at any real scale; force it off on fallback for
        # ANY non-explicit value: the unset default would resolve ON
        # under BENCH_SOLVE_MODE=pallas (a supported off-TPU A/B leg),
        # not just under an explicit BENCH_FUSED_GATHER=1
        if fused_gather or solve_mode == "pallas":
            print(
                "bench: BENCH_FUSED_GATHER ignored on CPU fallback",
                file=sys.stderr,
            )
        fused_gather = False
    if fused_gather and solve_mode == "auto":
        solve_mode = "pallas"  # explicit fused build forces the solver
    cfg = ALSConfig(
        rank=50, iterations=iterations, lambda_=0.05, seed=0,
        solve_mode=solve_mode, gather_dtype=gather_dtype,
        fused_gather=fused_gather,
    )
    if sort_gather:
        from predictionio_tpu.ops.als import sort_bucket_indices
    _maybe_sort = sort_bucket_indices if sort_gather else (lambda b: b)

    # Warm the compilation cache with the REAL bucket shapes (jit keys on
    # shapes: a smaller sliver would leave the timed run paying XLA compile).
    # 2 warm-up iterations: the first executed iteration runs as two
    # half-programs (staging overlap), later ones as the fused program —
    # both must be compiled before the timed section; the timed section
    # then measures steady-state bucketize + staging + training.
    warm_cfg = ALSConfig(
        rank=cfg.rank, iterations=2, lambda_=cfg.lambda_, seed=cfg.seed,
        solve_mode=solve_mode, gather_dtype=gather_dtype,
        fused_gather=fused_gather,
    )
    wu = stage(_maybe_sort(bucketize(users[tr], items[tr], ratings[tr],
                                     n_users, n_items, pad_to_blocks=True)))
    wi = stage(_maybe_sort(bucketize(items[tr], users[tr], ratings[tr],
                                     n_items, n_users, pad_to_blocks=True)))
    np.asarray(als_train(wu, wi, warm_cfg).user_factors)
    del wu, wi

    profile: dict = {}
    t0 = time.time()
    t_b = time.monotonic()
    # phase timers: bucketize is host CPU (threaded C++ scatter), stage is
    # view-reshape + async device_put issue — separating them tells the
    # hardware run WHICH host-side cost dominates (the transfer wait
    # itself lands in iteration_s[0], excluded from steady-state)
    bu = _maybe_sort(bucketize(users[tr], items[tr], ratings[tr], n_users,
                               n_items, pad_to_blocks=True))
    t_s1 = time.monotonic()
    by_user = stage(bu)  # async puts: item bucketize below overlaps them
    t_s2 = time.monotonic()
    bi = _maybe_sort(bucketize(items[tr], users[tr], ratings[tr], n_items,
                               n_users, pad_to_blocks=True))
    t_s3 = time.monotonic()
    by_item = stage(bi)
    t_end = time.monotonic()
    bucketize_stage_s = t_end - t_b
    phase_s = {
        "bucketize_user": round(t_s1 - t_b, 3),
        "stage_user": round(t_s2 - t_s1, 3),
        "bucketize_item": round(t_s3 - t_s2, 3),
        "stage_item": round(t_end - t_s3, 3),
    }
    factors = als_train(by_user, by_item, cfg, profile=profile)
    # force full materialization: block_until_ready alone does not
    # synchronize through some remote-device relays
    np.asarray(factors.user_factors)
    np.asarray(factors.item_factors)
    train_s = time.time() - t0

    holdout = rmse(factors, users[test], items[test], ratings[test])

    iter_s = profile.get("iteration_s", [])
    flops = profile.get("flops_per_iteration", 0.0)
    hbm_bytes = profile.get("hbm_bytes_per_iteration", 0.0)
    # steady state: the first iteration absorbs the async staging transfer
    steady = iter_s[1:] if len(iter_s) > 1 else iter_s
    avg_iter = float(np.mean(steady)) if steady else 0.0
    from predictionio_tpu.obs.profile import roofline

    rf = roofline(flops, hbm_bytes, avg_iter)
    tflops_per_s = rf["tflops_per_s"]
    mfu = rf["mfu"]
    hbm_util = rf["hbm_util"]

    record = {
        "metric": "ml20m_als_rank50_train_s",
        "value": round(train_s, 3),
        "unit": "s",
        "vs_baseline": round(_BASELINE_S / train_s, 2),
        "holdout_rmse": round(holdout, 4),
        "nnz": int(tr.sum()),
        "scale": scale,
        "iterations": iterations,
        "device": str(jax.devices()[0]),
        "bucketize_stage_s": round(bucketize_stage_s, 3),
        "bucketize_stage_phases_s": phase_s,
        "iteration_s": [round(s, 4) for s in iter_s],
        "est_tflops_per_s": round(tflops_per_s, 2),
        "est_mfu_f32_v5e": round(mfu, 4),
        "est_hbm_gb_per_iter": round(hbm_bytes / 1e9, 2),
        "est_hbm_util_v5e": round(hbm_util, 3),
        "bucket_shapes": profile.get("bucket_shapes"),
        # RESOLVED lever flags from the train run itself (tri-state
        # defaults resolve inside als_train) — the ledger must record
        # what executed, not what was requested. sort_gather is resolved
        # HERE: the bench sorts host-side before staging, so the config
        # flag the train run saw is moot.
        "solve_mode": profile.get("solve_mode", solve_mode),
        "gather_dtype": profile.get("gather_dtype", gather_dtype),
        "sort_gather": sort_gather,
        "fused_gather": profile.get("fused_gather", bool(fused_gather)),
        # compile/retrace accounting for THIS process (warmup included):
        # a bench round whose timed section quietly recompiled is not
        # measuring steady state, and this field says so
        "jit": default_telemetry().delta_since(jit_before),
    }
    if fallback:
        # A fallback run measures a shrunken workload on the wrong device:
        # the headline comparison must not claim the baseline was beaten,
        # and v5e-relative efficiency ratios computed from a CPU run are
        # noise — drop them rather than let a dashboard chart them.
        record["fallback"] = fallback
        record["vs_baseline"] = 0.0
        del record["est_mfu_f32_v5e"]
        del record["est_hbm_util_v5e"]
        _attach_last_good(record)
    # quality gate: noise floor is 0.5; MLlib-parity training lands near it.
    if holdout > 0.62:
        record["vs_baseline"] = 0.0
        record["error"] = f"holdout RMSE {holdout:.4f} failed quality gate"
        _append_ledger(record)
        print(json.dumps(record))
        return 1
    # bf16 precision gate (docs/performance.md#levers): every round
    # trains a reduced-precision twin on the SAME staged (and sorted)
    # buckets — only gather_dtype differs — and bounds its holdout-RMSE
    # drift vs the f32 run. The gate keeps the bf16 lever adoptable:
    # the bench fails LOUDLY the round bf16 precision drifts, instead
    # of a dashboard noticing a quality slide later. Default bound
    # 0.01 absolute RMSE: measured drift at CPU-fallback scale is
    # <1e-4 (round 12 — two orders of magnitude of headroom; the λ·n_u
    # ridge keeps the solves stable), while a real precision bug (e.g.
    # bf16 accumulation sneaking into the Gramian) shifts holdout RMSE
    # by >0.05. BENCH_BF16_GATE=0 opts out; BENCH_BF16_RMSE_GATE
    # overrides the bound.
    if os.environ.get("BENCH_BF16_GATE", "1") != "0":
        import dataclasses as _dc

        gate = float(os.environ.get("BENCH_BF16_RMSE_GATE", "0.01"))
        twin_dtype = "bf16" if record["gather_dtype"] == "f32" else "f32"
        # the twin runs the EINSUM build: gramian_fused upcasts bf16
        # tables to f32 at kernel entry (Mosaic cannot DMA half-width
        # sublanes), so a fused-path twin would measure f32 math under
        # a bf16 label — the einsum path is where the bf16 lever
        # actually feeds the MXU at reduced precision, and the only
        # path where it buys HBM bytes (estimate_iteration_hbm_bytes)
        twin_cfg = _dc.replace(
            cfg, gather_dtype=twin_dtype, fused_gather=False
        )
        twin = als_train(by_user, by_item, twin_cfg)
        twin_rmse = rmse(twin, users[test], items[test], ratings[test])
        if record["gather_dtype"] == "bf16" and record["fused_gather"]:
            # a bf16 MAIN run that resolved the fused build rode the
            # upcasting kernel — its holdout is f32 math under a bf16
            # label, not a bf16 measurement; train the einsum-built
            # bf16 leg explicitly so the gate compares real reduced-
            # precision math against the f32 twin
            bf16_leg = als_train(
                by_user, by_item,
                _dc.replace(cfg, gather_dtype="bf16", fused_gather=False),
            )
            bf16_rmse = rmse(
                bf16_leg, users[test], items[test], ratings[test]
            )
            f32_rmse = twin_rmse
        else:
            f32_rmse = (
                holdout if record["gather_dtype"] == "f32" else twin_rmse
            )
            bf16_rmse = twin_rmse if twin_dtype == "bf16" else holdout
        margin = abs(bf16_rmse - f32_rmse)
        record["bf16_gate"] = {
            "rmse_f32": round(f32_rmse, 4),
            "rmse_bf16": round(bf16_rmse, 4),
            "margin": round(margin, 4),
            "gate": gate,
            "ok": margin <= gate,
        }
        if margin > gate:
            record["vs_baseline"] = 0.0
            record["error"] = (
                f"bf16 gather RMSE drifted {margin:.4f} vs f32 "
                f"(gate {gate})"
            )
            _append_ledger(record)
            print(json.dumps(record))
            return 1
    if (
        not fallback
        and scale >= 1.0
        and jax.devices()[0].platform == "tpu"  # stable API, not str repr
    ):
        _save_last_good(record)
    # Closed-loop freshness (docs/continuous.md): the tiny in-process
    # feedback-stream scenario gives every BENCH round a measured
    # event-ingest → model-live number next to the train time. Opt out
    # with BENCH_FEEDBACK_STREAM=0; a failure here never fails the bench.
    if os.environ.get("BENCH_FEEDBACK_STREAM") != "0":
        try:
            from predictionio_tpu.tools.loadgen import run_feedback_stream

            fs = run_feedback_stream(total_events=40, burst=20)
            record["continuousFreshness"] = {
                "freshnessS": fs.get("freshnessS"),
                "events": fs.get("events"),
                "cycles": fs.get("cycles"),
                "mode": (fs.get("lastCycle") or {}).get("mode"),
                "ok": fs.get("ok"),
            }
            # quality block (docs/observability.md#quality): the drill's
            # monitor measured score PSI vs its pinned train-time
            # baseline and the feedback join's hit-rate — every BENCH
            # round gets a quality trajectory point next to train time
            quality = fs.get("quality")
            if isinstance(quality, dict):
                record["quality"] = dict(
                    quality, ok=bool(fs.get("ok") and quality.get("ok"))
                )
        except Exception as exc:  # the headline metric must still report
            record["continuousFreshness"] = {"error": str(exc)}
    # Serving-fleet trajectory (docs/fleet.md): a small in-process
    # router + replicas drive gives every BENCH round a servedQPS /
    # servedP99Ms number next to train time — the serving-scale metric
    # the ROADMAP asked for. Opt out with BENCH_FLEET=0; a failure here
    # never fails the bench.
    if os.environ.get("BENCH_FLEET") != "0":
        try:
            from predictionio_tpu.tools.loadgen import run_fleet_chaos

            fleet = run_fleet_chaos(
                replicas=2, kill_backend_at=None, queries=96
            )
            record["servingFleet"] = {
                "replicas": fleet.get("replicas"),
                "sharded": fleet.get("sharded"),
                "servedQPS": fleet.get("servedQPS"),
                "servedP50Ms": fleet.get("servedP50Ms"),
                "servedP99Ms": fleet.get("servedP99Ms"),
                "ok": fleet.get("ok"),
            }
        except Exception as exc:  # the headline metric must still report
            record["servingFleet"] = {"error": str(exc)}
    # Serve-from-memory (docs/fleet.md#cache): the cached-hot-set drive
    # gives every BENCH round the router cache's step-function QPS win
    # next to the uncached servedQPS — with the byte-identity and
    # zero-stale-after-rollout proofs hard-gating the block's ok. Opt
    # out with BENCH_CACHE=0; a failure here never fails the bench.
    if os.environ.get("BENCH_CACHE") != "0":
        try:
            from predictionio_tpu.tools.loadgen import run_cached_hot_set

            cached = run_cached_hot_set(queries=160)
            record["cachedFleet"] = {
                "replicas": cached.get("replicas"),
                "cachedQPS": cached.get("cachedQPS"),
                "uncachedQPS": cached.get("uncachedQPS"),
                "speedup": cached.get("speedup"),
                "hitRate": cached.get("hitRate"),
                "cachedP50Ms": cached.get("cachedP50Ms"),
                "cachedP99Ms": cached.get("cachedP99Ms"),
                "byteIdentical": cached.get("byteIdentical"),
                "staleAfterRollout": cached.get("staleAfterRollout"),
                "ok": cached.get("ok"),
            }
        except Exception as exc:
            record["cachedFleet"] = {"error": str(exc)}
    # Shared cache tier (docs/fleet.md#shared-cache-tier): the
    # kill-the-tier drill gives every BENCH round the fleet-wide hit
    # rate and the hedged healthy-phase p99 — with the zero-stale,
    # byte-identity, recorded-degrade and recovery proofs hard-gating
    # the block's ok. Opt out with BENCH_SHAREDCACHE=0; a failure here
    # never fails the bench.
    if os.environ.get("BENCH_SHAREDCACHE") != "0":
        try:
            from predictionio_tpu.tools.loadgen import run_shared_cache_drill

            shared = run_shared_cache_drill(queries=96)
            record["sharedCache"] = {
                "healthyQPS": shared.get("healthyQPS"),
                "hedgedP99Ms": shared.get("hedgedP99Ms"),
                "sharedHitRate": shared.get("sharedHitRate"),
                "degradesRecorded": shared.get("degradesRecorded"),
                "byteIdenticalAfterKill": shared.get(
                    "byteIdenticalAfterKill"
                ),
                "staleAfterRollout": shared.get("staleAfterRollout"),
                "clientFailures": shared.get("clientFailures"),
                "warmedEntries": shared.get("warmedEntries"),
                "ok": shared.get("ok"),
            }
        except Exception as exc:
            record["sharedCache"] = {"error": str(exc)}
    # Alert hygiene (docs/slo.md): the in-process brownout drill gives
    # every BENCH round a fired/cleared/false-positive count, so alert
    # noisiness is tracked across rounds like perf and quality already
    # are. Opt out with BENCH_BROWNOUT=0; a failure never fails the
    # bench.
    if os.environ.get("BENCH_BROWNOUT") != "0":
        try:
            from predictionio_tpu.tools.loadgen import run_brownout

            brownout = run_brownout()
            per_objective = brownout.get("alerts") or {}
            record["alerts"] = {
                "fired": sum(
                    a.get("fired", 0) for a in per_objective.values()
                ),
                "cleared": sum(
                    a.get("cleared", 0) for a in per_objective.values()
                ),
                "falsePositives": brownout.get("falsePositives"),
                "stallsDetected": brownout.get("stallsDetected"),
                "ok": brownout.get("ok"),
            }
        except Exception as exc:
            record["alerts"] = {"error": str(exc)}
    # Ingest scaling (docs/storage.md#partitioning): acked-writes/second
    # at 1, 2 and 4 event-store partitions — subprocess primaries with
    # the strict fsync-per-ack oplog, concurrent writer processes, best
    # of 2 rounds per N on this (possibly contended) box. Scaling tops
    # out at the box's core count: a 2-core CI box shows the 1→2 win
    # and a 4-way plateau; real silicon shows the full fan. Opt out
    # with BENCH_INGEST_SCALING=0; a failure never fails the bench.
    if os.environ.get("BENCH_INGEST_SCALING") != "0":
        try:
            from predictionio_tpu.tools.loadgen import run_ingest_scaling

            scaling = run_ingest_scaling()
            record["ingestScaling"] = {
                "counts": scaling.get("counts"),
                "writers": scaling.get("writers"),
                "rounds": scaling.get("rounds"),
                "ok": scaling.get("ok"),
            }
        except Exception as exc:
            record["ingestScaling"] = {"error": str(exc)}
    # Live-migration drill (docs/storage.md#live-migration): the full
    # N=2 -> M=3 chaos choreography — dual-write, coordinator kill,
    # new-primary kill mid-backfill, watermark, flip, cursor handoff.
    # Wall time and the dual-write ingest overhead ride the ledger
    # trend-only, keyed by "N->M" as `scale` so different layout moves
    # never compare. Opt out with BENCH_MIGRATE=0; a failure never
    # fails the bench.
    if os.environ.get("BENCH_MIGRATE") != "0":
        try:
            from predictionio_tpu.tools.loadgen import run_migrate_drill

            drill = run_migrate_drill()
            record["migrationDrill"] = {
                k: drill.get(k)
                for k in (
                    "ok", "oldPartitions", "newPartitions", "opsPerPhase",
                    "wallS", "dualWriteOverhead", "lostAckedWrites",
                    "duplicateFolds",
                )
            }
        except Exception as exc:
            record["migrationDrill"] = {"error": str(exc)}
    # Sharded training (docs/distributed_training.md): the ALX-style
    # shard_map trainer at 1/2/4 shards on forced virtual CPU devices —
    # subprocesses, because the device count must be pinned before jax
    # imports. Each shard count's wall clock rides the ledger keyed by N
    # as `scale` (train_sharded_s), so counts never gate each other.
    # Opt out with BENCH_SHARDED=0; a failure never fails the bench.
    if os.environ.get("BENCH_SHARDED") != "0":
        try:
            record["shardedTrain"] = run_sharded_train()
        except Exception as exc:
            record["shardedTrain"] = {"error": str(exc)}
    # Preemption drill (docs/checkpoint.md#preemption-drill): a
    # checkpointed sharded run SIGKILLed mid-train resumes at a
    # DIFFERENT shard count and lands within tolerance of the
    # uninterrupted twin; the checkpointing overhead ratio rides the
    # ledger trend-only (train_ckpt_overhead_ratio). Opt out with
    # BENCH_CKPT=0; a failure never fails the bench.
    if os.environ.get("BENCH_CKPT") != "0":
        try:
            record["ckptResume"] = run_ckpt_resume()
        except Exception as exc:
            record["ckptResume"] = {"error": str(exc)}
    # Lint-sweep wall clock (docs/lint.md#cache): cold vs warm over the
    # package with a throwaway cache, in-process (the linter is stdlib-
    # only — no device, no subprocess needed). Rides the ledger trend-
    # only as lint_wall_s; `identical` pins the cache contract where a
    # regression would show in history. Opt out with BENCH_LINT=0; a
    # failure never fails the bench.
    if os.environ.get("BENCH_LINT") != "0":
        try:
            record["lintSweep"] = run_lint_sweep()
        except Exception as exc:
            record["lintSweep"] = {"error": str(exc)}
    # Quantized serving tables (docs/quantization.md): quantize this
    # round's trained item table, measure the int8 footprint vs the f32
    # twin (serve_table_bytes, GATED) and the exactness-gate top-k
    # match rate (trend-only). Opt out with BENCH_QUANT=0; a failure
    # never fails the bench.
    if os.environ.get("BENCH_QUANT") != "0":
        try:
            record["quantServe"] = run_quant_serve(
                np.asarray(factors.user_factors),
                np.asarray(factors.item_factors),
            )
        except Exception as exc:
            record["quantServe"] = {"error": str(exc)}
    _append_ledger(record)
    print(json.dumps(record))
    return 0


#: Last successful full-scale TPU measurement, persisted so a run that has
#: to fall back (the accelerator tunnel wedges for hours at a time) can
#: still report the most recent REAL number — clearly labeled as prior
#: evidence, never merged into the fallback run's own fields.
_LAST_GOOD_PATH = os.path.join(_REPO_ROOT, "BENCH_LAST_GOOD.json")


def _save_last_good(record: dict) -> None:
    try:
        payload = dict(record)
        payload["recorded_at_unix"] = time.time()
        tmp = _LAST_GOOD_PATH + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(payload, fh)
        os.replace(tmp, _LAST_GOOD_PATH)
    except Exception:
        pass  # evidence caching must never fail a real run


def _attach_last_good(record: dict) -> None:
    try:
        with open(_LAST_GOOD_PATH) as fh:
            last = json.load(fh)
    except (OSError, ValueError):
        return
    record["last_known_tpu"] = {
        "value": last.get("value"),
        "scale": last.get("scale"),
        "nnz": last.get("nnz"),
        "vs_baseline_then": last.get("vs_baseline"),
        "holdout_rmse": last.get("holdout_rmse"),
        "device": last.get("device"),
        "solve_mode": last.get("solve_mode"),
        "recorded_at_unix": last.get("recorded_at_unix"),
        "note": (
            "most recent successful full-scale TPU run, attached because "
            "THIS run fell back to CPU (accelerator unreachable); not a "
            "measurement of the current code state"
        ),
    }


def main() -> int:
    scale = float(os.environ.get("BENCH_SCALE", "1.0"))
    iterations = int(os.environ.get("BENCH_ITERATIONS", "10"))
    fallback = os.environ.get("_PIO_BENCH_CHILD", "")

    # persistent compilation cache: the revalidation queue runs this
    # script ~8x in fresh subprocesses; without it each leg re-pays the
    # full XLA compile inside the scarce hardware window
    sys.path.insert(0, _REPO_ROOT)
    from predictionio_tpu.utils.jax_cache import enable_compilation_cache

    cache_dir = enable_compilation_cache()
    if cache_dir:
        print(f"bench: persistent compilation cache at {cache_dir}",
              file=sys.stderr)

    if not fallback:
        # Bring-up: probe the configured backend before the real workload.
        # A fast failure gets one retry (transient tunnel hiccup); a
        # timeout goes straight to fallback — the kill that ended the
        # probe can itself wedge the tunnel, so re-probing is futile.
        status = probe_device()
        if status == "failed":
            time.sleep(10.0)
            status = probe_device()
        if status != "ok":
            return _fallback_to_cpu(scale)

    try:
        return run_bench(scale, iterations, fallback)
    except Exception as exc:  # never leave the driver a bare traceback
        import traceback

        traceback.print_exc(file=sys.stderr)
        if not fallback:
            return _fallback_to_cpu(scale)
        failed = {
            "metric": "ml20m_als_rank50_train_s",
            "value": -1.0,
            "unit": "s",
            "vs_baseline": 0.0,
            "error": f"{type(exc).__name__}: {exc}",
        }
        _append_ledger(failed)
        print(json.dumps(failed))
        return 1


if __name__ == "__main__":
    sys.exit(main())
