"""Benchmark: ALS rank-50 on a MovieLens-20M-shaped workload.

Prints ONE JSON line:
``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}``

The north-star target (BASELINE.json) is MLlib ALS rank-50 on MovieLens-20M
training in < 60 s on a v5e-8 at RMSE parity. This bench runs on whatever
device is available (the driver provides one real TPU chip): it synthesizes a
20M-rating matrix with ML-20M's shape (138k users x 27k items, power-law
degrees, low-rank ground truth + noise), trains rank-50 for 10 iterations —
wall-clock includes bucketization, host→device staging and training — and
verifies holdout RMSE approaches the noise floor (quality gate; the run
fails loudly rather than reporting a fast-but-wrong number).

``vs_baseline`` = 60 s / measured train seconds (>1 beats the 8-chip target
even on this single chip).

Env knobs: ``BENCH_SCALE`` (default 1.0) scales the rating count for quick
smoke runs; ``BENCH_ITERATIONS`` (default 10).
"""

import json
import os
import sys
import time

import numpy as np


def synth_ml20m(scale: float, seed: int = 0):
    """ML-20M-shaped synthetic ratings: power-law user/item degrees, rank-8
    ground truth, sd-0.5 observation noise."""
    rng = np.random.default_rng(seed)
    n_users = max(64, int(138_000 * min(1.0, scale)))
    n_items = max(32, int(27_000 * min(1.0, scale)))
    nnz = int(20_000_000 * scale)

    # power-law sampling via Zipf-ish inverse-rank weights
    u_w = 1.0 / np.arange(1, n_users + 1) ** 0.8
    i_w = 1.0 / np.arange(1, n_items + 1) ** 0.9
    users = rng.choice(n_users, size=nnz, p=u_w / u_w.sum()).astype(np.int64)
    items = rng.choice(n_items, size=nnz, p=i_w / i_w.sum()).astype(np.int64)

    gt_rank = 8
    x = rng.normal(size=(n_users, gt_rank)) / np.sqrt(gt_rank)
    y = rng.normal(size=(n_items, gt_rank)) / np.sqrt(gt_rank)
    ratings = (
        (x[users] * y[items]).sum(axis=1) + 3.5 + rng.normal(0, 0.5, nnz)
    ).astype(np.float32)
    return users, items, ratings, n_users, n_items


def main() -> int:
    scale = float(os.environ.get("BENCH_SCALE", "1.0"))
    iterations = int(os.environ.get("BENCH_ITERATIONS", "10"))

    import jax

    from predictionio_tpu.ops.als import (
        ALSConfig,
        als_train,
        bucketize,
        rmse,
        stage,
    )

    users, items, ratings, n_users, n_items = synth_ml20m(scale)
    nnz = len(ratings)

    # holdout split for the quality gate
    rng = np.random.default_rng(1)
    test = rng.random(nnz) < 0.05
    tr = ~test

    cfg = ALSConfig(rank=50, iterations=iterations, lambda_=0.05, seed=0)

    # Warm the compilation cache with the REAL bucket shapes (jit keys on
    # shapes: a smaller sliver would leave the timed run paying XLA compile).
    # One warm-up iteration compiles every bucket kernel; the timed section
    # then measures steady-state bucketize + staging + training.
    warm_cfg = ALSConfig(
        rank=cfg.rank, iterations=1, lambda_=cfg.lambda_, seed=cfg.seed
    )
    wu = stage(bucketize(users[tr], items[tr], ratings[tr], n_users, n_items))
    wi = stage(bucketize(items[tr], users[tr], ratings[tr], n_items, n_users))
    np.asarray(als_train(wu, wi, warm_cfg).user_factors)
    del wu, wi

    t0 = time.time()
    by_user = stage(
        bucketize(users[tr], items[tr], ratings[tr], n_users, n_items)
    )
    by_item = stage(
        bucketize(items[tr], users[tr], ratings[tr], n_items, n_users)
    )
    factors = als_train(by_user, by_item, cfg)
    # force full materialization: block_until_ready alone does not
    # synchronize through some remote-device relays
    np.asarray(factors.user_factors)
    np.asarray(factors.item_factors)
    train_s = time.time() - t0

    holdout = rmse(factors, users[test], items[test], ratings[test])
    # quality gate: noise floor is 0.5; MLlib-parity training lands near it.
    if holdout > 0.62:
        print(
            json.dumps(
                {
                    "metric": "ml20m_als_rank50_train_s",
                    "value": round(train_s, 3),
                    "unit": "s",
                    "vs_baseline": 0.0,
                    "error": f"holdout RMSE {holdout:.4f} failed quality gate",
                }
            )
        )
        return 1

    print(
        json.dumps(
            {
                "metric": "ml20m_als_rank50_train_s",
                "value": round(train_s, 3),
                "unit": "s",
                "vs_baseline": round(60.0 / train_s, 2),
                "holdout_rmse": round(holdout, 4),
                "nnz": int(tr.sum()),
                "scale": scale,
                "device": str(jax.devices()[0]),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
