"""$set-property events for the classification quickstart.

Three feature attributes determine the plan label by a simple rule the
classifier should recover: plan = 1 when attr0 + attr1 > attr2 else 0.
"""
import json
import sys

import numpy as np


def main() -> int:
    n_users = int(sys.argv[1]) if len(sys.argv) > 1 else 300
    rng = np.random.default_rng(0)
    for u in range(n_users):
        a0, a1, a2 = (int(rng.integers(0, 5)) for _ in range(3))
        print(json.dumps({
            "event": "$set",
            "entityType": "user", "entityId": f"u{u}",
            "properties": {
                "attr0": a0, "attr1": a1, "attr2": a2,
                "plan": 1 if a0 + a1 > a2 else 0,
            },
        }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
