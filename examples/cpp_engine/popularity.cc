// Item-popularity recommender authored in C++.
//
// The worked second-language engine example (the counterpart of the
// reference's examples/experimental/java-local-tutorial engines built on
// the controller/java shim): this program implements the Algorithm role
// of a DASE engine over the framework's foreign-component protocol
// (line-delimited JSON on stdin/stdout; see
// predictionio_tpu/controller/foreign.py). The Python side supplies the
// DataSource/Preparator (event-store scan) and plugs this binary in via
// ForeignAlgorithm — mix-and-match across languages, exactly like the
// reference mixes Java components into Scala engines.
//
// train:   data = {"ratings": [["u1", "i3", 4.0], ...]}
//          model = {"items": ["i3", ...], "scores": [12.5, ...]}  (sorted)
// predict: query = {"user": "...", "num": N}
//          result = {"itemScores": [{"item": "...", "score": S}, ...]}
//
// Popularity = sum of rating values per item; the per-params "min_count"
// knob drops long-tail items. Build:
//   g++ -O2 -std=c++17 -I ../../sdk/cpp -o popularity popularity.cc

#include <algorithm>
#include <unordered_map>

#include "pio_engine.hpp"

using pio::Json;

int main() {
  pio::Handlers h;

  h.train = [](const Json& params, const Json& data) -> Json {
    int64_t min_count = params["min_count"].is_null()
                            ? 1
                            : params["min_count"].as_int();
    std::unordered_map<std::string, double> score;
    std::unordered_map<std::string, int64_t> count;
    for (const Json& row : data["ratings"].items()) {
      const std::string& item = row.items()[1].as_string();
      score[item] += row.items()[2].as_number();
      count[item] += 1;
    }
    std::vector<std::pair<std::string, double>> ranked;
    for (const auto& kv : score) {
      if (count[kv.first] >= min_count) ranked.push_back(kv);
    }
    std::sort(ranked.begin(), ranked.end(), [](const auto& a, const auto& b) {
      return a.second != b.second ? a.second > b.second : a.first < b.first;
    });
    Json items = Json::array(), scores = Json::array();
    for (const auto& kv : ranked) {
      items.push(Json(kv.first));
      scores.push(Json(kv.second));
    }
    Json model = Json::object();
    model.set("items", items);
    model.set("scores", scores);
    return model;
  };

  h.predict = [](const Json& model, const Json& query) -> Json {
    int64_t num = query["num"].is_null() ? 10 : query["num"].as_int();
    if (num < 0) throw std::runtime_error("num must be >= 0");
    const auto& items = model["items"].items();
    const auto& scores = model["scores"].items();
    Json out = Json::array();
    for (size_t i = 0; i < items.size() && (int64_t)i < num; i++) {
      Json row = Json::object();
      row.set("item", items[i]);
      row.set("score", scores[i]);
      out.push(row);
    }
    Json result = Json::object();
    result.set("itemScores", out);
    return result;
  };

  return pio::engine_main(h);
}
