"""Events for the ecommerce quickstart: $set users/items + rate events
(two-cohort structure: even users love even items)."""
import json
import sys

import numpy as np


def main() -> int:
    n_users = int(sys.argv[1]) if len(sys.argv) > 1 else 60
    n_items = int(sys.argv[2]) if len(sys.argv) > 2 else 24
    rng = np.random.default_rng(0)
    for u in range(n_users):
        print(json.dumps({"event": "$set", "entityType": "user",
                          "entityId": f"u{u}", "properties": {}}))
    for i in range(n_items):
        print(json.dumps({"event": "$set", "entityType": "item",
                          "entityId": f"i{i}", "properties": {}}))
    for u in range(n_users):
        for i in range(n_items):
            if rng.random() < 0.6:
                aligned = (u % 2) == (i % 2)
                print(json.dumps({
                    "event": "rate",
                    "entityType": "user", "entityId": f"u{u}",
                    "targetEntityType": "item", "targetEntityId": f"i{i}",
                    "properties": {"rating": 5.0 if aligned else 1.0},
                }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
