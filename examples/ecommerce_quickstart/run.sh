#!/usr/bin/env bash
# E-commerce lifecycle with LIVE serving-time filters: after deployment,
# new buy events and an $set unavailableItems constraint change results
# WITHOUT retraining -- the algorithm reads them from the event store at
# query time under a 200 ms budget.
set -euo pipefail
HERE="$(cd "$(dirname "${BASH_SOURCE[0]}")" && pwd)"
PIO="${HERE}/../../bin/pio"
WORK="${1:-$(mktemp -d)}"
mkdir -p "$WORK"
WORK="$(cd "$WORK" && pwd)"
PORT="${QUICKSTART_PORT:-8196}"
export PIO_FS_BASEDIR="${PIO_FS_BASEDIR:-$WORK/storage}"

echo "== 1. app + events"
APP_NAME="ecomdemo-$(date +%s)-$$"
"$PIO" app new "$APP_NAME" | tee "$WORK/app.json"
APP_ID=$(python3 -c "import json,sys; print(json.load(open(sys.argv[1]))['id'])" "$WORK/app.json")
python3 "$HERE/gen_events.py" > "$WORK/events.jsonl"
"$PIO" import --appid "$APP_ID" --input "$WORK/events.jsonl"

echo "== 2. engine + train"
if [ ! -f "$WORK/engine/engine.json" ]; then
  "$PIO" template get ecommerce "$WORK/engine"
fi
cd "$WORK/engine"
python3 - "$APP_ID" <<'PY'
import json, sys
v = json.load(open("engine.json"))
app_id = int(sys.argv[1])
v["datasource"]["params"]["app_id"] = app_id
for algo in v["algorithms"]:
    algo["params"]["app_id"] = app_id  # live serving-time reads
json.dump(v, open("engine.json", "w"), indent=2)
PY
"$PIO" build --engine-dir .
"$PIO" train --engine-dir .

echo "== 3. deploy"
"$PIO" deploy --engine-dir . --port "$PORT" --spawn
trap '"$PIO" undeploy --port "$PORT" >/dev/null 2>&1 || true' EXIT
up=""
for i in $(seq 1 45); do
  if curl -sf "http://127.0.0.1:$PORT/" >/dev/null 2>&1; then up=1; break; fi
  sleep 1
done
if [ -z "$up" ]; then
  echo "ERROR: query server did not come up on :$PORT within 45s" >&2
  tail -20 "$PIO_FS_BASEDIR"/logs/run_server-*.log >&2 || true
  exit 1
fi

query() {
  curl -s -X POST "http://127.0.0.1:$PORT/queries.json" \
    -H 'Content-Type: application/json' -d '{"user": "u0", "num": 3}'
}
echo "-- u0 top 3 before any live events:"
FIRST=$(query); echo "$FIRST"
TOP=$(python3 -c "import json,sys; print(json.loads(sys.argv[1])['itemScores'][0]['item'])" "$FIRST")
SECOND_ITEM=$(python3 -c "import json,sys; print(json.loads(sys.argv[1])['itemScores'][1]['item'])" "$FIRST")

echo "-- u0 buys $TOP (live event, no retrain)"
python3 -c "
import json
print(json.dumps({'event': 'buy', 'entityType': 'user', 'entityId': 'u0',
                  'targetEntityType': 'item', 'targetEntityId': '$TOP'}))
" > "$WORK/live.jsonl"
"$PIO" import --appid "$APP_ID" --input "$WORK/live.jsonl" >/dev/null

echo "-- $SECOND_ITEM goes out of stock (constraint entity)"
python3 -c "
import json
print(json.dumps({'event': '\$set', 'entityType': 'constraint',
                  'entityId': 'unavailableItems',
                  'properties': {'items': ['$SECOND_ITEM']}}))
" > "$WORK/live2.jsonl"
"$PIO" import --appid "$APP_ID" --input "$WORK/live2.jsonl" >/dev/null

echo "-- u0 top 3 after (bought + unavailable items filtered):"
AFTER=$(query); echo "$AFTER"
python3 - "$FIRST" "$AFTER" "$TOP" "$SECOND_ITEM" <<'PY'
import json, sys
first, after, top, second = sys.argv[1:5]
after_items = [r["item"] for r in json.loads(after)["itemScores"]]
assert top not in after_items, f"bought item {top} still recommended"
assert second not in after_items, f"unavailable item {second} still recommended"
print(f"live filters verified: {top} (bought) and {second} (unavailable) dropped")
PY

"$PIO" undeploy --port "$PORT"
trap - EXIT
echo "ECOMMERCE QUICKSTART COMPLETE (workdir: $WORK)"
