"""Timestamped view events for the sequencerec quickstart.

Users walk a fixed cycle i0 -> i1 -> ... -> i11 -> i0 with per-user
phase offsets, so the transformer can learn "next item = current + 1"
and the demo query's prediction is checkable.
"""
import datetime as dt
import json
import sys


def main() -> int:
    n_users = int(sys.argv[1]) if len(sys.argv) > 1 else 40
    cycle = 12
    base = dt.datetime(2026, 1, 1, tzinfo=dt.timezone.utc)
    for u in range(n_users):
        for step in range(24):
            item = (u + step) % cycle
            t = base + dt.timedelta(minutes=u * 1000 + step)
            print(json.dumps({
                "event": "view",
                "entityType": "user", "entityId": f"u{u}",
                "targetEntityType": "item", "targetEntityId": f"i{item}",
                "eventTime": t.isoformat().replace("+00:00", "Z"),
            }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
