#!/usr/bin/env bash
# Sequence-recommendation lifecycle: timestamped view streams -> causal
# transformer next-item training -> deployed history-aware predictions.
set -euo pipefail
HERE="$(cd "$(dirname "${BASH_SOURCE[0]}")" && pwd)"
PIO="${HERE}/../../bin/pio"
WORK="${1:-$(mktemp -d)}"
mkdir -p "$WORK"
WORK="$(cd "$WORK" && pwd)"
PORT="${QUICKSTART_PORT:-8195}"
export PIO_FS_BASEDIR="${PIO_FS_BASEDIR:-$WORK/storage}"

echo "== 1. app + events"
APP_NAME="seqdemo-$(date +%s)-$$"
"$PIO" app new "$APP_NAME" | tee "$WORK/app.json"
APP_ID=$(python3 -c "import json,sys; print(json.load(open(sys.argv[1]))['id'])" "$WORK/app.json")
python3 "$HERE/gen_events.py" > "$WORK/events.jsonl"
"$PIO" import --appid "$APP_ID" --input "$WORK/events.jsonl"

echo "== 2. engine + train (small transformer for the demo)"
if [ ! -f "$WORK/engine/engine.json" ]; then
  "$PIO" template get sequencerec "$WORK/engine"
fi
cd "$WORK/engine"
python3 - "$APP_ID" <<'PY'
import json, sys
v = json.load(open("engine.json"))
v["datasource"]["params"]["app_id"] = int(sys.argv[1])
v["algorithms"][0]["params"].update(
    {"d_model": 32, "n_layers": 1, "steps": 200}
)
json.dump(v, open("engine.json", "w"), indent=2)
PY
"$PIO" build --engine-dir .
"$PIO" train --engine-dir .

echo "== 3. deploy + query"
"$PIO" deploy --engine-dir . --port "$PORT" --spawn
trap '"$PIO" undeploy --port "$PORT" >/dev/null 2>&1 || true' EXIT
up=""
for i in $(seq 1 45); do
  if curl -sf "http://127.0.0.1:$PORT/" >/dev/null 2>&1; then up=1; break; fi
  sleep 1
done
if [ -z "$up" ]; then
  echo "ERROR: query server did not come up on :$PORT within 45s" >&2
  tail -20 "$PIO_FS_BASEDIR"/logs/run_server-*.log >&2 || true
  exit 1
fi
echo "-- history i3,i4,i5 (cycle says next = i6):"
curl -s -X POST "http://127.0.0.1:$PORT/queries.json" \
  -H 'Content-Type: application/json' \
  -d '{"recent_items": ["i3", "i4", "i5"], "num": 3}'
echo
echo "-- u0's stored history (ends ...i10,i11 => expect i0-ish):"
curl -s -X POST "http://127.0.0.1:$PORT/queries.json" \
  -H 'Content-Type: application/json' -d '{"user": "u0", "num": 3}'
echo

"$PIO" undeploy --port "$PORT"
trap - EXIT
echo "SEQUENCEREC QUICKSTART COMPLETE (workdir: $WORK)"
