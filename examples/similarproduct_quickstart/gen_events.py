"""Events for the similarproduct quickstart: $set users/items (with
categories), view streams, and like/dislike signals.

Items form two category clusters; users view within their cluster, so
items from one cluster should surface as most similar to each other.
"""
import json
import sys

import numpy as np


def main() -> int:
    n_users = int(sys.argv[1]) if len(sys.argv) > 1 else 120
    n_items = int(sys.argv[2]) if len(sys.argv) > 2 else 40
    rng = np.random.default_rng(0)
    for u in range(n_users):
        print(json.dumps({"event": "$set", "entityType": "user",
                          "entityId": f"u{u}", "properties": {}}))
    for i in range(n_items):
        cluster = "electronics" if i % 2 == 0 else "books"
        print(json.dumps({"event": "$set", "entityType": "item",
                          "entityId": f"i{i}",
                          "properties": {"categories": [cluster]}}))
    for u in range(n_users):
        parity = u % 2
        for _ in range(30):
            i = int(rng.integers(n_items // 2)) * 2 + parity
            print(json.dumps({"event": "view", "entityType": "user",
                              "entityId": f"u{u}",
                              "targetEntityType": "item",
                              "targetEntityId": f"i{i}"}))
            r = rng.random()
            if r < 0.3:
                print(json.dumps({"event": "like", "entityType": "user",
                                  "entityId": f"u{u}",
                                  "targetEntityType": "item",
                                  "targetEntityId": f"i{i}"}))
            elif r > 0.95:
                print(json.dumps({"event": "dislike", "entityType": "user",
                                  "entityId": f"u{u}",
                                  "targetEntityType": "item",
                                  "targetEntityId": f"i{i}"}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
