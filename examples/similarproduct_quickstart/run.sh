#!/usr/bin/env bash
# Similar-product lifecycle: $set users/items + view/like streams ->
# ALS item factors -> deployed "items similar to X" queries (ensemble
# serving with the like-filtered algorithm when configured).
set -euo pipefail
HERE="$(cd "$(dirname "${BASH_SOURCE[0]}")" && pwd)"
PIO="${HERE}/../../bin/pio"
WORK="${1:-$(mktemp -d)}"
mkdir -p "$WORK"
WORK="$(cd "$WORK" && pwd)"
PORT="${QUICKSTART_PORT:-8197}"
export PIO_FS_BASEDIR="${PIO_FS_BASEDIR:-$WORK/storage}"

echo "== 1. app + events"
APP_NAME="simdemo-$(date +%s)-$$"
"$PIO" app new "$APP_NAME" | tee "$WORK/app.json"
APP_ID=$(python3 -c "import json,sys; print(json.load(open(sys.argv[1]))['id'])" "$WORK/app.json")
python3 "$HERE/gen_events.py" > "$WORK/events.jsonl"
"$PIO" import --appid "$APP_ID" --input "$WORK/events.jsonl"

echo "== 2. engine + train"
if [ ! -f "$WORK/engine/engine.json" ]; then
  "$PIO" template get similarproduct "$WORK/engine"
fi
cd "$WORK/engine"
python3 - "$APP_ID" <<'PY'
import json, sys
v = json.load(open("engine.json"))
v["datasource"]["params"]["app_id"] = int(sys.argv[1])
json.dump(v, open("engine.json", "w"), indent=2)
PY
"$PIO" build --engine-dir .
"$PIO" train --engine-dir .

echo "== 3. deploy + query"
"$PIO" deploy --engine-dir . --port "$PORT" --spawn
trap '"$PIO" undeploy --port "$PORT" >/dev/null 2>&1 || true' EXIT
up=""
for i in $(seq 1 45); do
  if curl -sf "http://127.0.0.1:$PORT/" >/dev/null 2>&1; then up=1; break; fi
  sleep 1
done
if [ -z "$up" ]; then
  echo "ERROR: query server did not come up on :$PORT within 45s" >&2
  tail -20 "$PIO_FS_BASEDIR"/logs/run_server-*.log >&2 || true
  exit 1
fi
echo "-- items similar to i0 (electronics cluster => expect even ids):"
curl -s -X POST "http://127.0.0.1:$PORT/queries.json" \
  -H 'Content-Type: application/json' -d '{"items": ["i0"], "num": 5}'
echo
echo "-- items similar to i1 (books cluster => expect odd ids):"
curl -s -X POST "http://127.0.0.1:$PORT/queries.json" \
  -H 'Content-Type: application/json' -d '{"items": ["i1"], "num": 5}'
echo

"$PIO" undeploy --port "$PORT"
trap - EXIT
echo "SIMILARPRODUCT QUICKSTART COMPLETE (workdir: $WORK)"
