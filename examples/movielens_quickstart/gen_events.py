"""Generate a MovieLens-shaped events.jsonl for the quickstart.

Usage: python gen_events.py [n_users] [n_items] [n_events] > events.jsonl
"""
import json
import sys

import numpy as np


def main() -> int:
    n_users = int(sys.argv[1]) if len(sys.argv) > 1 else 200
    n_items = int(sys.argv[2]) if len(sys.argv) > 2 else 80
    n_events = int(sys.argv[3]) if len(sys.argv) > 3 else 5000
    rng = np.random.default_rng(0)
    # two-cohort structure so recommendations are visibly non-random
    for _ in range(n_events):
        u = int(rng.integers(n_users))
        i = int(rng.integers(n_items))
        aligned = (u % 2) == (i % 2)
        rating = float(rng.choice([4, 5] if aligned else [1, 2]))
        print(json.dumps({
            "event": "rate",
            "entityType": "user", "entityId": f"u{u}",
            "targetEntityType": "item", "targetEntityId": f"i{i}",
            "properties": {"rating": rating},
        }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
