#!/usr/bin/env bash
# Full lifecycle on synthetic data: app -> import -> engine scaffold ->
# build -> train -> deploy -> query -> undeploy. Runs anywhere (CPU ok);
# set PIO_FS_BASEDIR to keep the demo's storage isolated.
set -euo pipefail
HERE="$(cd "$(dirname "${BASH_SOURCE[0]}")" && pwd)"
PIO="${HERE}/../../bin/pio"
WORK="${1:-$(mktemp -d)}"
mkdir -p "$WORK"
WORK="$(cd "$WORK" && pwd)"  # absolutize: the script cds into the engine dir
PORT="${QUICKSTART_PORT:-8199}"
export PIO_FS_BASEDIR="${PIO_FS_BASEDIR:-$WORK/storage}"

echo "== 1. app + events"
# unique per-run app name: the demo works against pre-existing storage
# and reruns of the same workdir
APP_NAME="quickstart-$(date +%s)-$$"
"$PIO" app new "$APP_NAME" | tee "$WORK/app.json"
APP_ID=$(python3 -c "import json,sys; print(json.load(open(sys.argv[1]))['id'])" "$WORK/app.json")
python3 "$HERE/gen_events.py" > "$WORK/events.jsonl"
"$PIO" import --appid "$APP_ID" --input "$WORK/events.jsonl"

echo "== 2. engine project"
if [ ! -f "$WORK/engine/engine.json" ]; then
  "$PIO" template get recommendation "$WORK/engine"
fi
cd "$WORK/engine"
# point the scaffolded variant at THIS run's app id
python3 - "$APP_ID" <<'PY'
import json, sys
v = json.load(open("engine.json"))
v["datasource"]["params"]["app_id"] = int(sys.argv[1])
json.dump(v, open("engine.json", "w"), indent=2)
PY
"$PIO" build --engine-dir .

echo "== 3. train"
"$PIO" train --engine-dir .

echo "== 4. deploy + query"
"$PIO" deploy --engine-dir . --port "$PORT" --spawn
trap '"$PIO" undeploy --port "$PORT" >/dev/null 2>&1 || true' EXIT
up=""
for i in $(seq 1 45); do
  if curl -sf "http://127.0.0.1:$PORT/" >/dev/null 2>&1; then up=1; break; fi
  sleep 1
done
if [ -z "$up" ]; then
  echo "ERROR: query server did not come up on :$PORT within 45s" >&2
  tail -20 "$PIO_FS_BASEDIR"/logs/run_server-*.log >&2 || true
  exit 1
fi
echo "-- u0 (even cohort) top 5:"
curl -s -X POST "http://127.0.0.1:$PORT/queries.json" \
  -H 'Content-Type: application/json' -d '{"user": "u0", "num": 5}'
echo
echo "-- u1 (odd cohort) top 5:"
curl -s -X POST "http://127.0.0.1:$PORT/queries.json" \
  -H 'Content-Type: application/json' -d '{"user": "u1", "num": 5}'
echo

echo "== 5. undeploy"
"$PIO" undeploy --port "$PORT"
trap - EXIT
echo "QUICKSTART COMPLETE (workdir: $WORK)"
