"""Train/eval driver process — the ``CreateWorkflow`` analogue.

Rebuild of ``core/src/main/scala/io/prediction/workflow/CreateWorkflow.scala``:
the ``main`` of every ``pio train`` / ``pio eval``.  The reference is spawned
via spark-submit (``RunWorkflow.scala:103-169``); here the console either
invokes :func:`run` in-process or spawns
``python -m predictionio_tpu.tools.run_workflow`` to preserve the process
boundary (CLI process ↔ training driver process) with the same
metadata-store handshake.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
from typing import Optional, Sequence

from ..controller.engine import WorkflowParams
from ..storage import StorageRegistry, get_registry
from ..workflow import loader
from ..workflow.core_workflow import run_evaluation, run_train
from .register import load_engine_dir

logger = logging.getLogger(__name__)


def build_parser() -> argparse.ArgumentParser:
    """Flag grammar (``CreateWorkflow.scala:87-140``)."""
    p = argparse.ArgumentParser(prog="run_workflow")
    p.add_argument("--engine-dir", default=".", help="engine project directory")
    p.add_argument("--engine-id", default=None)
    p.add_argument("--engine-version", default=None)
    p.add_argument("--engine-variant", default="engine.json")
    p.add_argument("--engine-factory", default=None)
    p.add_argument("--engine-params-key", default=None)
    p.add_argument("--evaluation-class", default=None)
    p.add_argument("--engine-params-generator-class", default=None)
    p.add_argument("--batch", default="")
    p.add_argument("--verbose", action="store_true")
    p.add_argument("--skip-sanity-check", action="store_true")
    p.add_argument("--stop-after-read", action="store_true")
    p.add_argument("--stop-after-prepare", action="store_true")
    p.add_argument("--verbosity", type=int, default=0)
    p.add_argument(
        "--eval-parallelism", type=int, default=0,
        help="sweep parallelism over mesh slices (0 = auto, 1 = serial)",
    )
    p.add_argument(
        "--shards", type=int, default=None, metavar="N",
        help="train with both factor tables sharded over N devices "
             "(ALX-style shard_map trainer, docs/distributed_training.md); "
             "sets PIO_TRAIN_SHARDS, which the algorithm's `shards` "
             "tri-state resolves from — an explicit engine.json value "
             "still wins",
    )
    p.add_argument(
        "--checkpoint-every", type=int, default=None, metavar="N",
        help="checkpoint factor tables every N iterations "
             "(docs/checkpoint.md); the run's override in the "
             "checkpoint_every tri-state — an explicit engine.json "
             "value still wins, PIO_CKPT_EVERY is the fleet default",
    )
    p.add_argument(
        "--resume", default=None, action=argparse.BooleanOptionalAction,
        help="resume from the newest valid checkpoint (default; a "
             "mismatched recipe refuses loudly). --no-resume clears "
             "existing checkpoints and trains fresh. Env default: "
             "PIO_CKPT_RESUME",
    )
    return p


def run(
    args: argparse.Namespace, registry: Optional[StorageRegistry] = None
) -> str:
    """Execute one train or eval run; returns the instance id
    (``CreateWorkflow.main``, ``CreateWorkflow.scala:142-279``)."""
    loader.modify_logging(args.verbose)
    fn = lambda: _run_inner(args, registry)  # noqa: E731
    if getattr(args, "resume", None) is not None:
        # env-driven like --shards below, so --spawn and in-process runs
        # behave identically; scoped to this run
        from ..ckpt import RESUME_ENV

        fn = (lambda inner: lambda: _with_env(
            RESUME_ENV, "1" if args.resume else "0", inner
        ))(fn)
    if getattr(args, "shards", None) is not None:
        # an explicit 0 must reach resolve_shards and fail loudly there
        # — a falsy check would silently train single-device
        # the tri-state env the algorithm's `shards=None` resolves from
        # (ops.als_sharded.resolve_shards) — env-driven like every other
        # config tier, so --spawn and in-process runs behave identically.
        # Scoped to this run: an in-process console must not leak the
        # flag into a later train in the same process.
        from ..ops.als_sharded import SHARDS_ENV

        fn = (lambda inner: lambda: _with_env(
            SHARDS_ENV, str(args.shards), inner
        ))(fn)
    return fn()


def _with_env(key: str, value: str, fn):
    prior = os.environ.get(key)
    os.environ[key] = value
    try:
        return fn()
    finally:
        if prior is None:
            os.environ.pop(key, None)
        else:
            os.environ[key] = prior


def _run_inner(
    args: argparse.Namespace, registry: Optional[StorageRegistry] = None
) -> str:
    registry = registry or get_registry()
    wp = WorkflowParams(
        batch=args.batch,
        verbose=args.verbosity,
        skip_sanity_check=args.skip_sanity_check,
        stop_after_read=args.stop_after_read,
        stop_after_prepare=args.stop_after_prepare,
        eval_parallelism=args.eval_parallelism,
        checkpoint_every=getattr(args, "checkpoint_every", None),
    )

    # runtimeConf binds to every workflow run, train AND eval — the
    # reference applies embedded sparkConf to all SparkContext creations
    # (WorkflowUtils.scala:321-339). Eval runs may lack an engine.json
    # (evaluation classes can carry their own engines): absent = no-op,
    # but a PRESENT-yet-broken engine dir must not silently drop config.
    from .register import ENGINE_JSON

    ed = None
    if args.evaluation_class and not os.path.exists(
        os.path.join(args.engine_dir, ENGINE_JSON)
    ):
        pass  # eval without an engine.json: nothing to apply
    else:
        ed = load_engine_dir(args.engine_dir)
        loader.apply_runtime_conf(ed.variant)

    if args.evaluation_class:
        # Eval path (``CreateWorkflow.scala:180-199,264-277``).
        evaluation = loader.get_evaluation(args.evaluation_class, args.engine_dir)
        if args.engine_params_generator_class:
            generator = loader.get_engine_params_generator(
                args.engine_params_generator_class, args.engine_dir
            )
        else:
            # An Evaluation may itself carry the params list
            # (``Evaluation.scala:59-124`` couples engine+params).
            from ..controller.evaluation import EngineParamsGenerator

            generator = EngineParamsGenerator(
                [evaluation.engine.default_engine_params()]
                if hasattr(evaluation.engine, "default_engine_params")
                else []
            )
        return run_evaluation(evaluation, generator, registry, workflow_params=wp)

    # Train path (``CreateWorkflow.scala:219-263``). ``ed`` was loaded
    # above (train always has an engine dir).
    factory = args.engine_factory or ed.engine_factory
    engine = loader.get_engine(factory, search_dir=ed.path)
    if args.engine_params_key:
        # Programmatic params: factory object exposes engine_params(key)
        # (``CreateWorkflow.scala:227-231``).
        factory_obj = loader.load_object(factory, ed.path)
        engine_params = factory_obj.engine_params(args.engine_params_key)
    else:
        engine_params = engine.json_to_engine_params(ed.variant)
    return run_train(
        engine,
        engine_params,
        registry,
        engine_id=args.engine_id or ed.manifest.id,
        engine_version=args.engine_version or ed.manifest.version,
        engine_variant=args.engine_variant,
        engine_factory=factory,
        workflow_params=wp,
    )


def main(argv: Optional[Sequence[str]] = None) -> int:
    # Make the caller's platform choice stick before any backend init —
    # a boot hook may have programmatically overridden JAX_PLATFORMS=cpu
    # (the spark-submit env-propagation analogue, RunWorkflow.scala:37-40).
    from ..utils.jax_cache import enable_compilation_cache
    from ..utils.platform import apply_env_platform

    apply_env_platform()
    enable_compilation_cache()
    args = build_parser().parse_args(argv)
    instance_id = run(args)
    print(json.dumps({"engineInstanceId": instance_id}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
