"""Remote template gallery: fetch engine templates from a network index.

Rebuild of the reference's GitHub-backed gallery
(``tools/src/main/scala/io/prediction/tools/console/Template.scala:56-375``):
there, ``pio template list``/``get`` hit the GitHub API (repo tags →
zipball) with an **ETag cache** so repeated calls cost one conditional
request, fall back to the cached copy when offline, and honor an HTTP
proxy. The rebuild keeps the same contract against a self-describable
index:

* ``PIO_TEMPLATE_GALLERY_URL`` points at an index JSON:
  ``[{"name", "description", "archive_url", "version"}, ...]``
* every GET sends ``If-None-Match`` with the cached ETag; 304 → cache hit
  (``Template.scala:62-92``'s ``readMetadataFromCache``/ETag header dance)
* network failure falls back to the cache when present
  (``Template.scala:106-113``)
* proxies: urllib honors ``http_proxy``/``https_proxy`` env vars, the same
  knobs the reference reads (``Template.scala:115-135``)
* ``get`` downloads the template's zip archive and extracts it into the
  target directory (the zipball unpack, ``Template.scala:287-340``; the
  Scala package-rename step has no Python analogue and is dropped)

Cache layout: ``$PIO_FS_BASEDIR/template_cache/<sha1(url)>.{body,etag}``.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import urllib.error
import urllib.request
import zipfile
from typing import List, Optional, Tuple

GALLERY_URL_ENV = "PIO_TEMPLATE_GALLERY_URL"


class GalleryError(Exception):
    """Gallery unreachable and no cached copy exists."""


def gallery_url() -> Optional[str]:
    return os.environ.get(GALLERY_URL_ENV) or None


def _cache_dir() -> str:
    from ..storage.registry import base_dir

    d = os.path.join(base_dir(), "template_cache")
    os.makedirs(d, exist_ok=True)
    return d


def _cache_paths(url: str) -> Tuple[str, str]:
    key = hashlib.sha1(url.encode("utf-8")).hexdigest()
    root = _cache_dir()
    return os.path.join(root, f"{key}.body"), os.path.join(root, f"{key}.etag")


def fetch_cached(url: str, timeout: float = 30.0) -> bytes:
    """GET with ETag conditional-request caching and offline fallback."""
    body_path, etag_path = _cache_paths(url)
    headers = {}
    if os.path.exists(body_path) and os.path.exists(etag_path):
        with open(etag_path, "r", encoding="utf-8") as fh:
            etag = fh.read().strip()
        if etag:
            headers["If-None-Match"] = etag
    req = urllib.request.Request(url, headers=headers)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            body = resp.read()
            etag = resp.headers.get("ETag", "")
            with open(body_path, "wb") as fh:
                fh.write(body)
            with open(etag_path, "w", encoding="utf-8") as fh:
                fh.write(etag)
            return body
    except urllib.error.HTTPError as exc:
        if os.path.exists(body_path):
            # 304: the conditional request validated the cache. Any other
            # HTTP error (5xx from the gallery or a proxy): degrade to the
            # cached copy, same as being unreachable (Template.scala:106-113).
            with open(body_path, "rb") as fh:
                return fh.read()
        raise GalleryError(f"GET {url} → HTTP {exc.code}") from exc
    except urllib.error.URLError as exc:
        # offline: serve the cache when we have one (Template.scala:106-113)
        if os.path.exists(body_path):
            with open(body_path, "rb") as fh:
                return fh.read()
        raise GalleryError(f"GET {url} unreachable: {exc.reason}") from exc


def list_remote(url: Optional[str] = None) -> List[dict]:
    """``pio template list`` against the remote index."""
    url = url or gallery_url()
    if not url:
        raise GalleryError(
            f"No remote gallery configured (set {GALLERY_URL_ENV})"
        )
    entries = json.loads(fetch_cached(url))
    return [
        {
            "name": e["name"],
            "description": e.get("description", ""),
            "version": e.get("version", ""),
        }
        for e in entries
    ]


def get_remote(name: str, directory: str, url: Optional[str] = None) -> dict:
    """``pio template get`` from the remote gallery: download the archive
    (ETag-cached) and extract it into ``directory``."""
    url = url or gallery_url()
    if not url:
        raise GalleryError(
            f"No remote gallery configured (set {GALLERY_URL_ENV})"
        )
    entries = json.loads(fetch_cached(url))
    entry = next((e for e in entries if e["name"] == name), None)
    if entry is None:
        raise KeyError(
            f"Template {name!r} not in gallery; available: "
            f"{sorted(e['name'] for e in entries)}"
        )
    # validate the target before paying for the download; realpath so the
    # zip-slip containment check below agrees with symlinked targets
    directory = os.path.realpath(directory)
    if os.path.exists(directory) and os.listdir(directory):
        raise ValueError(f"Target directory {directory} is not empty")

    archive_url = entry["archive_url"]
    if not archive_url.startswith(("http://", "https://")):
        # relative to the index (the common same-host layout)
        archive_url = urllib.request.urljoin(url, archive_url)
    blob = fetch_cached(archive_url)
    os.makedirs(directory, exist_ok=True)
    with zipfile.ZipFile(io.BytesIO(blob)) as zf:
        names = zf.namelist()
        # strip a single top-level folder (GitHub-zipball shape) when present
        roots = {n.split("/", 1)[0] for n in names if n.strip("/")}
        strip = (
            f"{next(iter(roots))}/"
            if len(roots) == 1 and all("/" in n for n in names if n.strip("/"))
            else ""
        )
        for member in names:
            rel = member[len(strip):] if strip else member
            if not rel or rel.endswith("/"):
                continue
            # zip-slip guard: resolved path must stay inside the target
            dest = os.path.realpath(os.path.join(directory, rel))
            if dest != directory and not dest.startswith(directory + os.sep):
                raise ValueError(f"Archive member escapes target dir: {member}")
            os.makedirs(os.path.dirname(dest), exist_ok=True)
            with zf.open(member) as src, open(dest, "wb") as out:
                out.write(src.read())
    return {
        "template": name,
        "directory": directory,
        "version": entry.get("version", ""),
        "source": archive_url,
    }
