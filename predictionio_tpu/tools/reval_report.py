"""Summarize TPU_REVALIDATION.jsonl into the PERF.md-ready tables.

The revalidation queue (``tpu_revalidate``) appends one JSON line per
step; this tool folds them into a readable report the moment the
hardware window closes — baseline spread, the A/B lever matrix with RMSE
gates, compiled-path verdicts, and the serving sweeps — so the analysis
step can't be fumbled under time pressure when the tunnel is up.

Usage: ``python -m predictionio_tpu.tools.reval_report [path]``
(default: repo-root ``TPU_REVALIDATION.jsonl``; reads ALL runs in the
file, newest occurrence of each step wins).
"""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def load(path: str) -> dict:
    """Newest record per step name."""
    steps: dict = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if "step" in rec:
                steps[rec["step"]] = rec
    return steps


def _fmt_bench(rec: dict) -> str:
    if rec is None:
        return "— not run"
    if "error" in rec:
        return f"ERROR: {rec['error']}"
    parts = [f"{rec.get('value')}s train"]
    if rec.get("iteration_s"):
        it = rec["iteration_s"]
        steady = it[1:] if len(it) > 1 else it
        parts.append(f"steady iter {sum(steady)/len(steady):.3f}s")
    for k, lbl in (("holdout_rmse", "rmse"), ("bucketize_stage_s", "stage"),
                   ("est_hbm_util_v5e", "hbm_util"), ("device", "")):
        if rec.get(k) is not None:
            parts.append(f"{lbl + ' ' if lbl else ''}{rec[k]}")
    if rec.get("rmse_gate"):
        parts.append(f"gate={rec['rmse_gate']}")
    if "fallback" in rec:
        parts.append("FALLBACK — INVALID")
    return ", ".join(str(p) for p in parts)


def report(steps: dict) -> str:
    out = ["# TPU revalidation report", ""]

    out.append("## ALS bench (ML-20M shape, rank 50, 10 iter)")
    # repeat legs are named baseline_f32_rN for N=2..--repeats: derive
    # them from the records present rather than hard-coding N<=3
    repeat_names = sorted(
        (n for n in steps
         if n.startswith("baseline_f32_r") and n[14:].isdigit()),
        key=lambda n: int(n[14:]),
    )
    for name in ("baseline_f32", *repeat_names,
                 "bf16_gather", "sort_gather", "bf16_plus_sort",
                 "fused_gather", "fused_plus_bf16"):
        if name in steps:
            out.append(f"- **{name}**: {_fmt_bench(steps[name])}")
    var = steps.get("baseline_variance")
    if var:
        out.append(
            f"- spread over {var.get('runs')} runs: train_s "
            f"{var.get('train_s')} (Δ {var.get('train_s_spread')}s), "
            f"steady iters {var.get('steady_iter_s')}"
        )

    out.append("")
    out.append("## Compiled-path verdicts")
    for name in ("fused_smoke", "mesh_pallas", "flash_pallas"):
        rec = steps.get(name)
        if rec is None:
            out.append(f"- {name}: — not run")
        elif rec.get("ok"):
            detail = {
                k: v for k, v in rec.items()
                if any(t in k for t in ("rel", "err", "_ms_"))
            }
            out.append(
                f"- **{name}**: OK compiled={rec.get('compiled')} "
                f"({detail})"
            )
        else:
            out.append(f"- **{name}**: FAILED — {rec}")

    rec = steps.get("implicit_gate")
    if rec is not None:
        out.append("")
        out.append("## Implicit-mode quality gate (precision@10)")
        if "skipped" in rec:
            out.append(f"- skipped: {rec['skipped']}")
        elif "error" in rec:
            out.append(f"- ERROR: {rec['error']}")
        else:
            out.append(
                f"- f32 {rec.get('p10_f32')} vs lever "
                f"{rec.get('p10_lever')} (Δ {rec.get('delta')}) — "
                f"gate={rec.get('gate')}, lever={rec.get('lever')}"
            )

    rec = steps.get("profile_trace")
    if rec is not None:
        out.append("")
        out.append("## Profiler trace (op-level device timings)")
        if "error" in rec or "parse_error" in rec:
            out.append(f"- {rec.get('error') or rec.get('parse_error')} "
                       f"(trace dir: {rec.get('trace_dir')})")
        else:
            for plane, data in (rec.get("planes") or {}).items():
                out.append(f"- **{plane}** total {data.get('total_ms')} ms")
                for op, ms in list(data.get("top_ops_ms", {}).items())[:8]:
                    out.append(f"  - {op}: {ms} ms")
            out.append(f"- full trace: {rec.get('xplane')}")

    rec = steps.get("dispatch_bench")
    if rec and "catalogs" in rec:
        out.append("")
        out.append("## Device dispatch (batch-512 top-10)")
        out.append("| catalog | ms/batch | implied QPS @ depth 1 |")
        out.append("|---|---|---|")
        for n, d in rec["catalogs"].items():
            out.append(
                f"| {n} | {d['dispatch_ms_per_batch']} | "
                f"{d['implied_qps_at_depth1']:.0f} |"
            )

    for tag, title in (("", "Serving loadgen — quickstart catalog"),
                       ("_big", "Serving loadgen — 60k-item catalog")):
        rows = []
        for depth in (1, 2, 4, 8):
            h = steps.get(f"loadgen_depth{depth}{tag}")
            p = steps.get(f"loadgen_inproc_depth{depth}{tag}")
            if h or p:
                rows.append((depth, h, p))
        if rows:
            out.append("")
            out.append(f"## {title}")
            out.append(
                "| depth | HTTP QPS | HTTP p99 ms | in-proc QPS "
                "| in-proc p99 ms |"
            )
            out.append("|---|---|---|---|---|")
            for depth, h, p in rows:
                def cell(r, k):
                    if r is None:
                        return "—"
                    return r.get(k, f"ERR:{r.get('error', '?')[:40]}")
                out.append(
                    f"| {depth} | {cell(h, 'qps')} | {cell(h, 'p99_ms')} "
                    f"| {cell(p, 'qps')} | {cell(p, 'p99_ms')} |"
                )

    covered = {
        "baseline_f32", "baseline_variance", "bf16_gather", "sort_gather",
        "bf16_plus_sort", "fused_gather", "fused_plus_bf16",
        "fused_smoke", "mesh_pallas", "flash_pallas", "dispatch_bench",
        "implicit_gate", "profile_trace",
    } | set(repeat_names) | {
        f"loadgen_{kind}depth{d}{t}"
        for kind in ("", "inproc_") for d in (1, 2, 4, 8) for t in ("", "_big")
    } | {f"{n}_gate" for n in ("bf16_gather", "sort_gather",
                               "bf16_plus_sort", "fused_gather",
                               "fused_plus_bf16")}
    extra = sorted(set(steps) - covered)
    if extra:
        out.append("")
        out.append("## Other steps")
        for name in extra:
            out.append(f"- {name}: {json.dumps(steps[name])[:160]}")
    return "\n".join(out)


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    path = argv[0] if argv else os.path.join(REPO, "TPU_REVALIDATION.jsonl")
    if not os.path.exists(path):
        print(f"no evidence file at {path}", file=sys.stderr)
        return 1
    print(report(load(path)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
