"""Engine template gallery: scaffold bundled templates into a project dir.

Rebuild of ``tools/.../console/Template.scala:56-375``.  The reference fetches
templates from GitHub (tags/zipball with an ETag cache) and rewrites Scala
package names; this environment has no network egress, so the gallery is
*bundled*: ``pio template get <name> <dir>`` writes a ready-to-run engine
project (``engine.json`` + ``engine.py``) wrapping the corresponding
:mod:`predictionio_tpu.models` engine, which the user then edits in place —
the same customize-a-working-copy workflow the reference's downloads serve.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List


def _engine_py(factory_import: str, blurb: str) -> str:
    return f'''"""Engine template: {blurb}

Customize by subclassing/replacing any DASE component and re-pointing
``engineFactory`` in engine.json at your own factory.
"""

from {factory_import} import engine_factory  # noqa: F401
'''


_TEMPLATES: Dict[str, Dict[str, object]] = {
    "recommendation": {
        "blurb": "ALS collaborative filtering (rate/buy events → top-N items)",
        "factory": "predictionio_tpu.models.recommendation",
        "variant": {
            "id": "default",
            "description": "Recommendation engine (TPU ALS)",
            "engineFactory": "engine:engine_factory",
            "datasource": {"params": {"app_id": 1}},
            "algorithms": [
                {
                    "name": "als",
                    "params": {
                        "rank": 10,
                        "num_iterations": 10,
                        "lambda_": 0.01,
                    },
                }
            ],
        },
        "evaluation": '''"""Evaluation: Precision@K over a rank x lambda grid.

Run with:  pio eval --evaluation-class evaluation:RecEvaluation \\
                    --engine-params-generator-class evaluation:RecParamsGenerator
(the reference movielens-evaluation example's shape).
"""

from predictionio_tpu.models.recommendation import (  # noqa: F401
    PrecisionAtK,
    RecEvaluation,
    RecParamsGenerator,
)
''',
    },
    "classification": {
        "blurb": "Naive Bayes / random forest over entity properties",
        "factory": "predictionio_tpu.models.classification",
        "variant": {
            "id": "default",
            "description": "Classification engine (TPU Naive Bayes)",
            "engineFactory": "engine:engine_factory",
            "datasource": {"params": {"app_id": 1}},
            "algorithms": [{"name": "naive", "params": {"lam": 1.0}}],
        },
    },
    "similarproduct": {
        "blurb": "Item similarity from ALS factors (view/like events)",
        "factory": "predictionio_tpu.models.similarproduct",
        "variant": {
            "id": "default",
            "description": "Similar-product engine (TPU item-factor cosine)",
            "engineFactory": "engine:engine_factory",
            "datasource": {"params": {"app_id": 1}},
            "algorithms": [
                {"name": "als", "params": {"rank": 10, "num_iterations": 10}}
            ],
        },
    },
    "sequencerec": {
        "blurb": "Transformer next-item prediction over interaction histories",
        "factory": "predictionio_tpu.models.sequencerec",
        "variant": {
            "id": "default",
            "description": "Sequence-recommendation engine (TPU transformer)",
            "engineFactory": "engine:engine_factory",
            "datasource": {"params": {"app_id": 1}},
            "algorithms": [
                {
                    "name": "transformer",
                    "params": {
                        "d_model": 64,
                        "n_layers": 2,
                        "steps": 300,
                    },
                }
            ],
        },
    },
    "ecommerce": {
        "blurb": "E-commerce recommendation with live serving-time filters",
        "factory": "predictionio_tpu.models.ecommerce",
        "variant": {
            "id": "default",
            "description": "E-commerce engine (TPU ALS + live filters)",
            "engineFactory": "engine:engine_factory",
            "datasource": {"params": {"app_id": 1}},
            "algorithms": [
                {"name": "als", "params": {"rank": 10, "num_iterations": 10}}
            ],
        },
    },
}


def list_templates() -> List[dict]:
    """``pio template list`` (``Template.scala:262-285``)."""
    return [
        {"name": name, "description": spec["blurb"]}
        for name, spec in sorted(_TEMPLATES.items())
    ]


def get_template(name: str, directory: str) -> dict:
    """``pio template get`` (``Template.scala:287-375``): write the scaffold."""
    if name not in _TEMPLATES:
        raise KeyError(
            f"Unknown template {name!r}; available: {sorted(_TEMPLATES)}"
        )
    spec = _TEMPLATES[name]
    directory = os.path.abspath(directory)
    if os.path.exists(directory) and os.listdir(directory):
        raise ValueError(f"Target directory {directory} is not empty")
    os.makedirs(directory, exist_ok=True)
    with open(os.path.join(directory, "engine.json"), "w", encoding="utf-8") as fh:
        json.dump(spec["variant"], fh, indent=2)
        fh.write("\n")
    with open(os.path.join(directory, "engine.py"), "w", encoding="utf-8") as fh:
        fh.write(_engine_py(str(spec["factory"]), str(spec["blurb"])))
    if "evaluation" in spec:
        with open(
            os.path.join(directory, "evaluation.py"), "w", encoding="utf-8"
        ) as fh:
            fh.write(str(spec["evaluation"]))
    return {"template": name, "directory": directory}
