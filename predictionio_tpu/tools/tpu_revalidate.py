#!/usr/bin/env python3
"""One-shot TPU re-validation: the queued round-3 A/B matrix.

The accelerator tunnel wedges for hours at a time; this script exists so
the moment a probe succeeds, the ENTIRE evidence queue runs unattended
and lands in one JSON-lines file:

1. ``python bench.py`` — full-scale ALS baseline (expect ≤ 18.3 s),
   repeated ``--repeats`` times (default 3) for run-to-run spread — the
   previous last-good number was a single leg with compile in iter 1.
2. Compiled-path unknowns, cheapest first (``_reval_steps``): the fused
   gather+Gramian kernel and the shard_map-wrapped pallas solve have
   only ever run in interpret mode; a 1-device mesh on the real chip
   closes the Mosaic-lowering question without multi-chip hardware.
   Plus the pure device-dispatch serving cycle at big-catalog shapes.
3. ``BENCH_GATHER_DTYPE=bf16`` — halved gather bytes; RMSE-gated.
4. ``BENCH_SORT_GATHER=1`` — gather-locality sort; RMSE-gated.
5. bf16 + sort combined (only if both individually pass the gate).
6. ``BENCH_FUSED_GATHER=1`` — the fused-kernel A/B (only if the smoke
   step passed); RMSE-gated like the others.
7. With ``--engine-dir <trained engine project>``: serving loadgen over
   pipeline depth 1/2/4/8 — HTTP (deploys on the chip per depth) AND
   in-process (isolates the stack from the wire). Without the flag the
   sweep is skipped with instructions.

Each step appends its JSON line (plus a ``step`` key) to
``TPU_REVALIDATION.jsonl``. A wedge mid-step is recorded and the
remaining independent steps still run; completed steps are always on
disk. RMSE gate: within +0.002 of the f32 baseline's holdout RMSE.

Usage:
``python -m predictionio_tpu.tools.tpu_revalidate [--engine-dir D]``
(aborts immediately, writing nothing, if the device probe fails).

Tiering (VERDICT r4): ``--tier a`` runs only the golden-window records —
one f32 baseline plus the two never-compiled-kernel verdicts, ≤5 min of
device time — so a tunnel window that closes after minutes still yields
the headline evidence. ``--tier b`` runs everything else, reusing
tier-A records younger than 6 h from the evidence file instead of
re-spending device time. The watcher runs A then B; ``--tier all``
(default) is the pre-tier single-invocation behavior.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
OUT = os.path.join(REPO, "TPU_REVALIDATION.jsonl")
RMSE_GATE_DELTA = 0.002


def log(msg: str) -> None:
    print(f"[revalidate +{time.strftime('%H:%M:%S')}] {msg}", file=sys.stderr)


def append(record: dict) -> None:
    record.setdefault("t_unix", round(time.time(), 1))
    with open(OUT, "a") as f:
        f.write(json.dumps(record) + "\n")


def _recent(step: str, max_age_s: float = 6 * 3600.0) -> dict | None:
    """Newest record for ``step`` in OUT if it was written in the last
    ``max_age_s`` seconds — how tier B reuses tier A's records instead of
    re-spending device time on them. Unstamped (pre-tier) records never
    qualify, and neither do CPU-sourced ones: a stray CPU-env invocation
    (or a mid-window fallback) must not become the RMSE gate — or stand
    in for Mosaic validation — on a real TPU window."""
    try:
        with open(OUT) as f:
            lines = [ln.strip() for ln in f if ln.strip()]
    except OSError:
        return None
    for line in reversed(lines):
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if rec.get("step") == step:
            t = rec.get("t_unix")
            if t is None or time.time() - float(t) > max_age_s:
                return None
            dev = f"{rec.get('device', '')} {rec.get('backend', '')}"
            if "cpu" in dev.lower():
                return None
            return rec
    return None


def run_bench(step: str, env_extra: dict, timeout_s: float = 1800) -> dict:
    env = dict(os.environ, **env_extra)
    log(f"bench step {step}: {env_extra or '(baseline)'}")
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            env=env, cwd=REPO, capture_output=True, text=True,
            timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        # a mid-run tunnel wedge must not kill the chain: record it and
        # let the remaining independent steps try (the tunnel sometimes
        # recovers between runs)
        rec = {
            "step": step, "rc": -1,
            "error": f"bench timed out after {timeout_s:.0f}s "
                     "(tunnel wedge mid-run?)",
        }
        append(rec)
        log(f"  -> TIMEOUT after {timeout_s:.0f}s; continuing the queue")
        return rec
    lines = [l for l in proc.stdout.splitlines() if l.startswith("{")]
    try:
        rec = json.loads(lines[-1]) if lines else {"error": "no JSON line"}
    except ValueError:
        rec = {"error": f"malformed JSON line: {lines[-1][:120]!r}"}
    rec["step"] = step
    rec["rc"] = proc.returncode
    if "fallback" in rec:
        rec["note"] = "DEVICE FELL BACK — evidence invalid for this step"
    append(rec)
    log(f"  -> value={rec.get('value')} rmse={rec.get('holdout_rmse')} "
        f"device={rec.get('device')}")
    return rec


def run_step(step: str, timeout_s: float = 900,
             env_extra: dict | None = None) -> dict:
    """Run one ``_reval_steps`` subcommand in a subprocess (a tunnel
    wedge mid-step must be a recorded timeout, not a dead queue).
    ``env_extra`` overlays the inherited environment — how the
    implicit-quality gate receives the lever flags under test."""
    log(f"device step {step}" + (f" env={env_extra}" if env_extra else ""))
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "predictionio_tpu.tools._reval_steps",
             step],
            cwd=REPO, capture_output=True, text=True, timeout=timeout_s,
            env=dict(os.environ, **env_extra) if env_extra else None,
        )
    except subprocess.TimeoutExpired:
        rec = {"step": step, "rc": -1,
               "error": f"timed out after {timeout_s:.0f}s"}
        append(rec)
        log(f"  -> TIMEOUT after {timeout_s:.0f}s; continuing the queue")
        return rec
    lines = [l for l in proc.stdout.splitlines() if l.startswith("{")]
    rec = None
    if lines:
        try:
            rec = json.loads(lines[-1])
        except ValueError:
            rec = {"error": f"malformed JSON line: {lines[-1][:120]!r}"}
    if rec is None:
        tail = proc.stderr.strip().splitlines()
        rec = {"error": tail[-1] if tail else "no JSON line"}
    # one name per logical step regardless of outcome (the inner record's
    # own step name, if any, is preserved under inner_step)
    if rec.get("step") not in (None, step):
        rec["inner_step"] = rec["step"]
    rec["step"] = step
    rec["rc"] = proc.returncode
    append(rec)
    log(f"  -> {json.dumps({k: v for k, v in rec.items() if k != 'step'})[:200]}")
    return rec


def _engine_env(engine_dir: str) -> dict:
    """Environment for deploy/loadgen children of ``engine_dir``.

    The quickstart/big-engine recipe keeps each demo's storage in a
    ``storage/`` sibling of the engine project
    (``examples/movielens_quickstart/run.sh`` exports
    ``PIO_FS_BASEDIR=$WORK/storage``). The queue inherits neither shell,
    so without this the deploys come up against the DEFAULT store and die
    with "No completed engine instance" — discovered by the round-5
    end-to-end drive, which is exactly how every loadgen sweep would have
    failed on hardware day. An explicit PIO_FS_BASEDIR in the caller's
    environment still wins."""
    env = dict(os.environ)
    storage = os.path.join(
        os.path.dirname(os.path.abspath(engine_dir)), "storage"
    )
    if "PIO_FS_BASEDIR" not in os.environ and os.path.isdir(storage):
        env["PIO_FS_BASEDIR"] = storage
    return env


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def run_inprocess_sweep(engine_dir: str, duration_s: float,
                        concurrency: int, tag: str = "") -> list:
    """In-process loadgen at each pipeline depth: the serving stack's own
    ceiling (micro-batcher + device dispatch) with the HTTP wire removed —
    one subprocess per depth so the device state is fresh each time.
    Returns the step names that errored (for the exit-code roll-up)."""
    failed = []
    env = _engine_env(engine_dir)
    for depth in (1, 2, 4, 8):
        step = f"loadgen_inproc_depth{depth}{tag}"
        log(f"in-process loadgen: depth={depth}")
        try:
            proc = subprocess.run(
                [sys.executable, "-m", "predictionio_tpu.tools.loadgen",
                 "--in-process", "--engine-dir", engine_dir,
                 "--pipeline-depth", str(depth),
                 "--concurrency", str(concurrency),
                 "--duration", str(duration_s)],
                cwd=REPO, capture_output=True, text=True, timeout=600,
                env=env,
            )
        except subprocess.TimeoutExpired:
            append({"step": step,
                    "error": "timed out (tunnel wedge mid-run?)"})
            failed.append(step)
            continue
        lines = [l for l in proc.stdout.splitlines() if l.startswith("{")]
        rec = None
        if lines:
            try:
                rec = json.loads(lines[-1])
            except ValueError:
                rec = {"error": f"malformed JSON: {lines[-1][:120]!r}"}
        if rec is None:
            tail = proc.stderr.strip().splitlines()
            rec = {"error": tail[-1] if tail else "no JSON"}
        rec["step"] = step
        rec["rc"] = proc.returncode
        append(rec)
        if proc.returncode != 0 or "error" in rec:
            failed.append(step)
        log(f"  -> depth {depth}: qps={rec.get('qps')} "
            f"p99={rec.get('p99_ms')}ms errors={rec.get('errors')}")
    return failed


def run_loadgen_sweep(engine_dir: str, duration_s: float,
                      concurrency: int, tag: str = "") -> list:
    """Deploy the engine at each pipeline depth, hammer it, undeploy.
    Returns the step names that errored (for the exit-code roll-up)."""
    import urllib.request

    failed = []
    env = _engine_env(engine_dir)
    pio = os.path.join(REPO, "bin", "pio")
    for depth in (1, 2, 4, 8):
        step = f"loadgen_depth{depth}{tag}"
        port = _free_port()
        log(f"loadgen sweep: deploying depth={depth} on :{port}")
        rc = subprocess.run(
            [pio, "deploy", "--engine-dir", engine_dir,
             "--port", str(port), "--batch-pipeline-depth", str(depth),
             "--spawn"],
            cwd=engine_dir, capture_output=True, text=True, env=env,
        ).returncode
        if rc != 0:
            append({"step": step, "error": f"deploy failed rc={rc}"})
            failed.append(step)
            continue
        up = False
        for _ in range(60):
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/", timeout=2
                ).read()
                up = True
                break
            except Exception:
                # pio: lint-ok[robust-bare-sleep-retry] readiness poll of a local spawn at a fixed 1 s cadence (60 s budget); one waiter, so jitter has nothing to spread
                time.sleep(1)
        try:
            if not up:
                append({"step": step, "error": "server never came up"})
                failed.append(step)
                continue
            time.sleep(3)  # let the first-query compile settle
            proc = subprocess.run(
                [sys.executable, "-m", "predictionio_tpu.tools.loadgen",
                 "--url", f"http://127.0.0.1:{port}/queries.json",
                 "--concurrency", str(concurrency),
                 "--duration", str(duration_s)],
                cwd=REPO, capture_output=True, text=True, timeout=600,
            )
            lines = [
                l for l in proc.stdout.splitlines() if l.startswith("{")
            ]
            try:
                rec = (
                    json.loads(lines[-1]) if lines
                    else {"error": "no loadgen JSON"}
                )
            except ValueError:
                rec = {"error": f"malformed JSON: {lines[-1][:120]!r}"}
            rec["step"] = step
            rec["rc"] = proc.returncode
            append(rec)
            if proc.returncode != 0 or "error" in rec:
                failed.append(step)
            log(f"  -> depth {depth}: qps={rec.get('qps')} "
                f"p99={rec.get('p99_ms')}ms errors={rec.get('errors')}")
        except subprocess.TimeoutExpired:
            append({"step": step, "error": "loadgen timed out"})
            failed.append(step)
        finally:
            subprocess.run(
                [pio, "undeploy", "--port", str(port)],
                capture_output=True,
            )
            time.sleep(1)
    return failed


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--skip-loadgen", action="store_true")
    ap.add_argument("--engine-dir", default=None,
                    help="trained engine project for the loadgen sweep "
                         "(e.g. a movielens_quickstart workdir's engine/); "
                         "omitting it skips the sweep with instructions")
    ap.add_argument("--engine-dir-big", default=None,
                    help="trained BIG-catalog engine (60k+ items — "
                         "streaming-top-k territory) for an additional "
                         "loadgen pass at the catalog shapes the serving "
                         "claims are priced at")
    ap.add_argument("--loadgen-duration", type=float, default=15.0)
    ap.add_argument("--loadgen-concurrency", type=int, default=128)
    ap.add_argument("--iterations", default=None,
                    help="override BENCH_ITERATIONS")
    ap.add_argument("--repeats", type=int, default=3,
                    help="baseline bench repeat count (run-to-run spread)")
    ap.add_argument("--tier", choices=["a", "b", "all"], default="all",
                    help="a: golden-window records only (≤5 min of device "
                         "time — one f32 baseline + fused_smoke + "
                         "mesh_pallas), so a short tunnel window still "
                         "yields the headline evidence; b: everything "
                         "else, reusing tier-A records younger than 6 h; "
                         "all: both inline (the pre-tier behavior)")
    args = ap.parse_args()

    sys.path.insert(0, REPO)
    import bench

    from predictionio_tpu.utils.jax_cache import enable_compilation_cache

    # sets JAX_COMPILATION_CACHE_DIR in os.environ, so every subprocess
    # leg below (bench runs, _reval_steps, deploys, loadgen) inherits it
    # and only the first compiler of each program pays inside the window
    cache_dir = enable_compilation_cache()
    if cache_dir:
        log(f"persistent compilation cache: {cache_dir}")

    status = bench.probe_device(timeout_s=120)
    if status != "ok":
        log(f"device probe: {status} — aborting (nothing written)")
        return 2

    base_env: dict = {
        # the queue runs bench.py ~8x; cache the deterministic synthetic
        # dataset so generation cost is paid once, not per run
        "BENCH_SYNTH_CACHE": os.environ.get(
            "BENCH_SYNTH_CACHE", "/tmp/pio-bench-synth"
        ),
    }
    if args.iterations:
        base_env["BENCH_ITERATIONS"] = str(args.iterations)

    failures: list = []

    def _track(rec: dict) -> dict:
        """A step that timed out or errored must surface in the exit
        code: the watcher keeps watching on rc!=0, and a tier-B run that
        reused its baseline but then lost the device to a re-wedge would
        otherwise report 'complete' with nothing measured."""
        if rec.get("rc") != 0 or "error" in rec:
            failures.append(rec.get("step"))
        return rec

    def _reused(rec: dict) -> dict:
        """Tag + report a tier-A record reused instead of re-measured.
        The evidence file gets an explicit marker under a DISTINCT step
        name (so ``_recent`` can never mistake the marker for a fresh
        measurement and chain reuse past the 6 h window), and the
        in-memory record carries ``reused=True`` so downstream
        aggregation — the baseline_variance spread — can tell a
        cross-window leg from one measured in this invocation."""
        now = time.time()
        append({
            "step": "reused_tier_a_record",
            "of": rec.get("step"),
            "source_t_unix": rec.get("t_unix"),
            "age_s": round(now - float(rec.get("t_unix", now)), 1),
        })
        return {**rec, "reused": True}

    def step_once(step: str) -> dict:
        """Tier B reuses a recent (≤6 h, successful) tier-A record for
        ``step`` rather than re-spending device time; everything else
        runs it. A failed/timed-out record (rc!=0) is never reused —
        the step gets a fresh chance on the healthy device."""
        if args.tier == "b":
            rec = _recent(step)
            if rec is not None and rec.get("rc") == 0:
                log(f"reusing recent {step} record (t_unix="
                    f"{rec.get('t_unix')})")
                return _reused(rec)
        return _track(run_step(step))

    baseline = None
    if args.tier == "b":
        rec = _recent("baseline_f32")
        # the reused record must have been measured under THIS run's
        # bench config — a gate computed from a different scale or
        # iteration count would quietly invalidate every A/B verdict
        want_scale = float(os.environ.get("BENCH_SCALE", "1.0"))
        want_iters = int(
            args.iterations or os.environ.get("BENCH_ITERATIONS", "10")
        )
        if (rec is not None and rec.get("rc") == 0
                and "fallback" not in rec and "holdout_rmse" in rec
                and float(rec.get("scale", -1.0)) == want_scale
                and int(rec.get("iterations", -1)) == want_iters):
            baseline = _reused(rec)
            log(f"tier B: reusing tier-A baseline "
                f"({rec.get('value')}s, rmse {rec.get('holdout_rmse')})")
    if baseline is None:
        baseline = run_bench("baseline_f32", dict(base_env))
        if baseline.get("rc") != 0 or "fallback" in baseline:
            log("baseline failed or fell back; aborting the A/B chain")
            return 1

    if args.tier == "a":
        # the two never-compiled-kernel verdicts are the other
        # highest-information records; then stop — tier B's repeats and
        # sweeps are exactly what a short window cannot afford. A step
        # that timed out/errored makes tier A rc=1: the watcher must NOT
        # launch tier B into a tunnel that just wedged mid-step.
        _track(run_step("fused_smoke"))
        _track(run_step("mesh_pallas"))
        if failures:
            log(f"tier A done with FAILED steps {failures}; "
                f"evidence in {OUT}")
            return 1
        log(f"tier A complete; evidence in {OUT}")
        return 0

    gate = float(baseline["holdout_rmse"]) + RMSE_GATE_DELTA

    # repeat runs: the prior last-good number was a single leg whose first
    # iteration included compile; record spread + steady-state separately.
    # The spread is a WITHIN-window statistic — a tier-B baseline reused
    # from an earlier tier-A window (possibly hours old) would fold
    # window-to-window drift into it, so only legs measured in this
    # invocation enter the aggregate.
    repeats = [] if baseline.get("reused") else [baseline]
    for rep in range(2, max(1, args.repeats) + 1):
        rec = _track(run_bench(f"baseline_f32_r{rep}", dict(base_env)))
        if rec.get("rc") == 0 and "fallback" not in rec:
            repeats.append(rec)
    if len(repeats) > 1:
        trains = [float(r["value"]) for r in repeats]
        steadies = [
            float(sum(r["iteration_s"][1:]) / len(r["iteration_s"][1:]))
            for r in repeats if len(r.get("iteration_s", [])) > 1
        ]
        append({
            "step": "baseline_variance",
            "runs": len(repeats),
            "reused_baseline_excluded": bool(baseline.get("reused")),
            "train_s": trains,
            "train_s_spread": round(max(trains) - min(trains), 3),
            "steady_iter_s": [round(s, 4) for s in steadies],
            "bucketize_stage_s": [
                r.get("bucketize_stage_s") for r in repeats
            ],
        })


    def gated(step: str, env: dict) -> dict:
        # _track: an rc!=0/timeout leg is a failure; a leg that merely
        # FAILS the RMSE gate is a completed measurement, not a failure
        rec = _track(run_bench(step, {**base_env, **env}))
        ok = (
            rec.get("rc") == 0
            and "fallback" not in rec
            and float(rec.get("holdout_rmse", 9.9)) <= gate
        )
        rec["rmse_gate"] = "pass" if ok else "FAIL"
        append({"step": f"{step}_gate", "gate": rec["rmse_gate"],
                "threshold": round(gate, 4)})
        return rec

    bf16 = gated("bf16_gather", {"BENCH_GATHER_DTYPE": "bf16"})
    srt = gated("sort_gather", {"BENCH_SORT_GATHER": "1"})
    if bf16.get("rmse_gate") == "pass" and srt.get("rmse_gate") == "pass":
        gated("bf16_plus_sort",
              {"BENCH_GATHER_DTYPE": "bf16", "BENCH_SORT_GATHER": "1"})

    # Never-compiled paths only AFTER the proven-lever evidence is on
    # disk: a Mosaic experiment that wedges the tunnel must not cost the
    # bf16/sort measurements (rounds 2-3 each lost their whole window).
    # fused_smoke's verdict gates the full-scale fused A/B. (Under
    # --tier b these two were usually already run by tier A.)
    fused_smoke = step_once("fused_smoke")
    step_once("mesh_pallas")
    _track(run_step("dispatch_bench"))
    _track(run_step("flash_pallas"))
    # real profiler trace of the two hot paths: op-level device timings
    # for the HBM-utilization story (summary lands in the evidence file,
    # full trace stays under PIO_PROFILE_DIR for TensorBoard)
    _track(run_step("profile_trace", timeout_s=1200))
    fused = None
    if fused_smoke.get("ok"):
        fused = gated("fused_gather", {"BENCH_FUSED_GATHER": "1"})
        if fused.get("rmse_gate") == "pass" and bf16.get("rmse_gate") == "pass":
            # composability check, NOT a byte saving: the fused kernel
            # upcasts bf16 tables (per-row DMA floor is 128 lanes × 32
            # bits — see gramian_fused), so this leg measures fused at
            # f32 table width with bf16 gathers everywhere else
            gated("fused_plus_bf16",
                  {"BENCH_FUSED_GATHER": "1", "BENCH_GATHER_DTYPE": "bf16"})
    else:
        append({"step": "fused_gather", "skipped":
                "fused_smoke failed or did not run — Mosaic lowering "
                "unvalidated, full-scale A/B withheld"})

    # Implicit-mode quality gate (VERDICT r4 item 5): levers that passed
    # the EXPLICIT RMSE gate must also clear a ranking-metric gate on the
    # implicit path before any default flip — explicit evidence alone
    # cannot certify Hu-Koren confidence weighting.
    passed_levers = {}
    if bf16.get("rmse_gate") == "pass":
        passed_levers["BENCH_GATHER_DTYPE"] = "bf16"
    if srt.get("rmse_gate") == "pass":
        passed_levers["BENCH_SORT_GATHER"] = "1"
    if fused is not None and fused.get("rmse_gate") == "pass":
        passed_levers["BENCH_FUSED_GATHER"] = "1"
    if passed_levers:
        # gather dtype is ALWAYS explicit: the step's standalone default
        # is bf16, which must not leak in when bf16 just FAILED its gate
        # and only sort/fused are under certification
        _track(run_step(
            "implicit_gate", timeout_s=1800,
            env_extra={"BENCH_GATHER_DTYPE": "f32", **passed_levers},
        ))
    else:
        append({"step": "implicit_gate", "skipped":
                "no lever passed the explicit RMSE gate; nothing to "
                "certify for implicit mode"})

    if args.skip_loadgen:
        pass
    else:
        if args.engine_dir:
            failures += run_loadgen_sweep(
                args.engine_dir, args.loadgen_duration,
                args.loadgen_concurrency,
            )
            failures += run_inprocess_sweep(
                args.engine_dir, args.loadgen_duration,
                args.loadgen_concurrency,
            )
        if args.engine_dir_big:
            # independent of --engine-dir: the big-catalog pass alone is
            # a valid (and sometimes the only wanted) measurement
            failures += run_loadgen_sweep(
                args.engine_dir_big, args.loadgen_duration,
                args.loadgen_concurrency, tag="_big",
            )
            failures += run_inprocess_sweep(
                args.engine_dir_big, args.loadgen_duration,
                args.loadgen_concurrency, tag="_big",
            )
        if not (args.engine_dir or args.engine_dir_big):
            log("loadgen sweep skipped: pass --engine-dir <trained engine "
                "project> (e.g. run examples/movielens_quickstart/run.sh "
                "once, then point at <workdir>/engine)")

    if failures:
        # rc=1 keeps the watcher alive for another window: completed
        # records are on disk, but the matrix is not done
        log(f"done with FAILED/timed-out steps {failures}; evidence in {OUT}")
        return 1
    log(f"done; evidence in {OUT}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
