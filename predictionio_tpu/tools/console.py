"""The ``pio`` console: operator CLI for the whole framework.

Rebuild of ``tools/src/main/scala/io/prediction/tools/console/Console.scala``
(scopt grammar ``:122-558``, dispatch ``:582-644``) plus the app/accesskey
consoles (``console/{App,AccessKey}.scala``).  Subcommands:

    app new|list|show|delete|data-delete
    accesskey new|list|delete
    build                      — verify + register the engine project
    train | eval               — run the training / evaluation workflow
    deploy | undeploy          — query server lifecycle (undeploy = GET /stop)
    eventserver | dashboard    — REST servers
    status                     — storage verification (Storage.scala:230-250)
    export | import            — events ↔ JSON-lines files
    template list|get          — bundled + remote engine templates

Process model: the reference launches train/deploy as separate JVMs via
spark-submit (``RunWorkflow.scala:103-169``); here ``--spawn`` runs them as
``python -m predictionio_tpu.tools.run_workflow`` / ``run_server`` child
processes with the same metadata-store handshake, and the default is
in-process (the simplification called out in SURVEY §7).
"""

from __future__ import annotations

import argparse
import json
import os
import secrets
import subprocess
import sys
import time
import urllib.error
import urllib.request
from typing import List, Optional, Sequence

from ..storage import StorageRegistry, get_registry
from ..storage.metadata import AccessKey, App
from . import register as register_mod
from . import run_server, run_workflow

EXIT_OK = 0
EXIT_FAIL = 1


# ---------------------------------------------------------------------------
# app / accesskey consoles (console/App.scala, console/AccessKey.scala)
# ---------------------------------------------------------------------------


def app_new(
    registry: StorageRegistry,
    name: str,
    app_id: Optional[int] = None,
    access_key: Optional[str] = None,
    description: Optional[str] = None,
) -> dict:
    """``pio app new`` (``App.scala:33-77``): create app, init its event
    store, mint a default access key valid for all events."""
    md = registry.get_metadata()
    if md.app_get_by_name(name) is not None:
        raise ValueError(f"App {name!r} already exists")
    new_id = md.app_insert(
        App(id=app_id or 0, name=name, description=description)
    )
    if new_id is None:
        raise ValueError(f"Could not create app {name!r} (id conflict?)")
    registry.get_events().init(new_id)
    key = access_key or secrets.token_urlsafe(32)
    md.access_key_insert(AccessKey(key=key, appid=new_id, events=()))
    return {"name": name, "id": new_id, "accessKey": key}


def app_list(registry: StorageRegistry) -> List[dict]:
    md = registry.get_metadata()
    out = []
    for app in sorted(md.app_get_all(), key=lambda a: a.name):
        keys = [ak.key for ak in md.access_key_get_by_app(app.id)]
        out.append({"name": app.name, "id": app.id, "accessKeys": keys})
    return out


def app_show(registry: StorageRegistry, name: str) -> dict:
    md = registry.get_metadata()
    app = md.app_get_by_name(name)
    if app is None:
        raise KeyError(f"App {name!r} not found")
    keys = [
        {"key": ak.key, "events": list(ak.events)}
        for ak in md.access_key_get_by_app(app.id)
    ]
    return {
        "name": app.name,
        "id": app.id,
        "description": app.description,
        "accessKeys": keys,
    }


def app_delete(registry: StorageRegistry, name: str) -> dict:
    """``pio app delete``: remove app + keys + event data (``App.scala:79-120``)."""
    md = registry.get_metadata()
    app = md.app_get_by_name(name)
    if app is None:
        raise KeyError(f"App {name!r} not found")
    registry.get_events().remove(app.id)
    for ak in md.access_key_get_by_app(app.id):
        md.access_key_delete(ak.key)
    md.app_delete(app.id)
    return {"name": name, "id": app.id, "deleted": True}


def app_data_delete(registry: StorageRegistry, name: str) -> dict:
    """``pio app data-delete``: wipe + re-init the app's event store
    (``App.scala:122-141``)."""
    md = registry.get_metadata()
    app = md.app_get_by_name(name)
    if app is None:
        raise KeyError(f"App {name!r} not found")
    ev = registry.get_events()
    ev.remove(app.id)
    ev.init(app.id)
    return {"name": name, "id": app.id, "dataDeleted": True}


def accesskey_new(
    registry: StorageRegistry,
    app_name: str,
    events: Sequence[str] = (),
    key: Optional[str] = None,
) -> dict:
    md = registry.get_metadata()
    app = md.app_get_by_name(app_name)
    if app is None:
        raise KeyError(f"App {app_name!r} not found")
    new_key = key or secrets.token_urlsafe(32)
    md.access_key_insert(AccessKey(key=new_key, appid=app.id, events=tuple(events)))
    return {"app": app_name, "accessKey": new_key, "events": list(events)}


def accesskey_list(
    registry: StorageRegistry, app_name: Optional[str] = None
) -> List[dict]:
    md = registry.get_metadata()
    apps = (
        [a for a in [md.app_get_by_name(app_name)] if a is not None]
        if app_name
        else md.app_get_all()
    )
    out = []
    for app in apps:
        for ak in md.access_key_get_by_app(app.id):
            out.append(
                {"key": ak.key, "app": app.name, "events": list(ak.events)}
            )
    return out


def accesskey_delete(registry: StorageRegistry, key: str) -> dict:
    if not registry.get_metadata().access_key_delete(key):
        raise KeyError(f"Access key {key!r} not found")
    return {"accessKey": key, "deleted": True}


# ---------------------------------------------------------------------------
# undeploy / status (Console.scala:798-824, :930-986)
# ---------------------------------------------------------------------------


def undeploy(ip: str = "localhost", port: int = 8000) -> dict:
    """HTTP GET /stop against a running query server."""
    url = f"http://{ip}:{port}/stop"
    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            return {"url": url, "status": resp.status}
    except (urllib.error.URLError, OSError) as exc:
        raise RuntimeError(f"Nothing to undeploy at {url}: {exc}") from exc


def status(registry: StorageRegistry) -> dict:
    """``pio status``: verify every storage repository with live operations."""
    results = registry.verify_all_data_objects()
    return {"storage": results, "ok": all(results.values())}


# ---------------------------------------------------------------------------
# rollout console (docs/rollouts.md) — thin HTTP client over the query
# server's /rollout routes, like undeploy over /stop
# ---------------------------------------------------------------------------


def _rollout_request(
    ip: str, port: int, method: str, path: str, body: Optional[dict] = None
) -> dict:
    url = f"http://{ip}:{port}{path}"
    data = json.dumps(body).encode("utf-8") if body is not None else None
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return json.loads(resp.read().decode("utf-8"))
    except urllib.error.HTTPError as exc:
        raw = exc.read().decode("utf-8", "replace")
        try:
            message = json.loads(raw).get("message", raw)
        except ValueError:
            message = raw
        raise RuntimeError(
            f"query server answered {exc.code}: {message}"
        ) from exc
    except (urllib.error.URLError, OSError) as exc:
        raise RuntimeError(f"no query server at {url}: {exc}") from exc


def continuous_command(args: argparse.Namespace) -> dict:
    """``pio continuous start|status|pause|trigger`` — thin HTTP client
    over the query server's /continuous routes (docs/continuous.md)."""
    sub = args.continuous_command
    if sub == "status":
        return _rollout_request(args.ip, args.port, "GET", "/continuous.json")
    body: dict = {}
    if sub == "trigger" and args.full:
        body["full"] = True
    return _rollout_request(
        args.ip, args.port, "POST", f"/continuous/{sub}", body
    )


def rollout_command(args: argparse.Namespace) -> dict:
    """``pio rollout start|status|promote|abort``."""
    sub = args.rollout_command
    if sub == "start":
        gates = {}
        for item in args.gate:
            key, sep, value = item.partition("=")
            if not sep:
                raise ValueError(f"bad --gate {item!r}: expected KEY=VALUE")
            gates[key.strip()] = float(value)
        body: dict = {}
        if args.instance_id:
            body["instanceId"] = args.instance_id
        if args.percent is not None:
            body["percent"] = args.percent
        if gates:
            body["gates"] = gates
        return _rollout_request(args.ip, args.port, "POST", "/rollout/start", body)
    if sub == "status":
        return _rollout_request(args.ip, args.port, "GET", "/rollout.json")
    if sub == "promote":
        return _rollout_request(
            args.ip, args.port, "POST", "/rollout/promote",
            {"reason": args.reason},
        )
    return _rollout_request(
        args.ip, args.port, "POST", "/rollout/abort", {"reason": args.reason}
    )


def migrate_command(args: argparse.Namespace) -> int:
    """``pio migrate start|pump|status|cutover|abort`` — drives one
    :class:`~predictionio_tpu.storage.migration.PartitionMigration`
    over its durable state dir (docs/storage.md#live-migration). Every
    invocation is a fresh coordinator instance resuming from the files;
    ``pump`` is the bounded tick an operator (or cron) repeats until
    ``status`` reports the watermark ok, then ``cutover`` flips."""
    from ..storage.migration import open_migration

    sub = args.migrate_command
    mig = open_migration(
        args.state,
        old_url=getattr(args, "old", "") or "",
        new_url=getattr(args, "new", "") or "",
    )
    try:
        if sub == "start":
            _emit(mig.start())
        elif sub == "pump":
            rounds = [
                mig.pump(max_ops=args.max_ops)
                for _ in range(max(1, args.rounds))
            ]
            _emit({"rounds": rounds, "status": mig.status()})
        elif sub == "status":
            out = mig.status()
            if mig.mirroring():
                out["watermark"] = mig.watermark()
            _emit(out)
        elif sub == "cutover":
            _emit(mig.cutover(timeout_s=args.timeout))
        elif sub == "abort":
            _emit(mig.abort(args.reason))
        return EXIT_OK
    finally:
        mig.close()


def autoscale_command(args: argparse.Namespace) -> int:
    """``pio autoscale --signals FILE [--ticks N] [--execute]`` — run
    the :class:`~predictionio_tpu.fleet.autoscale.FleetAutoscaler`
    control loop over a signals snapshot and print every decision
    (docs/robustness.md#autoscaler). Dry-run unless ``--execute``; the
    CLI wires no actuator, so even executed runs emit recommendations —
    the posture still flips the ``dry_run`` label on the counter and
    the ledger, which is what the drill pins."""
    from ..fleet.autoscale import (
        AutoscaleConfig,
        FleetAutoscaler,
        signals_from_dict,
    )

    with open(args.signals, encoding="utf-8") as fh:
        signals = signals_from_dict(json.load(fh))
    config = AutoscaleConfig.from_env(
        **({"dry_run": False} if args.execute else {})
    )
    scaler = FleetAutoscaler(config)
    actions = []
    for _ in range(max(1, args.ticks)):
        for action in scaler.observe(signals):
            actions.append(action.to_json())
    _emit({
        "dryRun": config.dry_run,
        "ticks": scaler.tick_count,
        "actions": actions,
        "decisions": scaler.decisions(),
    })
    return EXIT_OK


# ---------------------------------------------------------------------------
# CLI grammar + dispatch
# ---------------------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="pio", description="PredictionIO-TPU operator console"
    )
    sub = p.add_subparsers(dest="command", required=True)

    app = sub.add_parser("app", help="manage apps")
    app_sub = app.add_subparsers(dest="app_command", required=True)
    ap_new = app_sub.add_parser("new")
    ap_new.add_argument("name")
    ap_new.add_argument("--id", type=int, default=None)
    ap_new.add_argument("--access-key", default=None)
    ap_new.add_argument("--description", default=None)
    app_sub.add_parser("list")
    for nm in ("show", "delete", "data-delete"):
        sp = app_sub.add_parser(nm)
        sp.add_argument("name")
        if nm != "show":
            sp.add_argument("--force", "-f", action="store_true")

    ak = sub.add_parser("accesskey", help="manage access keys")
    ak_sub = ak.add_subparsers(dest="accesskey_command", required=True)
    ak_new = ak_sub.add_parser("new")
    ak_new.add_argument("app_name")
    ak_new.add_argument("events", nargs="*")
    ak_list = ak_sub.add_parser("list")
    ak_list.add_argument("app_name", nargs="?", default=None)
    ak_del = ak_sub.add_parser("delete")
    ak_del.add_argument("key")

    build = sub.add_parser("build", help="verify + register engine project")
    build.add_argument("--engine-dir", default=".")

    train = sub.add_parser("train", help="run the training workflow")
    for flag, kw in _WORKFLOW_FLAGS:
        train.add_argument(flag, **kw)
    train.add_argument("--spawn", action="store_true")

    ev = sub.add_parser("eval", help="run an evaluation")
    ev.add_argument("evaluation_class")
    ev.add_argument("engine_params_generator_class", nargs="?", default=None)
    for flag, kw in _WORKFLOW_FLAGS:
        ev.add_argument(flag, **kw)
    ev.add_argument("--spawn", action="store_true")

    dp = sub.add_parser("deploy", help="serve the latest trained instance")
    dp.add_argument("--engine-dir", default=".")
    dp.add_argument("--engine-instance-id", default=None)
    dp.add_argument("--ip", default="localhost")
    dp.add_argument("--port", type=int, default=8000)
    dp.add_argument("--feedback", action="store_true")
    dp.add_argument("--event-server-ip", default="localhost")
    dp.add_argument("--event-server-port", type=int, default=7070)
    dp.add_argument("--accesskey", default=None)
    dp.add_argument("--batch", default="")
    dp.add_argument("--log-url", default=None,
                    help="POST serving errors here (CreateServer --log-url)")
    dp.add_argument("--batch-max", type=int, default=None,
                    help="micro-batch size cap (size to catalog and depth)")
    dp.add_argument("--batch-pipeline-depth", type=int, default=None,
                    help="batches in flight at once (default 2)")
    dp.add_argument("--shard-index", type=int, default=None, metavar="I",
                    help="serve item-factor shard I of --shard-count "
                    "behind a `pio router --sharded` tier (docs/fleet.md)")
    dp.add_argument("--shard-count", type=int, default=None, metavar="N",
                    help="total item-factor shards (1 = unsharded)")
    dp.add_argument("--continuous-app", type=int, default=None,
                    metavar="APP_ID",
                    help="attach the continuous-learning loop for this app "
                    "(docs/continuous.md)")
    dp.add_argument("--continuous-feed", default=None, metavar="URL",
                    help="storage primary to tail for the continuous loop")
    dp.add_argument("--spawn", action="store_true")

    ud = sub.add_parser("undeploy", help="stop a running query server")
    ud.add_argument("--ip", default="localhost")
    ud.add_argument("--port", type=int, default=8000)

    ro = sub.add_parser(
        "rollout",
        help="staged deploys against a running query server: shadow -> "
        "canary -> live with metric gates (docs/rollouts.md)",
    )
    ro_sub = ro.add_subparsers(dest="rollout_command", required=True)
    ro_start = ro_sub.add_parser(
        "start", help="load a candidate instance and enter SHADOW"
    )
    ro_start.add_argument(
        "--instance-id", default=None,
        help="candidate engine instance (default: latest COMPLETED newer "
        "than the deployed baseline)",
    )
    ro_start.add_argument(
        "--percent", type=float, default=None,
        help="canary traffic share (default 10)",
    )
    ro_start.add_argument(
        "--gate", action="append", default=[], metavar="KEY=VALUE",
        help="gate override, repeatable (window_s, min_samples, "
        "max_error_rate_delta, max_p99_latency_ratio, max_divergence, "
        "shadow_hold_s, canary_hold_s, canary_percent)",
    )
    ro_sub.add_parser("status", help="active plan, windows, gate verdict")
    ro_prom = ro_sub.add_parser(
        "promote", help="advance one stage regardless of gates"
    )
    ro_prom.add_argument("--reason", default="manual promote")
    ro_abort = ro_sub.add_parser(
        "abort", help="retire the candidate; baseline takes 100%%"
    )
    ro_abort.add_argument("--reason", default="manual abort")
    for sp in (ro_start, ro_prom, ro_abort) + tuple(
        [ro_sub.choices["status"]]
    ):
        sp.add_argument("--ip", default="localhost")
        sp.add_argument("--port", type=int, default=8000)

    co = sub.add_parser(
        "continuous",
        help="continuous-learning loop on a running query server: "
        "changefeed-driven fold-in training with automatic rollout "
        "submission (docs/continuous.md)",
    )
    co_sub = co.add_subparsers(dest="continuous_command", required=True)
    co_start = co_sub.add_parser(
        "start", help="(re)start the background watch/train loop"
    )
    co_sub.add_parser(
        "status", help="cursor, feed lag, pending delta, last cycle"
    )
    co_pause = co_sub.add_parser(
        "pause", help="stop triggering cycles (the cursor keeps its place)"
    )
    co_trig = co_sub.add_parser(
        "trigger", help="force a training cycle on the next tick"
    )
    co_trig.add_argument(
        "--full", action="store_true",
        help="force a full retrain instead of fold-in",
    )
    for sp in (co_start, co_pause, co_trig, co_sub.choices["status"]):
        sp.add_argument("--ip", default="localhost")
        sp.add_argument("--port", type=int, default=8000)

    rt = sub.add_parser(
        "router",
        help="serving-fleet router tier: fronts N query servers with "
        "consistent routing, per-app quotas, replica failover and "
        "sharded-model top-k merge (docs/fleet.md)",
    )
    rt.add_argument("--ip", default="localhost")
    rt.add_argument("--port", type=int, default=8700)
    rt.add_argument(
        "--backends", required=True, metavar="HOST:PORT,...",
        help="query servers to front; in --sharded mode position i must "
        "serve shard i of N",
    )
    rt.add_argument(
        "--sharded", action="store_true",
        help="scatter/gather mode: each backend holds one item-factor "
        "partition, answers merge into the exact global top-k",
    )
    rt.add_argument(
        "--replicas-per-shard", type=int, default=1, metavar="R",
        help="with --sharded: every R consecutive backends serve one "
        "shard (backend i serves shard i//R) and a shard leg fails "
        "over inside its replica group — a sharded fleet survives a "
        "backend kill (docs/fleet.md#replicas-per-shard)",
    )
    rt.add_argument(
        "--no-cache", action="store_true",
        help="disable the router response cache (docs/fleet.md#cache; "
        "default on, PIO_ROUTER_CACHE=0 also disables)",
    )
    rt.add_argument(
        "--cache-ttl", type=float, default=None, metavar="S",
        help="response-cache TTL backstop in seconds (default "
        "PIO_ROUTER_CACHE_TTL_S or 30; correctness comes from "
        "rollout/model epoch invalidation, not the TTL)",
    )
    rt.add_argument(
        "--cache-max-entries", type=int, default=None, metavar="N",
        help="response-cache LRU bound (default PIO_ROUTER_CACHE_MAX "
        "or 2048)",
    )
    rt.add_argument(
        "--quota", action="append", default=[], metavar="APP=N",
        help="per-app in-flight cap (X-PIO-App header), repeatable",
    )
    rt.add_argument(
        "--default-quota", type=int, default=0,
        help="in-flight cap for apps without an explicit --quota "
        "(0 = unbounded)",
    )
    rt.add_argument("--timeout", type=float, default=10.0,
                    help="per-backend-leg socket timeout (seconds)")
    rt.add_argument(
        "--engine-id", default=None,
        help="engine whose active rollout plan the variant-consistency "
        "check mirrors (default: discovered from the latest completed "
        "instance)",
    )
    rt.add_argument("--engine-version", default=None)
    rt.add_argument("--engine-variant", default="engine.json")
    rt.add_argument(
        "--shared-cache", default=None, metavar="HOST:PORT",
        help="consult a `pio sharedcache` sidecar between the local LRU "
        "and the backend fan-out (docs/fleet.md#shared-cache-tier; "
        "advisory by construction — any doubt is a miss, killing the "
        "sidecar degrades to per-router caching; also "
        "PIO_ROUTER_SHARED_CACHE)",
    )
    rt.add_argument(
        "--meta-feed", default=None, metavar="URL",
        help="storage-server base URL whose metadata changefeed pushes "
        "epoch invalidations (docs/fleet.md#shared-cache-tier; the "
        "plan poll stretches to a watchdog while the subscription is "
        "live; also PIO_ROUTER_META_FEED)",
    )
    rt.add_argument(
        "--no-hedge", action="store_true",
        help="disable tail-latency request hedging (docs/fleet.md"
        "#hedging; default on, PIO_ROUTER_HEDGE=0 also disables)",
    )

    sc = sub.add_parser(
        "sharedcache",
        help="shared response-cache sidecar for a router fleet: one "
        "epoch-checked LRU every `pio router --shared-cache` replica "
        "consults before fanning out (docs/fleet.md#shared-cache-tier)",
    )
    sc.add_argument("--ip", default="localhost")
    sc.add_argument("--port", type=int, default=8800)
    sc.add_argument(
        "--max-entries", type=int, default=8192, metavar="N",
        help="LRU bound (default 8192)",
    )
    sc.add_argument(
        "--ttl", type=float, default=30.0, metavar="S",
        help="entry TTL backstop in seconds (default 30; correctness "
        "comes from epoch checks, not the TTL)",
    )

    es = sub.add_parser("eventserver", help="run the event REST server")
    es.add_argument("--ip", default="localhost")
    es.add_argument("--port", type=int, default=7070)
    es.add_argument("--stats", action="store_true")

    db = sub.add_parser("dashboard", help="run the evaluation dashboard")
    db.add_argument("--ip", default="localhost")
    db.add_argument("--port", type=int, default=9000)
    db.add_argument(
        "--nodes", default="", metavar="HOST:PORT,...",
        help="fleet nodes the /fleet panel scrapes",
    )

    ss = sub.add_parser(
        "storageserver",
        help="serve this host's storage backends over HTTP (type=remote peer)",
    )
    ss.add_argument("--ip", default="localhost")
    ss.add_argument("--port", type=int, default=7079)
    ss.add_argument(
        "--replica-of", default=None, metavar="URL",
        help="run as a warm-standby replica tailing URL's changefeed: "
             "serves reads, rejects writes with 409 + primary hint, "
             "reports lag on /status.json (docs/storage.md#replication)",
    )
    ss.add_argument(
        "--oplog-dir", default=None,
        help="changefeed op-log directory (primary mode; default "
             "$PIO_FS_BASEDIR/oplog)",
    )
    ss.add_argument(
        "--no-changefeed", action="store_true",
        help="primary mode without a changefeed (no replication, no "
             "X-PIO-Seq tokens) — the pre-ISSUE-3 behavior",
    )
    ss.add_argument(
        "--poll-interval", type=float, default=0.5,
        help="replica changefeed poll interval in seconds",
    )
    ss.add_argument(
        "--partition-index", type=int, default=0, metavar="I",
        help="this node's keyspace slot in a partitioned event store "
             "(docs/storage.md#partitioning): stamped into the oplog "
             "meta and enforced on every event write; replicas refuse "
             "to tail a primary declaring a different slot",
    )
    ss.add_argument(
        "--partition-count", type=int, default=1, metavar="N",
        help="total partitions of the event store (1 = unpartitioned)",
    )
    ss.add_argument(
        "--sync-every", type=int, default=None, metavar="N",
        help="oplog fsync cadence (primary mode; default 256): 1 = "
             "fsync before every ack, the strict power-loss-safe ack "
             "discipline",
    )

    sub.add_parser("status", help="verify storage backends")

    ex = sub.add_parser("export", help="export app events (json/parquet)")
    ex.add_argument("--appid", type=int, required=True)
    ex.add_argument("--output", required=True)
    ex.add_argument("--format", choices=("json", "parquet"), default="json")

    im = sub.add_parser("import", help="import events into an app (json/parquet)")
    im.add_argument("--appid", type=int, required=True)
    im.add_argument("--input", required=True)
    im.add_argument("--format", choices=("json", "parquet"), default="json")

    tp = sub.add_parser(
        "template",
        help="engine templates: bundled scaffolds + remote gallery "
        "(PIO_TEMPLATE_GALLERY_URL)",
    )
    tp_sub = tp.add_subparsers(dest="template_command", required=True)
    tp_sub.add_parser("list")
    tp_get = tp_sub.add_parser("get")
    tp_get.add_argument("template_name")
    tp_get.add_argument("directory")

    ln = sub.add_parser(
        "lint",
        help="TPU-hygiene static analysis (Mosaic + jit-boundary rules)",
        # the lint CLI owns its option surface (tools/lint.py) — forward
        # everything, -h included, so flags are defined exactly once
        add_help=False,
    )
    ln.add_argument("lint_args", nargs=argparse.REMAINDER)

    ck = sub.add_parser(
        "ckpt",
        help="checkpoint store: ls | verify | gc "
        "(docs/checkpoint.md#operator-surface)",
        # the ckpt CLI owns its option surface (ckpt/cli.py) — forwarded
        # verbatim like lint/perf
        add_help=False,
    )
    ck.add_argument("ckpt_args", nargs=argparse.REMAINDER)

    top = sub.add_parser(
        "top",
        help="fleet table: scrape GET /metrics from a node list "
        "(docs/observability.md)",
    )
    top.add_argument(
        "--nodes", default=None, metavar="HOST:PORT,...",
        help="nodes to scrape (default: localhost query/event/storage "
        "ports)",
    )
    top.add_argument("--json", action="store_true",
                     help="emit rows as JSON instead of the table")
    top.add_argument("--timeout", type=float, default=5.0)

    pf = sub.add_parser(
        "profile",
        help="compile/retrace + phase/roofline report: smoke train, "
        "live node, or completed instance "
        "(docs/observability.md#profiling)",
        # the profile CLI owns its option surface (tools/perf.py)
        add_help=False,
    )
    pf.add_argument("profile_args", nargs=argparse.REMAINDER)

    pp = sub.add_parser(
        "perf",
        help="durable perf ledger: `perf diff` regression gate, "
        "`perf trend` trajectory (docs/performance.md#perf-ledger)",
        add_help=False,
    )
    pp.add_argument("perf_args", nargs=argparse.REMAINDER)

    qa = sub.add_parser(
        "quality",
        help="model & data quality report: score drift (PSI), feedback "
        "hit-rate, ingest mix — from a live /metrics scrape or the "
        "quality-snapshot ledger; `--diff` is the CI drift gate "
        "(docs/observability.md#quality)",
        # the quality CLI owns its option surface (tools/quality.py)
        add_help=False,
    )
    qa.add_argument("quality_args", nargs=argparse.REMAINDER)

    tr = sub.add_parser(
        "trace",
        help="stitch one X-PIO-Trace id's spans across a node list "
        "(GET /traces.json)",
    )
    tr.add_argument("trace_id")
    tr.add_argument(
        "--nodes", default=None, metavar="HOST:PORT,...",
        help="nodes to query (default: localhost query/event/storage "
        "ports)",
    )
    tr.add_argument("--json", action="store_true",
                    help="emit raw spans as JSON")
    tr.add_argument("--timeout", type=float, default=5.0)

    mg = sub.add_parser(
        "migrate",
        help="live event-store partition migration: dual-write + "
        "backfill + watermark cutover with zero ingest downtime "
        "(docs/storage.md#live-migration)",
    )
    mg_sub = mg.add_subparsers(dest="migrate_command", required=True)
    mg_start = mg_sub.add_parser(
        "start", help="enter dual_write: every acked write mirrors to "
        "the new layout"
    )
    mg_start.add_argument(
        "--old", required=True, metavar="URL",
        help="current layout (pio+ha:// partition sets)",
    )
    mg_start.add_argument(
        "--new", required=True, metavar="URL",
        help="target layout (pio+ha:// partition sets, M partitions)",
    )
    mg_pump = mg_sub.add_parser(
        "pump", help="bounded coordinator ticks: drain the mirror "
        "queue, advance the backfill, promote to ready at the watermark"
    )
    mg_pump.add_argument("--rounds", type=int, default=1, metavar="N")
    mg_pump.add_argument("--max-ops", type=int, default=500, metavar="K",
                         help="queue entries / oplog ops per round")
    mg_status = mg_sub.add_parser(
        "status", help="phase, cursors, queue depth, per-keyspace "
        "watermark verdict"
    )
    mg_cut = mg_sub.add_parser(
        "cutover", help="freeze writes, final drain, verify the "
        "watermark per keyspace, flip reads+writes atomically"
    )
    mg_cut.add_argument("--timeout", type=float, default=30.0,
                        help="seconds the freeze may hold before the "
                        "cutover aborts (writes thaw, phase unchanged)")
    mg_abort = mg_sub.add_parser(
        "abort", help="abandon before the flip: mirror queue discarded, "
        "old layout stays the system of record, byte-identical"
    )
    mg_abort.add_argument("--reason", default="operator abort")
    for sp in (mg_start, mg_pump, mg_status, mg_cut, mg_abort):
        sp.add_argument(
            "--state", required=True, metavar="DIR",
            help="durable coordinator state dir (phase, queue, cursors)",
        )

    asc = sub.add_parser(
        "autoscale",
        help="SLO-driven fleet autoscaler: at most one bounded, "
        "hysteresis-damped action per tick, dry-run by default "
        "(docs/robustness.md#autoscaler)",
    )
    asc.add_argument(
        "--signals", required=True, metavar="FILE",
        help="JSON signals snapshot: replicasPerShard, partitionCount, "
        "firing, burn, breakerOpenBackends, shardPressure, partitionShed "
        "(docs/cli.md)",
    )
    asc.add_argument(
        "--ticks", type=int, default=1, metavar="N",
        help="control ticks over the snapshot (hysteresis needs "
        "sustained pressure: up_ticks consecutive hot ticks)",
    )
    asc.add_argument(
        "--execute", action="store_true",
        help="clear dry-run for this run (PIO_AUTOSCALE_DRY_RUN=0 "
        "equivalent); without a wired actuator actions stay "
        "recommendations",
    )

    up = sub.add_parser(
        "upgrade", help="migrate event data between storage backends"
    )
    up.add_argument("--from-type", required=True,
                    choices=("sqlite", "native"))
    up.add_argument("--from-path", required=True)
    up.add_argument("--to-type", required=True,
                    choices=("sqlite", "native"))
    up.add_argument("--to-path", required=True)
    up.add_argument("--appid", type=int, action="append", default=None,
                    help="app to migrate (repeatable; default: all apps)")
    return p


_WORKFLOW_FLAGS = [
    ("--engine-dir", {"default": "."}),
    ("--engine-variant", {"default": "engine.json"}),
    ("--engine-params-key", {"default": None}),
    ("--batch", {"default": ""}),
    ("--verbose", {"action": "store_true"}),
    ("--skip-sanity-check", {"action": "store_true"}),
    ("--stop-after-read", {"action": "store_true"}),
    ("--stop-after-prepare", {"action": "store_true"}),
    ("--eval-parallelism", {"type": int, "default": 0}),
    ("--shards", {"type": int, "default": None, "metavar": "N",
                  "help": "train with both factor tables sharded over N "
                          "devices (docs/distributed_training.md)"}),
    ("--checkpoint-every", {"type": int, "default": None, "metavar": "N",
                            "help": "checkpoint factor tables every N "
                                    "iterations (docs/checkpoint.md)"}),
    ("--resume", {"default": None,
                  "action": argparse.BooleanOptionalAction,
                  "help": "resume from the newest valid checkpoint "
                          "(default); --no-resume trains fresh "
                          "(docs/checkpoint.md)"}),
]


def _emit(obj) -> None:
    print(json.dumps(obj, indent=2, default=str))


def _spawn(module: str, argv: Sequence[str]) -> int:
    """Blocking child-process launch, the spark-submit analogue for batch
    runs — train/eval wait for completion (``RunWorkflow.scala:103-169``).

    The child gets an explicit platform environment (``jax_child_env``):
    a CPU-pinned parent produces a hard-pinned CPU child even when a
    sitecustomize boot hook would otherwise drag the child onto an
    accelerator backend (the spark-submit ``--env`` propagation analogue,
    ``RunWorkflow.scala:37-40,169``)."""
    from ..utils.platform import jax_child_env

    return subprocess.call(
        [sys.executable, "-m", module, *argv], env=jax_child_env()
    )


def _spawn_detached(module: str, argv: Sequence[str]) -> int:
    """Detached child-process launch for long-running servers: ``deploy
    --spawn`` returns with the server pid (the reference's RunServer child,
    ``RunServer.scala:77-126`` — its CLI parent exits and the driver JVM
    keeps serving; ``undeploy`` stops it over HTTP).

    The child's output goes to a log file under ``$PIO_FS_BASEDIR/logs``
    and a short liveness poll catches immediate failures (bad port, broken
    engine dir) instead of reporting a dead pid as success."""
    from ..storage.registry import base_dir

    log_dir = os.path.join(base_dir(), "logs")
    os.makedirs(log_dir, exist_ok=True)
    stamp = time.strftime("%Y%m%d-%H%M%S")
    log_path = os.path.join(log_dir, f"{module.rsplit('.', 1)[-1]}-{stamp}.log")
    from ..utils.platform import jax_child_env

    with open(log_path, "ab") as log_f:
        proc = subprocess.Popen(
            [sys.executable, "-m", module, *argv],
            start_new_session=True,
            stdout=log_f,
            stderr=subprocess.STDOUT,
            env=jax_child_env(),
        )
    # liveness poll: long enough to catch startup failures that surface
    # after the (slow) jax import; a healthy server costs the full window,
    # still far below the reference's spark-submit launch time.
    # PIO_SPAWN_POLL_S overrides (e.g. on heavily loaded hosts).
    deadline = time.monotonic() + float(os.environ.get("PIO_SPAWN_POLL_S", "4"))
    while time.monotonic() < deadline and proc.poll() is None:
        time.sleep(0.2)
    if proc.poll() is not None:
        with open(log_path, "rb") as f:
            tail = f.read()[-2000:].decode("utf-8", "replace")
        _emit({
            "error": f"spawned {module} exited immediately "
                     f"(code {proc.returncode})",
            "log": log_path,
            "log_tail": tail,
        })
        return EXIT_FAIL
    _emit({"spawned": module, "pid": proc.pid, "log": log_path})
    return EXIT_OK


def _workflow_argv(args: argparse.Namespace, extra: Sequence[str] = ()) -> List[str]:
    argv = [
        "--engine-dir", args.engine_dir,
        "--engine-variant", args.engine_variant,
        "--batch", args.batch,
    ]
    if args.engine_params_key:
        argv += ["--engine-params-key", args.engine_params_key]
    for flag in ("verbose", "skip_sanity_check", "stop_after_read", "stop_after_prepare"):
        if getattr(args, flag):
            argv.append("--" + flag.replace("_", "-"))
    if getattr(args, "eval_parallelism", 0):
        argv += ["--eval-parallelism", str(args.eval_parallelism)]
    if getattr(args, "shards", None) is not None:
        # forward an explicit 0 too: it must fail loudly in
        # resolve_shards, never silently train single-device
        argv += ["--shards", str(args.shards)]
    if getattr(args, "checkpoint_every", None) is not None:
        argv += ["--checkpoint-every", str(args.checkpoint_every)]
    if getattr(args, "resume", None) is not None:
        argv.append("--resume" if args.resume else "--no-resume")
    return argv + list(extra)


def main(
    argv: Optional[Sequence[str]] = None,
    registry: Optional[StorageRegistry] = None,
) -> int:
    from ..utils.platform import apply_env_platform

    import signal

    # `pio lint` forwards verbatim BEFORE argparse: the lint CLI owns its
    # whole option surface (tools/lint.py), argparse's REMAINDER cannot
    # capture leading --flags, and pure static analysis needs neither the
    # storage plane nor a jax import — it must work on an unconfigured
    # host.
    head = list(sys.argv[1:] if argv is None else argv)[:1]
    if head == ["lint"]:
        from . import lint as lint_mod

        tail = list(sys.argv[2:] if argv is None else argv[1:])
        return lint_mod.main(tail)
    if head == ["ckpt"]:
        # forwarded verbatim like lint: the ckpt CLI owns its option
        # surface (ckpt/cli.py) and is pure filesystem — it must work on
        # an unconfigured host, the box you ssh into after a preemption.
        from ..ckpt import cli as ckpt_cli

        tail = list(sys.argv[2:] if argv is None else argv[1:])
        return ckpt_cli.main(tail)
    if head == ["quality"]:
        # forwarded verbatim like lint/perf: the quality CLI owns its
        # whole option surface (tools/quality.py) and needs neither the
        # storage plane nor jax — a pure scraper/snapshot reader.
        from . import quality as quality_mod

        tail = list(sys.argv[2:] if argv is None else argv[1:])
        return quality_mod.main(tail)
    if head in (["health"], ["alerts"], ["blackbox"]):
        # the fleet-health CLIs (tools/health.py, docs/slo.md) own their
        # option surface and are pure scrapers/ledger readers — jax-free,
        # storage-free, forwarded verbatim with the subcommand included.
        from . import health as health_mod

        tail = list(sys.argv[2:] if argv is None else argv[1:])
        return health_mod.main(head + tail)
    if head in (["profile"], ["perf"]):
        # same REMAINDER limitation as lint: these CLIs own their whole
        # option surface (tools/perf.py), so forward verbatim. `perf`
        # needs neither storage nor jax; `profile --train-smoke` imports
        # jax itself, after the platform env is applied below.
        from . import perf as perf_mod

        tail = list(sys.argv[2:] if argv is None else argv[1:])
        if head == ["perf"]:
            return perf_mod.run_perf(
                perf_mod.build_perf_parser().parse_args(tail)
            )
        apply_env_platform()
        return perf_mod.run_profile(
            perf_mod.build_profile_parser().parse_args(tail),
            registry=registry,
        )

    apply_env_platform()
    args = build_parser().parse_args(argv)
    # Short-lived CLI commands die quietly on a closed pipe (`pio app new
    # | grep -q ...` closes stdout early) — default Unix behavior, not a
    # Python traceback. Server subcommands keep Python's SIGPIPE=ignored
    # so a client disconnect mid-write surfaces as the BrokenPipeError
    # their handlers treat as normal operation, instead of killing the
    # process. The old disposition is RESTORED on return (after a flush
    # that still runs under SIG_DFL, so a dead pipe kills quietly before
    # the interpreter's exit flush can raise noisily): in-process callers
    # (tests, embedding apps) must not inherit a process-killing SIGPIPE.
    prev = None
    if args.command in (
        "eventserver", "dashboard", "storageserver", "deploy", "router",
        "sharedcache",
    ):
        # long-running server commands arm the crash path (docs/slo.md):
        # with PIO_FLIGHT_DIR set, SIGTERM/exit leaves the flight-
        # recorder timeline behind; a CLI entry point may own signal
        # dispositions (run_server does the same for spawned deploys)
        from ..obs.flight import arm

        arm(signals=True)
    else:
        try:
            cur = signal.getsignal(signal.SIGPIPE)
            if cur is not None:  # None = C-installed handler: unrestorable,
                signal.signal(signal.SIGPIPE, signal.SIG_DFL)  # leave as-is
                prev = cur
        except (AttributeError, ValueError):
            pass  # non-POSIX, or a non-main thread (tests)
    try:
        registry = registry or get_registry()
        return _dispatch(args, registry)
    except KeyboardInterrupt:
        return EXIT_FAIL
    except Exception as exc:  # every operator error → JSON + exit 1
        _emit({"error": str(exc)})
        return EXIT_FAIL
    finally:
        if prev is not None:
            try:
                sys.stdout.flush()
            except (BrokenPipeError, OSError):
                pass
            try:
                signal.signal(signal.SIGPIPE, prev)
            except (AttributeError, ValueError):
                pass


def _confirm_destructive(args: argparse.Namespace, action: str) -> bool:
    """``App.scala:79-120``: destructive app commands prompt 'YES' unless
    --force; non-interactive invocations must pass --force explicitly."""
    if args.force:
        return True
    if not sys.stdin.isatty():
        _emit({"error": f"refusing to {action} without --force (non-interactive)"})
        return False
    answer = input(f"About to {action}. Enter 'YES' to proceed: ")
    if answer != "YES":
        _emit({"error": "aborted"})
        return False
    return True


def _dispatch(args: argparse.Namespace, registry: StorageRegistry) -> int:
    cmd = args.command
    if cmd == "app":
        sub = args.app_command
        if sub == "new":
            _emit(app_new(registry, args.name, args.id, args.access_key, args.description))
        elif sub == "list":
            _emit(app_list(registry))
        elif sub == "show":
            _emit(app_show(registry, args.name))
        elif sub == "delete":
            if not _confirm_destructive(args, f"delete app {args.name!r} and ALL its data"):
                return EXIT_FAIL
            _emit(app_delete(registry, args.name))
        elif sub == "data-delete":
            if not _confirm_destructive(args, f"delete ALL event data of app {args.name!r}"):
                return EXIT_FAIL
            _emit(app_data_delete(registry, args.name))
        return EXIT_OK

    if cmd == "accesskey":
        sub = args.accesskey_command
        if sub == "new":
            _emit(accesskey_new(registry, args.app_name, args.events))
        elif sub == "list":
            _emit(accesskey_list(registry, args.app_name))
        elif sub == "delete":
            _emit(accesskey_delete(registry, args.key))
        return EXIT_OK

    if cmd == "build":
        from ..workflow.version_check import check_upgrade

        check_upgrade("build")  # Console.scala:842-844
        ed = register_mod.register_engine(registry, args.engine_dir)
        # Pre-compile the native runtime components so the first train /
        # deploy doesn't pay the C++ build (the reference's `pio build`
        # runs sbt compile up front — same idea, RunWorkflow launches are
        # then pure execution). Best-effort: a toolchain-less host falls
        # back to the Python paths at runtime anyway.
        from ..native import LIBRARIES, NativeBuildError, build_library

        native_built = []
        for name in LIBRARIES:
            try:
                build_library(name)
                native_built.append(name)
            except (NativeBuildError, OSError):
                # best-effort: toolchain-less or read-only installs fall
                # back to the Python paths at runtime
                pass
        _emit({
            "engineId": ed.manifest.id,
            "engineVersion": ed.manifest.version,
            "nativeLibraries": native_built,
        })
        return EXIT_OK

    if cmd == "train":
        register_mod.register_engine(registry, args.engine_dir, verify_import=False)
        if args.spawn:
            return _spawn("predictionio_tpu.tools.run_workflow", _workflow_argv(args))
        wf_args = run_workflow.build_parser().parse_args(_workflow_argv(args))
        instance_id = run_workflow.run(wf_args, registry)
        _emit({"engineInstanceId": instance_id})
        return EXIT_OK

    if cmd == "eval":
        extra = ["--evaluation-class", args.evaluation_class]
        if args.engine_params_generator_class:
            extra += [
                "--engine-params-generator-class",
                args.engine_params_generator_class,
            ]
        if args.spawn:
            return _spawn(
                "predictionio_tpu.tools.run_workflow", _workflow_argv(args, extra)
            )
        wf_args = run_workflow.build_parser().parse_args(_workflow_argv(args, extra))
        instance_id = run_workflow.run(wf_args, registry)
        _emit({"evaluationInstanceId": instance_id})
        return EXIT_OK

    if cmd == "deploy":
        srv_argv = [
            "--engine-dir", args.engine_dir,
            "--ip", args.ip,
            "--port", str(args.port),
            "--event-server-ip", args.event_server_ip,
            "--event-server-port", str(args.event_server_port),
            "--batch", args.batch,
        ]
        if args.engine_instance_id:
            srv_argv += ["--engine-instance-id", args.engine_instance_id]
        if args.feedback:
            srv_argv.append("--feedback")
        if args.accesskey:
            srv_argv += ["--accesskey", args.accesskey]
        if args.log_url:
            srv_argv += ["--log-url", args.log_url]
        if args.batch_max is not None:
            srv_argv += ["--batch-max", str(args.batch_max)]
        if args.batch_pipeline_depth is not None:
            srv_argv += ["--batch-pipeline-depth",
                         str(args.batch_pipeline_depth)]
        if args.shard_index is not None:
            srv_argv += ["--shard-index", str(args.shard_index)]
        if args.shard_count is not None:
            srv_argv += ["--shard-count", str(args.shard_count)]
        if args.continuous_app is not None:
            srv_argv += ["--continuous-app", str(args.continuous_app)]
        if args.continuous_feed:
            srv_argv += ["--continuous-feed", args.continuous_feed]
        if args.spawn:
            return _spawn_detached("predictionio_tpu.tools.run_server", srv_argv)
        srv_args = run_server.build_parser().parse_args(srv_argv)
        run_server.make_server(srv_args, registry, block=True)
        return EXIT_OK

    if cmd == "undeploy":
        _emit(undeploy(args.ip, args.port))
        return EXIT_OK

    if cmd == "rollout":
        _emit(rollout_command(args))
        return EXIT_OK

    if cmd == "continuous":
        _emit(continuous_command(args))
        return EXIT_OK

    if cmd == "router":
        from ..fleet.router import RouterConfig, create_router

        backends = tuple(
            b.strip() for b in args.backends.split(",") if b.strip()
        )
        quotas = {}
        for item in args.quota:
            app, sep, value = item.partition("=")
            if not sep:
                raise ValueError(f"bad --quota {item!r}: expected APP=N")
            try:
                quotas[app.strip()] = int(value)
            except ValueError:
                raise ValueError(
                    f"bad --quota {item!r}: N must be an integer"
                ) from None
        config = RouterConfig(
            ip=args.ip,
            port=args.port,
            backends=backends,
            sharded=args.sharded,
            replicas_per_shard=args.replicas_per_shard,
            quotas=quotas,
            default_quota=args.default_quota,
            timeout_s=args.timeout,
            engine_id=args.engine_id,
            engine_version=args.engine_version,
            engine_variant=args.engine_variant,
            cache_enabled=False if args.no_cache else None,
            cache_ttl_s=args.cache_ttl,
            cache_max_entries=args.cache_max_entries,
            shared_cache=args.shared_cache,
            meta_feed=args.meta_feed,
            hedge_enabled=False if args.no_hedge else None,
        )
        create_router(config, registry=registry, block=True)
        return EXIT_OK

    if cmd == "sharedcache":
        from ..fleet.sharedcache import SharedCacheServer

        server = SharedCacheServer(
            ip=args.ip,
            port=args.port,
            max_entries=args.max_entries,
            ttl_s=args.ttl,
        )
        _emit(
            f"shared cache sidecar on {args.ip}:{server.bound_port} "
            f"({args.max_entries} entries, {args.ttl}s TTL)"
        )
        try:
            server.serve_forever()
        finally:
            server.server_close()
        return EXIT_OK

    if cmd == "eventserver":
        from ..api.event_server import EventServerConfig, create_event_server

        create_event_server(
            EventServerConfig(ip=args.ip, port=args.port, stats=args.stats),
            registry=registry,
            block=True,
        )
        return EXIT_OK

    if cmd == "dashboard":
        from .dashboard import DashboardConfig, create_dashboard

        create_dashboard(
            DashboardConfig(ip=args.ip, port=args.port, nodes=args.nodes),
            registry,
            block=True,
        )
        return EXIT_OK

    if cmd == "storageserver":
        if args.replica_of:
            from ..storage.replica import create_storage_replica

            replica = create_storage_replica(
                args.ip, args.port, args.replica_of, registry,
                partition_index=args.partition_index,
                partition_count=args.partition_count,
            )
            replica.start_tailing(poll_interval_s=args.poll_interval)
            _emit({
                "status": "serving", "role": "replica",
                "port": replica.bound_port, "primary": args.replica_of,
                "partition": [args.partition_index, args.partition_count],
            })
            try:
                replica.serve_forever()
            except KeyboardInterrupt:
                replica.stop_tailing()
                replica.server_close()
            return EXIT_OK

        from ..storage.registry import base_dir
        from ..storage.storage_server import create_storage_server

        oplog_dir = None
        if not args.no_changefeed:
            oplog_dir = args.oplog_dir or os.path.join(base_dir(), "oplog")
        server = create_storage_server(
            args.ip, args.port, registry, oplog_dir=oplog_dir,
            partition_index=args.partition_index,
            partition_count=args.partition_count,
            sync_every=args.sync_every,
        )
        _emit({
            "status": "serving", "role": "primary",
            "port": server.bound_port,
            "changefeed": oplog_dir is not None,
            "partition": [args.partition_index, args.partition_count],
        })
        try:
            server.serve_forever()
        except KeyboardInterrupt:
            server.server_close()
        return EXIT_OK

    if cmd == "top":
        from ..obs.top import DEFAULT_NODES, run_top

        return run_top(
            args.nodes or DEFAULT_NODES,
            timeout=args.timeout,
            as_json=args.json,
        )

    if cmd == "trace":
        from ..obs.top import DEFAULT_NODES, run_trace

        return run_trace(
            args.trace_id,
            args.nodes or DEFAULT_NODES,
            timeout=args.timeout,
            as_json=args.json,
        )

    if cmd == "status":
        result = status(registry)
        _emit(result)
        return EXIT_OK if result["ok"] else EXIT_FAIL

    if cmd == "migrate":
        return migrate_command(args)

    if cmd == "autoscale":
        return autoscale_command(args)

    if cmd == "upgrade":
        from .upgrade import run_upgrade

        _emit(run_upgrade(
            registry, args.from_type, args.from_path,
            args.to_type, args.to_path, app_ids=args.appid,
        ))
        return EXIT_OK

    if cmd == "export":
        from .export_events import export_events, export_events_parquet

        if args.format == "parquet":
            n = export_events_parquet(registry, args.appid, args.output)
        else:
            with open(args.output, "w", encoding="utf-8") as fh:
                n = export_events(registry, args.appid, fh)
        _emit({"appId": args.appid, "events": n, "output": args.output,
               "format": args.format})
        return EXIT_OK

    if cmd == "import":
        from .import_events import import_events, import_events_parquet

        if args.format == "parquet":
            n = import_events_parquet(registry, args.appid, args.input)
        else:
            with open(args.input, "r", encoding="utf-8") as fh:
                n = import_events(registry, args.appid, fh)
        _emit({"appId": args.appid, "events": n, "input": args.input})
        return EXIT_OK

    if cmd == "template":
        from .gallery import GalleryError, gallery_url, get_remote, list_remote
        from .templates import get_template, list_templates

        if args.template_command == "list":
            # one flat list (the original CLI contract — scripts iterate
            # entries); remote entries are tagged by "source"
            out = [dict(t, source="bundled") for t in list_templates()]
            if gallery_url():
                # a broken gallery (unreachable, HTML error page, malformed
                # index) must not take down the bundled listing
                try:
                    out.extend(
                        dict(t, source="remote") for t in list_remote()
                    )
                except Exception as exc:
                    print(
                        f"warning: remote gallery failed: "
                        f"{type(exc).__name__}: {exc}",
                        file=sys.stderr,
                    )
            _emit(out)
        else:
            # bundled names win; anything else resolves via the remote
            # gallery when one is configured (Template.scala:287-375)
            try:
                _emit(get_template(args.template_name, args.directory))
            except KeyError:
                if not gallery_url():
                    raise
                _emit(get_remote(args.template_name, args.directory))
        return EXIT_OK

    raise ValueError(f"Unknown command {cmd!r}")


if __name__ == "__main__":
    sys.exit(main())
