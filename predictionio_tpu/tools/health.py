"""``pio health`` / ``pio alerts`` / ``pio blackbox`` — the fleet-health CLIs.

Read-only, storage-free, jax-free scrapers over the health plane's wire
surfaces (``docs/slo.md``), forwarded verbatim by the console like
``pio quality``:

- ``pio health [--nodes ...]`` — scrape every node's ``GET
  /health.json`` into one table: firing objectives, worst fast-window
  burn rate, stall detections, abstaining objectives. Exit codes are
  pinned like ``pio perf diff``: **0** healthy, **1** any node firing
  or stalled, **2** engine error (no node reachable).
- ``pio alerts [--ledger FILE | --node H:P]`` — the durable alert
  ledger (``PIO_ALERT_LEDGER``) rendered chronologically, or a live
  node's current alert states. Exit **1** when any objective's latest
  durable state is FIRING, **0** when everything cleared, **2** on a
  missing/unreadable ledger.
- ``pio blackbox dump|show`` — fetch a live node's flight-recorder
  ring (``GET /blackbox.json``) into a durable dump file, or render a
  dump (or the ring, live) as a timeline. Exit **2** when the source is
  unreachable/missing.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List, Optional, Sequence

from ..obs.flight import FLIGHT_DIR_ENV, load_dump, write_dump
from ..obs.slo import ALERT_LEDGER_ENV, load_alerts

EXIT_OK = 0
EXIT_UNHEALTHY = 1
EXIT_ERROR = 2


# -- scraping -----------------------------------------------------------------


def _fetch_json(node: str, path: str, timeout: float = 5.0) -> Optional[dict]:
    from ..obs.top import _fetch

    body = _fetch(node, path, timeout=timeout)
    if body is None:
        return None
    try:
        doc = json.loads(body)
    except ValueError:
        return None
    return doc if isinstance(doc, dict) else None


def node_health(node: str, timeout: float = 5.0) -> Optional[dict]:
    """One node's ``/health.json`` digested into a fleet-table row
    (None when the node is down). Shared by the CLI and the dashboard's
    ``/health`` panel."""
    doc = _fetch_json(node, "/health.json", timeout=timeout)
    if doc is None:
        return None
    objectives = [
        o for o in doc.get("objectives", []) if isinstance(o, dict)
    ]
    stalls = doc.get("stalls") or {}
    burns = [
        o.get("burnFast")
        for o in objectives
        if isinstance(o.get("burnFast"), (int, float))
    ]
    return {
        "node": node,
        "up": True,
        "kind": doc.get("kind", "?"),
        "objectives": objectives,
        "firing": [
            o.get("name", "?")
            for o in objectives
            if o.get("state") == "FIRING"
        ],
        "abstaining": sum(1 for o in objectives if o.get("abstaining")),
        "worstBurnFast": max(burns) if burns else None,
        "stallsDetected": stalls.get("detected", 0),
        "stallsActive": stalls.get("active") or [],
        "inflight": stalls.get("inflight", 0),
        "lastDump": stalls.get("lastDump"),
    }


# -- pio health ---------------------------------------------------------------


def render_health_table(rows: Sequence[dict]) -> str:
    headers = ["NODE", "KIND", "HEALTH", "FIRING", "BURN", "STALLS",
               "ABSTAIN"]
    table: List[List[str]] = [headers]
    for row in rows:
        if not row.get("up"):
            table.append([str(row.get("node", "?")), "-", "DOWN", "-",
                          "-", "-", "-"])
            continue
        firing = row.get("firing") or []
        stalls_active = row.get("stallsActive") or []
        health = "ALERT" if firing else (
            "STALL" if stalls_active else "ok"
        )
        burn = row.get("worstBurnFast")
        table.append([
            str(row.get("node", "?")),
            str(row.get("kind", "?")),
            health,
            " ".join(firing) or "-",
            "-" if burn is None else f"{burn:.2f}",
            str(row.get("stallsDetected", 0)),
            str(row.get("abstaining", 0)),
        ])
    widths = [max(len(r[i]) for r in table) for i in range(len(headers))]
    return "\n".join(
        "  ".join(cell.ljust(w) for cell, w in zip(row, widths)).rstrip()
        for row in table
    )


def run_health(
    nodes: str, timeout: float = 5.0, as_json: bool = False
) -> int:
    from ..obs.top import _split_nodes

    rows = []
    for node in _split_nodes(nodes):
        row = node_health(node, timeout=timeout)
        rows.append(row if row is not None else {"node": node, "up": False})
    if as_json:
        print(json.dumps(rows, default=str))
    else:
        print(render_health_table(rows))
    if not any(r.get("up") for r in rows):
        return EXIT_ERROR
    unhealthy = any(
        r.get("firing") or r.get("stallsActive") for r in rows
    )
    return EXIT_UNHEALTHY if unhealthy else EXIT_OK


# -- pio alerts ---------------------------------------------------------------


def _fmt_at(at) -> str:
    if not isinstance(at, (int, float)):
        return "-"
    return time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(at))


def render_alerts(alerts: Sequence[dict]) -> str:
    if not alerts:
        return "(no alert transitions recorded)"
    lines = []
    for alert in alerts:
        burn_fast = alert.get("burnFast")
        burn = (
            f"{burn_fast:.2f}"
            if isinstance(burn_fast, (int, float))
            else "-"
        )
        lines.append(
            f"{_fmt_at(alert.get('at'))}  "
            f"{alert.get('state', '?'):<8} "
            f"{alert.get('node', '?'):<10} "
            f"{alert.get('objective', '?'):<14} "
            f"burnFast={burn} "
            f"({alert.get('metric', '?')})"
        )
    return "\n".join(lines)


def latest_states(alerts: Sequence[dict]) -> Dict[str, str]:
    """Last durable state per (node, objective) — the ledger's verdict
    on what is firing right now."""
    out: Dict[str, str] = {}
    for alert in alerts:
        key = f"{alert.get('node', '?')}/{alert.get('objective', '?')}"
        out[key] = str(alert.get("state", "?"))
    return out


def run_alerts(
    ledger: Optional[str],
    node: Optional[str],
    timeout: float = 5.0,
    as_json: bool = False,
) -> int:
    if node:
        row = node_health(node, timeout=timeout)
        if row is None:
            print(f"error: no /health.json at {node}", file=sys.stderr)
            return EXIT_ERROR
        if as_json:
            print(json.dumps(row, default=str))
        else:
            for obj in row["objectives"]:
                marker = obj.get("state", "?")
                burn = obj.get("burnFast")
                print(
                    f"{marker:<8} {obj.get('name', '?'):<14} "
                    + ("abstaining" if obj.get("abstaining") else
                       f"burnFast={burn}")
                )
        return EXIT_UNHEALTHY if row["firing"] else EXIT_OK
    if not ledger:
        print(
            "error: pass --ledger FILE or --node HOST:PORT "
            f"(or set {ALERT_LEDGER_ENV})",
            file=sys.stderr,
        )
        return EXIT_ERROR
    alerts = load_alerts(ledger)
    if not alerts:
        # distinguish "readable but empty" (exit 0) from "missing or
        # unreadable" (exit 2 — a monitoring script must never read a
        # broken evidence ledger as everything-cleared)
        try:
            with open(ledger, encoding="utf-8") as fh:
                fh.read(1)
        except OSError:
            print(
                f"error: no readable alert ledger at {ledger}",
                file=sys.stderr,
            )
            return EXIT_ERROR
        print("(no alert transitions recorded)")
        return EXIT_OK
    states = latest_states(alerts)
    if as_json:
        print(json.dumps({"alerts": alerts, "latest": states}))
    else:
        print(render_alerts(alerts))
    firing = [key for key, state in states.items() if state == "FIRING"]
    return EXIT_UNHEALTHY if firing else EXIT_OK


# -- pio blackbox -------------------------------------------------------------


def render_dump(events: Sequence[dict], title: str) -> str:
    if not events:
        return f"blackbox [{title}]: (empty ring)"
    t0 = min(e.get("t", 0) for e in events)
    lines = [f"blackbox [{title}]: {len(events)} events"]
    for event in events:
        details = event.get("details") or {}
        detail_str = " ".join(
            f"{k}={v}" for k, v in sorted(details.items())
        )
        trace = event.get("trace")
        lines.append(
            f"  +{event.get('t', 0) - t0:10.3f}s  "
            f"{event.get('kind', '?'):<10} {event.get('site', '?'):<24} "
            f"{detail_str}"
            + (f"  trace={trace}" if trace else "")
        )
    return "\n".join(lines)


def _latest_dump_path(directory: str) -> Optional[str]:
    try:
        candidates = [
            os.path.join(directory, name)
            for name in os.listdir(directory)
            if name.endswith(".jsonl")
            and (name.startswith("flight-") or name.startswith("stall-"))
        ]
    except OSError:
        return None
    if not candidates:
        return None
    return max(candidates, key=lambda p: os.path.getmtime(p))


def run_blackbox(
    action: str,
    node: Optional[str],
    file: Optional[str],
    out: Optional[str],
    timeout: float = 5.0,
    as_json: bool = False,
) -> int:
    if action == "dump":
        if not node:
            print("error: blackbox dump needs --node HOST:PORT",
                  file=sys.stderr)
            return EXIT_ERROR
        doc = _fetch_json(node, "/blackbox.json", timeout=timeout)
        if doc is None:
            print(f"error: no /blackbox.json at {node}", file=sys.stderr)
            return EXIT_ERROR
        events = doc.get("events", [])
        if out:
            write_dump(out, events, f"pio blackbox dump {node}")
            print(f"wrote {len(events)} events to {out}")
        elif as_json:
            print(json.dumps(doc, default=str))
        else:
            print(render_dump(events, node))
        return EXIT_OK
    # show: a dump file, or the freshest dump under PIO_FLIGHT_DIR
    path = file
    if path is None:
        directory = os.environ.get(FLIGHT_DIR_ENV)
        if directory:
            path = _latest_dump_path(directory)
    if path is None:
        print(
            "error: blackbox show needs --file DUMP (or a dump under "
            f"${FLIGHT_DIR_ENV})",
            file=sys.stderr,
        )
        return EXIT_ERROR
    doc = load_dump(path)
    if doc is None:
        print(f"error: no readable flight dump at {path}", file=sys.stderr)
        return EXIT_ERROR
    if as_json:
        print(json.dumps(doc, default=str))
    else:
        reason = doc["header"].get("reason", "?")
        print(render_dump(doc["events"], f"{path} ({reason})"))
    return EXIT_OK


# -- CLI glue -----------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="pio health",
        description="fleet health: SLO burn-rate alerts, stall "
        "forensics, flight-recorder dumps (docs/slo.md)",
    )
    sub = p.add_subparsers(dest="command", required=True)

    he = sub.add_parser("health", help="scrape /health.json fleet-wide")
    he.add_argument("--nodes", default=None, metavar="HOST:PORT,...")
    he.add_argument("--timeout", type=float, default=5.0)
    he.add_argument("--json", action="store_true")

    al = sub.add_parser(
        "alerts", help="alert ledger / live alert states"
    )
    al.add_argument(
        "--ledger", default=None, metavar="FILE",
        help=f"alert-ledger JSONL (default: ${ALERT_LEDGER_ENV})",
    )
    al.add_argument(
        "--node", default=None, metavar="HOST:PORT",
        help="read a live node's alert states instead of the ledger",
    )
    al.add_argument("--timeout", type=float, default=5.0)
    al.add_argument("--json", action="store_true")

    bb = sub.add_parser(
        "blackbox", help="flight-recorder dump / timeline render"
    )
    bb.add_argument("action", choices=("dump", "show"))
    bb.add_argument("--node", default=None, metavar="HOST:PORT")
    bb.add_argument("--file", default=None, metavar="DUMP")
    bb.add_argument(
        "--out", default=None, metavar="FILE",
        help="with dump: write the fetched ring to this file",
    )
    bb.add_argument("--timeout", type=float, default=5.0)
    bb.add_argument("--json", action="store_true")
    return p


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.command == "health":
        from ..obs.top import DEFAULT_NODES

        return run_health(
            args.nodes or DEFAULT_NODES,
            timeout=args.timeout,
            as_json=args.json,
        )
    if args.command == "alerts":
        ledger = args.ledger or os.environ.get(ALERT_LEDGER_ENV)
        return run_alerts(
            ledger, args.node, timeout=args.timeout, as_json=args.json
        )
    return run_blackbox(
        args.action, args.node, args.file, args.out,
        timeout=args.timeout, as_json=args.json,
    )


if __name__ == "__main__":
    sys.exit(main())
