"""Event import: JSON-lines file → app's event store.

Rebuild of ``tools/.../imprt/FileToEvents.scala`` (read json lines →
``PEvents.write``): each line is one event document; invalid lines abort with
the offending line number (the reference fails the Spark job on first parse
error).  Uses the store's bulk ``write`` path, which on the native backend is
a single columnar append batch.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Iterable, Optional, Sequence

from ..storage import Event, StorageRegistry, get_registry
from ..storage.event import validate_event


class ImportError_(ValueError):
    """A line failed to parse/validate."""


def _parse_lines(lines: Iterable[str]) -> Iterable[Event]:
    for lineno, line in enumerate(lines, start=1):
        line = line.strip()
        if not line:
            continue
        try:
            event = Event.from_json_dict(json.loads(line))
            validate_event(event)
        except Exception as exc:
            raise ImportError_(f"line {lineno}: {exc}") from exc
        yield event


def import_events(
    registry: StorageRegistry,
    app_id: int,
    lines: Iterable[str],
    batch_size: int = 1000,
) -> int:
    """Bulk-insert events in batches; returns the number imported."""
    store = registry.get_events()
    store.init(app_id)
    batch = []
    count = 0
    for event in _parse_lines(lines):
        batch.append(event)
        if len(batch) >= batch_size:
            store.write(batch, app_id)
            count += len(batch)
            batch = []
    if batch:
        store.write(batch, app_id)
        count += len(batch)
    return count


def main(argv: Optional[Sequence[str]] = None) -> int:
    from ..utils.platform import apply_env_platform

    apply_env_platform()
    p = argparse.ArgumentParser(prog="import_events")
    p.add_argument("--appid", type=int, required=True)
    p.add_argument("--input", required=True)
    args = p.parse_args(argv)
    registry = get_registry()
    with open(args.input, "r", encoding="utf-8") as fh:
        n = import_events(registry, args.appid, fh)
    print(json.dumps({"appId": args.appid, "events": n, "input": args.input}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
