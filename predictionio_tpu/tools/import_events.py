"""Event import: JSON-lines file → app's event store.

Rebuild of ``tools/.../imprt/FileToEvents.scala`` (read json lines →
``PEvents.write``): each line is one event document; invalid lines abort with
the offending line number (the reference fails the Spark job on first parse
error).  Uses the store's bulk ``write`` path, which on the native backend is
a single columnar append batch.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Iterable, Optional, Sequence

from ..storage import Event, StorageRegistry, get_registry
from ..storage.event import validate_event


class ImportError_(ValueError):
    """A line failed to parse/validate."""


def _parse_lines(lines: Iterable[str]) -> Iterable[Event]:
    for lineno, line in enumerate(lines, start=1):
        line = line.strip()
        if not line:
            continue
        try:
            event = Event.from_json_dict(json.loads(line))
            validate_event(event)
        except Exception as exc:
            raise ImportError_(f"line {lineno}: {exc}") from exc
        yield event


def _write_batches(store, app_id: int, events: Iterable[Event],
                   batch_size: int) -> int:
    """Accumulate-and-flush shared by every import format."""
    batch: list = []
    count = 0
    for event in events:
        batch.append(event)
        if len(batch) >= batch_size:
            store.write(batch, app_id)
            count += len(batch)
            batch = []
    if batch:
        store.write(batch, app_id)
        count += len(batch)
    return count


def import_events(
    registry: StorageRegistry,
    app_id: int,
    lines: Iterable[str],
    batch_size: int = 1000,
) -> int:
    """Bulk-insert events in batches; returns the number imported."""
    store = registry.get_events()
    store.init(app_id)
    return _write_batches(store, app_id, _parse_lines(lines), batch_size)


def _parse_parquet_rows(path: str, batch_size: int) -> Iterable[Event]:
    """Row → Event stream with row-index error attribution (matching the
    JSON path's line-number contract)."""
    import pyarrow.parquet as pq

    pf = pq.ParquetFile(path)
    rowno = 0
    for record_batch in pf.iter_batches(batch_size=batch_size):
        for row in record_batch.to_pylist():
            rowno += 1
            try:
                obj = {
                    k: v
                    for k, v in row.items()
                    if k not in ("properties", "tags") and v is not None
                }
                obj["properties"] = json.loads(row["properties"] or "{}")
                tags = json.loads(row["tags"] or "[]")
                if tags:
                    obj["tags"] = tags
                event = Event.from_json_dict(obj)
                validate_event(event)
            except Exception as exc:
                raise ImportError_(f"row {rowno}: {exc}") from exc
            yield event


def import_events_parquet(
    registry: StorageRegistry,
    app_id: int,
    path: str,
    batch_size: int = 1000,
) -> int:
    """Import a parquet archive written by ``export_events_parquet``
    (row groups stream through bounded batches)."""
    store = registry.get_events()
    store.init(app_id)
    return _write_batches(
        store, app_id, _parse_parquet_rows(path, batch_size), batch_size
    )


def main(argv: Optional[Sequence[str]] = None) -> int:
    from ..utils.platform import apply_env_platform

    apply_env_platform()
    p = argparse.ArgumentParser(prog="import_events")
    p.add_argument("--appid", type=int, required=True)
    p.add_argument("--input", required=True)
    p.add_argument(
        "--format", choices=("json", "parquet"), default="json",
        help="json = JSON-lines (default); parquet = archives written by "
        "`pio export --format parquet`",
    )
    args = p.parse_args(argv)
    registry = get_registry()
    if args.format == "parquet":
        n = import_events_parquet(registry, args.appid, args.input)
    else:
        with open(args.input, "r", encoding="utf-8") as fh:
            n = import_events(registry, args.appid, fh)
    print(json.dumps({"appId": args.appid, "events": n, "input": args.input}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
