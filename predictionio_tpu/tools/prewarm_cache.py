"""Offline TPU compile of the exact bench/serving programs (deviceless).

Two jobs, one mechanism — ``jit(fn).lower(avals).compile()`` against a
compile-only v5e topology (``jax.experimental.topologies``; works with
the accelerator tunnel down):

1. **Full-program validation.** ``tests/test_mosaic_aot.py`` compiles
   each Pallas kernel in isolation; this tool compiles the WHOLE
   bench-shape ALS programs (``_als_half`` + ``_als_iteration`` per
   lever variant, every bucket, real ML-20M-shaped bucketization) and
   the serving top-k dispatch at the four catalog sizes the queue's
   ``dispatch_bench`` step measures. A lowering problem anywhere in the
   real program surfaces here, offline, instead of mid-window.

2. **Cache pre-warming (experimental).** The compiled executables land
   in the persistent compilation cache (``utils/jax_cache``). If the
   real chip computes the same cache key as the deviceless topology
   (same libtpu, same program, same options), the hardware window skips
   these compiles entirely; if the key differs, the attempt cost
   nothing from the window. Either way the compile *times* recorded
   here bound what the window will pay.

Usage::

    python -m predictionio_tpu.tools.prewarm_cache [--scale 1.0]
        [--variants f32,bf16,fused,fused_bf16]

Sorting (``sort_gather_indices``) permutes values host-side without
changing shapes, so it shares the f32 variant's program — no separate
compile exists to warm.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: ALSConfig kwargs per lever variant; solve_mode is "pallas" because
#: that is what bench's "auto" resolves to on a TPU backend — the
#: program compiled here must BE the program the chip runs.
VARIANTS = {
    "f32": dict(gather_dtype="f32", fused_gather=False),
    "bf16": dict(gather_dtype="bf16", fused_gather=False),
    "fused": dict(gather_dtype="f32", fused_gather=True),
    "fused_bf16": dict(gather_dtype="bf16", fused_gather=True),
}

DISPATCH_CATALOGS = (2_700, 27_000, 60_000, 120_000)


def _memory_record(compiled) -> dict:
    """XLA's OWN numbers for the compiled program — upgrades the
    hand-computed HBM accounting in PERF.md to compiler-reported data:
    ``temp_gb`` is the peak scratch the program actually allocates
    (does the [B, K, R] gathered intermediate materialize?), and
    ``bytes_accessed_gb``/``flops`` come from the compiler's cost model
    when it exposes one. Fully best-effort: an analysis gap must never
    turn a successful (cache-populating) compile into a failure."""
    rec: dict = {}
    try:
        m = compiled.memory_analysis()
        rec = {
            "arg_gb": round(m.argument_size_in_bytes / 1e9, 3),
            "out_gb": round(m.output_size_in_bytes / 1e9, 3),
            "temp_gb": round(m.temp_size_in_bytes / 1e9, 3),
            "code_mb": round(m.generated_code_size_in_bytes / 1e6, 2),
        }
    except Exception:
        pass
    try:
        costs = compiled.cost_analysis()
        if isinstance(costs, (list, tuple)):
            costs = costs[0] if costs else {}
        if costs.get("bytes accessed") is not None:
            rec["bytes_accessed_gb"] = round(
                costs["bytes accessed"] / 1e9, 3
            )
        if costs.get("flops") is not None:
            rec["gflops"] = round(costs["flops"] / 1e9, 2)
    except Exception:
        pass  # not all backends expose a cost model
    return rec


def _stage_avals(side, sh, row_multiple: int = 1):
    """Mirror ``ops.als.stage()``'s chunked device layout as
    ShapeDtypeStructs (same block rounding — including the mesh
    ``row_multiple`` round-up — padding and uint16 index narrowing; see
    ``stage()``), without touching any device. ``tests/test_prewarm.py``
    asserts this stays shape-identical to the real ``stage()``."""
    import jax

    from ..ops import als

    buckets = []
    for bucket in side.buckets:
        # right-sized allocation, same rule as stage(): the block is
        # capped by the bucket's own pow2 row envelope (round 12)
        n = bucket.rows.shape[0]
        block = als._alloc_block(bucket.width, n)
        if row_multiple > 1:
            block = (
                (block + row_multiple - 1) // row_multiple
            ) * row_multiple
        n_chunks = max(1, (n + block - 1) // block)
        idx_dtype = als._idx_dtype(side.n_cols)
        aval = lambda shape, dt: jax.ShapeDtypeStruct(
            shape, dt, sharding=sh
        )
        buckets.append((
            aval((n_chunks, block), bucket.rows.dtype),
            aval((n_chunks, block, bucket.width), idx_dtype),
            aval((n_chunks, block, bucket.width), bucket.val.dtype),
            aval((n_chunks, block), bucket.counts.dtype),
        ))
    return tuple(buckets)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="prewarm_cache")
    ap.add_argument("--scale", type=float,
                    default=float(os.environ.get("BENCH_SCALE", "1.0")))
    ap.add_argument("--rank", type=int, default=50)
    ap.add_argument("--variants", default="f32,bf16,fused,fused_bf16")
    ap.add_argument("--skip-dispatch", action="store_true")
    args = ap.parse_args(argv)

    from ..utils.jax_cache import enable_compilation_cache
    from ..utils.platform import force_cpu_in_process

    # This tool is ALWAYS offline: every TPU compile goes through the
    # deviceless topology client, never the default backend. Pinning the
    # default backend to CPU keeps any stray jnp op (or backend query
    # during lowering) from initializing a device plugin that would
    # block forever against a wedged accelerator tunnel.
    force_cpu_in_process()
    cache_dir = enable_compilation_cache()

    import jax
    import jax.numpy as jnp
    from jax.sharding import SingleDeviceSharding

    from ..ops import als
    from ..ops.pallas_kernels import top_k_streaming
    from ..utils.topology import get_deviceless_topology

    sys.path.insert(0, REPO)
    import bench

    # cache the deterministic dataset like the queue does: a tool meant
    # for cheap offline iteration must not re-pay a minute of host-side
    # generation per run
    os.environ.setdefault("BENCH_SYNTH_CACHE", "/tmp/pio-bench-synth")

    t_all = time.monotonic()
    try:
        # generous retry: a watcher probe or test session holding the
        # libtpu lockfile must delay this tool, not abort it
        topo = get_deviceless_topology(
            "v5e:1x1", retries=5, retry_delay_s=20.0,
            chips_per_host_bounds=(1, 1, 1),
        )
    except Exception as exc:
        print(json.dumps({"step": "prewarm_aot",
                          "error": f"no deviceless TPU topology: {exc}"}))
        return 1
    sh = SingleDeviceSharding(topo.devices[0])

    users, items, ratings, n_users, n_items = bench.synth_ml20m(args.scale)
    tr = ~bench.holdout_mask(len(ratings))  # the bench's exact split
    by_user = als.bucketize(users[tr], items[tr], ratings[tr],
                            n_users, n_items, pad_to_blocks=True)
    by_item = als.bucketize(items[tr], users[tr], ratings[tr],
                            n_items, n_users, pad_to_blocks=True)
    ub, ib = _stage_avals(by_user, sh), _stage_avals(by_item, sh)
    rank = args.rank
    y_aval = jax.ShapeDtypeStruct((n_items, rank), jnp.float32, sharding=sh)
    x_aval = jax.ShapeDtypeStruct((n_users, rank), jnp.float32, sharding=sh)
    scalar = jax.ShapeDtypeStruct((), jnp.float32, sharding=sh)

    rec = {"step": "prewarm_aot", "scale": args.scale, "rank": rank,
           "cache_dir": cache_dir, "programs": {}, "memory": {},
           "failed": []}
    for name in [v.strip() for v in args.variants.split(",") if v.strip()]:
        kw = VARIANTS[name]
        common = dict(rank=rank, implicit=False, solve_mode="pallas",
                      mesh=None, **kw)
        for prog, build in (
            (f"{name}/half_user", lambda: als._als_half.lower(
                y_aval, ub, scalar, scalar, n_rows=n_users, **common)),
            (f"{name}/half_item", lambda: als._als_half.lower(
                x_aval, ib, scalar, scalar, n_rows=n_items, **common)),
            (f"{name}/iteration", lambda: als._als_iteration.lower(
                ub, ib, y_aval, scalar, scalar,
                n_users=n_users, n_items=n_items, **common)),
        ):
            t0 = time.monotonic()
            try:
                compiled = build().compile()
                rec["programs"][prog] = round(time.monotonic() - t0, 2)
                rec["memory"][prog] = _memory_record(compiled)
            except Exception as exc:
                rec["failed"].append(
                    {prog: f"{type(exc).__name__}: {str(exc)[:300]}"}
                )
            print(f"[prewarm] {prog}: "
                  f"{rec['programs'].get(prog, 'FAILED')}s "
                  f"{rec['memory'].get(prog, '')}",
                  file=sys.stderr)

    if not args.skip_dispatch:
        import functools

        q = jax.ShapeDtypeStruct((512, rank), jnp.float32, sharding=sh)
        # one jit wrapper for every catalog size: each .lower() below is
        # a distinct program (that is the point of the prewarm), but the
        # wrapper itself must not be rebuilt per iteration
        dispatch_fn = jax.jit(functools.partial(
            top_k_streaming, k=10, interpret=False
        ))
        for n_cat in DISPATCH_CATALOGS:
            cat = jax.ShapeDtypeStruct((n_cat, rank), jnp.float32,
                                       sharding=sh)
            t0 = time.monotonic()
            try:
                compiled = dispatch_fn.lower(q, cat).compile()
                rec["programs"][f"dispatch/{n_cat}"] = round(
                    time.monotonic() - t0, 2
                )
                rec["memory"][f"dispatch/{n_cat}"] = _memory_record(
                    compiled
                )
            except Exception as exc:
                rec["failed"].append(
                    {f"dispatch/{n_cat}":
                     f"{type(exc).__name__}: {str(exc)[:300]}"}
                )

    rec["total_s"] = round(time.monotonic() - t_all, 1)
    rec["ok"] = not rec["failed"]
    print(json.dumps(rec))
    return 0 if rec["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
