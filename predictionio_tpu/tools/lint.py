"""``pio lint`` / ``python -m predictionio_tpu.tools.lint`` — run the
TPU-hygiene static analyzer over files or directories.

Exit codes are pinned (the contract the tier-1 gate, CI and the
pre-window checklist all read):

- ``0`` — clean: every finding suppressed (with a reason) or baselined
- ``1`` — unsuppressed findings remain
- ``2`` — engine error: a file failed to parse, a target path does not
  exist, git could not enumerate ``--changed`` files, or the
  ``--baseline`` file is unreadable — the run proved *nothing*, which
  must never be mistaken for "clean" OR for "has findings"

``--format json`` emits one machine-readable document on stdout.
``--changed`` lints only files git reports as modified/added/untracked
(diff-scoped pre-commit runs) *plus their reverse-import closure* — a
changed helper re-judges every file that can reach it through imports,
so the ``flow-*`` rules cannot miss a cross-file regression in a
diff-scoped run; ``--baseline FILE`` adopts legacy findings recorded by
an earlier ``--format json`` run and ratchets: baselined debt is
absorbed, anything new still fails.

Full-package default-rule runs keep an incremental result cache at
``<target>/.pio_lint_cache.json`` (``PIO_LINT_CACHE`` overrides the
path, ``PIO_LINT_CACHE=0``/``off`` or ``--no-cache`` disables it) and
parse files in parallel worker processes (``--jobs``, 0 = auto). Both
are speed levers only: a corrupt cache or a failed pool falls back to
the cold serial sweep with an unchanged verdict.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
from typing import List, Optional, Sequence

from ..lint import all_rules, lint_paths, render_json, render_text
from ..lint.engine import apply_baseline, load_baseline

#: default lint target: the installed package itself
PACKAGE_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

EXIT_CLEAN = 0
EXIT_FINDINGS = 1
EXIT_ENGINE_ERROR = 2


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="pio lint",
        description="TPU-hygiene static analysis (Mosaic/jit/robust/obs/"
        "conc/spmd rules; see docs/lint.md)",
    )
    p.add_argument(
        "paths", nargs="*", default=None,
        help="files or directories to lint (default: the "
        "predictionio_tpu package)",
    )
    p.add_argument(
        "--format", choices=("text", "json"), default="text",
        help="report format (json is the watcher/CI interface)",
    )
    p.add_argument(
        "--select", default=None,
        help="comma-separated rule ids to run (default: all)",
    )
    p.add_argument(
        "--changed", action="store_true",
        help="lint only files git reports changed (working tree + index "
        "+ untracked) under the target paths — the pre-commit scope",
    )
    p.add_argument(
        "--baseline", default=None, metavar="FILE",
        help="adopt legacy findings recorded by an earlier "
        "`pio lint --format json > FILE` run: baselined findings are "
        "absorbed (reported, not fatal), new ones still fail — the "
        "ratchet never loosens",
    )
    p.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalog and exit",
    )
    p.add_argument(
        "--explain", default=None, metavar="RULE_ID",
        help="print the rule's full docstring and docs/lint.md anchor, "
        "then exit (unknown id is exit 2)",
    )
    p.add_argument(
        "--no-cache", action="store_true",
        help="skip the incremental result cache for this run (same "
        "verdict, cold speed)",
    )
    p.add_argument(
        "--jobs", type=int, default=0, metavar="N",
        help="worker processes for the per-file pass (default 0 = auto; "
        "1 forces serial)",
    )
    return p


def _emit(text: str) -> None:
    """Print that dies quietly on a closed pipe (``pio lint | head``):
    the exit code still carries the gate verdict, and stdout is pointed
    at devnull so the interpreter's exit flush cannot raise a second
    traceback."""
    try:
        print(text)
        sys.stdout.flush()
    except BrokenPipeError:
        try:
            devnull = os.open(os.devnull, os.O_WRONLY)
            os.dup2(devnull, sys.stdout.fileno())
        except OSError:
            pass


def changed_files(paths: Sequence[str]) -> List[str]:
    """Python files git reports as changed (unstaged, staged, or
    untracked) that live under one of ``paths``. Raises RuntimeError
    when git cannot answer — the caller maps that to exit 2, because a
    silent empty set would read as "nothing to lint: clean".

    Git runs against the repository *containing the first target path*,
    not the process cwd (``pio lint --changed /other/repo`` must see
    that repo's status), and reads ``--porcelain -z`` so file names
    with spaces/non-ASCII arrive verbatim instead of C-quoted (a
    quoted name would fail the existence check and silently vanish
    from the scope)."""
    roots = [os.path.abspath(p) for p in paths]
    anchor = roots[0]
    git_cwd = anchor if os.path.isdir(anchor) else (
        os.path.dirname(anchor) or "."
    )

    def _git(*args: str) -> subprocess.CompletedProcess:
        try:
            return subprocess.run(
                ["git", *args], capture_output=True, text=True,
                timeout=30, cwd=git_cwd,
            )
        except (OSError, subprocess.TimeoutExpired) as exc:
            raise RuntimeError(f"git {args[0]} failed: {exc}")

    proc = _git("status", "--porcelain", "-z", "--untracked-files=all")
    if proc.returncode != 0:
        raise RuntimeError(
            f"git status failed: {proc.stderr.strip() or proc.returncode}"
        )
    top_proc = _git("rev-parse", "--show-toplevel")
    if top_proc.returncode != 0:
        raise RuntimeError(
            "git rev-parse failed: "
            f"{top_proc.stderr.strip() or top_proc.returncode}"
        )
    top = top_proc.stdout.strip()
    out: List[str] = []
    entries = proc.stdout.split("\0")
    i = 0
    while i < len(entries):
        entry = entries[i]
        i += 1
        if len(entry) < 4:
            continue
        status, path = entry[:2], entry[3:]
        if status[0] in ("R", "C"):
            i += 1  # -z renames/copies: the NEXT entry is the OLD path
        if status.strip() == "D":
            continue  # deleted: nothing to lint
        if not path.endswith(".py"):
            continue
        abspath = os.path.abspath(os.path.join(top, path))
        if not os.path.exists(abspath):
            continue
        if any(
            abspath == root or abspath.startswith(root + os.sep)
            for root in roots
        ):
            out.append(abspath)
    return sorted(out)


def _cache_path_for(paths: Sequence[str]) -> Optional[str]:
    """Default cache location: under the target root when the run lints
    exactly one directory (the full-sweep shape). ``PIO_LINT_CACHE``
    overrides the path; ``0``/``off``/empty disables."""
    env = os.environ.get("PIO_LINT_CACHE")
    if env is not None:
        if env.strip().lower() in ("", "0", "off"):
            return None
        return os.path.abspath(env)
    if len(paths) == 1 and os.path.isdir(paths[0]):
        return os.path.join(
            os.path.abspath(paths[0]), ".pio_lint_cache.json"
        )
    return None


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        seen = set()
        lines = []
        for rule in all_rules():
            if rule.id in seen:
                continue  # one id may have per-file + package variants
            seen.add(rule.id)
            lines.append(f"{rule.id} [{rule.severity}]: {rule.short}")
        _emit("\n".join(lines))
        return EXIT_CLEAN
    if args.explain:
        import inspect

        for rule in all_rules():
            if rule.id == args.explain:
                doc = inspect.cleandoc(
                    type(rule).__doc__ or rule.short or ""
                )
                _emit(
                    f"{rule.id} [{rule.severity}]\n\n{doc}\n\n"
                    f"docs: docs/lint.md#{rule.id}"
                )
                return EXIT_CLEAN
        _emit(f"error: --explain: no such rule '{args.explain}'")
        return EXIT_ENGINE_ERROR
    paths = args.paths or [PACKAGE_DIR]
    # validate the baseline BEFORE any early return: a typo'd baseline
    # path must be exit 2 even on a day when --changed finds nothing —
    # otherwise CI reads "clean" until the first changed file exposes
    # the broken configuration
    baseline = None
    if args.baseline:
        try:
            baseline = load_baseline(args.baseline)
        except (OSError, ValueError) as exc:
            _emit(f"error: --baseline: {exc}")
            return EXIT_ENGINE_ERROR
    cache_path = (
        None if (args.no_cache or args.changed or args.select)
        else _cache_path_for(paths)
    )
    if args.changed:
        dir_roots = [p for p in paths if os.path.isdir(p)]
        try:
            paths = changed_files(paths)
        except RuntimeError as exc:
            _emit(f"error: --changed: {exc}")
            return EXIT_ENGINE_ERROR
        if paths and dir_roots:
            # cross-file closure: a changed helper must re-judge every
            # file that can reach it through imports, or a flow-* rule's
            # verdict would silently go stale in diff-scoped runs
            from ..lint import packagectx

            paths = paths + packagectx.reverse_closure_paths(
                dir_roots, paths
            )
        if not paths:
            # the empty-scope happy path must still honor --format json:
            # a CI consumer piping into a JSON parser hits this on every
            # clean run
            if args.format == "json":
                _emit(render_json(lint_paths([])))
            else:
                _emit(
                    "0 files, 0 findings, 0 suppressed (no changed files)"
                )
            return EXIT_CLEAN
    select = (
        {token.strip() for token in args.select.split(",") if token.strip()}
        if args.select
        else None
    )
    jobs = args.jobs if args.jobs > 0 else min(8, os.cpu_count() or 1)
    result = lint_paths(
        paths, select=select, cache_path=cache_path, jobs=jobs
    )
    if baseline is not None:
        apply_baseline(result, baseline)
    _emit(render_json(result) if args.format == "json"
          else render_text(result))
    if result.errors:
        return EXIT_ENGINE_ERROR
    return EXIT_CLEAN if not result.findings else EXIT_FINDINGS


if __name__ == "__main__":
    sys.exit(main())
