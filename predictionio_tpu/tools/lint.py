"""``pio lint`` / ``python -m predictionio_tpu.tools.lint`` — run the
TPU-hygiene static analyzer over files or directories.

Exit code 0 when every finding is suppressed (with a reason), 1
otherwise — the same contract as the tier-1 gate in
``tests/test_lint.py``, so CI, the pre-window checklist
(docs/hardware_day.md) and the watcher all read the same signal.
``--format json`` emits one machine-readable document on stdout.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Optional, Sequence

from ..lint import all_rules, lint_paths, render_json, render_text

#: default lint target: the installed package itself
PACKAGE_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="pio lint",
        description="TPU-hygiene static analysis (Mosaic + jit-boundary "
        "rules; see docs/lint.md)",
    )
    p.add_argument(
        "paths", nargs="*", default=None,
        help="files or directories to lint (default: the "
        "predictionio_tpu package)",
    )
    p.add_argument(
        "--format", choices=("text", "json"), default="text",
        help="report format (json is the watcher/CI interface)",
    )
    p.add_argument(
        "--select", default=None,
        help="comma-separated rule ids to run (default: all)",
    )
    p.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalog and exit",
    )
    return p


def _emit(text: str) -> None:
    """Print that dies quietly on a closed pipe (``pio lint | head``):
    the exit code still carries the gate verdict, and stdout is pointed
    at devnull so the interpreter's exit flush cannot raise a second
    traceback."""
    try:
        print(text)
        sys.stdout.flush()
    except BrokenPipeError:
        try:
            devnull = os.open(os.devnull, os.O_WRONLY)
            os.dup2(devnull, sys.stdout.fileno())
        except OSError:
            pass


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        _emit("\n".join(
            f"{rule.id} [{rule.severity}]: {rule.short}"
            for rule in all_rules()
        ))
        return 0
    paths = args.paths or [PACKAGE_DIR]
    select = (
        {token.strip() for token in args.select.split(",") if token.strip()}
        if args.select
        else None
    )
    result = lint_paths(paths, select=select)
    _emit(render_json(result) if args.format == "json"
          else render_text(result))
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
