"""``pio lint`` / ``python -m predictionio_tpu.tools.lint`` — run the
TPU-hygiene static analyzer over files or directories.

Exit codes are pinned (the contract the tier-1 gate, CI and the
pre-window checklist all read):

- ``0`` — clean: every finding suppressed (with a reason) or baselined
- ``1`` — unsuppressed findings remain
- ``2`` — engine error: a file failed to parse, a target path does not
  exist, git could not enumerate ``--changed`` files, or the
  ``--baseline`` file is unreadable — the run proved *nothing*, which
  must never be mistaken for "clean" OR for "has findings"

``--format json`` emits one machine-readable document on stdout.
``--changed`` lints only files git reports as modified/added/untracked
(diff-scoped pre-commit runs); ``--baseline FILE`` adopts legacy
findings recorded by an earlier ``--format json`` run and ratchets:
baselined debt is absorbed, anything new still fails.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
from typing import List, Optional, Sequence

from ..lint import all_rules, lint_paths, render_json, render_text
from ..lint.engine import apply_baseline, load_baseline

#: default lint target: the installed package itself
PACKAGE_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

EXIT_CLEAN = 0
EXIT_FINDINGS = 1
EXIT_ENGINE_ERROR = 2


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="pio lint",
        description="TPU-hygiene static analysis (Mosaic/jit/robust/obs/"
        "conc/spmd rules; see docs/lint.md)",
    )
    p.add_argument(
        "paths", nargs="*", default=None,
        help="files or directories to lint (default: the "
        "predictionio_tpu package)",
    )
    p.add_argument(
        "--format", choices=("text", "json"), default="text",
        help="report format (json is the watcher/CI interface)",
    )
    p.add_argument(
        "--select", default=None,
        help="comma-separated rule ids to run (default: all)",
    )
    p.add_argument(
        "--changed", action="store_true",
        help="lint only files git reports changed (working tree + index "
        "+ untracked) under the target paths — the pre-commit scope",
    )
    p.add_argument(
        "--baseline", default=None, metavar="FILE",
        help="adopt legacy findings recorded by an earlier "
        "`pio lint --format json > FILE` run: baselined findings are "
        "absorbed (reported, not fatal), new ones still fail — the "
        "ratchet never loosens",
    )
    p.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalog and exit",
    )
    return p


def _emit(text: str) -> None:
    """Print that dies quietly on a closed pipe (``pio lint | head``):
    the exit code still carries the gate verdict, and stdout is pointed
    at devnull so the interpreter's exit flush cannot raise a second
    traceback."""
    try:
        print(text)
        sys.stdout.flush()
    except BrokenPipeError:
        try:
            devnull = os.open(os.devnull, os.O_WRONLY)
            os.dup2(devnull, sys.stdout.fileno())
        except OSError:
            pass


def changed_files(paths: Sequence[str]) -> List[str]:
    """Python files git reports as changed (unstaged, staged, or
    untracked) that live under one of ``paths``. Raises RuntimeError
    when git cannot answer — the caller maps that to exit 2, because a
    silent empty set would read as "nothing to lint: clean".

    Git runs against the repository *containing the first target path*,
    not the process cwd (``pio lint --changed /other/repo`` must see
    that repo's status), and reads ``--porcelain -z`` so file names
    with spaces/non-ASCII arrive verbatim instead of C-quoted (a
    quoted name would fail the existence check and silently vanish
    from the scope)."""
    roots = [os.path.abspath(p) for p in paths]
    anchor = roots[0]
    git_cwd = anchor if os.path.isdir(anchor) else (
        os.path.dirname(anchor) or "."
    )

    def _git(*args: str) -> subprocess.CompletedProcess:
        try:
            return subprocess.run(
                ["git", *args], capture_output=True, text=True,
                timeout=30, cwd=git_cwd,
            )
        except (OSError, subprocess.TimeoutExpired) as exc:
            raise RuntimeError(f"git {args[0]} failed: {exc}")

    proc = _git("status", "--porcelain", "-z", "--untracked-files=all")
    if proc.returncode != 0:
        raise RuntimeError(
            f"git status failed: {proc.stderr.strip() or proc.returncode}"
        )
    top_proc = _git("rev-parse", "--show-toplevel")
    if top_proc.returncode != 0:
        raise RuntimeError(
            "git rev-parse failed: "
            f"{top_proc.stderr.strip() or top_proc.returncode}"
        )
    top = top_proc.stdout.strip()
    out: List[str] = []
    entries = proc.stdout.split("\0")
    i = 0
    while i < len(entries):
        entry = entries[i]
        i += 1
        if len(entry) < 4:
            continue
        status, path = entry[:2], entry[3:]
        if status[0] in ("R", "C"):
            i += 1  # -z renames/copies: the NEXT entry is the OLD path
        if status.strip() == "D":
            continue  # deleted: nothing to lint
        if not path.endswith(".py"):
            continue
        abspath = os.path.abspath(os.path.join(top, path))
        if not os.path.exists(abspath):
            continue
        if any(
            abspath == root or abspath.startswith(root + os.sep)
            for root in roots
        ):
            out.append(abspath)
    return sorted(out)


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        _emit("\n".join(
            f"{rule.id} [{rule.severity}]: {rule.short}"
            for rule in all_rules()
        ))
        return EXIT_CLEAN
    paths = args.paths or [PACKAGE_DIR]
    # validate the baseline BEFORE any early return: a typo'd baseline
    # path must be exit 2 even on a day when --changed finds nothing —
    # otherwise CI reads "clean" until the first changed file exposes
    # the broken configuration
    baseline = None
    if args.baseline:
        try:
            baseline = load_baseline(args.baseline)
        except (OSError, ValueError) as exc:
            _emit(f"error: --baseline: {exc}")
            return EXIT_ENGINE_ERROR
    if args.changed:
        try:
            paths = changed_files(paths)
        except RuntimeError as exc:
            _emit(f"error: --changed: {exc}")
            return EXIT_ENGINE_ERROR
        if not paths:
            # the empty-scope happy path must still honor --format json:
            # a CI consumer piping into a JSON parser hits this on every
            # clean run
            if args.format == "json":
                _emit(render_json(lint_paths([])))
            else:
                _emit(
                    "0 files, 0 findings, 0 suppressed (no changed files)"
                )
            return EXIT_CLEAN
    select = (
        {token.strip() for token in args.select.split(",") if token.strip()}
        if args.select
        else None
    )
    result = lint_paths(paths, select=select)
    if baseline is not None:
        apply_baseline(result, baseline)
    _emit(render_json(result) if args.format == "json"
          else render_text(result))
    if result.errors:
        return EXIT_ENGINE_ERROR
    return EXIT_CLEAN if not result.findings else EXIT_FINDINGS


if __name__ == "__main__":
    sys.exit(main())
