"""Serving driver process — the ``CreateServer`` spawn analogue.

Rebuild of ``tools/.../RunServer.scala:29-139`` + the served ``CreateServer``
main (``core/.../workflow/CreateServer.scala:100-182``): resolve the engine
project, load its factory, and serve the latest COMPLETED engine instance on
``POST /queries.json`` (with ``GET /reload`` hot-swap and ``GET /stop``).
"""

from __future__ import annotations

import argparse
import logging
import sys
from typing import Optional, Sequence

from ..storage import StorageRegistry, get_registry
from ..workflow import loader
from ..workflow.serving import QueryServer, ServerConfig, create_query_server
from .register import load_engine_dir

logger = logging.getLogger(__name__)


def build_parser() -> argparse.ArgumentParser:
    """Flag grammar (``CreateServer.scala:101-147``)."""
    p = argparse.ArgumentParser(prog="run_server")
    p.add_argument("--engine-dir", default=".")
    p.add_argument("--engine-instance-id", default=None)
    p.add_argument("--ip", default="localhost")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--engine-variant", default="engine.json")
    p.add_argument("--feedback", action="store_true")
    p.add_argument("--event-server-ip", default="localhost")
    p.add_argument("--event-server-port", type=int, default=7070)
    p.add_argument("--accesskey", default=None)
    p.add_argument("--batch", default="")
    p.add_argument("--log-url", default=None,
                   help="POST serving errors here (CreateServer --log-url)")
    p.add_argument("--batch-max", type=int, default=None,
                   help="micro-batch size cap (default 512; size to catalog)")
    p.add_argument("--batch-pipeline-depth", type=int, default=None,
                   help="batches in flight at once (default 2; raise when "
                        "the host-to-device round trip dwarfs device time)")
    p.add_argument("--shard-index", type=int, default=0, metavar="I",
                   help="serve item-factor partition I of --shard-count "
                        "behind a `pio router --sharded` tier "
                        "(docs/fleet.md)")
    p.add_argument("--shard-count", type=int, default=1, metavar="N",
                   help="total shards the item factors partition into "
                        "(1 = unsharded)")
    p.add_argument("--continuous-app", type=int, default=None, metavar="APP_ID",
                   help="attach the continuous-learning loop for this app: "
                        "changefeed-driven fold-in training with automatic "
                        "rollout submission (docs/continuous.md)")
    p.add_argument("--continuous-feed", default=None, metavar="URL",
                   help="storage primary whose GET /replicate/changes the "
                        "loop tails; a ';'-separated partitioned URL "
                        "(storage.md#partitioning) tails one changefeed "
                        "per partition with independent durable cursors "
                        "(default: $PIO_STORAGE_SOURCES_*_URL when the "
                        "registry is remote)")
    p.add_argument("--continuous-min-events", type=int, default=10,
                   help="delta size that triggers a training cycle")
    p.add_argument("--continuous-staleness-s", type=float, default=300.0,
                   help="trigger below min-events once the oldest pending "
                        "event is this stale (freshness floor)")
    p.add_argument("--verbose", action="store_true")
    return p


def _continuous_config(args: argparse.Namespace, registry):
    """Build a ContinuousConfig from the CLI surface (None = disabled)."""
    if getattr(args, "continuous_app", None) is None:
        return None
    from ..continuous.controller import ContinuousConfig

    feed_url = getattr(args, "continuous_feed", None)
    if not feed_url:
        # derive the primaries from a remote-registry env: the loop
        # tails the same storage server(s) every other plane already
        # talks to — one changefeed per partition primary on a
        # partitioned URL (docs/storage.md#partitioning)
        from ..storage.partition import partition_primaries

        env = registry._env if registry is not None else {}
        for key, value in env.items():
            if key.startswith("PIO_STORAGE_SOURCES_") and (
                key.endswith("_URL") or key.endswith("_PARTITIONS")
            ):
                if key.endswith("_PARTITIONS"):
                    value = f"pio+ha://{value}"
                feed_url = ";".join(partition_primaries(value))
                break
    if not feed_url:
        raise SystemExit(
            "--continuous-app needs a changefeed source: pass "
            "--continuous-feed URL (the storage primary) or configure a "
            "remote storage registry (docs/continuous.md)"
        )
    return ContinuousConfig(
        app_id=args.continuous_app,
        feed_url=feed_url,
        min_events=args.continuous_min_events,
        max_staleness_s=args.continuous_staleness_s,
    )


def make_server(
    args: argparse.Namespace,
    registry: Optional[StorageRegistry] = None,
    block: bool = True,
) -> QueryServer:
    loader.modify_logging(args.verbose)
    registry = registry or get_registry()
    ed = load_engine_dir(args.engine_dir)
    loader.apply_runtime_conf(ed.variant)  # the embedded-sparkConf analogue
    engine = loader.get_engine(ed.engine_factory, search_dir=ed.path)
    config = ServerConfig(
        ip=args.ip,
        port=args.port,
        engine_instance_id=args.engine_instance_id,
        engine_id=ed.manifest.id,
        engine_version=ed.manifest.version,
        engine_variant=args.engine_variant,
        feedback=args.feedback,
        event_server_ip=args.event_server_ip,
        event_server_port=args.event_server_port,
        access_key=args.accesskey,
        batch=args.batch,
        log_url=args.log_url,
        shard_index=getattr(args, "shard_index", 0),
        shard_count=getattr(args, "shard_count", 1),
        continuous=_continuous_config(args, registry),
        # frozen dataclass: only override the defaults when flags were given
        **{
            k: v
            for k, v in (
                ("batch_max", getattr(args, "batch_max", None)),
                ("batch_pipeline_depth",
                 getattr(args, "batch_pipeline_depth", None)),
            )
            if v is not None
        },
    )
    return create_query_server(engine, config, registry, block=block)


def main(argv: Optional[Sequence[str]] = None) -> int:
    # Platform self-forcing before any backend init (see run_workflow.main).
    from ..utils.jax_cache import enable_compilation_cache
    from ..utils.platform import apply_env_platform

    apply_env_platform()
    # serving compiles per (batch-shape, depth); the loadgen sweep deploys
    # this server once per pipeline depth — warm starts matter there
    enable_compilation_cache()
    # arm the crash path (docs/slo.md): with PIO_FLIGHT_DIR set, a dying
    # server leaves its flight-recorder timeline and faulthandler stacks
    # behind; signals=True also dumps on SIGTERM (CLI entry points only —
    # a library import must never steal signal dispositions)
    from ..obs.flight import arm

    arm(signals=True)
    args = build_parser().parse_args(argv)
    make_server(args, block=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
