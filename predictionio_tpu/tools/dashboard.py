"""Evaluation dashboard: HTML list of completed evaluations + drill-down.

Rebuild of ``tools/.../dashboard/Dashboard.scala`` (spray server on :9000
listing completed ``EvaluationInstance`` rows newest-first, with per-instance
HTML and JSON result pages) and ``CorsSupport.scala`` (CORS headers on every
response so external UIs can consume the JSON).
"""

from __future__ import annotations

import argparse
import dataclasses
import html
import sys
from typing import Optional, Sequence
from urllib.parse import urlparse

from ..api.http import BackgroundHTTPServer, JsonHTTPHandler
from ..storage import StorageRegistry, get_registry

DEFAULT_PORT = 9000  # Dashboard.scala default


@dataclasses.dataclass(frozen=True)
class DashboardConfig:
    ip: str = "localhost"
    port: int = DEFAULT_PORT
    #: node list the /fleet panel scrapes (GET /metrics per node); the
    #: quickstart topology by default — override with --nodes
    nodes: str = ""
    #: per-node scrape timeout for /fleet (the page must render even
    #: with half the fleet down)
    scrape_timeout_s: float = 2.0


def _fmt_time(dt) -> str:
    return dt.strftime("%Y-%m-%d %H:%M:%S")


def _page(title: str, body: str) -> str:
    """Shared page skeleton for every dashboard panel — one place for
    the doctype and style block so the panels cannot drift visually."""
    return (
        f"<!DOCTYPE html><html><head><title>{html.escape(title)}</title>"
        "<style>body{font-family:sans-serif}table{border-collapse:collapse}"
        "td,th{border:1px solid #ccc;padding:4px 8px}</style></head><body>"
        + body
        + "</body></html>"
    )


def render_index(instances) -> str:
    """The main listing page (``Dashboard.scala`` index route)."""
    rows = []
    for inst in instances:
        rows.append(
            "<tr>"
            f"<td>{html.escape(inst.id)}</td>"
            f"<td>{html.escape(inst.evaluation_class)}</td>"
            f"<td>{html.escape(inst.engine_params_generator_class)}</td>"
            f"<td>{html.escape(inst.batch)}</td>"
            f"<td>{_fmt_time(inst.start_time)}</td>"
            f"<td>{_fmt_time(inst.end_time)}</td>"
            f"<td>{html.escape(inst.evaluator_results)}</td>"
            f'<td><a href="/engine_instances/{inst.id}/evaluator_results.html">HTML</a> '
            f'<a href="/engine_instances/{inst.id}/evaluator_results.json">JSON</a></td>'
            "</tr>"
        )
    return _page(
        "PredictionIO-TPU Dashboard",
        "<h1>Completed evaluations</h1>"
        "<table><tr><th>ID</th><th>Evaluation</th><th>Params generator</th>"
        "<th>Batch</th><th>Start</th><th>End</th><th>Result</th><th>Detail</th></tr>"
        + "".join(rows)
        + "</table>",
    )


def render_train_runs(instances) -> str:
    """``GET /train_runs``: engine (training) instances with the
    per-phase timings the workflow persisted into the instance record
    (``utils/profiling.phases_from_env``, docs/observability.md) — the
    training-time twin of the evaluations listing."""
    from ..utils.profiling import phases_from_env

    rows = []
    for inst in sorted(instances, key=lambda i: i.start_time, reverse=True):
        phases = phases_from_env(inst.env)
        phase_text = (
            ", ".join(f"{k}={v:.3f}s" for k, v in sorted(phases.items()))
            or "-"
        )
        rows.append(
            "<tr>"
            f"<td>{html.escape(inst.id)}</td>"
            f"<td>{html.escape(inst.status)}</td>"
            f"<td>{html.escape(inst.engine_id)} "
            f"{html.escape(inst.engine_version)}</td>"
            f"<td>{_fmt_time(inst.start_time)}</td>"
            f"<td>{_fmt_time(inst.end_time)}</td>"
            f"<td>{html.escape(phase_text)}</td>"
            "</tr>"
        )
    return _page(
        "Train runs",
        "<h1>Train runs</h1>"
        "<table><tr><th>ID</th><th>Status</th><th>Engine</th>"
        "<th>Start</th><th>End</th><th>Train phases</th></tr>"
        + "".join(rows)
        + "</table>",
    )


def render_rollouts(plans) -> str:
    """``GET /rollouts``: every RolloutPlan newest-first — the staged
    deploys' audit trail (stage, split, gate verdicts that drove each
    transition; ``docs/rollouts.md``)."""
    rows = []
    for plan in plans:
        last = plan.history[-1] if plan.history else {}
        rows.append(
            "<tr>"
            f"<td>{html.escape(plan.id)}</td>"
            f"<td>{html.escape(plan.stage)}</td>"
            f"<td>{html.escape(plan.engine_id)} "
            f"{html.escape(plan.engine_version)}</td>"
            f"<td>{html.escape(plan.baseline_instance_id)}</td>"
            f"<td>{html.escape(plan.candidate_instance_id)}</td>"
            f"<td>{plan.percent:g}%</td>"
            f"<td>{_fmt_time(plan.updated_time)}</td>"
            f"<td>{html.escape(str(last.get('reason', '-')))}</td>"
            "</tr>"
        )
    return _page(
        "Rollouts",
        "<h1>Rollouts</h1>"
        "<table><tr><th>ID</th><th>Stage</th><th>Engine</th>"
        "<th>Baseline</th><th>Candidate</th><th>Canary %</th>"
        "<th>Updated</th><th>Last transition</th></tr>"
        + "".join(rows)
        + "</table>",
    )


def rollouts_json(plans) -> list:
    """Machine-readable twin of ``/rollouts`` — the same wire shape the
    query server's ``/rollout.json`` uses (``rollout/plan.py``)."""
    from ..rollout.plan import plan_to_json

    return [plan_to_json(plan) for plan in plans]


def train_runs_json(instances) -> list:
    """Machine-readable twin of ``/train_runs``."""
    from ..utils.profiling import phases_from_env

    return [
        {
            "id": inst.id,
            "status": inst.status,
            "engineId": inst.engine_id,
            "engineVersion": inst.engine_version,
            "startTime": str(inst.start_time),
            "endTime": str(inst.end_time),
            "trainPhases": phases_from_env(inst.env),
        }
        for inst in sorted(
            instances, key=lambda i: i.start_time, reverse=True
        )
    ]


def render_fleet(rows) -> str:
    """``GET /fleet``: the ``pio top`` table as a dashboard panel —
    per-node serving latency, shed/breaker state, replication lag,
    event-store partition health (PARTS: partitions reachable / total
    from each node's ``/replication.json``,
    docs/storage.md#partitioning), continuous-learning freshness
    (FEEDLAG / CANDAGE, docs/continuous.md) and jit compile/retrace
    counts (docs/observability.md#profiling)."""
    from ..obs.top import FLEET_COLUMNS, format_row

    header = "".join(
        f"<th>{html.escape(title)}</th>" for title, _, _ in FLEET_COLUMNS
    )
    body = [
        "<tr>"
        + "".join(f"<td>{html.escape(c)}</td>" for c in format_row(row))
        + "</tr>"
        for row in rows
    ]
    return _page(
        "Fleet",
        "<h1>Fleet</h1>"
        f"<table><tr>{header}</tr>" + "".join(body) + "</table>"
        "<p>FEEDLAG/CANDAGE: continuous-learning freshness; "
        "JITC/RETRACE: jit compiles / new-signature retraces.</p>",
    )


def render_quality(rows) -> str:
    """``GET /quality``: per-node quality digest — score drift (PSI vs
    the pinned baseline), feedback hit-rate, ingest mix drift and
    violation counts (docs/observability.md#quality)."""

    def fmt(value, spec="{:.4f}"):
        return "-" if value is None else spec.format(value)

    body = []
    for row in rows:
        if not row.get("up"):
            body.append(
                f"<tr><td>{html.escape(str(row.get('node', '?')))}</td>"
                "<td colspan=\"5\">DOWN</td></tr>"
            )
            continue
        psi = row.get("scorePsi") or {}
        feedback = row.get("feedback") or {}
        ingest = row.get("ingest") or {}
        mix = " ".join(
            f"{app}:{fmt(stats.get('mixPsi'))}"
            for app, stats in sorted(ingest.items())
        )
        violations = sum(
            n
            for stats in ingest.values()
            for n in (stats.get("violations") or {}).values()
        )
        body.append(
            "<tr>"
            f"<td>{html.escape(str(row.get('node', '?')))}</td>"
            f"<td>{fmt(psi.get('baseline'))}</td>"
            f"<td>{fmt(psi.get('candidate'))}</td>"
            f"<td>{fmt(feedback.get('hitRate'), '{:.3f}')}</td>"
            f"<td>{html.escape(mix) or '-'}</td>"
            f"<td>{violations if ingest else '-'}</td>"
            "</tr>"
        )
    return _page(
        "Quality",
        "<h1>Quality</h1>"
        "<table><tr><th>NODE</th><th>PSI baseline</th>"
        "<th>PSI candidate</th><th>HITRATE</th><th>MIX PSI</th>"
        "<th>VIOLATIONS</th></tr>" + "".join(body) + "</table>"
        "<p>PSI: served-score drift vs the baseline snapshot pinned at "
        "model LIVE; HITRATE: feedback items found in the user's served "
        "list; MIX PSI: per-app event-type mix drift at ingest "
        "(docs/observability.md#quality).</p>",
    )


def render_health(rows) -> str:
    """``GET /health``: per-node SLO/stall digest scraped from each
    node's ``/health.json`` (docs/slo.md) — firing objectives, worst
    fast-window burn, stall counts, abstaining objectives."""

    def fmt(value, spec="{:.2f}"):
        return "-" if value is None else spec.format(value)

    body = []
    for row in rows:
        if not row.get("up"):
            body.append(
                f"<tr><td>{html.escape(str(row.get('node', '?')))}</td>"
                "<td colspan=\"5\">DOWN</td></tr>"
            )
            continue
        firing = row.get("firing") or []
        body.append(
            "<tr>"
            f"<td>{html.escape(str(row.get('node', '?')))}</td>"
            f"<td>{html.escape(str(row.get('kind', '?')))}</td>"
            f"<td>{html.escape(' '.join(firing)) or 'ok'}</td>"
            f"<td>{fmt(row.get('worstBurnFast'))}</td>"
            f"<td>{row.get('stallsDetected', 0)}</td>"
            f"<td>{row.get('abstaining', 0)}</td>"
            "</tr>"
        )
    return _page(
        "Health",
        "<h1>Health</h1>"
        "<table><tr><th>NODE</th><th>KIND</th><th>FIRING</th>"
        "<th>BURN</th><th>STALLS</th><th>ABSTAIN</th></tr>"
        + "".join(body) + "</table>"
        "<p>FIRING: objectives whose error budget burns past the "
        "multi-window threshold; BURN: worst fast-window burn rate; "
        "STALLS: watchdog detections; ABSTAIN: objectives with no "
        "data — never read as healthy (docs/slo.md).</p>",
    )


class _DashboardHandler(JsonHTTPHandler):
    server: "DashboardServer"

    def end_headers(self) -> None:
        # CorsSupport.scala: allow-all origin on every response.
        self.send_header("Access-Control-Allow-Origin", "*")
        super().end_headers()

    def do_GET(self) -> None:  # noqa: N802
        path = urlparse(self.path).path
        # fleet health panel BEFORE serve_obs: on the dashboard,
        # /health is the scraped fleet view (docs/slo.md), and
        # /health.json answers the uniform per-node contract (a DICT
        # with this process's own objectives — `pio health` must never
        # misread a live dashboard as DOWN) with the scraped fleet rows
        # riding along under "fleet"
        if path == "/health":
            self.respond(
                200,
                render_health(self.server.health_rows()),
                content_type="text/html",
            )
            return
        if path == "/health.json":
            doc = (
                self.server.health.health_json()
                if self.server.health is not None
                else {}
            )
            doc["fleet"] = self.server.health_rows()
            self.respond(200, doc)
            return
        if self.serve_obs(path):  # /metrics, /traces.json, /blackbox.json
            return
        md = self.server.registry.get_metadata()
        if path == "/":
            instances = md.evaluation_instance_get_completed()
            self.respond(200, render_index(instances), content_type="text/html")
            return
        # /train_runs, NOT /engine_instances: the pre-existing
        # /engine_instances/<id>/evaluator_results.* detail routes name
        # EVALUATION instances (reference parity, Dashboard.scala) — the
        # training listing must not squat on that prefix
        if path == "/train_runs":
            self.respond(
                200,
                render_train_runs(md.engine_instance_get_all()),
                content_type="text/html",
            )
            return
        if path == "/train_runs.json":
            self.respond(
                200, train_runs_json(md.engine_instance_get_all())
            )
            return
        if path == "/rollouts":
            self.respond(
                200,
                render_rollouts(md.rollout_plan_get_all()),
                content_type="text/html",
            )
            return
        if path == "/rollouts.json":
            self.respond(200, rollouts_json(md.rollout_plan_get_all()))
            return
        if path in ("/fleet", "/fleet.json"):
            rows = self.server.fleet_rows()
            if path == "/fleet.json":
                self.respond(200, rows)
            else:
                self.respond(
                    200, render_fleet(rows), content_type="text/html"
                )
            return
        if path in ("/quality", "/quality.json"):
            rows = self.server.quality_rows()
            if path == "/quality.json":
                self.respond(200, rows)
            else:
                self.respond(
                    200, render_quality(rows), content_type="text/html"
                )
            return
        parts = [p for p in path.split("/") if p]
        if len(parts) == 3 and parts[0] == "engine_instances":
            inst = md.evaluation_instance_get(parts[1])
            if inst is None:
                self.respond(404, {"message": f"{parts[1]} not found"})
                return
            if parts[2] == "evaluator_results.html":
                self.respond(
                    200, inst.evaluator_results_html or "<html></html>",
                    content_type="text/html",
                )
                return
            if parts[2] == "evaluator_results.json":
                self.respond(
                    200, inst.evaluator_results_json or "{}",
                    content_type="application/json; charset=utf-8",
                )
                return
        self.respond(404, {"message": "Not Found"})


class DashboardServer(BackgroundHTTPServer):
    def __init__(self, config: DashboardConfig, registry: StorageRegistry):
        self.config = config
        self.registry = registry
        super().__init__(
            (config.ip, config.port), _DashboardHandler,
            health_kind="dashboard",
        )

    def _scrape_nodes(self, per_node) -> list:
        """Run ``per_node(node, timeout)`` over the configured node list
        concurrently, so a panel answers in ~one ``scrape_timeout_s``
        even with the whole fleet down — not nodes × timeout."""
        from concurrent.futures import ThreadPoolExecutor

        from ..obs.top import DEFAULT_NODES

        nodes = [
            node.strip()
            for node in (self.config.nodes or DEFAULT_NODES).split(",")
            if node.strip()
        ]
        if not nodes:
            return []
        with ThreadPoolExecutor(max_workers=min(8, len(nodes))) as pool:
            return list(
                pool.map(
                    lambda node: per_node(
                        node, timeout=self.config.scrape_timeout_s
                    ),
                    nodes,
                )
            )

    def fleet_rows(self) -> list:
        """Scrape the configured node list for the /fleet panel (a dead
        node renders DOWN)."""
        from ..obs.top import node_row

        return self._scrape_nodes(node_row)

    def quality_rows(self) -> list:
        """Scrape the node list for the /quality panel."""
        from .quality import node_report

        def scrape(node: str, timeout: float) -> dict:
            report = node_report(node, timeout=timeout)
            return report if report is not None else {
                "node": node, "up": False,
            }

        return self._scrape_nodes(scrape)

    def health_rows(self) -> list:
        """Scrape the node list's ``/health.json`` for the /health
        panel (docs/slo.md); a dead node renders DOWN."""
        from .health import node_health

        def scrape(node: str, timeout: float) -> dict:
            report = node_health(node, timeout=timeout)
            return report if report is not None else {
                "node": node, "up": False,
            }

        return self._scrape_nodes(scrape)


def create_dashboard(
    config: DashboardConfig = DashboardConfig(),
    registry: Optional[StorageRegistry] = None,
    block: bool = True,
) -> DashboardServer:
    registry = registry or get_registry()
    server = DashboardServer(config, registry)
    if block:
        try:
            server.serve_forever()
        finally:
            server.server_close()
    else:
        server.start_background()
    return server


def main(argv: Optional[Sequence[str]] = None) -> int:
    from ..utils.platform import apply_env_platform

    apply_env_platform()
    p = argparse.ArgumentParser(prog="dashboard")
    p.add_argument("--ip", default="localhost")
    p.add_argument("--port", type=int, default=DEFAULT_PORT)
    p.add_argument(
        "--nodes", default="", metavar="HOST:PORT,...",
        help="fleet nodes the /fleet panel scrapes "
        "(default: the localhost quickstart topology)",
    )
    args = p.parse_args(argv)
    create_dashboard(
        DashboardConfig(ip=args.ip, port=args.port, nodes=args.nodes),
        block=True,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
