"""Evaluation dashboard: HTML list of completed evaluations + drill-down.

Rebuild of ``tools/.../dashboard/Dashboard.scala`` (spray server on :9000
listing completed ``EvaluationInstance`` rows newest-first, with per-instance
HTML and JSON result pages) and ``CorsSupport.scala`` (CORS headers on every
response so external UIs can consume the JSON).
"""

from __future__ import annotations

import argparse
import dataclasses
import html
import sys
from typing import Optional, Sequence
from urllib.parse import urlparse

from ..api.http import BackgroundHTTPServer, JsonHTTPHandler
from ..storage import StorageRegistry, get_registry

DEFAULT_PORT = 9000  # Dashboard.scala default


@dataclasses.dataclass(frozen=True)
class DashboardConfig:
    ip: str = "localhost"
    port: int = DEFAULT_PORT


def _fmt_time(dt) -> str:
    return dt.strftime("%Y-%m-%d %H:%M:%S")


def render_index(instances) -> str:
    """The main listing page (``Dashboard.scala`` index route)."""
    rows = []
    for inst in instances:
        rows.append(
            "<tr>"
            f"<td>{html.escape(inst.id)}</td>"
            f"<td>{html.escape(inst.evaluation_class)}</td>"
            f"<td>{html.escape(inst.engine_params_generator_class)}</td>"
            f"<td>{html.escape(inst.batch)}</td>"
            f"<td>{_fmt_time(inst.start_time)}</td>"
            f"<td>{_fmt_time(inst.end_time)}</td>"
            f"<td>{html.escape(inst.evaluator_results)}</td>"
            f'<td><a href="/engine_instances/{inst.id}/evaluator_results.html">HTML</a> '
            f'<a href="/engine_instances/{inst.id}/evaluator_results.json">JSON</a></td>'
            "</tr>"
        )
    return (
        "<!DOCTYPE html><html><head><title>PredictionIO-TPU Dashboard</title>"
        "<style>body{font-family:sans-serif}table{border-collapse:collapse}"
        "td,th{border:1px solid #ccc;padding:4px 8px}</style></head><body>"
        "<h1>Completed evaluations</h1>"
        "<table><tr><th>ID</th><th>Evaluation</th><th>Params generator</th>"
        "<th>Batch</th><th>Start</th><th>End</th><th>Result</th><th>Detail</th></tr>"
        + "".join(rows)
        + "</table></body></html>"
    )


class _DashboardHandler(JsonHTTPHandler):
    server: "DashboardServer"

    def end_headers(self) -> None:
        # CorsSupport.scala: allow-all origin on every response.
        self.send_header("Access-Control-Allow-Origin", "*")
        super().end_headers()

    def do_GET(self) -> None:  # noqa: N802
        path = urlparse(self.path).path
        md = self.server.registry.get_metadata()
        if path == "/":
            instances = md.evaluation_instance_get_completed()
            self.respond(200, render_index(instances), content_type="text/html")
            return
        parts = [p for p in path.split("/") if p]
        if len(parts) == 3 and parts[0] == "engine_instances":
            inst = md.evaluation_instance_get(parts[1])
            if inst is None:
                self.respond(404, {"message": f"{parts[1]} not found"})
                return
            if parts[2] == "evaluator_results.html":
                self.respond(
                    200, inst.evaluator_results_html or "<html></html>",
                    content_type="text/html",
                )
                return
            if parts[2] == "evaluator_results.json":
                self.respond(
                    200, inst.evaluator_results_json or "{}",
                    content_type="application/json; charset=utf-8",
                )
                return
        self.respond(404, {"message": "Not Found"})


class DashboardServer(BackgroundHTTPServer):
    def __init__(self, config: DashboardConfig, registry: StorageRegistry):
        self.config = config
        self.registry = registry
        super().__init__((config.ip, config.port), _DashboardHandler)


def create_dashboard(
    config: DashboardConfig = DashboardConfig(),
    registry: Optional[StorageRegistry] = None,
    block: bool = True,
) -> DashboardServer:
    registry = registry or get_registry()
    server = DashboardServer(config, registry)
    if block:
        try:
            server.serve_forever()
        finally:
            server.server_close()
    else:
        server.start_background()
    return server


def main(argv: Optional[Sequence[str]] = None) -> int:
    from ..utils.platform import apply_env_platform

    apply_env_platform()
    p = argparse.ArgumentParser(prog="dashboard")
    p.add_argument("--ip", default="localhost")
    p.add_argument("--port", type=int, default=DEFAULT_PORT)
    args = p.parse_args(argv)
    create_dashboard(DashboardConfig(ip=args.ip, port=args.port), block=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
