"""Serving load generator: measure query throughput and tail latency.

The BASELINE.json north star asks for ≥10k queries/s/chip from the
deployed recommender. This tool produces the evidence: it hammers a query
server with concurrent persistent-connection workers and reports QPS and
latency percentiles as one JSON line.

Two modes:

- **HTTP** (default): end-to-end through ``POST /queries.json`` — what a
  client sees, including HTTP parsing and the Python server stack.
- **--in-process**: builds the deployment and drives
  ``QueryServer.handle_query`` directly from worker threads — isolates
  the prediction path (micro-batcher + device dispatch) from HTTP
  overhead, i.e. the ceiling the serving stack itself imposes.

Resilience drive (``docs/robustness.md`` cookbook):

- ``--deadline-ms N`` stamps every request with an ``X-PIO-Deadline-Ms``
  budget; responses shed by the server (503) and expired-deadline 504s
  are counted separately from hard errors, so the report shows the
  *server's* overload behavior instead of burying it in ``errors``.
- ``--fault SPEC`` (repeatable; ``site=kind[:arg][*times]``, the
  ``PIO_FAULTS`` syntax) activates the deterministic fault harness in
  this process — faults fire inside an ``--in-process`` server's I/O.
  Against a live HTTP server, start *it* with ``PIO_FAULTS=...`` in its
  environment and use loadgen to observe the degradation; loadgen prints
  the equivalent env assignment so the two stay in sync.

Usage::

    python -m predictionio_tpu.tools.loadgen \
        --url http://localhost:8000/queries.json \
        --payload '{"user": "1", "num": 10}' \
        --concurrency 32 --duration 10 --deadline-ms 250

The payload may contain ``{i}`` which each worker substitutes with a
rotating integer (vary the queried user).
"""

from __future__ import annotations

import argparse
import dataclasses
import http.client
import json
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Sequence
from urllib.parse import urlparse

import numpy as np

#: response-class counters beyond plain latency samples
_SHED = 503
_EXPIRED = 504


class _Worker(threading.Thread):
    def __init__(self, target, payloads: Sequence[bytes], stop_at: float):
        super().__init__(daemon=True)
        self.target = target
        self.payloads = payloads
        self.stop_at = stop_at
        self.latencies: List[float] = []
        self.errors = 0
        self.shed = 0
        self.deadline_expired = 0

    def run(self) -> None:
        i = 0
        while time.monotonic() < self.stop_at:
            payload = self.payloads[i % len(self.payloads)]
            t0 = time.monotonic()
            try:
                status = self.target(payload)
            except Exception:
                status = -1
            elapsed = time.monotonic() - t0
            if status == 200:
                self.latencies.append(elapsed)
            elif status == _SHED:
                self.shed += 1
            elif status == _EXPIRED:
                self.deadline_expired += 1
            else:
                self.errors += 1
            i += 1


def _http_target(url: str, deadline_ms: Optional[float] = None):
    parsed = urlparse(url)
    # One persistent connection PER WORKER THREAD: http.client connections
    # are not thread-safe, and sharing one socket across workers would
    # interleave request/response pairs and corrupt every measurement.
    local = threading.local()

    def send(payload: bytes) -> int:
        conn = getattr(local, "conn", None)
        if conn is None:
            conn = http.client.HTTPConnection(
                parsed.hostname, parsed.port or 80, timeout=30
            )
            local.conn = conn
        headers = {"Content-Type": "application/json"}
        if deadline_ms is not None:
            headers["X-PIO-Deadline-Ms"] = str(int(deadline_ms))
        try:
            conn.request(
                "POST",
                parsed.path or "/queries.json",
                body=payload,
                headers=headers,
            )
            resp = conn.getresponse()
            resp.read()
            return resp.status
        except Exception:
            local.conn = None  # reconnect next attempt
            try:
                conn.close()
            except Exception:
                pass
            raise

    return send


def run_load(
    target,
    payloads: Sequence[bytes],
    concurrency: int,
    duration_s: float,
) -> dict:
    """Drive ``target(payload) -> status`` from ``concurrency`` threads
    for ``duration_s``; returns {qps, p50_ms, p99_ms, shed, ...}."""
    stop_at = time.monotonic() + duration_s
    t0 = time.monotonic()
    workers = [_Worker(target, payloads, stop_at) for _ in range(concurrency)]
    for w in workers:
        w.start()
    for w in workers:
        w.join()
    wall = time.monotonic() - t0
    lats = np.concatenate(
        [np.asarray(w.latencies) for w in workers if w.latencies]
    ) if any(w.latencies for w in workers) else np.zeros(0)
    errors = sum(w.errors for w in workers)
    shed = sum(w.shed for w in workers)
    expired = sum(w.deadline_expired for w in workers)
    n = int(lats.size)
    out = {
        "requests": n,
        "errors": errors,
        "shed": shed,
        "deadline_expired": expired,
        "wall_s": round(wall, 3),
        "qps": round(n / wall, 1) if wall > 0 else 0.0,
        "concurrency": concurrency,
    }
    if n:
        out["p50_ms"] = round(float(np.percentile(lats, 50)) * 1000, 3)
        out["p90_ms"] = round(float(np.percentile(lats, 90)) * 1000, 3)
        out["p99_ms"] = round(float(np.percentile(lats, 99)) * 1000, 3)
        out["mean_ms"] = round(float(lats.mean()) * 1000, 3)
    return out


def _scrape_raw(url: str, timeout: float = 5.0) -> Optional[dict]:
    """``GET /metrics`` on the target's host:port → parsed samples
    (the shared ``obs.top`` scraper: one dead/garbled endpoint reports
    as None, never a traceback mid-run)."""
    from ..obs.top import fetch_metrics

    parsed = urlparse(url)
    return fetch_metrics(
        f"{parsed.hostname}:{parsed.port or 80}", timeout=timeout
    )


def scrape_server_metrics(url: str, timeout: float = 5.0) -> Optional[dict]:
    """``--scrape-metrics``: pull ``GET /metrics`` from the target and
    digest the *server-side* view of the run — histogram percentiles and
    shed/expired counters. Reported next to loadgen's client-side
    percentiles: the difference between the two IS the network + HTTP
    stack, and the server's p99 survives even when client sampling is
    thin (docs/observability.md)."""
    raw = _scrape_raw(url, timeout=timeout)
    return None if raw is None else digest_serving_metrics(raw)


def digest_serving_metrics(metrics: dict) -> dict:
    """Exposition samples → the loadgen report's ``server`` section."""
    from ..obs.metrics import percentile_from_buckets
    from ..obs.top import merge_histogram_buckets

    out: dict = {}
    hist = merge_histogram_buckets(
        metrics.get("pio_serving_request_seconds_bucket")
    )
    if hist is not None:
        bounds, cums = hist
        out["requests"] = cums[-1] if cums else 0
        for q, key in ((0.5, "p50_ms"), (0.95, "p95_ms"), (0.99, "p99_ms")):
            out[key] = round(
                percentile_from_buckets(bounds, cums, q) * 1000, 3
            )
    for kind in ("shed", "deadline_expired", "retries"):
        for labels, value in metrics.get("pio_serving_events_total", []):
            if labels.get("kind") == kind:
                out[kind] = int(value)
    return out


def _expand_payloads(template: str, n: int = 256) -> List[bytes]:
    if "{i}" in template:
        return [template.replace("{i}", str(i)).encode() for i in range(n)]
    return [template.encode()]


def _inprocess_target(engine_dir: str, batching: bool,
                      pipeline_depth: int = 2,
                      deadline_ms: Optional[float] = None):
    """Build a QueryServer (without binding HTTP traffic through sockets)
    and return a callable driving handle_query directly."""
    from ..storage.registry import get_registry
    from ..utils.resilience import Deadline, DeadlineExceeded
    from ..workflow import loader
    from ..workflow.serving import QueryServer, ServerConfig
    from .register import load_engine_dir

    ed = load_engine_dir(engine_dir)
    engine = loader.get_engine(ed.engine_factory, search_dir=ed.path)
    config = ServerConfig(
        port=0,
        engine_id=ed.manifest.id,
        engine_version=ed.manifest.version,
        batching=batching,
        batch_pipeline_depth=pipeline_depth,
    )
    server = QueryServer(config, engine, get_registry())

    def send(payload: bytes) -> int:
        deadline = (
            Deadline.after_ms(deadline_ms) if deadline_ms is not None else None
        )
        try:
            result, status = server.handle_query(
                json.loads(payload), deadline
            )
        except DeadlineExceeded:
            return _EXPIRED
        return status

    return send, server


# ---------------------------------------------------------------------------
# Toy-train workspace cache: the chaos drills each need a trained toy
# model, and training is by far their dominant cost. Each recipe trains
# ONCE per process into a cache dir; every drill run (and every re-run
# of the same drill in a test module) then clones the finished
# workspace with a copytree — everything under PIO_FS_BASEDIR (event
# store, metadata, model blobs) is relocatable by construction, so the
# clone is a complete independent universe. Tier-1 runs single-process
# (-p no:xdist), so the cache pays across test FILES, not just within
# one (the PR 9 sweep_factors pattern applied to the drill fleet).
# ---------------------------------------------------------------------------

_TOY_CACHE: dict = {}
_TOY_CACHE_LOCK = threading.Lock()


def _prepared_workspace(tag: str, build, dest: str) -> dict:
    """Clone the cached workspace for ``tag`` into ``dest`` (training it
    first on the process's first use). ``build(registry)`` trains into a
    fresh registry rooted at the cache dir and returns a JSON-able info
    dict (instance ids) persisted alongside."""
    import atexit
    import json as _json
    import os as _os
    import shutil
    import tempfile

    import predictionio_tpu.storage.registry as regmod
    from ..storage import StorageRegistry

    with _TOY_CACHE_LOCK:
        cached = _TOY_CACHE.get(tag)
    if cached is None:
        cache_dir = tempfile.mkdtemp(prefix=f"pio-toytrain-{tag}-")
        registry = StorageRegistry(env={"PIO_FS_BASEDIR": cache_dir})
        prev = regmod._default_registry
        regmod._default_registry = registry  # RecDataSource reads through it
        try:
            info = build(registry)
        finally:
            regmod._default_registry = prev
        with open(
            _os.path.join(cache_dir, "toytrain.json"), "w", encoding="utf-8"
        ) as fh:
            _json.dump(info or {}, fh)
        with _TOY_CACHE_LOCK:
            # pio: lint-ok[robust-unbounded-cache] keys are the drills' recipe tags — a closed in-tree set, one workspace each, reclaimed atexit
            cached = _TOY_CACHE.setdefault(tag, cache_dir)
        if cached != cache_dir:  # lost a build race: drop the duplicate
            shutil.rmtree(cache_dir, ignore_errors=True)
        else:
            atexit.register(shutil.rmtree, cache_dir, ignore_errors=True)
    shutil.copytree(cached, dest, dirs_exist_ok=True)
    with open(
        _os.path.join(dest, "toytrain.json"), encoding="utf-8"
    ) as fh:
        return _json.load(fh)


def _seed_rating_events(
    n_users: int, n_items: int, *, seed: int, mod: int,
    hi: float, lo: float, keep: float, scale: float = 1.0,
) -> List:
    """The drill fleet's shared toy corpus: a (u, i) rating lattice —
    ``hi`` where ``u % mod == i % mod`` else ``lo``, each pair kept with
    probability ``keep`` under a fixed rng seed. ONE generator for every
    builder, so a corpus-shape change can never apply to three drills
    and miss the fourth."""
    from ..storage import DataMap, Event

    rng = np.random.default_rng(seed)
    return [
        Event(
            event="rate", entity_type="user", entity_id=f"u{u}",
            target_entity_type="item", target_entity_id=f"i{i}",
            properties=DataMap(
                {"rating": scale * (hi if (u % mod) == (i % mod) else lo)}
            ),
        )
        for u in range(n_users)
        for i in range(n_items)
        if rng.random() < keep
    ]


def _toy_engine_params(app_id: int = 1, iterations: int = 2):
    from ..controller.engine import EngineParams
    from ..models.recommendation import (
        ALSAlgorithmParams,
        RecDataSourceParams,
    )

    return EngineParams(
        data_source_params=("", RecDataSourceParams(app_id=app_id)),
        algorithm_params_list=[
            ("als", ALSAlgorithmParams(rank=4, num_iterations=iterations)),
        ],
    )


def _build_score_drift_workspace(
    registry, n_users: int, n_items: int, skew: float
) -> dict:
    """Baseline + skew-scaled candidate for ``--score-drift``."""
    from ..controller import WorkflowParams
    from ..models.recommendation import engine_factory
    from ..workflow.core_workflow import run_train

    app_id = 1
    events_store = registry.get_events()
    events_store.init(app_id)

    def seed(scale: float) -> List:
        # fixed rng seed per call: baseline and candidate must sample
        # the SAME (u, i) subset — the drill's premise is a pure
        # distribution shift, not a data change
        return _seed_rating_events(
            n_users, n_items, seed=13, mod=3, hi=5.0, lo=2.0,
            keep=0.8, scale=scale,
        )

    engine = engine_factory()
    ep = _toy_engine_params(app_id)
    events_store.write(seed(1.0), app_id)
    baseline_id = run_train(
        engine, ep, registry,
        workflow_params=WorkflowParams(batch="drift-baseline"),
    )
    # the skewed candidate: SAME interactions, ratings x skew — its
    # learned factors reproduce the scaled matrix, so every score it
    # serves is ~skew x the baseline's (a pure distribution shift)
    events_store.remove(app_id)
    events_store.init(app_id)
    events_store.write(seed(skew), app_id)
    candidate_id = run_train(
        engine, ep, registry,
        workflow_params=WorkflowParams(batch="drift-candidate"),
    )
    return {
        "baselineInstanceId": baseline_id,
        "candidateInstanceId": candidate_id,
    }


def _build_fleet_workspace(registry, n_users: int, n_items: int) -> dict:
    """Baseline + candidate for ``--replicas`` (the sharded mode uses
    only the baseline; training both here lets one cache serve both
    drill modes)."""
    from ..controller import WorkflowParams
    from ..models.recommendation import engine_factory
    from ..workflow.core_workflow import run_train

    app_id = 1
    events_store = registry.get_events()
    events_store.init(app_id)
    events_store.write(
        _seed_rating_events(
            n_users, n_items, seed=11, mod=3, hi=5.0, lo=2.0, keep=0.8
        ),
        app_id,
    )
    engine = engine_factory()
    ep = _toy_engine_params(app_id)
    baseline_id = run_train(
        engine, ep, registry,
        workflow_params=WorkflowParams(batch="fleet-baseline"),
    )
    candidate_id = run_train(
        engine, ep, registry,
        workflow_params=WorkflowParams(batch="fleet-candidate"),
    )
    return {
        "baselineInstanceId": baseline_id,
        "candidateInstanceId": candidate_id,
    }


def _build_feedback_workspace(registry, n_users: int, n_items: int) -> dict:
    """App + access key + seed corpus + baseline train for
    ``--feedback-stream`` (pre-changefeed history: the loop only ever
    folds what arrives AFTER its cursor)."""
    from ..controller import WorkflowParams
    from ..models.recommendation import engine_factory
    from ..storage.metadata import AccessKey, App
    from ..workflow.core_workflow import run_train

    app_id = 1
    md = registry.get_metadata()
    events_store = registry.get_events()
    events_store.init(app_id)
    md.app_insert(App(id=app_id, name="feedback-stream"))
    md.access_key_insert(AccessKey(key="LG", appid=app_id, events=[]))
    events_store.write(
        _seed_rating_events(
            n_users, n_items, seed=7, mod=2, hi=5.0, lo=1.0, keep=0.7
        ),
        app_id,
    )
    engine = engine_factory()
    ep = _toy_engine_params(app_id, iterations=3)
    run_train(
        engine, ep, registry,
        workflow_params=WorkflowParams(batch="feedback-stream-baseline"),
    )
    return {}


def _build_brownout_workspace(registry, n_users: int, n_items: int) -> dict:
    """One baseline model for ``--brownout``."""
    from ..controller import WorkflowParams
    from ..models.recommendation import engine_factory
    from ..workflow.core_workflow import run_train

    app_id = 1
    events_store = registry.get_events()
    events_store.init(app_id)
    events_store.write(
        _seed_rating_events(
            n_users, n_items, seed=23, mod=2, hi=5.0, lo=2.0, keep=0.8
        ),
        app_id,
    )
    engine = engine_factory()
    baseline_id = run_train(
        engine, _toy_engine_params(app_id), registry,
        workflow_params=WorkflowParams(batch="brownout-baseline"),
    )
    return {"baselineInstanceId": baseline_id}


def run_storage_chaos(
    total_ops: int = 200,
    kill_at: int = 100,
    state_root: Optional[str] = None,
) -> dict:
    """Replication failover chaos scenario (``--kill-primary-at N``).

    Builds an in-process primary (with changefeed) + warm-standby
    replica + ``pio+ha://`` client, interleaves event writes with
    read-backs of already-acked events, and at op N **hard-kills** the
    primary (live connections severed — ``BackgroundHTTPServer.kill``).
    Reads continue against the replica carrying the last-acked seq
    token; at the end the replica is promoted and every acked write is
    verified readable.

    Replication is drained (``catch_up``) immediately before the kill:
    the scenario proves *failover correctness* — zero failed reads, zero
    lost acked-and-replicated writes, token semantics intact — not a
    zero-RPO claim async replication cannot make (docs/storage.md).
    The breaker threshold is pinned to 1 for the run so the first
    post-kill read fails over in-call instead of burning the default
    5-failure budget.
    """
    import os
    import tempfile

    from ..storage import MetadataStore, SqliteEventStore
    from ..storage import remote
    from ..storage.changefeed import Changefeed
    from ..storage.event import Event
    from ..storage.model_store import SqliteModelStore
    from ..storage.oplog import OpLog
    from ..storage.replica import StorageReplica
    from ..storage.storage_server import StorageServer

    root = state_root or tempfile.mkdtemp(prefix="pio-chaos-")
    prev_threshold = os.environ.get("PIO_BREAKER_FAILURES")
    os.environ["PIO_BREAKER_FAILURES"] = "1"
    remote.reset_resilience()
    primary = replica = None
    try:
        primary = StorageServer(
            "127.0.0.1", 0,
            SqliteEventStore(":memory:"), MetadataStore(":memory:"),
            SqliteModelStore(":memory:"),
            changefeed=None,
        )
        primary.changefeed = Changefeed(
            OpLog(os.path.join(root, "oplog")),
            primary.events, primary.metadata, primary.models,
        )
        primary.start_background()
        replica = StorageReplica(
            "127.0.0.1", 0,
            SqliteEventStore(":memory:"), MetadataStore(":memory:"),
            SqliteModelStore(":memory:"),
            f"http://127.0.0.1:{primary.bound_port}",
            os.path.join(root, "replica_state"),
            catchup_wait_s=0.0,
        )
        replica.start_background()
        store = remote.RemoteEventStore(
            f"pio+ha://127.0.0.1:{primary.bound_port},"
            f"127.0.0.1:{replica.bound_port}",
            timeout=10.0,
        )
        store.init(1)
        replica.catch_up()

        # drill corpus through the SHARED lattice generator (the other
        # drills' one home for corpus shape, PR 11) — cycled when the op
        # count outruns it; per-op entity suffix keeps every insert a
        # distinct event
        corpus = _seed_rating_events(
            16, 12, seed=17, mod=3, hi=5.0, lo=2.0, keep=0.9
        )
        acked: List[str] = []
        failed_reads = reads = 0
        killed_at = None
        for i in range(total_ops):
            if killed_at is None and i >= kill_at:
                replica.catch_up()  # drain, then die (see docstring)
                primary.kill()
                killed_at = i
            if killed_at is None:
                seeded = corpus[i % len(corpus)]
                acked.append(
                    store.insert(
                        dataclasses.replace(
                            seeded, entity_id=f"{seeded.entity_id}-{i}"
                        ),
                        1,
                    )
                )
                if i % 5 == 0:
                    replica.catch_up()  # steady-state tailing
            if acked:
                reads += 1
                try:
                    if store.get(acked[i % len(acked)], 1) is None:
                        failed_reads += 1
                except remote.RemoteStorageError:
                    failed_reads += 1
        lost = 0
        for eid in acked:
            try:
                if store.get(eid, 1) is None:
                    lost += 1
            except remote.RemoteStorageError:
                lost += 1
        status = replica.promote(os.path.join(root, "promoted-oplog"))
        promoted = remote.RemoteEventStore(
            f"http://127.0.0.1:{replica.bound_port}", timeout=10.0
        )
        post_promote_id = promoted.insert(
            Event(event="rate", entity_type="user", entity_id="post"), 1
        )
        # Observability acceptance: the replication-lag gauge must read 0
        # after promotion — measured through the real /metrics exposition
        # of the (now-primary) replica, not by poking its internals.
        lag_after = None
        scraped = _scrape_raw(
            f"http://127.0.0.1:{replica.bound_port}/", timeout=10.0
        )
        if scraped is not None:
            lags = [v for _l, v in scraped.get("pio_replication_lag_ops", [])]
            lag_after = lags[0] if lags else None
        return {
            "mode": "storage-chaos",
            "ops": total_ops,
            "killPrimaryAt": kill_at,
            "ackedWrites": len(acked),
            "reads": reads,
            "failedReads": failed_reads,
            "lostAckedWrites": lost,
            "promotedSeq": status.get("seq"),
            "postPromoteWriteOk": promoted.get(post_promote_id, 1)
            is not None,
            "replicationLagAfterPromote": lag_after,
        }
    finally:
        if prev_threshold is None:
            os.environ.pop("PIO_BREAKER_FAILURES", None)
        else:
            os.environ["PIO_BREAKER_FAILURES"] = prev_threshold
        remote.reset_resilience()
        for server in (primary, replica):
            if server is not None:
                try:
                    server.kill()
                except Exception:
                    pass


def _boot_partition_fleet(root: str, partitions: int, with_replicas: bool):
    """N in-process partition primaries (partition-tagged changefeeds)
    plus, optionally, one warm-standby replica each. Returns
    ``(primaries, replicas, partitioned_url)``."""
    import os
    import tempfile

    from ..storage import MetadataStore, SqliteEventStore
    from ..storage.changefeed import Changefeed
    from ..storage.model_store import SqliteModelStore
    from ..storage.oplog import OpLog
    from ..storage.replica import StorageReplica
    from ..storage.storage_server import StorageServer

    primaries: List = []
    replicas: List = []
    sets: List[str] = []
    for i in range(partitions):
        primary = StorageServer(
            "127.0.0.1", 0,
            SqliteEventStore(":memory:"), MetadataStore(":memory:"),
            SqliteModelStore(":memory:"),
            changefeed=None, partition=(i, partitions),
        )
        primary.changefeed = Changefeed(
            OpLog(
                os.path.join(root, f"oplog-{i}"),
                partition=(i, partitions) if partitions > 1 else None,
            ),
            primary.events, primary.metadata, primary.models,
        )
        primary.start_background()
        primaries.append(primary)
        endpoints = f"127.0.0.1:{primary.bound_port}"
        if with_replicas:
            replica = StorageReplica(
                "127.0.0.1", 0,
                SqliteEventStore(":memory:"), MetadataStore(":memory:"),
                SqliteModelStore(":memory:"),
                f"http://127.0.0.1:{primary.bound_port}",
                os.path.join(root, f"replica-{i}"),
                catchup_wait_s=0.0, partition=(i, partitions),
            )
            replica.start_background()
            replicas.append(replica)
            endpoints += f",127.0.0.1:{replica.bound_port}"
        sets.append(endpoints)
    return primaries, replicas, "pio+ha://" + ";".join(sets)


def _partition_corpus(store, app_id: int, n: int, tag: str) -> List:
    """``n`` distinct rating events off the shared lattice generator
    (cycled, per-op entity suffix) — the drill fleet's one corpus home,
    with the entity spread the partition hash fans across primaries."""
    corpus = _seed_rating_events(
        24, 12, seed=29, mod=3, hi=5.0, lo=2.0, keep=0.9
    )
    out = []
    for i in range(n):
        seeded = corpus[i % len(corpus)]
        out.append(
            dataclasses.replace(
                seeded, entity_id=f"{seeded.entity_id}-{tag}{i}"
            )
        )
    return out


def run_partition_chaos(
    partitions: int = 3,
    kill_partition: int = 1,
    ops_per_phase: int = 30,
    concurrency: int = 4,
    state_root: Optional[str] = None,
) -> dict:
    """Partitioned write-path chaos scenario (``--partitions N
    --kill-partition-at I``, docs/storage.md#partitioning) — the
    N-primary generalization of ``--kill-primary-at``:

    - N in-process partition primaries (partition-tagged changefeeds) +
      one warm-standby replica each, one partitioned ``pio+ha://``
      client fanning writes by the (app, entity) hash;
    - **phase A**: concurrent writers across ALL partitions; a merged
      :class:`~predictionio_tpu.continuous.watcher.
      PartitionedFeedWatcher` tails every changefeed and COMMITS its
      per-partition durable cursors (the batch "went live");
    - partition ``I``'s replica is drained, then its primary is
      **hard-killed** (live connections severed);
    - **phase B**: writes to partition I's keyspace shed
      (:class:`~predictionio_tpu.storage.remote.PartitionUnavailable`
      → the event server's 503) while every other partition keeps
      acking — one failed write on an unaffected partition fails the
      drill;
    - partition I's replica is **promoted** (the same single-chain
      failover, scoped to one partition) and **phase C** proves the
      client's write path discovers the new primary: writes to the
      killed keyspace ack again with NO reconfiguration;
    - acceptance: **every acked write of all three phases is readable**
      (zero lost acked writes), zero failures on unaffected partitions,
      the promoted partition's replication-lag gauge reads 0, and a
      RESTARTED watcher (same cursor dir, partition I's feed re-pointed
      at the promoted replica) resumes without re-delivering any
      committed event — the killed partition's generation change is
      adopted as a promoted continuation, no replay, no spurious gap.
    """
    import os
    import tempfile

    from ..continuous.watcher import FeedGap, PartitionedFeedWatcher, RemoteFeed
    from ..storage import remote

    if not (0 <= kill_partition < partitions):
        raise ValueError(
            f"--kill-partition-at must name a partition in [0, {partitions})"
        )
    if partitions < 2:
        raise ValueError("--partitions needs at least 2 primaries")
    root = state_root or tempfile.mkdtemp(prefix="pio-partition-chaos-")
    prev_threshold = os.environ.get("PIO_BREAKER_FAILURES")
    os.environ["PIO_BREAKER_FAILURES"] = "1"
    remote.reset_resilience()
    primaries: List = []
    replicas: List = []
    report: dict = {
        "mode": "partition-chaos",
        "partitions": partitions,
        "killPartition": kill_partition,
    }
    try:
        primaries, replicas, url = _boot_partition_fleet(
            root, partitions, with_replicas=True
        )
        store = remote.RemoteEventStore(url, timeout=10.0)
        app_id = 1
        store.init(app_id)
        for replica in replicas:
            replica.catch_up()

        acked: dict = {}  # event_id -> partition
        lock = threading.Lock()
        counters = {"shedKilled": 0, "shedUnaffected": 0, "failures": 0}

        def drive(events: List, expect_dead: Optional[int]) -> None:
            cursor = {"next": 0}

            def worker() -> None:
                while True:
                    with lock:
                        pos = cursor["next"]
                        if pos >= len(events):
                            return
                        cursor["next"] = pos + 1
                    event = events[pos]
                    part = store.partition_for(app_id, event.entity_id)
                    try:
                        eid = store.insert(event, app_id)
                        with lock:
                            acked[eid] = part
                        if part == expect_dead:
                            # an ack from a keyspace with no promoted
                            # primary would be a lie
                            with lock:
                                counters["failures"] += 1
                    except remote.PartitionUnavailable as exc:
                        with lock:
                            if part == expect_dead and tuple(
                                exc.partitions
                            ) == (part,):
                                counters["shedKilled"] += 1
                            else:
                                counters["shedUnaffected"] += 1
                    except Exception:
                        with lock:
                            counters["failures"] += 1

            threads = [
                threading.Thread(target=worker, daemon=True)
                for _ in range(concurrency)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()

        def make_watcher_feeds(promoted: bool) -> List[RemoteFeed]:
            feeds = []
            for i, primary in enumerate(primaries):
                if promoted and i == kill_partition:
                    feeds.append(RemoteFeed(
                        f"http://127.0.0.1:{replicas[i].bound_port}"
                    ))
                else:
                    feeds.append(RemoteFeed(
                        f"http://127.0.0.1:{primary.bound_port}"
                    ))
            return feeds

        watcher_dir = os.path.join(root, "watcher")
        watcher = PartitionedFeedWatcher(
            make_watcher_feeds(promoted=False), app_id,
            {"rate": "rating"}, watcher_dir,
        )

        # -- phase A: all partitions alive ------------------------------
        drive(
            _partition_corpus(store, app_id, ops_per_phase, "a"),
            expect_dead=None,
        )
        watcher.poll()
        batch_a = watcher.take_batch()
        report["watcherPhaseAEvents"] = (
            len(batch_a.events) if batch_a else 0
        )
        if batch_a is not None:
            watcher.commit(batch_a.upto_seq)  # the delta "went live"
        committed = {
            int(k): v for k, v in watcher.cursor_seq.items()
        }

        # -- kill partition I (drain its replica first: the scenario
        # proves failover correctness, not a zero-RPO claim async
        # replication cannot make — run_storage_chaos's discipline) ----
        for replica in replicas:
            replica.catch_up()
        primaries[kill_partition].kill()

        # -- phase B: the killed keyspace sheds, the rest keep acking --
        drive(
            _partition_corpus(store, app_id, ops_per_phase, "b"),
            expect_dead=kill_partition,
        )
        report["shedOnKilledPartition"] = counters["shedKilled"]
        report["shedOnUnaffected"] = counters["shedUnaffected"]

        # -- promote + phase C: the keyspace comes back ----------------
        status = replicas[kill_partition].promote(
            os.path.join(root, "promoted-oplog")
        )
        report["promotedSeq"] = status.get("seq")
        drive(
            _partition_corpus(store, app_id, ops_per_phase, "c"),
            expect_dead=None,
        )
        report["failuresOnUnaffected"] = counters["failures"]
        report["ackedWrites"] = len(acked)
        report["ackedByPartition"] = {
            str(i): sum(1 for p in acked.values() if p == i)
            for i in range(partitions)
        }

        # -- zero lost acked writes ------------------------------------
        lost = 0
        for eid in acked:
            try:
                if store.get(eid, app_id) is None:
                    lost += 1
            except remote.RemoteStorageError:
                lost += 1
        report["lostAckedWrites"] = lost

        # -- replication lag pins to 0 on the promoted partition -------
        lag_after = None
        scraped = _scrape_raw(
            f"http://127.0.0.1:{replicas[kill_partition].bound_port}/",
            timeout=10.0,
        )
        if scraped is not None:
            lags = [
                v for _l, v in scraped.get("pio_replication_lag_ops", [])
            ]
            lag_after = lags[0] if lags else None
        report["replicationLagAfterPromote"] = lag_after

        # -- watcher restart: merged cursor resumes, never replays -----
        resumed = PartitionedFeedWatcher(
            make_watcher_feeds(promoted=True), app_id,
            {"rate": "rating"}, watcher_dir,
        )
        gap = None
        try:
            resumed.poll()
        except FeedGap as exc:
            gap = str(exc)
        report["watcherResumeGap"] = gap
        batch_resume = resumed.take_batch()
        replayed = 0
        for i, child in enumerate(resumed.watchers):
            floor = committed.get(i, 0)
            child_batch = child.take_batch()
            if child_batch is not None:
                replayed += sum(
                    1 for e in child_batch.events if e.seq <= floor
                )
        report["watcherReplayedCommitted"] = replayed
        report["watcherResumeEvents"] = (
            len(batch_resume.events) if batch_resume else 0
        )

        report["ok"] = bool(
            report["lostAckedWrites"] == 0
            and report["failuresOnUnaffected"] == 0
            and report["shedOnUnaffected"] == 0
            and report["shedOnKilledPartition"] > 0
            and report["replicationLagAfterPromote"] == 0
            and gap is None
            and replayed == 0
            and report["watcherResumeEvents"] > 0
        )
        return report
    finally:
        if prev_threshold is None:
            os.environ.pop("PIO_BREAKER_FAILURES", None)
        else:
            os.environ["PIO_BREAKER_FAILURES"] = prev_threshold
        remote.reset_resilience()
        for server in primaries + replicas:
            try:
                server.kill()
            except Exception:
                pass


def run_migrate_drill(
    old_partitions: int = 2,
    new_partitions: int = 3,
    ops_per_phase: int = 18,
    concurrency: int = 3,
    kill_new_partition: int = 1,
    state_root: Optional[str] = None,
) -> dict:
    """Live partition-migration chaos drill (``--migrate-drill``,
    docs/storage.md#live-migration): N=2 → M=3 under concurrent
    writers, with BOTH failure injections the design claims to survive:

    - **coordinator killed mid-dual-write**: the first
      :class:`~predictionio_tpu.storage.migration.PartitionMigration`
      is killed after the first write wave; writers keep acking through
      its surviving mirror role (the event-server side of the split),
      and a second instance over the same ``state_dir`` resumes from
      the durable phase/queue/cursor files;
    - **new-layout primary killed mid-backfill**: partition
      ``kill_new_partition`` of the NEW fleet is drained then
      hard-killed; the backfill stalls only the affected keyspace
      slices (loudly, retried), a cutover attempted inside the window
      is REFUSED because the watermark cannot verify, and after the
      replica promotes the backfill converges with no reconfiguration;
    - acceptance: zero lost acked writes (every acked id readable from
      the new layout after the flip, old and new id sets identical at
      flip time), cutover only after the per-keyspace watermark, and
      zero duplicated folded events across the
      :class:`~predictionio_tpu.continuous.watcher.PartitionedFeedWatcher`
      cursor handoff (old-layout folds ∩ new-layout folds = ∅).

    Returns a report dict; ``report["ok"]`` is the drill verdict. Wall
    time and dual-write overhead ride into the perf ledger via the
    ``migrationDrill`` bench block (trend-only).
    """
    import os
    import tempfile
    import time as _time

    from ..continuous.watcher import (
        PartitionedFeedWatcher,
        RemoteFeed,
        handoff_cursors,
    )
    from ..storage import remote
    from ..storage.migration import MigrationError, PartitionMigration

    if not (0 <= kill_new_partition < new_partitions):
        raise ValueError(
            "--kill-partition-at must name a NEW-layout partition in "
            f"[0, {new_partitions})"
        )
    root = state_root or tempfile.mkdtemp(prefix="pio-migrate-drill-")
    remote.reset_resilience()
    report: dict = {
        "mode": "migrate-drill",
        "oldPartitions": old_partitions,
        "newPartitions": new_partitions,
        "killNewPartition": kill_new_partition,
    }
    old_primaries: List = []
    new_primaries: List = []
    new_replicas: List = []
    migs: List = []
    t_start = _time.monotonic()
    try:
        old_primaries, _none, old_url = _boot_partition_fleet(
            os.path.join(root, "old"), old_partitions, with_replicas=False
        )
        new_primaries, new_replicas, new_url = _boot_partition_fleet(
            os.path.join(root, "new"), new_partitions, with_replicas=True
        )
        old_store = remote.RemoteEventStore(old_url, timeout=10.0)
        new_store = remote.RemoteEventStore(new_url, timeout=10.0)
        app_id = 1
        old_store.init(app_id)
        new_store.init(app_id)
        for replica in new_replicas:
            replica.catch_up()

        acked: dict = {}  # event_id -> corpus tag
        lock = threading.Lock()
        failures = {"count": 0}

        def drive(writer, events: List, tag: str) -> float:
            """Concurrent writers through ``writer(event) -> id``;
            returns the wave's wall seconds."""
            cursor = {"next": 0}

            def worker() -> None:
                while True:
                    with lock:
                        pos = cursor["next"]
                        if pos >= len(events):
                            return
                        cursor["next"] = pos + 1
                    try:
                        eid = writer(events[pos])
                        with lock:
                            acked[eid] = tag
                    except Exception:
                        with lock:
                            failures["count"] += 1

            t0 = _time.monotonic()
            threads = [
                threading.Thread(target=worker, daemon=True)
                for _ in range(concurrency)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            return _time.monotonic() - t0

        # -- pre-migration history (also the dual-write-overhead
        # baseline: the same writer fan, no mirror in the path) --------
        seed = _partition_corpus(old_store, app_id, ops_per_phase, "seed")
        plain_wall = drive(
            lambda e: old_store.insert(e, app_id), seed, "seed"
        )

        # old-layout watcher accumulates folds through the whole
        # migration; its per-partition cursors are the handoff's floor
        watcher_dir = os.path.join(root, "watcher")
        old_feeds = [
            RemoteFeed(f"http://127.0.0.1:{p.bound_port}")
            for p in old_primaries
        ]
        watcher = PartitionedFeedWatcher(
            old_feeds, app_id, {"rate": "rating"}, watcher_dir,
        )
        folded_old: set = set()

        state_dir = os.path.join(root, "migration")
        meta = remote.RemoteMetadataStore(old_url, timeout=10.0)
        mig = PartitionMigration(
            old_store, new_store, state_dir,
            old_url=old_url, new_url=new_url,
            old_feeds=old_feeds, metadata=meta,
        )
        migs.append(mig)
        mig.start()

        # -- dual-write wave 1, then the coordinator "dies" ------------
        wave1 = _partition_corpus(old_store, app_id, ops_per_phase, "w1")
        dual_wall = drive(
            lambda e: mig.write([e], app_id)[0], wave1, "w1"
        )
        report["dualWriteOverhead"] = (
            dual_wall / plain_wall if plain_wall > 0 else None
        )
        mig.kill()  # coordinator crash; the mirror role survives
        refused = False
        try:
            mig.pump()
        except MigrationError:
            refused = True
        report["deadCoordinatorRefusesPump"] = refused

        # -- wave 2 rides the surviving mirror role while a NEW
        # coordinator instance resumes from the durable state ----------
        wave2 = _partition_corpus(old_store, app_id, ops_per_phase, "w2")
        drive(lambda e: mig.write([e], app_id)[0], wave2, "w2")
        mig2 = PartitionMigration(
            old_store, new_store, state_dir,
            old_url=old_url, new_url=new_url,
            old_feeds=old_feeds, metadata=meta,
        )
        migs.append(mig2)
        report["resumedPhase"] = mig2.phase  # "dual_write"
        mig2.begin_backfill()
        mig2.pump(max_ops=5)  # partial backfill before the kill

        # -- kill a NEW-layout primary mid-backfill --------------------
        new_replicas[kill_new_partition].catch_up()
        new_primaries[kill_new_partition].kill()
        stalled_rounds = 0
        for _ in range(3):
            out = mig2.pump(max_ops=10)
            rows = (out.get("backfill") or {}).values()
            if any(r.get("stalled") for r in rows):
                stalled_rounds += 1
        report["stalledRoundsDuringKill"] = stalled_rounds
        wm_dead = mig2.watermark()
        report["watermarkDuringKill"] = wm_dead["ok"]
        early_refused = None
        if not wm_dead["ok"]:
            try:
                mig2.cutover(timeout_s=0.2)
            except MigrationError:
                early_refused = True
            else:
                early_refused = False
        report["earlyCutoverRefused"] = early_refused

        # -- promote the replica; the pio+ha chain client discovers the
        # new primary with no reconfiguration, backfill converges ------
        promoted = new_replicas[kill_new_partition].promote(
            os.path.join(root, "promoted-oplog")
        )
        report["promotedSeq"] = promoted.get("seq")
        deadline = _time.monotonic() + 30.0
        while mig2.phase == "backfill" and _time.monotonic() < deadline:
            mig2.pump()
        report["phaseBeforeCutover"] = mig2.phase  # "ready"

        # -- flip, then prove old == new at flip time ------------------
        mig2.cutover(timeout_s=30.0)
        report["phaseAfterCutover"] = mig2.phase  # "done"

        def _all_ids(store) -> set:
            from ..storage.events import EventFilter

            return {
                e.event_id
                for e in store.find(app_id, EventFilter(limit=1_000_000))
            }

        old_ids = _all_ids(old_store)
        new_ids = _all_ids(new_store)
        report["oldLayoutEvents"] = len(old_ids)
        report["newLayoutEvents"] = len(new_ids)
        report["layoutsIdenticalAtFlip"] = old_ids == new_ids
        lost = sum(1 for eid in acked if eid not in new_ids)
        report["ackedWrites"] = len(acked)
        report["lostAckedWrites"] = lost
        report["writerFailures"] = failures["count"]

        # -- fold the whole old-layout history, then hand the cursors
        # off to the new layout and prove nothing folds twice ----------
        watcher.poll()
        batch = watcher.take_batch()
        while batch is not None:
            for e in batch.events:
                folded_old.add((e.user, e.item, e.event_time_ms))
            watcher.commit(batch.upto_seq)
            watcher.poll()
            batch = watcher.take_batch()
        report["foldedOldLayout"] = len(folded_old)

        new_feeds = []
        for i, p in enumerate(new_primaries):
            port = (
                new_replicas[i].bound_port
                if i == kill_new_partition
                else p.bound_port
            )
            new_feeds.append(RemoteFeed(f"http://127.0.0.1:{port}"))
        handoff_cursors(new_feeds, watcher_dir)

        # post-flip writes land ONLY in the new layout
        wave3 = _partition_corpus(old_store, app_id, ops_per_phase, "w3")
        drive(lambda e: mig2.write([e], app_id)[0], wave3, "w3")
        report["postFlipInNewOnly"] = bool(
            _all_ids(new_store) - new_ids
        ) and _all_ids(old_store) == old_ids

        resumed = PartitionedFeedWatcher(
            new_feeds, app_id, {"rate": "rating"}, watcher_dir,
        )
        folded_new: set = set()
        resumed.poll()
        batch = resumed.take_batch()
        while batch is not None:
            for e in batch.events:
                folded_new.add((e.user, e.item, e.event_time_ms))
            resumed.commit(batch.upto_seq)
            resumed.poll()
            batch = resumed.take_batch()
        dup = folded_old & folded_new
        report["foldedNewLayout"] = len(folded_new)
        report["duplicateFolds"] = len(dup)

        report["wallS"] = _time.monotonic() - t_start
        report["ok"] = bool(
            report["lostAckedWrites"] == 0
            and report["writerFailures"] == 0
            and report["duplicateFolds"] == 0
            and report["foldedNewLayout"] == ops_per_phase
            and report["layoutsIdenticalAtFlip"]
            and report["postFlipInNewOnly"]
            and report["deadCoordinatorRefusesPump"]
            and report["resumedPhase"] == "dual_write"
            and report["stalledRoundsDuringKill"] > 0
            and report["earlyCutoverRefused"] is True
            and report["phaseAfterCutover"] == "done"
        )
        return report
    finally:
        remote.reset_resilience()
        for m in migs:
            try:
                m.close()
            except Exception:
                pass
        for server in old_primaries + new_primaries + new_replicas:
            try:
                server.kill()
            except Exception:
                pass


#: self-contained partition primary for the ingest-scaling drive: its
#: own interpreter (real CPU parallelism across partitions, which one
#: GIL cannot show) with the STRICT ack discipline (sync_every=1 —
#: every ack waits its partition's oplog fsync), so the serialized
#: per-partition resource the drive measures is the durable ack path.
_SCALING_SERVER_SRC = """
import sys
from predictionio_tpu.storage import MetadataStore, SqliteEventStore
from predictionio_tpu.storage.changefeed import Changefeed
from predictionio_tpu.storage.model_store import SqliteModelStore
from predictionio_tpu.storage.oplog import OpLog
from predictionio_tpu.storage.storage_server import StorageServer
idx, count, oplog_dir = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
s = StorageServer(
    "127.0.0.1", 0, SqliteEventStore(":memory:"), MetadataStore(":memory:"),
    SqliteModelStore(":memory:"), changefeed=None, partition=(idx, count))
s.changefeed = Changefeed(
    OpLog(oplog_dir, sync_every=1,
          partition=(idx, count) if count > 1 else None),
    s.events, s.metadata, s.models)
print(s.bound_port, flush=True)
s.serve_forever()
"""

#: one concurrent writer: builds its corpus, signals ready, waits for
#: the starting gun, then inserts flat out and reports its wall
_SCALING_WRITER_SRC = """
import sys, time
from predictionio_tpu.storage import remote
from predictionio_tpu.tools.loadgen import _partition_corpus
url, events, tag = sys.argv[1], int(sys.argv[2]), sys.argv[3]
store = remote.RemoteEventStore(url, timeout=10.0)
corpus = _partition_corpus(store, 1, events, tag)
print("ready", flush=True)
sys.stdin.readline()
errs = 0
t0 = time.monotonic()
for e in corpus:
    try:
        store.insert(e, 1)
    except Exception:
        errs += 1
print(time.monotonic() - t0, errs, flush=True)
"""


def _readline_deadline(proc, timeout_s: float, what: str) -> str:
    """Bounded readline from a child's stdout: a wedged subprocess must
    surface as a raised error the bench records, never hang the whole
    run (``a failure never fails the bench`` does not cover a hang)."""
    import select

    ready, _, _ = select.select([proc.stdout], [], [], timeout_s)
    if not ready:
        raise RuntimeError(
            f"ingest-scaling subprocess did not produce {what} within "
            f"{timeout_s:.0f}s"
        )
    line = proc.stdout.readline()
    if not line:
        raise RuntimeError(
            f"ingest-scaling subprocess died before producing {what}"
        )
    return line


def _ingest_round(n: int, events: int, writers: int) -> dict:
    """One measured round: ``n`` subprocess partition primaries, the
    partitioned client, ``writers`` subprocess writers racing keyed
    traffic across the whole keyspace."""
    import shutil
    import subprocess
    import tempfile

    from ..storage import remote

    root = tempfile.mkdtemp(prefix=f"pio-ingest-scale-{n}-")
    servers: List = []
    writer_procs: List = []
    try:
        sets = []
        for i in range(n):
            proc = subprocess.Popen(
                [sys.executable, "-c", _SCALING_SERVER_SRC,
                 str(i), str(n), os.path.join(root, f"oplog-{i}")],
                stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                text=True,
            )
            servers.append(proc)
        for proc in servers:
            port = int(_readline_deadline(proc, 60.0, "its port"))
            sets.append(f"127.0.0.1:{port}")
        url = "pio+ha://" + ";".join(sets)
        remote.reset_resilience()
        store = remote.RemoteEventStore(url, timeout=10.0)
        store.init(1)
        per_writer = max(1, events // writers)
        writer_procs = [
            subprocess.Popen(
                [sys.executable, "-c", _SCALING_WRITER_SRC,
                 url, str(per_writer), f"w{w}-"],
                stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                stderr=subprocess.DEVNULL, text=True,
            )
            for w in range(writers)
        ]
        for proc in writer_procs:
            # "ready": corpus built, store wired
            _readline_deadline(proc, 60.0, "its ready line")
        t0 = time.monotonic()
        for proc in writer_procs:  # the starting gun
            proc.stdin.write("go\n")
            proc.stdin.flush()
        errors = 0
        for proc in writer_procs:
            line = _readline_deadline(proc, 300.0, "its result").split()
            errors += int(line[1]) if len(line) > 1 else per_writer
        wall = time.monotonic() - t0
        acked = per_writer * writers - errors
        return {
            "partitions": n,
            "acked": acked,
            "errors": errors,
            "wallS": round(wall, 3),
            "ackedQPS": round(acked / wall, 1) if wall > 0 else 0.0,
        }
    finally:
        for proc in servers + writer_procs:
            try:
                proc.kill()
            except Exception:
                pass
        shutil.rmtree(root, ignore_errors=True)


def run_ingest_scaling(
    partition_counts: Sequence[int] = (1, 2, 4),
    events: int = 480,
    writers: int = 4,
    rounds: int = 2,
    in_process: bool = False,
) -> dict:
    """Ingest-scaling drive (BENCH's ``ingestScaling`` block,
    docs/performance.md): for each partition count N, boot N partition
    primaries — each in its OWN interpreter, with the strict
    fsync-per-ack oplog — and race ``writers`` concurrent writer
    processes of keyed events across the whole keyspace through the
    partitioned ``pio+ha://`` client. Reports acked-writes/second per
    N; same box, same corpus, same client code — the only variable is
    the partition count, so the trajectory IS the partitioning win.

    Each N runs ``rounds`` times and reports the BEST round: the drive
    shares a (possibly contended) CI box with whatever else runs there,
    and the best of a few rounds estimates the box's capability where a
    single sample measures its weather (the same reasoning that gave
    the fleet p99 ledger records their wide noise bands). Records land
    in the perf ledger keyed by partition count (``scale``), so ``pio
    perf diff`` never gates across different N.

    ``in_process=True`` is the tier-1 shape check: everything in this
    process (one GIL — real scaling cannot show), single round, cheap.
    """
    report: dict = {
        "mode": "ingest-scaling",
        "events": events,
        "writers": writers,
        "rounds": rounds,
        "inProcess": bool(in_process),
        "counts": {},
    }
    ok = True
    for n in partition_counts:
        if in_process:
            best = _ingest_round_in_process(n, events, writers)
        else:
            best = None
            for _ in range(max(1, rounds)):
                row = _ingest_round(n, events, writers)
                if best is None or row["ackedQPS"] > best["ackedQPS"]:
                    best = row
        report["counts"][str(n)] = best
        if best["errors"]:
            ok = False
    report["ok"] = ok
    return report


def _ingest_round_in_process(n: int, events: int, writers: int) -> dict:
    """The in-process twin of :func:`_ingest_round` (tier-1 shape test:
    no subprocesses, threads only)."""
    import shutil
    import tempfile

    from ..storage import remote

    root = tempfile.mkdtemp(prefix=f"pio-ingest-scale-{n}-")
    remote.reset_resilience()
    primaries: List = []
    try:
        primaries, _replicas, url = _boot_partition_fleet(
            root, n, with_replicas=False
        )
        store = remote.RemoteEventStore(url, timeout=10.0)
        store.init(1)
        corpus = _partition_corpus(store, 1, events, f"s{n}-")
        errors = [0]
        lock = threading.Lock()
        cursor = {"next": 0}

        def worker() -> None:
            while True:
                with lock:
                    pos = cursor["next"]
                    if pos >= len(corpus):
                        return
                    cursor["next"] = pos + 1
                try:
                    store.insert(corpus[pos], 1)
                except Exception:
                    with lock:
                        errors[0] += 1

        threads = [
            threading.Thread(target=worker, daemon=True)
            for _ in range(writers)
        ]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.monotonic() - t0
        acked = events - errors[0]
        return {
            "partitions": n,
            "acked": acked,
            "errors": errors[0],
            "wallS": round(wall, 3),
            "ackedQPS": round(acked / wall, 1) if wall > 0 else 0.0,
        }
    finally:
        for server in primaries:
            try:
                server.kill()
            except Exception:
                pass
        remote.reset_resilience()
        shutil.rmtree(root, ignore_errors=True)


def run_rollout_chaos(
    engine=None,
    registry=None,
    engine_dir: str = ".",
    baseline_instance_id: Optional[str] = None,
    candidate_instance_id: Optional[str] = None,
    payload_template: str = '{"user": "{i}", "num": 10}',
    queries_per_phase: int = 40,
    percent: float = 50.0,
    gates: Optional[dict] = None,
    clock=None,
) -> dict:
    """Rollout chaos scenario (``--rollout``, docs/rollouts.md).

    Builds an in-process query server, starts a rollout (candidate in
    SHADOW next to the baseline), drives traffic, promotes to CANARY,
    then arms the deterministic fault harness at ``serving.candidate``
    so every candidate-routed prediction fails — and asserts the
    acceptance contract: the plan **auto-rolls back** on the error-rate
    gate, **zero** requests fail client-side (canary containment serves
    every faulted request from the baseline), the baseline takes 100% of
    subsequent traffic, and the terminal ``ROLLED_BACK`` state is
    durably recorded in metadata.

    Deterministic by construction: stage changes ride explicit promote
    + gate-driven rollback (no hold-timer waits), faults come from
    ``testing/faults``, and shadow duplicates are drained, so the tier-1
    wiring (``tests/test_rollout.py``) needs no wall-clock sleeps.
    """
    import time as _time

    from ..storage.registry import get_registry
    from ..testing import faults
    from ..workflow.serving import QueryServer, ServerConfig

    if engine is None:
        from ..workflow import loader
        from .register import load_engine_dir

        ed = load_engine_dir(engine_dir)
        engine = loader.get_engine(ed.engine_factory, search_dir=ed.path)
    registry = registry or get_registry()

    payloads = [json.loads(p) for p in _expand_payloads(payload_template, 256)]
    config = ServerConfig(
        ip="127.0.0.1", port=0, batching=False,
        engine_instance_id=baseline_instance_id,
    )
    server = QueryServer(
        config, engine, registry, clock=clock or _time.monotonic
    )
    gate_cfg = {
        "min_samples": 10,
        "window_s": 100_000.0,
        "shadow_hold_s": 100_000.0,     # stages advance by explicit promote
        "canary_hold_s": 100_000.0,
        "max_divergence": 1.0,          # divergence gate has its own tests
        # the drill proves the ERROR gate; real wall-clock latencies in
        # tiny windows would let scheduler jitter trip the p99 gate first
        "max_p99_latency_ratio": 1_000.0,
        **(gates or {}),
    }
    report: dict = {"mode": "rollout-chaos", "clientFailures": 0}
    try:
        candidate = (
            candidate_instance_id or server.deployment.instance.id
        )
        status = server.rollout.start(
            candidate_instance_id=candidate, percent=percent, gates=gate_cfg
        )
        report["planId"] = status["plan"]["id"]

        def drive(n: int) -> dict:
            counts = {"baseline": 0, "candidate": 0, "-": 0}
            for i in range(n):
                info: dict = {}
                try:
                    _result, http_status = server.handle_query(
                        payloads[i % len(payloads)], info=info
                    )
                    if http_status != 200:
                        report["clientFailures"] += 1
                except Exception:
                    report["clientFailures"] += 1
                counts[info.get("variant", "-")] = (
                    counts.get(info.get("variant", "-"), 0) + 1
                )
            return counts

        drive(queries_per_phase)                     # shadow traffic
        server.rollout.drain_shadow()
        ctl = server.rollout.controller
        report["shadowSamples"] = ctl.candidate.count()
        report["meanDivergence"] = ctl.mean_divergence()

        server.rollout.promote("chaos drill: shadow -> canary")
        report["canaryStage"] = server.rollout.stage

        # candidate dies mid-canary: every candidate-routed request must
        # still answer 200 (from the baseline) and the error gate must
        # roll the plan back on its own
        with faults.inject(
            faults.FaultSpec(site="serving.candidate", kind="refuse")
        ) as plan:
            canary_counts = drive(queries_per_phase)
            report["candidateFaultsFired"] = plan.fired("serving.candidate")
        report["canaryCounts"] = canary_counts
        report["finalStage"] = server.rollout.stage
        report["rolledBack"] = server.rollout.stage == "ROLLED_BACK"

        post_counts = drive(queries_per_phase)       # after rollback
        report["postRollbackCandidateServed"] = post_counts.get("candidate", 0)

        durable = registry.get_metadata().rollout_plan_get(report["planId"])
        report["durableStage"] = durable.stage if durable else None
        report["ok"] = bool(
            report["rolledBack"]
            and report["clientFailures"] == 0
            and report["postRollbackCandidateServed"] == 0
            and report["durableStage"] == "ROLLED_BACK"
            and report["candidateFaultsFired"] > 0
        )
        return report
    finally:
        server.server_close()


def run_score_drift(
    queries: int = 60,
    n_users: int = 16,
    n_items: int = 12,
    skew: float = 4.0,
    max_score_psi: float = 0.25,
    base_dir: Optional[str] = None,
    on_live=None,
) -> dict:
    """Score-drift chaos scenario (``--score-drift``,
    docs/observability.md#quality).

    The quality plane's acceptance proof: a candidate whose *score
    distribution* is skewed — trained on ratings scaled by ``skew``, so
    every prediction is a perfectly well-formed answer with ~``skew``×
    the magnitude — would sail through every pre-existing gate (it never
    errors, its latency is normal, and the divergence gate is disabled
    here exactly because divergence has its own tests and would mask the
    signal under test). The drill asserts the ``max_score_psi`` gate
    alone catches it:

    - baseline traffic pins the quality monitor's score snapshot;
    - the skewed candidate enters SHADOW behind the rollout plane; its
      shadow answers feed the candidate sketch;
    - the PSI gate **auto-rolls back** with **zero** client-visible
      failures (clients only ever saw baseline answers);
    - the terminal ``ROLLED_BACK`` plan is durable, and a *restarted*
      server quarantines the drifted candidate — it re-serves the
      plan's baseline even though the candidate is the latest completed
      instance.

    ``on_live(server)`` (optional) runs after the rollback while the
    server's HTTP surface is still up — the tier-1 test scrapes
    ``pio quality --node`` through it.
    """
    import shutil
    import tempfile

    import predictionio_tpu.storage.registry as regmod
    from ..models.recommendation import engine_factory
    from ..obs.quality import QualityConfig
    from ..storage import StorageRegistry
    from ..testing.clock import FakeClock
    from ..workflow.serving import QueryServer, ServerConfig

    tmp = base_dir or tempfile.mkdtemp(prefix="pio-score-drift-")
    owns_tmp = base_dir is None
    registry = StorageRegistry(env={"PIO_FS_BASEDIR": tmp})
    prev_registry = regmod._default_registry
    regmod._default_registry = registry  # RecDataSource reads through it
    report: dict = {"mode": "score-drift", "clientFailures": 0,
                    "skew": skew, "maxScorePsi": max_score_psi}
    server = restarted = None
    try:
        engine = engine_factory()
        info = _prepared_workspace(
            f"score-drift-{n_users}x{n_items}-{skew:g}",
            lambda reg: _build_score_drift_workspace(
                reg, n_users=n_users, n_items=n_items, skew=skew
            ),
            tmp,
        )
        baseline_id = info["baselineInstanceId"]
        candidate_id = info["candidateInstanceId"]
        report["baselineInstanceId"] = baseline_id
        report["candidateInstanceId"] = candidate_id

        clock = FakeClock()
        server = QueryServer(
            ServerConfig(
                ip="127.0.0.1", port=0, batching=False,
                engine_instance_id=baseline_id,
                quality=QualityConfig(
                    pin_min_samples=40, min_psi_samples=40,
                    window_s=1e9,
                    # pinned under the drill dir: an ambient
                    # PIO_QUALITY_SNAPSHOTS must never collect this
                    # deliberately skewed toy model's snapshots
                    snapshot_path=tmp + "/quality-snapshots.jsonl",
                ),
            ),
            engine, registry, clock=clock,
        )
        server.start_background()

        def drive(n: int) -> dict:
            counts: dict = {}
            for i in range(n):
                info: dict = {}
                try:
                    _result, http_status = server.handle_query(
                        {"user": f"u{i % n_users}", "num": 5}, info=info
                    )
                    if http_status != 200:
                        report["clientFailures"] += 1
                except Exception:
                    report["clientFailures"] += 1
                variant = info.get("variant", "-")
                counts[variant] = counts.get(variant, 0) + 1
            return counts

        drive(queries // 3)  # pin the baseline score distribution
        report["pinnedBeforeRollout"] = server.quality.pinned()

        server.rollout.start(
            candidate_instance_id=candidate_id,
            gates={
                "min_samples": 10,
                "window_s": 1e9,
                "shadow_hold_s": 1e9,      # PSI rolls back on its own;
                "canary_hold_s": 1e9,      # nothing else may promote
                "max_divergence": 1.0,     # divergence has its own tests
                "max_p99_latency_ratio": 1e9,
                "max_score_psi": max_score_psi,
            },
        )
        report["planId"] = server.rollout.plan.id

        # shadow traffic, drained in slices: under post-tier-1 CPU load
        # the 2-worker shadow pool falls behind a flat-out drive, and
        # pending shadow queries past the cap are DROPPED (by design —
        # shadow must never block serving). Dropped shadows starve the
        # candidate's PSI sketch below min_psi_samples and the gate
        # abstains instead of rolling back — a load-dependent flake,
        # not a quality-plane verdict. Slices below the pending cap +
        # a drain per slice keep every shadow answer in the sketch at
        # any host load, without changing what the gate measures.
        for start in range(0, queries, 12):
            drive(min(12, queries - start))
            server.rollout.drain_shadow(timeout_s=60.0)
        drive(2)                            # one more gate evaluation
        report["candidatePsi"] = server.quality.score_psi("candidate")
        report["finalStage"] = server.rollout.stage
        report["rolledBack"] = server.rollout.stage == "ROLLED_BACK"
        plan = server.rollout.plan
        report["rollbackReason"] = (
            plan.history[-1].get("reason") if plan.history else None
        )
        post_counts = drive(queries // 3)   # after rollback
        report["postRollbackCandidateServed"] = post_counts.get(
            "candidate", 0
        )
        durable = registry.get_metadata().rollout_plan_get(report["planId"])
        report["durableStage"] = durable.stage if durable else None

        if on_live is not None:
            on_live(server)

        # restart: the drifted candidate is the LATEST COMPLETED
        # instance, but the quarantine path must re-serve the plan's
        # baseline instead of silently undoing the rollback
        restarted = QueryServer(
            ServerConfig(ip="127.0.0.1", port=0, batching=False),
            engine, registry,
        )
        report["restartServes"] = restarted.deployment.instance.id
        report["quarantined"] = (
            restarted.deployment.instance.id == baseline_id
        )

        report["ok"] = bool(
            report["rolledBack"]
            and report["clientFailures"] == 0
            and report["pinnedBeforeRollout"]
            and report["postRollbackCandidateServed"] == 0
            and report["durableStage"] == "ROLLED_BACK"
            and report["quarantined"]
            and "score PSI" in (report["rollbackReason"] or "")
        )
        return report
    finally:
        regmod._default_registry = prev_registry
        for srv in (server, restarted):
            if srv is not None:
                try:
                    srv.server_close()
                except Exception:
                    pass
        if owns_tmp:
            shutil.rmtree(tmp, ignore_errors=True)


def run_feedback_stream(
    total_events: int = 60,
    burst: int = 20,
    n_users: int = 16,
    n_items: int = 10,
    max_rounds: int = 40,
    base_dir: Optional[str] = None,
) -> dict:
    """Closed-loop freshness scenario (``--feedback-stream``,
    docs/continuous.md).

    Builds the whole continuous-learning loop in one process — storage
    primary with a changefeed, event server writing through it, query
    server with the continuous controller attached — then drives a
    steady feedback trickle through ``POST /events.json`` and measures
    **end-to-end freshness**: wall-clock from the oldest event of a
    delta batch entering the event server to the fold-in candidate it
    produced going LIVE through the shadow→canary gates. That number is
    the closed loop's figure of merit (it rides into the BENCH output as
    ``continuousFreshness``).

    Decision clocks are injected (gate holds advance without sleeping);
    only the freshness measurement reads the real wall clock — it is a
    measurement, not a wait.
    """
    import datetime as _dt
    import os as _os
    import shutil
    import tempfile

    import requests as _requests

    import predictionio_tpu.storage.registry as regmod
    from ..api.event_server import EventServer, EventServerConfig
    from ..continuous.controller import ContinuousConfig
    from ..models.recommendation import engine_factory
    from ..storage import StorageRegistry
    from ..storage.changefeed import Changefeed
    from ..storage.oplog import OpLog
    from ..storage.remote import RemoteEventStore
    from ..storage.storage_server import StorageServer
    from ..workflow.serving import QueryServer, ServerConfig

    tmp = base_dir or tempfile.mkdtemp(prefix="pio-feedback-stream-")
    owns_tmp = base_dir is None
    registry = StorageRegistry(env={"PIO_FS_BASEDIR": tmp})
    prev_registry = regmod._default_registry
    regmod._default_registry = registry  # RecDataSource reads through it
    report: dict = {"mode": "feedback-stream", "events": 0}
    storage_srv = event_srv = server = None
    try:
        app_id = 1
        _prepared_workspace(
            f"feedback-{n_users}x{n_items}",
            lambda reg: _build_feedback_workspace(
                reg, n_users=n_users, n_items=n_items
            ),
            tmp,
        )
        md = registry.get_metadata()
        events_store = registry.get_events()
        engine = engine_factory()

        storage_srv = StorageServer(
            "127.0.0.1", 0, events_store, md, registry.get_models(),
            changefeed=Changefeed(
                OpLog(_os.path.join(tmp, "oplog")),
                events_store, md, registry.get_models(),
            ),
        )
        storage_srv.start_background()
        primary = f"http://127.0.0.1:{storage_srv.bound_port}"
        event_srv = EventServer(
            EventServerConfig(ip="127.0.0.1", port=0),
            events=RemoteEventStore(primary),
            metadata=md,
        )
        event_srv.start_background()
        ingest = (
            f"http://127.0.0.1:{event_srv.bound_port}/events.json"
            "?accessKey=LG"
        )

        from ..testing.clock import FakeClock

        from ..obs.quality import QualityConfig

        clock = FakeClock()
        server = QueryServer(
            ServerConfig(
                ip="127.0.0.1", port=0, batching=False,
                # toy-scale monitor thresholds so the drill's quality
                # digest (bench's record["quality"]) carries a real PSI
                # instead of abstaining at the defaults' sample floors
                quality=QualityConfig(
                    pin_min_samples=20, min_psi_samples=20, window_s=1e9,
                    # drill-local: never append to an ambient
                    # PIO_QUALITY_SNAPSHOTS ledger (same isolation as
                    # PIO_FS_BASEDIR via the private registry)
                    snapshot_path=_os.path.join(
                        tmp, "quality-snapshots.jsonl"
                    ),
                ),
                continuous=ContinuousConfig(
                    app_id=app_id,
                    feed_url=primary,
                    min_events=burst,
                    max_staleness_s=1e9,  # the trickle triggers on size
                    rollout_gates={
                        "min_samples": 5,
                        "window_s": 100_000.0,
                        "shadow_hold_s": 5.0,
                        "canary_hold_s": 5.0,
                        "max_divergence": 1.0,
                        "max_p99_latency_ratio": 1_000.0,
                    },
                    quarantine_backoff_s=0.0,
                    autostart=False,  # the scenario drives ticks itself
                ),
            ),
            engine, registry, clock=clock,
        )
        continuous = server.continuous
        assert continuous is not None

        report["clientFailures"] = 0

        def drive(n: int, start: int) -> None:
            for i in range(start, start + n):
                try:
                    _result, http_status = server.handle_query(
                        {"user": f"u{i % n_users}", "num": 3}
                    )
                    if http_status != 200:
                        report["clientFailures"] += 1
                except Exception:
                    report["clientFailures"] += 1
            server.rollout.drain_shadow()

        # serve everyone once BEFORE the first feedback burst: the
        # quality monitor's feedback join can only hit items that were
        # actually served, and this also pins the baseline score
        # distribution from the trained model's own traffic
        drive(n_users, start=0)

        posted = 0
        t_first_post = None
        rounds = 0
        while posted < total_events and rounds < max_rounds:
            rounds += 1
            now_iso = _dt.datetime.now(_dt.timezone.utc).isoformat(
                timespec="milliseconds"
            )
            for k in range(burst):
                u = f"u{(posted + k) % (n_users + 4)}"  # a few NEW users
                i = f"i{(posted + k) % n_items}"
                resp = _requests.post(
                    ingest,
                    json={
                        "event": "rate",
                        "entityType": "user",
                        "entityId": u,
                        "targetEntityType": "item",
                        "targetEntityId": i,
                        "eventTime": now_iso,
                        "properties": {"rating": 4.0},
                    },
                    timeout=10,
                )
                resp.raise_for_status()
            if t_first_post is None:
                t_first_post = time.time()
            posted += burst
            continuous.tick()  # poll + (maybe) cycle + submit
            # feed the rollout gates and walk the stages on the fake clock
            def live() -> bool:
                cycle = continuous.status().get("lastCycle") or {}
                return cycle.get("outcome") == "live"

            for _ in range(8):
                if server.rollout.active:
                    drive(8, start=rounds * 100)
                    clock.advance(6.0)
                    drive(2, start=rounds * 100 + 50)
                    server.rollout.drain_shadow()
                continuous.tick()
                if live():
                    break
            if live():
                break

        status = continuous.status()
        report["events"] = posted
        report["rounds"] = rounds
        report["cycles"] = status.get("cycles", 0)
        report["state"] = status.get("state")
        report["feedLagOps"] = status.get("feedLagOps")
        if status.get("lastCycle"):
            report["lastCycle"] = status["lastCycle"]
        report["freshnessS"] = status.get("lastFreshnessS")
        if report["freshnessS"] is None and t_first_post is not None:
            report["elapsedS"] = round(time.time() - t_first_post, 3)
        # the fold-in going LIVE re-pinned the monitor: a short post-live
        # drive re-establishes the new model's baseline so the digest
        # below reports a real (steady-state, ~0) PSI instead of
        # abstaining at the sample floor
        drive(3 * n_users, start=50_000)
        # quality digest (docs/observability.md#quality): the drill's
        # query server ran the full monitor — score PSI vs the baseline
        # it pinned from its own early traffic, and the feedback join's
        # hit-rate over the trickle the watcher tapped through
        quality = server.quality.summary()
        online = quality.get("online") or {}
        report["quality"] = {
            "ok": True,
            "pinned": quality.get("pinned"),
            "scorePsi": (quality.get("scorePsi") or {}).get("baseline"),
            "feedbackHitRate": online.get("hitRate"),
            "feedbackSamples": online.get("feedbackSamples"),
        }
        report["ok"] = bool(
            report["freshnessS"] is not None
            and status.get("lastCycle", {}).get("outcome") == "live"
            and report["clientFailures"] == 0
        )
        return report
    finally:
        regmod._default_registry = prev_registry
        for srv in (server, event_srv, storage_srv):
            if srv is not None:
                try:
                    srv.server_close()
                except Exception:
                    pass
        if owns_tmp:
            shutil.rmtree(tmp, ignore_errors=True)


def run_brownout(
    queries: int = 30,
    wedge_errors: int = 10,
    wedge_slow: int = 10,
    wedge_latency_ms: float = 250.0,
    n_users: int = 12,
    n_items: int = 8,
    base_dir: Optional[str] = None,
) -> dict:
    """Brownout chaos scenario (``--brownout``, docs/slo.md).

    The fleet-health plane's acceptance proof: a backend that is *sick,
    not dead* — fault-injected latency and refusals on the predict path
    (``serving.predict``), never a kill — is exactly the failure every
    pre-existing drill misses (a killed backend fails over; a wedged one
    just gets slow and wrong). Four phases on one injected clock:

    1. **control** — clean traffic over the full fast window; the SLO
       engine must fire ZERO alerts (the false-positive bar);
    2. **stall** — one request wedges in flight past the watchdog bar;
       the watchdog fires ``pio_stall_detected_total{site}`` and dumps
       the flight-recorder ring durably, naming the wedged site;
    3. **wedge** — injected 500s and slow answers burn the availability
       and latency error budgets in BOTH windows → durable FIRING
       alerts in the ledger;
    4. **recovery** — the fault clears, clean traffic drains the fast
       window → durable CLEARED alerts.

    Acceptance: stall dump names the wedged site, both alerts fire AND
    clear durably, zero false positives (no control alerts, no flaps).
    """
    import shutil
    import tempfile

    import predictionio_tpu.storage.registry as regmod
    from ..models.recommendation import engine_factory
    from ..obs.flight import load_dump
    from ..obs.slo import HealthConfig, SLOObjective, load_alerts
    from ..storage import StorageRegistry
    from ..testing import faults
    from ..testing.clock import FakeClock
    from ..workflow.serving import QueryServer, ServerConfig

    tmp = base_dir or tempfile.mkdtemp(prefix="pio-brownout-")
    owns_tmp = base_dir is None
    registry = StorageRegistry(env={"PIO_FS_BASEDIR": tmp})
    prev_registry = regmod._default_registry
    regmod._default_registry = registry
    report: dict = {
        "mode": "brownout",
        "wedgeErrors": wedge_errors,
        "wedgeSlow": wedge_slow,
    }
    ledger = os.path.join(tmp, "alert-ledger.jsonl")
    flight_dir = os.path.join(tmp, "flight")
    server = None
    try:
        engine = engine_factory()
        info = _prepared_workspace(
            f"brownout-{n_users}x{n_items}",
            lambda reg: _build_brownout_workspace(
                reg, n_users=n_users, n_items=n_items
            ),
            tmp,
        )
        clock = FakeClock()
        # drill-sized objectives: the production shapes (availability
        # over status codes, latency over the serving histogram; fast
        # 5 m / slow 1 h windows) at toy-traffic sample floors
        objectives = (
            SLOObjective(
                name="availability", kind="ratio",
                metric="pio_http_responses_total", target=0.999,
                burn_threshold=8.0, min_window_events=10,
            ),
            SLOObjective(
                name="latency", kind="ratio",
                metric="pio_serving_request_seconds",
                latency_threshold_s=0.128, target=0.99,
                burn_threshold=8.0, min_window_events=10,
            ),
        )
        server = QueryServer(
            ServerConfig(
                ip="127.0.0.1", port=0, batching=False,
                engine_instance_id=info["baselineInstanceId"],
                health=HealthConfig(
                    alert_ledger=ledger,
                    flight_dir=flight_dir,
                    tick_s=0,  # the drill drives ticks on the fake clock
                    objectives=objectives,
                ),
            ),
            engine, registry, clock=clock,
        )
        server.start_background()
        plane = server.health
        assert plane is not None
        target = _http_target(
            f"http://127.0.0.1:{server.bound_port}/queries.json"
        )
        payloads = _expand_payloads(
            '{"user": "u{i}", "num": 5}', n=n_users
        )

        def drive(n: int) -> dict:
            counts: dict = {}
            for i in range(n):
                try:
                    status = target(payloads[i % len(payloads)])
                except Exception:
                    status = -1
                counts[status] = counts.get(status, 0) + 1
            return counts

        def fired_total(summary: dict) -> int:
            return sum(o["fired"] for o in summary["objectives"])

        # -- phase 1: control — a full fast window of clean traffic ----
        summary: dict = {}
        for _ in range(5):
            drive(max(4, queries // 5))
            clock.advance(60)
            summary = plane.tick()
        report["controlAlertsFired"] = fired_total(summary)

        # -- phase 2: one wedged in-flight request → stall + dump ------
        # the "latency" fault's sleep is INJECTED: the wedged request
        # blocks on an Event only released AFTER the watchdog has run,
        # so the stall detection is deterministic — no real-time window
        # between "seen in flight" and "checked" to lose on a loaded box
        release = threading.Event()
        faults.activate(
            faults.FaultSpec(
                site="serving.predict", kind="latency",
                arg=1.0, times=1,
            ),
            sleep=lambda _s: release.wait(timeout=30.0),
        )
        wedged = threading.Thread(
            target=lambda: drive(1), daemon=True
        )
        wedged.start()
        watchdog = plane.watchdog
        for _ in range(1000):  # bounded wait: the request cannot exit
            if watchdog.summary()["inflight"] > 0:
                break
            time.sleep(0.01)
        report["inflightSeen"] = watchdog.summary()["inflight"]
        clock.advance(60)  # fake: far past stall_factor x default budget
        plane.tick()
        release.set()
        wedged.join(timeout=10)
        faults.deactivate()
        stall_summary = watchdog.summary()
        report["stallsDetected"] = stall_summary["detected"]
        report["stallDump"] = stall_summary["lastDump"]
        dump = (
            load_dump(stall_summary["lastDump"])
            if stall_summary["lastDump"]
            else None
        )
        report["stallDumpNamesSite"] = bool(
            dump
            and any(
                e.get("kind") == "stall"
                and e.get("site") == "serving.request"
                for e in dump["events"]
            )
        )

        # -- phase 3: the wedge — errors + slow answers, alerts FIRE ---
        faults.activate(
            faults.FaultSpec(
                site="serving.predict", kind="refuse",
                times=wedge_errors,
            ),
            faults.FaultSpec(
                site="serving.predict", kind="latency",
                arg=wedge_latency_ms, times=wedge_slow,
            ),
        )
        wedge_counts = drive(wedge_errors + wedge_slow + 4)
        faults.deactivate()
        report["wedgeStatuses"] = {
            str(k): v for k, v in sorted(wedge_counts.items())
        }
        clock.advance(60)
        summary = plane.tick()
        report["firedAfterWedge"] = sorted(
            o["name"] for o in summary["objectives"]
            if o["state"] == "FIRING"
        )

        # -- phase 4: recovery — fast window drains, alerts CLEAR ------
        for _ in range(6):
            drive(max(4, queries // 5))
            clock.advance(60)
            summary = plane.tick()
        report["firingAfterRecovery"] = summary["firing"]

        per_objective = {
            o["name"]: (o["fired"], o["cleared"])
            for o in summary["objectives"]
        }
        report["alerts"] = {
            name: {"fired": fired, "cleared": cleared}
            for name, (fired, cleared) in sorted(per_objective.items())
        }
        # flaps (an objective firing more than once) are false alerts,
        # exactly like a control-run fire
        report["falsePositives"] = report["controlAlertsFired"] + sum(
            max(0, fired - 1) for fired, _ in per_objective.values()
        )
        durable = load_alerts(ledger)
        report["ledger"] = [
            {"objective": a["objective"], "state": a["state"]}
            for a in durable
        ]
        expected = {
            ("availability", "FIRING"), ("availability", "CLEARED"),
            ("latency", "FIRING"), ("latency", "CLEARED"),
        }
        seen = {(a["objective"], a["state"]) for a in durable}
        report["ok"] = bool(
            report["controlAlertsFired"] == 0
            and report["stallsDetected"] >= 1
            and report["stallDumpNamesSite"]
            and expected <= seen
            and report["firedAfterWedge"] == ["availability", "latency"]
            and report["firingAfterRecovery"] == 0
            and report["falsePositives"] == 0
        )
        return report
    finally:
        faults.deactivate()
        regmod._default_registry = prev_registry
        if server is not None:
            try:
                server.server_close()
            except Exception:
                pass
        if owns_tmp:
            shutil.rmtree(tmp, ignore_errors=True)


def run_fleet_chaos(
    replicas: int = 3,
    sharded: bool = False,
    replicas_per_shard: int = 1,
    kill_backend_at: Optional[int] = None,
    queries: int = 120,
    concurrency: int = 4,
    n_users: int = 24,
    n_items: int = 16,
    percent: float = 50.0,
    base_dir: Optional[str] = None,
) -> dict:
    """Serving-fleet chaos scenario (``--replicas N``, docs/fleet.md).

    Builds an in-process fleet — N query servers behind a
    :class:`~predictionio_tpu.fleet.router.RouterServer` — and proves
    the tier's three contracts:

    - **replicated** (default): a rollout is driven to CANARY so every
      backend serves the same sticky split; traffic flows through the
      router over real HTTP; at ``kill_backend_at`` one backend is
      **hard-killed** (live connections severed) mid-run. Acceptance:
      zero client-visible failures (the router retries dead-backend
      reads on the survivors) and the per-key variant assignments after
      the kill are **byte-identical** to before — the pure
      ``salt|key → bucket`` split needs no coordination to survive a
      replica death.
    - **sharded** (``--sharded``): each backend holds one item-factor
      partition; the router's merged top-k must equal the unsharded
      top-k of the same model **exactly** (compared as canonical JSON).
    - Fleet consistency is double-checked server-side: the router's
      ``pio_router_variant_mismatch_total`` (its own pure-function
      assignment vs. each backend's ``X-PIO-Variant`` echo) must be 0.

    Reports ``servedQPS``/``servedP99Ms`` — the serving-scale numbers
    ``bench.py`` attaches to its output and the perf ledger.
    """
    import shutil
    import tempfile

    import predictionio_tpu.storage.registry as regmod
    from ..fleet.router import RouterConfig, RouterServer, VARIANT_HEADER
    from ..models.recommendation import engine_factory
    from ..obs.expo import parse_text as _parse_expo
    from ..obs.expo import render as _render_expo
    from ..storage import StorageRegistry
    from ..workflow.serving import QueryServer, ServerConfig

    if replicas < 2:
        raise ValueError("--replicas needs at least 2 backends")
    if replicas_per_shard < 1:
        raise ValueError("--replicas-per-shard must be >= 1")
    if replicas_per_shard > 1 and not sharded:
        raise ValueError(
            "--replicas-per-shard needs --sharded (replicated mode "
            "already treats every backend as a replica)"
        )
    total_backends = (
        replicas * replicas_per_shard if sharded else replicas
    )
    if kill_backend_at is not None and not (
        0 <= kill_backend_at < total_backends
    ):
        raise ValueError(
            f"--kill-backend-at must name a backend in [0, {total_backends})"
        )
    if sharded and replicas_per_shard == 1 and kill_backend_at is not None:
        raise ValueError(
            "--sharded with one backend per shard has no replica "
            "redundancy (a dead shard fails reads loudly by design) — "
            "the kill drill needs --replicas-per-shard >= 2"
        )
    tmp = base_dir or tempfile.mkdtemp(prefix="pio-fleet-chaos-")
    owns_tmp = base_dir is None
    registry = StorageRegistry(env={"PIO_FS_BASEDIR": tmp})
    prev_registry = regmod._default_registry
    regmod._default_registry = registry  # RecDataSource reads through it
    report: dict = {
        "mode": "fleet-chaos",
        "replicas": replicas,
        "sharded": sharded,
        "replicasPerShard": replicas_per_shard if sharded else None,
        "clientFailures": 0,
    }
    backends: List[QueryServer] = []
    router = reference = None
    try:
        engine = engine_factory()
        info = _prepared_workspace(
            f"fleet-{n_users}x{n_items}",
            lambda reg: _build_fleet_workspace(
                reg, n_users=n_users, n_items=n_items
            ),
            tmp,
        )
        baseline_id = info["baselineInstanceId"]
        candidate_id = None if sharded else info["candidateInstanceId"]

        def backend_config(i: int) -> ServerConfig:
            return ServerConfig(
                ip="127.0.0.1", port=0, batching=False,
                # shard layout in sharded mode (backend i serves shard
                # i // replicas_per_shard — consecutive replica groups,
                # mirroring the router's ring math); in replicated mode
                # the FIRST backend pins the baseline and starts the
                # rollout, the rest resolve it from replicated metadata
                shard_index=(i // replicas_per_shard) if sharded else 0,
                shard_count=replicas if sharded else 1,
                engine_instance_id=(
                    baseline_id if (sharded or i == 0) else None
                ),
            )

        first = QueryServer(backend_config(0), engine, registry)
        backends.append(first)
        if not sharded:
            # CANARY fleet-wide: backend 0 opens the plan and promotes;
            # later backends resume the SAME durable plan (same salt,
            # same percent) via rollout_plan_get_active on construction
            first.rollout.start(
                candidate_instance_id=candidate_id,
                percent=percent,
                gates={
                    "min_samples": 1_000_000,  # the drill drives stages
                    "window_s": 1e9,
                    "shadow_hold_s": 1e9,
                    "canary_hold_s": 1e9,
                    "max_divergence": 1.0,
                    "max_p99_latency_ratio": 1e9,
                },
            )
            first.rollout.promote("fleet chaos drill: shadow -> canary")
            report["rolloutPlanId"] = first.rollout.plan.id
        for i in range(1, total_backends):
            backends.append(QueryServer(backend_config(i), engine, registry))
        for server in backends:
            server.start_background()
        if not sharded:
            stages = [s.rollout.stage for s in backends]
            report["backendStages"] = stages

        router = RouterServer(
            RouterConfig(
                ip="127.0.0.1", port=0,
                backends=tuple(
                    f"127.0.0.1:{s.bound_port}" for s in backends
                ),
                sharded=sharded,
                replicas_per_shard=replicas_per_shard,
                timeout_s=10.0,
                plan_refresh_s=0.0,  # every request re-checks consistency
                # failover is the thing under test: the response cache
                # would mask it (a hit never exercises a backend) — the
                # cached-hot-set drive (run_cached_hot_set) owns the
                # cache's own acceptance
                cache_enabled=False,
            ),
            registry=registry,
        )
        router.start_background()

        keys = [f"u{u}" for u in range(n_users)]
        lock = threading.Lock()
        latencies: List[float] = []

        def drive_phase(rounds: int) -> dict:
            """Each key queried ``rounds`` times through the router from
            ``concurrency`` workers; returns {key: variant}."""
            variants: dict = {}
            work = [k for _ in range(rounds) for k in keys]
            cursor = {"next": 0}

            def worker() -> None:
                while True:
                    with lock:
                        pos = cursor["next"]
                        if pos >= len(work):
                            return
                        cursor["next"] = pos + 1
                    key = work[pos]
                    payload = json.dumps({"user": key, "num": 5}).encode()
                    t0 = time.monotonic()
                    try:
                        status, headers = _post_with_headers(
                            f"127.0.0.1:{router.bound_port}", payload
                        )
                    except Exception:
                        status, headers = -1, {}
                    elapsed = time.monotonic() - t0
                    with lock:
                        if status == 200:
                            latencies.append(elapsed)
                            served = headers.get(VARIANT_HEADER.lower(), "-")
                            prior = variants.get(key)
                            if prior is not None and prior != served:
                                report["inconsistentVariants"] = (
                                    report.get("inconsistentVariants", 0) + 1
                                )
                            variants[key] = served
                        else:
                            report["clientFailures"] += 1

            threads = [
                threading.Thread(target=worker, daemon=True)
                for _ in range(concurrency)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            return variants

        rounds = max(1, queries // (2 * len(keys)))
        t_start = time.monotonic()
        variants_before = drive_phase(rounds)
        if kill_backend_at is not None:
            backends[kill_backend_at].kill()
            report["killedBackend"] = kill_backend_at
        variants_after = drive_phase(rounds)
        wall = time.monotonic() - t_start

        report["requests"] = len(latencies) + report["clientFailures"]
        report["servedQPS"] = (
            round(len(latencies) / wall, 1) if wall > 0 else 0.0
        )
        if latencies:
            lat = np.asarray(latencies)
            report["servedP50Ms"] = round(
                float(np.percentile(lat, 50)) * 1000, 3
            )
            report["servedP99Ms"] = round(
                float(np.percentile(lat, 99)) * 1000, 3
            )
        report["variantsIdentical"] = variants_before == variants_after
        report["variantCounts"] = {
            v: sum(1 for x in variants_after.values() if x == v)
            for v in set(variants_after.values())
        }
        report.setdefault("inconsistentVariants", 0)

        # server-side consistency double-check off the router's own
        # exposition: its pure-function assignment vs the backend echo
        scraped = _parse_expo(_render_expo(router.metrics))
        report["variantMismatches"] = int(
            sum(v for _l, v in scraped.get(
                "pio_router_variant_mismatch_total", []
            ))
        )
        report["routerRetries"] = int(
            sum(v for _l, v in scraped.get("pio_router_retries_total", []))
        )

        merged_ok = True
        if sharded:
            # Exact-merge acceptance: the router's scatter/gather answer
            # must equal an unsharded server's answer on the same model —
            # identical item RANKING (the top-k itself), scores to f32
            # reassociation tolerance. Bitwise score equality is not a
            # promise f32 can keep: XLA's matmul accumulation order
            # varies with matrix shape (a 6-item shard vs the 12-item
            # catalog), last-ulp noise only — the same analysis as the
            # ROUND7 sort-gather satellite (docs/fleet.md).
            reference = QueryServer(
                ServerConfig(
                    ip="127.0.0.1", port=0, batching=False,
                    engine_instance_id=baseline_id,
                ),
                engine, registry,
            )
            checked = 0
            for key in keys[: min(8, len(keys))]:
                payload = {"user": key, "num": 5}
                expect, _status = reference.handle_query(dict(payload))
                raw = json.dumps(payload).encode()
                status, body, _variant = router.route_query(raw, None)
                if status != 200 or not merged_matches_reference(
                    body, expect
                ):
                    merged_ok = False
                checked += 1
            report["shardMergeChecked"] = checked
            report["mergedEqualsUnsharded"] = merged_ok

        report["ok"] = bool(
            report["clientFailures"] == 0
            and report["inconsistentVariants"] == 0
            and report["variantMismatches"] == 0
            and report["variantsIdentical"]
            and merged_ok
        )
        return report
    finally:
        regmod._default_registry = prev_registry
        for srv in [router, reference, *backends]:
            if srv is not None:
                try:
                    srv.kill()
                except Exception:
                    pass
        if owns_tmp:
            shutil.rmtree(tmp, ignore_errors=True)


def run_cached_hot_set(
    queries: int = 240,
    concurrency: int = 4,
    n_users: int = 24,
    n_items: int = 16,
    zipf_s: float = 1.2,
    percent: float = 50.0,
    cache_ttl_s: float = 120.0,
    base_dir: Optional[str] = None,
) -> dict:
    """The serve-from-memory acceptance drive (``--cached-hot-set``,
    docs/fleet.md#cache): a Zipfian hot-set query mix through two
    routers over the SAME backend — one cache-off, one cache-on — so
    the step-function QPS win is measured against an identical server
    on the same box, plus the two correctness proofs the cache must
    carry:

    - **byte identity**: for sampled keys, the cached hit's response
      body equals the filling miss's body byte-for-byte (only the trace
      id / cache-verdict headers differ);
    - **invalidation**: a rollout stage transition mid-drive flushes the
      keyspace — every post-transition response's ``X-PIO-Variant``
      matches the NEW plan's pure-function assignment (zero stale
      responses), and the router's epoch-invalidation counter moved.

    One backend on purpose: the cache tier is the thing under test (a
    mid-drive stage transition is only immediately visible on the
    backend that performs it), and failover already has its own drill
    (:func:`run_fleet_chaos`). Reports ``cachedQPS``/``uncachedQPS``/
    ``hitRate`` — the numbers ``bench.py`` attaches (``cachedFleet``,
    opt out ``BENCH_CACHE=0``) and the perf ledger records as
    ``fleet_cached_qps`` (trend) and ``fleet_cached_p99_s`` (gated).
    """
    import shutil
    import tempfile

    import predictionio_tpu.storage.registry as regmod
    from ..fleet.cache import CACHE_HEADER
    from ..fleet.router import RouterConfig, RouterServer, VARIANT_HEADER
    from ..models.recommendation import engine_factory
    from ..rollout.plan import sticky_key, variant_for_key
    from ..storage import StorageRegistry
    from ..workflow.serving import QueryServer, ServerConfig

    tmp = base_dir or tempfile.mkdtemp(prefix="pio-cached-hot-set-")
    owns_tmp = base_dir is None
    registry = StorageRegistry(env={"PIO_FS_BASEDIR": tmp})
    prev_registry = regmod._default_registry
    regmod._default_registry = registry
    report: dict = {
        "mode": "cached-hot-set",
        "replicas": 1,
        "clientFailures": 0,
    }
    backends: List[QueryServer] = []
    routers: List[RouterServer] = []
    try:
        engine = engine_factory()
        # the fleet drills' shared train-once workspace: this drive adds
        # ZERO training cost to a process that already ran a fleet drill
        info = _prepared_workspace(
            f"fleet-{n_users}x{n_items}",
            lambda reg: _build_fleet_workspace(
                reg, n_users=n_users, n_items=n_items
            ),
            tmp,
        )
        baseline_id = info["baselineInstanceId"]
        candidate_id = info["candidateInstanceId"]
        backends.append(
            QueryServer(
                ServerConfig(
                    ip="127.0.0.1", port=0, batching=False,
                    engine_instance_id=baseline_id,
                ),
                engine, registry,
            )
        )
        for server in backends:
            server.start_background()

        def make_router(cache_on: bool) -> RouterServer:
            router = RouterServer(
                RouterConfig(
                    ip="127.0.0.1", port=0,
                    backends=tuple(
                        f"127.0.0.1:{s.bound_port}" for s in backends
                    ),
                    timeout_s=10.0,
                    # observe every durable plan write immediately: the
                    # invalidation proof must not race the refresh cadence
                    plan_refresh_s=0.0,
                    cache_enabled=cache_on,
                    cache_ttl_s=cache_ttl_s,
                ),
                registry=registry,
            )
            router.start_background()
            routers.append(router)
            return router

        uncached_router = make_router(False)
        cached_router = make_router(True)

        # Zipfian hot-set mix: rank r drawn with weight 1/r^s — the
        # "millions of users" head, shrunk to drill size. One fixed
        # sequence drives BOTH routers, so the QPS comparison is
        # apples-to-apples.
        rng = np.random.default_rng(7)
        keys = [f"u{u}" for u in range(n_users)]
        weights = np.array(
            [1.0 / (r + 1) ** zipf_s for r in range(len(keys))]
        )
        weights /= weights.sum()
        mix = [
            keys[i]
            for i in rng.choice(len(keys), size=queries, p=weights)
        ]
        payloads = {
            k: json.dumps({"user": k, "num": 5}).encode() for k in keys
        }

        lock = threading.Lock()

        def drive(router: RouterServer) -> dict:
            latencies: List[float] = []
            cursor = {"next": 0}

            def worker() -> None:
                while True:
                    with lock:
                        pos = cursor["next"]
                        if pos >= len(mix):
                            return
                        cursor["next"] = pos + 1
                    t0 = time.monotonic()
                    try:
                        status, _headers, _body = _post_raw(
                            f"127.0.0.1:{router.bound_port}",
                            payloads[mix[pos]],
                        )
                    except Exception:
                        status = -1
                    elapsed = time.monotonic() - t0
                    with lock:
                        if status == 200:
                            latencies.append(elapsed)
                        else:
                            report["clientFailures"] += 1

            t_start = time.monotonic()
            threads = [
                threading.Thread(target=worker, daemon=True)
                for _ in range(concurrency)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.monotonic() - t_start
            out = {
                "qps": round(len(latencies) / wall, 1) if wall > 0 else 0.0,
            }
            if latencies:
                lat = np.asarray(latencies)
                out["p50Ms"] = round(float(np.percentile(lat, 50)) * 1e3, 3)
                out["p99Ms"] = round(float(np.percentile(lat, 99)) * 1e3, 3)
            return out

        # -- proof 1: hit bodies are byte-identical to the filling miss
        byte_identical = True
        for key in keys[:6]:
            s1, h1, b1 = _post_raw(
                f"127.0.0.1:{cached_router.bound_port}", payloads[key]
            )
            s2, h2, b2 = _post_raw(
                f"127.0.0.1:{cached_router.bound_port}", payloads[key]
            )
            if not (
                s1 == s2 == 200
                and h1.get(CACHE_HEADER.lower()) == "miss"
                and h2.get(CACHE_HEADER.lower()) == "hit"
                and b1 == b2
            ):
                byte_identical = False
        report["byteIdentical"] = byte_identical
        # the warmup pairs above pre-filled part of the hot set; flush so
        # the throughput phase measures a cold-start cache honestly, and
        # snapshot the counters so the reported hit rate is the DRIVE's
        # delta, not contaminated by the warmup lookups
        if cached_router._cache is not None:
            cached_router._cache.flush(reason="explicit")
        before = (
            cached_router._cache.snapshot()
            if cached_router._cache is not None
            else {}
        )

        # -- the step function: same mix, cache off vs on
        uncached = drive(uncached_router)
        cached = drive(cached_router)
        report["uncachedQPS"] = uncached["qps"]
        report["uncachedP99Ms"] = uncached.get("p99Ms")
        report["cachedQPS"] = cached["qps"]
        report["cachedP50Ms"] = cached.get("p50Ms")
        report["cachedP99Ms"] = cached.get("p99Ms")
        report["speedup"] = (
            round(cached["qps"] / uncached["qps"], 2)
            if uncached["qps"] > 0
            else None
        )
        snap = (
            cached_router._cache.snapshot()
            if cached_router._cache is not None
            else {}
        )
        hits = snap.get("hits", 0) - before.get("hits", 0)
        lookups = hits + snap.get("misses", 0) - before.get("misses", 0)
        report["hitRate"] = round(hits / lookups, 3) if lookups else 0.0

        # -- proof 2: a rollout stage change mid-drive leaves ZERO stale
        # responses. Start a canary (epoch move #1: SHADOW; #2: CANARY),
        # then require every response's variant header to match the NEW
        # plan's pure-function assignment.
        stale = 0
        backends[0].rollout.start(
            candidate_instance_id=candidate_id,
            percent=percent,
            gates={
                "min_samples": 1_000_000, "window_s": 1e9,
                "shadow_hold_s": 1e9, "canary_hold_s": 1e9,
                "max_divergence": 1.0, "max_p99_latency_ratio": 1e9,
            },
        )
        backends[0].rollout.promote("cached-hot-set drill: -> canary")
        plan = backends[0].rollout.plan
        for key in keys:
            status, headers, _body = _post_raw(
                f"127.0.0.1:{cached_router.bound_port}", payloads[key]
            )
            if status != 200:
                report["clientFailures"] += 1
                continue
            expected = variant_for_key(
                plan.salt, sticky_key({"user": key, "num": 5}), plan.percent
            )
            if headers.get(VARIANT_HEADER.lower()) != expected:
                stale += 1
        # drive the hot set AGAIN through the cache and re-verify: hits
        # (this time cached under the canary epoch) must still carry the
        # canary assignment
        for key in keys[:8]:
            status, headers, _body = _post_raw(
                f"127.0.0.1:{cached_router.bound_port}", payloads[key]
            )
            expected = variant_for_key(
                plan.salt, sticky_key({"user": key, "num": 5}), plan.percent
            )
            if status == 200 and (
                headers.get(VARIANT_HEADER.lower()) != expected
            ):
                stale += 1
        report["staleAfterRollout"] = stale
        snap = (
            cached_router._cache.snapshot()
            if cached_router._cache is not None
            else {}
        )
        report["invalidations"] = sum(
            snap.get("invalidations", {}).values()
        )
        report["epochInvalidations"] = snap.get("invalidations", {}).get(
            "epoch", 0
        )
        report["ok"] = bool(
            report["clientFailures"] == 0
            and byte_identical
            and stale == 0
            and report["epochInvalidations"] > 0
            and report["hitRate"] > 0.3
            and report["cachedQPS"] > report["uncachedQPS"]
        )
        return report
    finally:
        regmod._default_registry = prev_registry
        for srv in [*routers, *backends]:
            try:
                srv.kill()
            except Exception:
                pass
        if owns_tmp:
            shutil.rmtree(tmp, ignore_errors=True)


def run_shared_cache_drill(
    queries: int = 240,
    concurrency: int = 4,
    n_users: int = 24,
    n_items: int = 16,
    zipf_s: float = 1.2,
    percent: float = 50.0,
    base_dir: Optional[str] = None,
) -> dict:
    """The kill-the-tier acceptance drive (``--shared-cache-drill``,
    docs/fleet.md#shared-cache-tier): two routers over the same two
    backends share one ``pio sharedcache`` sidecar, with pushed
    invalidation subscribed to the metadata changefeed and request
    hedging armed. The drill proves the tier's one-line contract —
    *the sidecar can make the fleet faster, it can never make it
    wrong* — by killing it and watching nothing break:

    - **cross-router reuse**: a key filled through router A answers
      router B's first lookup from the shared tier (``hit-shared``),
      byte-identical to A's body;
    - **fail-soft**: the sidecar is HARD-KILLED mid-Zipfian-drive —
      zero client failures, byte-identical answers, and every degrade
      recorded (breaker open / transport error outcomes), i.e. exactly
      the per-router cache behavior with the tier subtracted;
    - **recovery + warming**: a restarted sidecar (same port) refills
      and serves shared hits again once the client breaker re-probes,
      and a router booted AFTER the restart pre-fills its local LRU
      from the sidecar's top-keys export (``warmedEntries > 0``);
    - **pushed invalidation**: a rollout flip lands with the plan poll
      stretched to minutes (``plan_refresh_s=300``) — the changefeed
      subscription must flush both routers within the push latency,
      zero stale variant assignments, no poll to wait for.

    Reports ``sharedHitRate`` (trend) and ``hedgedP99Ms`` (gated) —
    the numbers ``bench.py`` attaches (``sharedCache``, opt out
    ``BENCH_SHAREDCACHE=0``) and the perf ledger records as
    ``fleet_shared_hit_rate`` / ``fleet_hedged_p99_s``."""
    import os as _os
    import shutil
    import tempfile

    import predictionio_tpu.storage.registry as regmod
    from ..continuous.watcher import LocalFeed
    from ..fleet.cache import CACHE_HEADER
    from ..fleet.router import RouterConfig, RouterServer, VARIANT_HEADER
    from ..fleet.sharedcache import SharedCacheServer
    from ..models.recommendation import engine_factory
    from ..obs.expo import parse_text, render
    from ..rollout.plan import sticky_key, variant_for_key
    from ..storage import StorageRegistry
    from ..storage.changefeed import Changefeed, RecordingRegistry
    from ..storage.oplog import OpLog
    from ..utils.resilience import CircuitBreaker
    from ..workflow.serving import QueryServer, ServerConfig

    tmp = base_dir or tempfile.mkdtemp(prefix="pio-shared-cache-")
    owns_tmp = base_dir is None
    registry = StorageRegistry(env={"PIO_FS_BASEDIR": tmp})
    prev_registry = regmod._default_registry
    regmod._default_registry = registry
    report: dict = {
        "mode": "shared-cache-drill",
        "clientFailures": 0,
        "staleAfterRollout": 0,
    }
    backends: List[QueryServer] = []
    routers: List[RouterServer] = []
    sidecars: List[SharedCacheServer] = []
    try:
        engine = engine_factory()
        # the fleet drills' shared train-once workspace: zero extra
        # training cost in a process that already ran a fleet drill
        info = _prepared_workspace(
            f"fleet-{n_users}x{n_items}",
            lambda reg: _build_fleet_workspace(
                reg, n_users=n_users, n_items=n_items
            ),
            tmp,
        )
        baseline_id = info["baselineInstanceId"]
        candidate_id = info["candidateInstanceId"]
        # every metadata mutation flows through the changefeed, so the
        # routers have a live feed to subscribe to — the same recording
        # discipline a storage server applies (storage/changefeed.py)
        oplog = OpLog(_os.path.join(tmp, "oplog"))
        changefeed = Changefeed(
            oplog,
            registry.get_events(),
            registry.get_metadata(),
            registry.get_models(),
        )
        recording = RecordingRegistry(registry, changefeed)
        for _ in range(2):  # two replicas: the hedge needs a second leg
            backends.append(
                QueryServer(
                    ServerConfig(
                        ip="127.0.0.1", port=0, batching=False,
                        engine_instance_id=baseline_id,
                    ),
                    engine, recording,
                )
            )
        for server in backends:
            server.start_background()
        sidecar = SharedCacheServer(ip="127.0.0.1", port=0)
        sidecar.start_background()
        sidecars.append(sidecar)
        shared_addr = f"127.0.0.1:{sidecar.bound_port}"

        def make_router() -> RouterServer:
            router = RouterServer(
                RouterConfig(
                    ip="127.0.0.1", port=0,
                    backends=tuple(
                        f"127.0.0.1:{s.bound_port}" for s in backends
                    ),
                    timeout_s=10.0,
                    # minutes of poll staleness ON PURPOSE: only the
                    # pushed invalidation can make the flip proof pass
                    plan_refresh_s=300.0,
                    cache_enabled=True,
                    shared_cache=shared_addr,
                    shared_warm=False,  # warming proven on router C
                ),
                registry=recording,
                meta_feed=LocalFeed(oplog),
            )
            # drill-speed breaker: open after 2 failures, re-probe
            # after 0.3s — the drill proves reopen/recovery without
            # waiting out the production cooldown
            router._shared.breaker = CircuitBreaker.from_env(
                "sharedcache-drill",
                env={
                    "PIO_BREAKER_FAILURES": "2",
                    "PIO_BREAKER_RESET_S": "0.3",
                },
            )
            router.start_background()
            routers.append(router)
            return router

        router_a = make_router()
        router_b = make_router()

        rng = np.random.default_rng(7)
        keys = [f"u{u}" for u in range(n_users)]
        weights = np.array(
            [1.0 / (r + 1) ** zipf_s for r in range(len(keys))]
        )
        weights /= weights.sum()
        mix = [
            keys[i]
            for i in rng.choice(len(keys), size=queries, p=weights)
        ]
        payloads = {
            k: json.dumps({"user": k, "num": 5}).encode() for k in keys
        }
        lock = threading.Lock()

        def drive(
            router: RouterServer, kill_at: Optional[int] = None
        ) -> dict:
            """Concurrent Zipfian drive; with ``kill_at``, hard-kill
            the live sidecar once that many queries have completed —
            the drive itself must not notice."""
            latencies: List[float] = []
            cursor = {"next": 0, "done": 0, "killed": False}

            def worker() -> None:
                while True:
                    with lock:
                        pos = cursor["next"]
                        if pos >= len(mix):
                            return
                        cursor["next"] = pos + 1
                    t0 = time.monotonic()
                    try:
                        status, _headers, _body = _post_raw(
                            f"127.0.0.1:{router.bound_port}",
                            payloads[mix[pos]],
                        )
                    except Exception:
                        status = -1
                    elapsed = time.monotonic() - t0
                    with lock:
                        cursor["done"] += 1
                        if status == 200:
                            latencies.append(elapsed)
                        else:
                            report["clientFailures"] += 1
                        do_kill = (
                            kill_at is not None
                            and cursor["done"] >= kill_at
                            and not cursor["killed"]
                        )
                        if do_kill:
                            cursor["killed"] = True
                    if do_kill:
                        sidecars[-1].kill()

            t_start = time.monotonic()
            threads = [
                threading.Thread(target=worker, daemon=True)
                for _ in range(concurrency)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.monotonic() - t_start
            out = {
                "qps": round(len(latencies) / wall, 1) if wall > 0 else 0.0,
            }
            if latencies:
                lat = np.asarray(latencies)
                out["p50Ms"] = round(float(np.percentile(lat, 50)) * 1e3, 3)
                out["p99Ms"] = round(float(np.percentile(lat, 99)) * 1e3, 3)
            return out

        def shared_outcomes(router: RouterServer) -> Dict[str, int]:
            return dict(router._shared.outcomes)

        # -- phase A: healthy tier — cross-router reuse, byte identity
        reference: Dict[str, bytes] = {}
        cross_router = True
        for key in keys[:6]:
            s1, h1, b1 = _post_raw(
                f"127.0.0.1:{router_a.bound_port}", payloads[key]
            )
            s2, h2, b2 = _post_raw(
                f"127.0.0.1:{router_b.bound_port}", payloads[key]
            )
            reference[key] = b1
            if not (
                s1 == s2 == 200
                and h1.get(CACHE_HEADER.lower()) == "miss"
                and h2.get(CACHE_HEADER.lower()) == "hit-shared"
                and b1 == b2
            ):
                cross_router = False
        report["crossRouterReuse"] = cross_router
        healthy = drive(router_a)
        report["healthyQPS"] = healthy["qps"]
        report["hedgedP99Ms"] = healthy.get("p99Ms")
        # router B rides A's fills: flush its local LRU so every lookup
        # exercises the shared tier, then measure the tier's hit rate
        router_b._cache.flush(reason="explicit")
        before_b = shared_outcomes(router_b)
        drive(router_b)
        after_b = shared_outcomes(router_b)
        shared_hits = after_b.get("hit", 0) - before_b.get("hit", 0)
        shared_lookups = shared_hits + (
            after_b.get("miss", 0) - before_b.get("miss", 0)
        )
        report["sharedHitRate"] = (
            round(shared_hits / shared_lookups, 3) if shared_lookups else 0.0
        )

        # -- phase B: hard-kill the sidecar mid-drive. The flushed local
        # LRU forces every miss through the (dying) shared tier; the
        # contract is zero client failures and recorded degrades.
        router_a._cache.flush(reason="explicit")
        before_a = shared_outcomes(router_a)
        drive(router_a, kill_at=max(1, queries // 3))
        after_a = shared_outcomes(router_a)
        degrades = sum(
            after_a.get(k, 0) - before_a.get(k, 0)
            for k in ("error", "open", "put_error")
        )
        report["degradesRecorded"] = degrades
        byte_identical = True
        for key in keys[:6]:
            status, _h, body = _post_raw(
                f"127.0.0.1:{router_a.bound_port}", payloads[key]
            )
            if status != 200 or body != reference[key]:
                byte_identical = False
        report["byteIdenticalAfterKill"] = byte_identical

        # -- phase C: restart the sidecar on the SAME port; the breaker
        # re-probes after its cooldown and shared hits resume
        sidecar = SharedCacheServer(
            ip="127.0.0.1", port=sidecars[-1].bound_port
        )
        sidecar.start_background()
        sidecars.append(sidecar)
        time.sleep(0.4)  # past the drill breaker's reset window
        router_a._cache.flush(reason="explicit")
        drive(router_a)  # refills sidecar through the put path
        router_a._cache.flush(reason="explicit")
        before_a = shared_outcomes(router_a)
        drive(router_a)
        after_a = shared_outcomes(router_a)
        report["recoveredSharedHits"] = (
            after_a.get("hit", 0) - before_a.get("hit", 0)
        )
        # a router booted NOW pre-fills from the sidecar's top keys
        router_c = make_router()
        warmed = router_c.warm_from_shared()
        report["warmedEntries"] = warmed
        warm_key = keys[0]
        status, h, body = _post_raw(
            f"127.0.0.1:{router_c.bound_port}", payloads[warm_key]
        )
        report["warmServesLocalHit"] = bool(
            status == 200
            and h.get(CACHE_HEADER.lower()) == "hit"
            and body == reference[warm_key]
        )

        # -- phase D: pushed invalidation — a rollout flip must land on
        # every router within push latency, with the poll 300s away
        backends[0].rollout.start(
            candidate_instance_id=candidate_id,
            percent=percent,
            gates={
                "min_samples": 1_000_000, "window_s": 1e9,
                "shadow_hold_s": 1e9, "canary_hold_s": 1e9,
                "max_divergence": 1.0, "max_p99_latency_ratio": 1e9,
            },
        )
        backends[0].rollout.promote("shared-cache drill: -> canary")
        backends[1].rollout.resume()  # second replica re-reads the plan
        plan = backends[0].rollout.plan
        deadline = time.monotonic() + 2.0
        flushed = False
        while time.monotonic() < deadline and not flushed:
            flushed = all(
                any(
                    labels.get("source") == "push" and value > 0
                    for labels, value in parse_text(
                        render(r.metrics)
                    ).get("pio_router_epoch_events_total", [])
                )
                for r in (router_a, router_b, router_c)
            )
            if not flushed:
                time.sleep(0.05)
        report["pushFlushObserved"] = flushed
        stale = 0
        for router in (router_a, router_b, router_c):
            for key in keys:
                status, headers, _body = _post_raw(
                    f"127.0.0.1:{router.bound_port}", payloads[key]
                )
                if status != 200:
                    report["clientFailures"] += 1
                    continue
                expected = variant_for_key(
                    plan.salt,
                    sticky_key({"user": key, "num": 5}),
                    plan.percent,
                )
                if headers.get(VARIANT_HEADER.lower()) != expected:
                    stale += 1
        report["staleAfterRollout"] = stale
        snap = router_a._cache.snapshot()
        report["epochInvalidations"] = snap.get("invalidations", {}).get(
            "epoch", 0
        )
        hedges: Dict[str, float] = {}
        for labels, value in parse_text(render(router_a.metrics)).get(
            "pio_router_hedges_total", []
        ):
            hedges[labels.get("outcome", "-")] = value
        report["hedges"] = hedges
        report["ok"] = bool(
            report["clientFailures"] == 0
            and cross_router
            and byte_identical
            and degrades > 0
            and report["recoveredSharedHits"] > 0
            and warmed > 0
            and report["warmServesLocalHit"]
            and flushed
            and stale == 0
            and report["epochInvalidations"] > 0
            and report["sharedHitRate"] > 0.3
        )
        return report
    finally:
        regmod._default_registry = prev_registry
        for srv in [*routers, *backends, *sidecars]:
            try:
                srv.kill()
            except Exception:
                pass
        if owns_tmp:
            shutil.rmtree(tmp, ignore_errors=True)


def _post_raw(node: str, payload: bytes):
    """One POST /queries.json against ``host:port`` → (status, headers
    dict lowercase, raw body BYTES). The cached-hot-set drive compares
    hit and miss bodies byte-for-byte — parsing would hide an encoding
    difference the byte-identity contract forbids."""
    host, _, port = node.partition(":")
    conn = http.client.HTTPConnection(host, int(port), timeout=30)
    try:
        conn.request(
            "POST", "/queries.json", body=payload,
            headers={"Content-Type": "application/json"},
        )
        resp = conn.getresponse()
        body = resp.read()
        return (
            resp.status,
            {k.lower(): v for k, v in resp.getheaders()},
            body,
        )
    finally:
        conn.close()


# merged_matches_reference moved to fleet/merge.py — ONE home for the
# f32 ranking-equality contract, shared with the fused top-k
# equivalence tests (re-exported here for the drill callers/tests).
from ..fleet.merge import merged_matches_reference  # noqa: E402,F401


def _post_with_headers(node: str, payload: bytes):
    """One POST /queries.json against ``host:port`` → (status, headers
    dict, lowercase keys). Fresh connection per call: the chaos drive
    must see a killed backend's reset as that request's outcome, never
    poison a pooled socket for a later request."""
    host, _, port = node.partition(":")
    conn = http.client.HTTPConnection(host, int(port), timeout=30)
    try:
        conn.request(
            "POST", "/queries.json", body=payload,
            headers={"Content-Type": "application/json"},
        )
        resp = conn.getresponse()
        resp.read()
        return resp.status, {k.lower(): v for k, v in resp.getheaders()}
    finally:
        conn.close()


def main(argv: Optional[Sequence[str]] = None) -> int:
    from ..utils.platform import apply_env_platform

    apply_env_platform()
    p = argparse.ArgumentParser(prog="loadgen")
    p.add_argument("--url", default="http://localhost:8000/queries.json")
    p.add_argument("--payload", default='{"user": "{i}", "num": 10}')
    p.add_argument("--concurrency", type=int, default=32)
    p.add_argument("--duration", type=float, default=10.0)
    p.add_argument("--in-process", action="store_true",
                   help="drive handle_query directly (no HTTP)")
    p.add_argument("--engine-dir", default=".",
                   help="engine project dir for --in-process")
    p.add_argument("--no-batching", action="store_true",
                   help="disable micro-batching in --in-process mode")
    p.add_argument("--pipeline-depth", type=int, default=2,
                   help="in-flight batch depth in --in-process mode")
    p.add_argument("--deadline-ms", type=float, default=None,
                   help="per-request X-PIO-Deadline-Ms budget; 504s are "
                        "reported as deadline_expired, not errors")
    p.add_argument("--scrape-metrics", action="store_true",
                   help="after the run, GET /metrics from the target and "
                        "report server-side percentiles next to the "
                        "client-side ones (docs/observability.md)")
    p.add_argument("--fault", action="append", default=[],
                   metavar="SITE=KIND[:ARG][*N]",
                   help="activate the deterministic fault harness "
                        "(predictionio_tpu.testing.faults) in this "
                        "process; repeatable. For a live HTTP server, "
                        "start it with PIO_FAULTS set instead.")
    p.add_argument("--rollout", action="store_true",
                   help="rollout chaos scenario (docs/rollouts.md): "
                        "in-process server from --engine-dir, start "
                        "shadow, promote to canary, fail the candidate, "
                        "assert auto-rollback with zero client-visible "
                        "failures and a durable ROLLED_BACK plan")
    p.add_argument("--score-drift", action="store_true",
                   help="score-drift chaos scenario "
                        "(docs/observability.md#quality): skewed "
                        "candidate behind the rollout plane; asserts "
                        "the max_score_psi gate auto-rolls back with "
                        "zero client failures, a durable ROLLED_BACK "
                        "plan and restart quarantine")
    p.add_argument("--skew", type=float, default=4.0,
                   help="rating/score scale factor of the drifted "
                        "candidate for --score-drift")
    p.add_argument("--max-score-psi", type=float, default=0.25,
                   help="PSI gate threshold for --score-drift")
    p.add_argument("--brownout", action="store_true",
                   help="brownout chaos scenario (docs/slo.md): wedge "
                        "the predict path with injected latency + "
                        "refusals (not a kill); asserts the stall "
                        "watchdog dumps forensics naming the wedged "
                        "site and the availability/latency SLO burn "
                        "alerts fire then CLEAR durably, with zero "
                        "false alerts on the clean control phase")
    p.add_argument("--feedback-stream", action="store_true",
                   help="closed-loop freshness scenario "
                        "(docs/continuous.md): in-process storage "
                        "primary + event server + query server with the "
                        "continuous controller, steady feedback trickle, "
                        "reports event-ingest -> model-live freshness")
    p.add_argument("--events", type=int, default=60,
                   help="total feedback events for --feedback-stream")
    p.add_argument("--burst", type=int, default=20,
                   help="events per trickle burst (= the fold trigger "
                        "size) for --feedback-stream")
    p.add_argument("--replicas", type=int, default=None, metavar="N",
                   help="serving-fleet chaos scenario (docs/fleet.md): "
                        "N in-process query servers behind a router; "
                        "reports servedQPS/servedP99Ms and proves "
                        "fleet-consistent variant assignment")
    p.add_argument("--sharded", action="store_true",
                   help="with --replicas: partition the item factors "
                        "across the backends and assert the router's "
                        "merged top-k equals the unsharded top-k exactly")
    p.add_argument("--replicas-per-shard", type=int, default=1, metavar="R",
                   help="with --replicas --sharded: R backends per shard "
                        "(total servers = N*R); the kill drill then "
                        "proves a sharded fleet survives a backend kill "
                        "exactly like the replicated fleet "
                        "(docs/fleet.md#replicas-per-shard)")
    p.add_argument("--kill-backend-at", type=int, default=None, metavar="I",
                   help="with --replicas: hard-kill backend I between "
                        "the two drive phases; acceptance is zero client "
                        "failures and byte-identical variant assignments")
    p.add_argument("--queries", type=int, default=120,
                   help="total queries across the --replicas drive phases")
    p.add_argument("--cached-hot-set", action="store_true",
                   help="serve-from-memory acceptance drive "
                        "(docs/fleet.md#cache): Zipfian hot-set mix "
                        "through cache-off and cache-on routers over the "
                        "same backend; proves the step-function QPS win, "
                        "byte-identical hit bodies, and zero stale "
                        "responses across a mid-drive rollout stage "
                        "transition (the BENCH cachedFleet block)")
    p.add_argument("--shared-cache-drill", action="store_true",
                   help="kill-the-tier acceptance drive (docs/fleet.md"
                        "#shared-cache-tier): two routers share one "
                        "sharedcache sidecar with pushed invalidation "
                        "and hedging armed; the sidecar is hard-killed "
                        "mid-Zipfian-drive — acceptance is zero client "
                        "failures, zero stale responses, recorded "
                        "degrades, recovery + warming after restart, "
                        "and a rollout flip landing by push with the "
                        "plan poll minutes away (the BENCH sharedCache "
                        "block)")
    p.add_argument("--partitions", type=int, default=None, metavar="N",
                   help="partitioned write-path chaos scenario "
                        "(docs/storage.md#partitioning): N in-process "
                        "partition primaries + replicas, concurrent "
                        "writers across all partitions, one partition "
                        "hard-killed mid-run (--kill-partition-at); "
                        "acceptance is zero lost acked writes, zero "
                        "failures on unaffected partitions, and the "
                        "merged watcher resuming without replay")
    p.add_argument("--kill-partition-at", type=int, default=None,
                   metavar="I",
                   help="with --partitions: the partition whose primary "
                        "is hard-killed mid-run (default 1)")
    p.add_argument("--migrate-drill", action="store_true",
                   help="live partition-migration chaos drill: N=2 -> "
                        "M=3 dual-write + backfill under concurrent "
                        "writers, coordinator killed mid-dual-write, a "
                        "new-layout primary killed mid-backfill, cutover "
                        "only behind the per-keyspace watermark "
                        "(docs/storage.md#live-migration)")
    p.add_argument("--new-partitions", type=int, default=3, metavar="M",
                   help="with --migrate-drill: the target layout's "
                        "partition count (default 3)")
    p.add_argument("--ingest-scaling", action="store_true",
                   help="ingest-scaling drive: acked-writes/second at "
                        "1, 2 and 4 partitions on this box (the BENCH "
                        "ingestScaling block)")
    p.add_argument("--kill-primary-at", type=int, default=None, metavar="N",
                   help="storage-plane chaos scenario: in-process "
                        "primary+replica, hard-kill the primary at op N, "
                        "fail reads over to the replica, promote, verify "
                        "zero failed reads / zero lost acked writes "
                        "(ignores the query-server flags)")
    p.add_argument("--ops", type=int, default=None,
                   help="total ops for --kill-primary-at (default 2N)")
    args = p.parse_args(argv)

    if args.rollout:
        from ..utils.jax_cache import enable_compilation_cache

        enable_compilation_cache()
        result = run_rollout_chaos(
            engine_dir=args.engine_dir, payload_template=args.payload
        )
        print(json.dumps(result))
        return 0 if result["ok"] else 1

    if args.score_drift:
        from ..utils.jax_cache import enable_compilation_cache

        enable_compilation_cache()
        result = run_score_drift(
            skew=args.skew, max_score_psi=args.max_score_psi
        )
        print(json.dumps(result))
        return 0 if result["ok"] else 1

    if args.brownout:
        from ..utils.jax_cache import enable_compilation_cache

        enable_compilation_cache()
        result = run_brownout()
        print(json.dumps(result))
        return 0 if result["ok"] else 1

    if args.replicas is not None:
        from ..utils.jax_cache import enable_compilation_cache

        enable_compilation_cache()
        result = run_fleet_chaos(
            replicas=args.replicas,
            sharded=args.sharded,
            replicas_per_shard=args.replicas_per_shard,
            kill_backend_at=args.kill_backend_at,
            queries=args.queries,
        )
        print(json.dumps(result))
        return 0 if result["ok"] else 1

    if args.cached_hot_set:
        from ..utils.jax_cache import enable_compilation_cache

        enable_compilation_cache()
        result = run_cached_hot_set(queries=args.queries)
        print(json.dumps(result))
        return 0 if result["ok"] else 1

    if args.shared_cache_drill:
        from ..utils.jax_cache import enable_compilation_cache

        enable_compilation_cache()
        result = run_shared_cache_drill(queries=args.queries)
        print(json.dumps(result))
        return 0 if result["ok"] else 1

    if args.feedback_stream:
        from ..utils.jax_cache import enable_compilation_cache

        enable_compilation_cache()
        result = run_feedback_stream(
            total_events=args.events, burst=args.burst
        )
        print(json.dumps(result))
        return 0 if result["ok"] else 1

    if args.migrate_drill:
        result = run_migrate_drill(
            old_partitions=args.partitions or 2,
            new_partitions=args.new_partitions,
            kill_new_partition=(
                args.kill_partition_at
                if args.kill_partition_at is not None
                else 1
            ),
        )
        print(json.dumps(result))
        return 0 if result["ok"] else 1

    if args.partitions is not None:
        result = run_partition_chaos(
            partitions=args.partitions,
            kill_partition=(
                args.kill_partition_at
                if args.kill_partition_at is not None
                else 1
            ),
        )
        print(json.dumps(result))
        return 0 if result["ok"] else 1

    if args.ingest_scaling:
        result = run_ingest_scaling()
        print(json.dumps(result))
        return 0 if result["ok"] else 1

    if args.kill_primary_at is not None:
        result = run_storage_chaos(
            total_ops=args.ops or 2 * args.kill_primary_at,
            kill_at=args.kill_primary_at,
        )
        print(json.dumps(result))
        ok = not result["failedReads"] and not result["lostAckedWrites"] \
            and result["postPromoteWriteOk"] \
            and result["replicationLagAfterPromote"] == 0
        return 0 if ok else 1

    if args.fault:
        from ..testing import faults

        specs = [s for text in args.fault for s in faults.parse(text)]
        faults.activate(*specs)
        if not args.in_process:
            # faults live in the SERVER process; hand the operator the
            # exact env line to arm a live server identically
            print(
                f"# to arm a live server: PIO_FAULTS={';'.join(args.fault)!r}",
                file=sys.stderr,
            )

    payloads = _expand_payloads(args.payload)
    server = None
    if args.in_process:
        # only this mode compiles anything; the HTTP client path must not
        # pay a jax import at startup (the queue spawns six of them)
        from ..utils.jax_cache import enable_compilation_cache

        enable_compilation_cache()
        target, server = _inprocess_target(
            args.engine_dir, batching=not args.no_batching,
            pipeline_depth=args.pipeline_depth,
            deadline_ms=args.deadline_ms,
        )
    else:
        target = _http_target(args.url, deadline_ms=args.deadline_ms)

    # warm-up: first queries pay jit compile
    for payload in payloads[:4]:
        try:
            target(payload)
        except Exception as exc:
            print(f"loadgen warm-up failed: {exc}", file=sys.stderr)
            return 1

    result = run_load(target, payloads, args.concurrency, args.duration)
    result["mode"] = "in-process" if args.in_process else "http"
    if args.deadline_ms is not None:
        result["deadline_ms"] = args.deadline_ms
    if args.fault:
        result["faults"] = args.fault
    if server is not None and server._batcher is not None:
        result["batching"] = server._batcher.stats
    if server is not None:
        result["serving_stats"] = server.stats.snapshot()
    if args.scrape_metrics:
        if server is not None:
            # in-process: the "server side" is this process's registry
            from ..obs.expo import render
            from ..obs.expo import parse_text as _parse

            result["server"] = digest_serving_metrics(
                _parse(render(server.metrics))
            )
        else:
            server_view = scrape_server_metrics(args.url)
            if server_view is None:
                print("# --scrape-metrics: GET /metrics failed",
                      file=sys.stderr)
            else:
                result["server"] = server_view
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
