"""``pio profile`` / ``pio perf`` — the performance-observability CLIs.

``pio profile`` renders the one-screen compile/phase/roofline report
(``obs/profile.render_profile_report``) from one of three sources:

- ``--train-smoke`` — run a tiny in-process ALS train (synthetic data,
  CPU-friendly scale) with the :class:`~predictionio_tpu.obs.profile.
  PhaseProfiler` and jit telemetry live, and report per-phase wall /
  device time, compile and retrace counts, and roofline estimates.
  The zero-hardware smoke proof that the whole profiling stack works;
  also the quickest way to see what a code change did to compile
  behavior.
- ``--node HOST:PORT`` — scrape a live server's ``/metrics`` and report
  its ``pio_jit_*`` families plus the deployed instance's persisted
  train phases. Works against any server, query server first among
  them.
- ``--instance ID`` (default: the latest completed instance) — read the
  ``PIO_TRAIN_PHASES`` / ``PIO_TRAIN_PROFILE`` env entries the training
  workflow persisted into the engine-instance record.

``pio perf diff`` / ``pio perf trend`` drive the durable perf ledger
(``obs/perfledger.py``): ``diff`` exits 1 when the latest comparable
record regressed beyond the noise band (the CI gate), ``trend`` renders
the whole trajectory. Both read the checked-in ``BENCH_r0*.json``
history plus an optional ledger file.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional, Sequence

from ..obs import perfledger
from ..obs.profile import (
    PhaseProfiler,
    default_telemetry,
    render_profile_report,
)

#: default location of the checked-in BENCH history and the repo ledger:
#: the repository root (the parent of the installed package)
REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

EXIT_OK = 0
EXIT_REGRESSION = 1
EXIT_ERROR = 2


# -- pio profile ------------------------------------------------------------


def run_smoke_train(
    iterations: int = 2,
    rank: int = 8,
    n_users: int = 384,
    n_items: int = 128,
    nnz: int = 4000,
) -> dict:
    """A tiny in-process ALS train with profiling on: returns the report
    inputs (``phases``/``jit``/``cache``/``device``). Small enough for a
    laptop CPU in seconds; the shapes still walk the real bucketize →
    stage → solve path, so the compile counters count real programs."""
    import numpy as np

    from ..ops import als

    telemetry = default_telemetry()
    telemetry.attach_monitoring()
    jit_before = telemetry.snapshot()
    prof = PhaseProfiler(enabled=True)

    rng = np.random.default_rng(7)
    users = rng.integers(0, n_users, size=nnz).astype(np.int64)
    items = rng.integers(0, n_items, size=nnz).astype(np.int64)
    ratings = rng.normal(3.5, 1.0, size=nnz).astype(np.float32)

    with prof.phase("bucketize"):
        by_user = als.bucketize(
            users, items, ratings, n_users, n_items, pad_to_blocks=True
        )
        by_item = als.bucketize(
            items, users, ratings, n_items, n_users, pad_to_blocks=True
        )
    cfg = als.ALSConfig(
        rank=rank, iterations=iterations, lambda_=0.05, seed=0,
        solve_mode="chunked",
    )
    profile: dict = {}
    with prof.phase("train") as ph:
        factors = als.als_train(by_user, by_item, cfg, profile=profile)
        ph.fence((factors.user_factors, factors.item_factors))
    # adopt the fenced per-iteration timings als_train measured, with
    # its FLOP/byte estimates, so the roofline columns carry real data
    flops = profile.get("flops_per_iteration", 0.0)
    hbm = profile.get("hbm_bytes_per_iteration", 0.0)
    for seconds in profile.get("iteration_s", []):
        prof.record(
            "train.iteration", wall_s=seconds, flops=flops, hbm_bytes=hbm
        )
    if "stage_s" in profile:
        prof.record("stage", wall_s=profile["stage_s"])

    import jax

    delta = telemetry.delta_since(jit_before)
    return {
        "phases": prof.summary(),
        "jit": delta["fns"],
        "cache": delta["cache"],
        "device": str(jax.devices()[0]),
    }


def _report_from_metrics(parsed: dict) -> dict:
    """Scraped ``/metrics`` samples → report inputs. Tolerant of absent
    families (a node that never compiled simply has no jit section)."""
    jit: dict = {}
    for labels, value in parsed.get("pio_jit_compiles_total", []):
        fn = labels.get("fn")
        if fn:
            jit.setdefault(fn, {})["compiles"] = value
    for labels, value in parsed.get("pio_jit_retraces_total", []):
        fn = labels.get("fn")
        if fn:
            jit.setdefault(fn, {})["retraces"] = value
    for labels, value in parsed.get("pio_jit_compile_seconds_sum", []):
        fn = labels.get("fn")
        if fn:
            jit.setdefault(fn, {})["compile_s"] = value

    def _scalar(name: str) -> float:
        samples = parsed.get(name)
        return samples[0][1] if samples else 0.0

    cache = {
        "hits": _scalar("pio_jit_cache_hits"),
        "misses": _scalar("pio_jit_cache_misses"),
        "backend_compiles": _scalar(
            "pio_jit_backend_compile_seconds_count"
        ),
        "backend_compile_s": _scalar("pio_jit_backend_compile_seconds_sum"),
    }
    phases = {}
    for labels, value in parsed.get("pio_train_phase_seconds", []):
        phase = labels.get("phase")
        if phase:
            phases[phase] = {"count": 1, "wall_s": value, "device_s": value}
    return {"phases": phases, "jit": jit, "cache": cache}


def _report_from_instance(instance) -> dict:
    from ..utils.profiling import phases_from_env, profile_from_env

    phases = {
        name: {"count": 1, "wall_s": seconds, "device_s": seconds}
        for name, seconds in phases_from_env(instance.env).items()
    }
    profile = profile_from_env(instance.env)
    return {
        "phases": phases,
        "jit": profile.get("fns", {}),
        "cache": profile.get("cache") or None,
        "train_wall_s": profile.get("train_wall_s"),
    }


def run_profile(args: argparse.Namespace, registry=None) -> int:
    if args.train_smoke:
        data = run_smoke_train(
            iterations=args.iterations, rank=args.rank
        )
        title = "smoke train"
    elif args.node:
        from ..obs.top import fetch_metrics

        parsed = fetch_metrics(args.node, timeout=args.timeout)
        if parsed is None:
            print(f"error: no /metrics at {args.node}", file=sys.stderr)
            return EXIT_ERROR
        data = _report_from_metrics(parsed)
        title = f"node {args.node}"
    else:
        if registry is None:
            from ..storage import get_registry

            registry = get_registry()
        md = registry.get_metadata()
        from ..storage import STATUS_COMPLETED

        if args.instance:
            instance = md.engine_instance_get(args.instance)
        else:
            instances = [
                inst
                for inst in md.engine_instance_get_all()
                if inst.status == STATUS_COMPLETED
            ]
            instances.sort(key=lambda inst: inst.start_time)
            instance = instances[-1] if instances else None
        if instance is None:
            print(
                "error: no completed engine instance to profile "
                "(train first, or use --train-smoke / --node)",
                file=sys.stderr,
            )
            return EXIT_ERROR
        data = _report_from_instance(instance)
        title = f"engine instance {instance.id}"
        wall = data.get("train_wall_s")
        if isinstance(wall, (int, float)):
            title += f" (train wall {wall:.3f}s)"
    if args.json:
        print(json.dumps(data, sort_keys=True))
        return EXIT_OK
    print(
        render_profile_report(
            title,
            phases=data.get("phases"),
            jit=data.get("jit"),
            cache=data.get("cache"),
            device=data.get("device"),
        )
    )
    return EXIT_OK


# -- pio perf ---------------------------------------------------------------


def _load_records(args: argparse.Namespace) -> list:
    """History + ledger, chronological: the checked-in BENCH rounds are
    the oldest evidence, ledger appends follow in file order."""
    records = perfledger.load_bench_history(args.history_dir)
    ledger_path = args.ledger
    if ledger_path is None:
        default = os.path.join(args.history_dir, "PERF_LEDGER.jsonl")
        ledger_path = default if os.path.exists(default) else None
    if ledger_path:
        records.extend(perfledger.load_ledger(ledger_path))
    return records


def run_perf(args: argparse.Namespace) -> int:
    records = _load_records(args)
    if args.perf_command == "trend":
        if args.json:
            print(json.dumps(records))
        else:
            print(perfledger.render_trend(records))
        return EXIT_OK
    # diff: the regression gate
    if not records:
        print(
            "error: no performance records found (no BENCH_r*.json under "
            f"{args.history_dir} and no ledger)",
            file=sys.stderr,
        )
        return EXIT_ERROR
    flagged = perfledger.detect_regressions(
        records, noise_band=args.noise_band
    )
    no_prior = perfledger.find_no_prior(records)
    if args.json:
        print(
            json.dumps(
                {
                    "regressions": flagged,
                    "noPrior": no_prior,
                    "records": len(records),
                }
            )
        )
        return EXIT_REGRESSION if flagged else EXIT_OK
    if flagged:
        for item in flagged:
            key = item["key"]
            print(
                f"REGRESSION {key['metric']} [{key['device_class']} "
                f"scale={key['scale']}]: latest {item['latest']:.3f}s "
                f"({item['latest_source']}) vs median "
                f"{item['baseline_median']:.3f}s over {item['history']} "
                f"runs — {item['ratio']:.2f}x, band "
                f"{1.0 + item['noise_band']:.2f}x"
            )
    # "no baseline yet" is a different statement from "stable": a lever
    # default flip starts a fresh comparable group (flags are part of
    # the key), and reporting nothing would read as "no regression"
    for item in no_prior:
        key = item["key"]
        levers = (
            f"solve={key['solve_mode']} gather={key['gather_dtype']}"
            + (" sort" if key["sort_gather"] else "")
            + (" fused" if key["fused_gather"] else "")
        )
        print(
            f"NO COMPARABLE PRIOR {key['metric']} [{key['device_class']} "
            f"scale={key['scale']} {levers}]: latest {item['latest']:.3f}s "
            f"({item['latest_source']}) has {item['history']} prior "
            f"run(s), needs {item['needed']} — not gated, not 'stable'"
        )
    if not flagged:
        print(
            f"no regressions across {len(records)} records "
            f"(noise band {args.noise_band:.0%}"
            + (
                f"; {len(no_prior)} group(s) await comparable history"
                if no_prior
                else ""
            )
            + ")"
        )
    return EXIT_REGRESSION if flagged else EXIT_OK


# -- CLI glue ---------------------------------------------------------------


def build_profile_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="pio profile",
        description="compile/retrace + phase/roofline report "
        "(docs/observability.md#profiling)",
    )
    p.add_argument(
        "--train-smoke", action="store_true",
        help="run a tiny in-process ALS train with profiling on",
    )
    p.add_argument(
        "--node", default=None, metavar="HOST:PORT",
        help="scrape a live server's /metrics instead",
    )
    p.add_argument(
        "--instance", default=None,
        help="report a completed engine instance (default: latest)",
    )
    p.add_argument("--iterations", type=int, default=2,
                   help="smoke-train iterations")
    p.add_argument("--rank", type=int, default=8, help="smoke-train rank")
    p.add_argument("--timeout", type=float, default=5.0)
    p.add_argument("--json", action="store_true")
    return p


def build_perf_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="pio perf",
        description="durable perf ledger: regression gate + trajectory "
        "(docs/performance.md#perf-ledger)",
    )
    sub = p.add_subparsers(dest="perf_command", required=True)
    for name in ("diff", "trend"):
        sp = sub.add_parser(name)
        sp.add_argument(
            "--ledger", default=None, metavar="FILE",
            help="perf ledger JSONL (default: PERF_LEDGER.jsonl next to "
            "the BENCH history, if present)",
        )
        sp.add_argument(
            "--history-dir", default=REPO_ROOT, metavar="DIR",
            help="directory holding the checked-in BENCH_r0*.json rounds",
        )
        sp.add_argument("--json", action="store_true")
        if name == "diff":
            sp.add_argument(
                "--noise-band", type=float,
                default=perfledger.DEFAULT_NOISE_BAND,
                help="flag only regressions beyond this fraction "
                "(default %(default)s)",
            )
    return p


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] in ("diff", "trend"):
        return run_perf(build_perf_parser().parse_args(argv))
    return run_profile(build_profile_parser().parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
