"""Event export: app's events → JSON-lines or Parquet.

Rebuild of ``tools/.../export/EventsToFile.scala``: ``--format json``
streams one JSON document per line — the ONLY cross-implementation interop
format; these files round-trip with the reference. ``--format parquet``
writes a columnar archive in *this implementation's own schema* (scalar
event fields as string columns, ``properties``/``tags`` as JSON-encoded
strings); it round-trips exactly within this framework but is NOT readable
by the reference's parquet import, which expects SQLContext-inferred
nested schemas. The fixed schema is deliberate: inference over free-form
property bags would null-fill missing keys, which corrupts ``$unset``
semantics on re-import. Use ``json`` for interchange, ``parquet`` for
compact self-archives.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Iterator, Optional, Sequence, TextIO

from ..storage import EventFilter, StorageRegistry, get_registry
from ..storage.event import Event, format_event_time

#: rows per parquet row group / streaming chunk
_CHUNK = 10_000

_PARQUET_COLUMNS = (
    "eventId", "event", "entityType", "entityId", "targetEntityType",
    "targetEntityId", "properties", "eventTime", "tags", "prId",
    "creationTime",
)


def _event_row(e: Event) -> dict:
    return {
        "eventId": e.event_id,
        "event": e.event,
        "entityType": e.entity_type,
        "entityId": e.entity_id,
        "targetEntityType": e.target_entity_type,
        "targetEntityId": e.target_entity_id,
        "properties": json.dumps(e.properties.to_dict(), separators=(",", ":")),
        "eventTime": format_event_time(e.event_time),
        "tags": json.dumps(list(e.tags)),
        "prId": e.pr_id,
        "creationTime": format_event_time(e.creation_time),
    }


def export_events(
    registry: StorageRegistry,
    app_id: int,
    out: TextIO,
    event_filter: Optional[EventFilter] = None,
) -> int:
    """Stream every matching event as one JSON object per line; returns the
    number of events written."""
    store = registry.get_events()
    count = 0
    for event in store.find(app_id, event_filter or EventFilter()):
        out.write(json.dumps(event.to_json_dict(), separators=(",", ":")))
        out.write("\n")
        count += 1
    return count


def export_events_parquet(
    registry: StorageRegistry,
    app_id: int,
    path: str,
    event_filter: Optional[EventFilter] = None,
) -> int:
    """Columnar export, streamed in row groups (bounded memory)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    schema = pa.schema([(c, pa.string()) for c in _PARQUET_COLUMNS])
    store = registry.get_events()

    def chunks() -> Iterator[list]:
        buf: list = []
        for event in store.find(app_id, event_filter or EventFilter()):
            buf.append(_event_row(event))
            if len(buf) >= _CHUNK:
                yield buf
                buf = []
        if buf:
            yield buf

    count = 0
    writer = pq.ParquetWriter(path, schema)
    try:
        wrote = False
        for buf in chunks():
            writer.write_table(pa.Table.from_pylist(buf, schema=schema))
            count += len(buf)
            wrote = True
        if not wrote:  # schema-only file so imports of empty exports work
            writer.write_table(pa.Table.from_pylist([], schema=schema))
    except BaseException:
        # close() finalizes a VALID footer over whatever was written — a
        # partial archive that would later import silently. Remove it.
        writer.close()
        try:
            os.unlink(path)
        except OSError:
            pass
        raise
    writer.close()
    return count


def main(argv: Optional[Sequence[str]] = None) -> int:
    from ..utils.platform import apply_env_platform

    apply_env_platform()
    p = argparse.ArgumentParser(prog="export_events")
    p.add_argument("--appid", type=int, required=True)
    p.add_argument("--output", required=True)
    p.add_argument(
        "--format", choices=("json", "parquet"), default="json",
        help="json = interop JSON-lines (default); parquet = columnar "
        "archive (the reference's default format)",
    )
    args = p.parse_args(argv)
    registry = get_registry()
    if args.format == "parquet":
        n = export_events_parquet(registry, args.appid, args.output)
    else:
        with open(args.output, "w", encoding="utf-8") as fh:
            n = export_events(registry, args.appid, fh)
    print(
        json.dumps(
            {
                "appId": args.appid,
                "events": n,
                "output": args.output,
                "format": args.format,
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
