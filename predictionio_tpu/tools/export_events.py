"""Event export: app's events → JSON-lines file.

Rebuild of ``tools/.../export/EventsToFile.scala`` (``PEvents.find`` → one
JSON document per line via SQLContext there; a streamed JSON-lines writer
here — same on-disk format as the reference's ``--format json`` mode, so
files round-trip between the two).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional, Sequence, TextIO

from ..storage import EventFilter, StorageRegistry, get_registry


def export_events(
    registry: StorageRegistry,
    app_id: int,
    out: TextIO,
    event_filter: Optional[EventFilter] = None,
) -> int:
    """Stream every matching event as one JSON object per line; returns the
    number of events written."""
    store = registry.get_events()
    count = 0
    for event in store.find(app_id, event_filter or EventFilter()):
        out.write(json.dumps(event.to_json_dict(), separators=(",", ":")))
        out.write("\n")
        count += 1
    return count


def main(argv: Optional[Sequence[str]] = None) -> int:
    from ..utils.platform import apply_env_platform

    apply_env_platform()
    p = argparse.ArgumentParser(prog="export_events")
    p.add_argument("--appid", type=int, required=True)
    p.add_argument("--output", required=True)
    args = p.parse_args(argv)
    registry = get_registry()
    with open(args.output, "w", encoding="utf-8") as fh:
        n = export_events(registry, args.appid, fh)
    print(json.dumps({"appId": args.appid, "events": n, "output": args.output}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
