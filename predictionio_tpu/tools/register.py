"""Engine project registration.

Rebuild of ``tools/.../RegisterEngine.scala:30-120`` plus the console's
auto-generated ``manifest.json`` keyed by a SHA-1 of the project directory
(``console/Console.scala:1017-1061``).  The reference copies built jars to
``PIO_FS_ENGINESDIR/<id>/<version>``; here "build" means verifying the Python
engine factory imports, and registration records the project directory (the
code location) in the manifest so train/deploy can re-import it.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
from typing import List, Optional

from ..storage import StorageRegistry
from ..storage.metadata import EngineManifest

logger = logging.getLogger(__name__)

MANIFEST_JSON = "manifest.json"
ENGINE_JSON = "engine.json"


class EngineDirError(Exception):
    """Missing/invalid engine.json or manifest (``Console.scala:1063-1077``)."""


@dataclasses.dataclass
class EngineDir:
    """A resolved engine project directory."""

    path: str
    manifest: EngineManifest
    variant: dict
    variant_path: str

    @property
    def engine_factory(self) -> str:
        factory = self.variant.get("engineFactory", "")
        if not factory:
            raise EngineDirError(
                f"{self.variant_path}: missing required key 'engineFactory'"
            )
        return factory


def _cwd_sha1(path: str) -> str:
    """``Console.scala:1027``: manifest id is a SHA-1 of the project path."""
    return hashlib.sha1(os.path.abspath(path).encode("utf-8")).hexdigest()


def _source_version(path: str) -> str:
    """Version = digest of the engine's Python sources + engine.json, so a
    re-``build`` after an edit produces a new version (the analogue of the
    reference's rebuilt-jar fingerprint)."""
    h = hashlib.sha1()
    names: List[str] = []
    for root, dirs, files in os.walk(path):
        dirs[:] = [d for d in dirs if not d.startswith((".", "__pycache__"))]
        for f in sorted(files):
            if f.endswith(".py") or f == ENGINE_JSON:
                names.append(os.path.join(root, f))
    for name in sorted(names):
        h.update(name.encode("utf-8"))
        with open(name, "rb") as fh:
            h.update(fh.read())
    return h.hexdigest()[:12] or "0"


def load_engine_dir(path: str) -> EngineDir:
    """Resolve a project's manifest + variant, without touching disk state
    (train/deploy call this on every run; only ``pio build`` writes)."""
    path = os.path.abspath(path)
    variant_path = os.path.join(path, ENGINE_JSON)
    if not os.path.exists(variant_path):
        raise EngineDirError(f"{variant_path} not found; not an engine project?")
    with open(variant_path, "r", encoding="utf-8") as fh:
        variant = json.load(fh)
    manifest = EngineManifest(
        id=_cwd_sha1(path),
        version=_source_version(path),
        name=os.path.basename(path),
        description=variant.get("description", ""),
        files=[path],
        engine_factory=variant.get("engineFactory", ""),
    )
    return EngineDir(
        path=path, manifest=manifest, variant=variant, variant_path=variant_path
    )


def _write_manifest(ed: EngineDir) -> EngineManifest:
    m = ed.manifest
    with open(os.path.join(ed.path, MANIFEST_JSON), "w", encoding="utf-8") as fh:
        json.dump(
            {
                "id": m.id,
                "version": m.version,
                "name": m.name,
                "description": m.description,
                "files": list(m.files),
                "engineFactory": m.engine_factory,
            },
            fh,
            indent=2,
        )
    return m


def generate_manifest(path: str) -> EngineManifest:
    """Regenerate ``manifest.json`` on disk (``Console.scala:1019-1061``)."""
    return _write_manifest(load_engine_dir(path))


def register_engine(
    registry: StorageRegistry, path: str, verify_import: bool = True
) -> EngineDir:
    """``pio build``: verify the factory imports, upsert the manifest
    (``RegisterEngine.registerEngine``, ``RegisterEngine.scala:46-120``)."""
    ed = load_engine_dir(path)
    _write_manifest(ed)
    if verify_import:
        from ..workflow.loader import get_engine

        get_engine(ed.engine_factory, search_dir=ed.path)
        logger.info("Engine factory %s imports cleanly", ed.engine_factory)
    registry.get_metadata().manifest_update(ed.manifest, upsert=True)
    logger.info(
        "Registered engine %s %s (%s)", ed.manifest.id, ed.manifest.version, ed.path
    )
    return ed


def registered_manifest(
    registry: StorageRegistry, path: str
) -> Optional[EngineManifest]:
    """``Console.withRegisteredManifest`` lookup (``Console.scala:1079-1100``)."""
    ed = load_engine_dir(path)
    return registry.get_metadata().manifest_get(ed.manifest.id, ed.manifest.version)
