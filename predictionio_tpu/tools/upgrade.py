"""``pio upgrade``: migrate event data between storage backends.

The reference ships upgrade tools that rewrite HBase event tables between
row-key schemes (``data/src/main/scala/io/prediction/data/storage/hbase/
upgrade/{HB_0_8_0,Upgrade,Upgrade_0_8_3}.scala``, driven by ``pio upgrade``,
``Console.scala``). The TPU-native equivalent migrates an app's events
between *backends* (e.g. the SQLite default → the native C++ log), streaming
``find()`` → ``write()`` per app and verifying counts.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Sequence

from ..storage.events import EventStore
from ..storage.registry import StorageRegistry, make_event_store

logger = logging.getLogger(__name__)

_BATCH = 1000
_VERIFY_SAMPLE = 10_000


def _make_store(stype: str, path: str) -> EventStore:
    if stype == "memory":
        # an in-memory store closed at the end of the migration would
        # silently discard everything while reporting success
        raise ValueError("'memory' is not a valid migration endpoint")
    return make_event_store(stype, path)


def migrate_events(
    source: EventStore,
    target: EventStore,
    app_ids: Sequence[int],
) -> Dict[int, int]:
    """Copy every event of each app from ``source`` to ``target`` (event ids
    preserved, so re-running is idempotent via upsert semantics). Returns
    migrated counts per app.

    Verification is id-based (robust against pre-existing target events): a
    bounded sample of migrated event ids must all be present in the target
    after the copy; any missing id raises."""
    migrated: Dict[int, int] = {}
    for app_id in app_ids:
        target.init(app_id)
        batch: List = []
        n = 0
        sample: set = set()
        for event in source.find(app_id):
            batch.append(event)
            if event.event_id and len(sample) < _VERIFY_SAMPLE:
                sample.add(event.event_id)
            if len(batch) >= _BATCH:
                target.write(batch, app_id)
                n += len(batch)
                batch = []
        if batch:
            target.write(batch, app_id)
            n += len(batch)
        if sample:
            found = {
                e.event_id for e in target.find(app_id)
                if e.event_id in sample
            }
            missing = sample - found
            if missing:
                raise RuntimeError(
                    f"app {app_id}: {len(missing)} of {len(sample)} sampled "
                    f"event ids missing from target after migration "
                    f"(e.g. {next(iter(missing))!r})"
                )
        migrated[app_id] = n
        logger.info("app %s: migrated %d events", app_id, n)
    return migrated


def run_upgrade(
    registry: StorageRegistry,
    from_type: str,
    from_path: str,
    to_type: str,
    to_path: str,
    app_ids: Optional[Sequence[int]] = None,
) -> dict:
    """CLI entry: resolve app list from metadata when not given, migrate,
    report counts."""
    if app_ids is None:
        app_ids = [a.id for a in registry.get_metadata().app_get_all()]
    source = _make_store(from_type, from_path)
    target = _make_store(to_type, to_path)
    try:
        counts = migrate_events(source, target, app_ids)
    finally:
        source.close()
        target.close()
    return {
        "from": {"type": from_type, "path": from_path},
        "to": {"type": to_type, "path": to_path},
        "apps": {str(k): v for k, v in counts.items()},
        "total": sum(counts.values()),
    }
