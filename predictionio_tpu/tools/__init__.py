"""Operator tools: the ``pio``-equivalent CLI, runners, export/import, dashboard.

Rebuild of ``tools/src/main/scala/io/prediction/tools/`` — the console
(``console/Console.scala``), the spark-submit assemblers
(``RunWorkflow.scala`` / ``RunServer.scala``, here plain Python subprocesses),
engine registration (``RegisterEngine.scala``), event export/import
(``export/EventsToFile.scala`` / ``imprt/FileToEvents.scala``) and the
evaluation dashboard (``dashboard/Dashboard.scala``).
"""

from .register import EngineDir, generate_manifest, register_engine

__all__ = ["EngineDir", "generate_manifest", "register_engine"]
