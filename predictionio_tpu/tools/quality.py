"""``pio quality`` — model & data quality report + drift diff.

Three sources, one report (``docs/observability.md#quality``):

- ``--node HOST:PORT`` — scrape a live server's ``/metrics`` and digest
  its ``pio_quality_*`` families: per-variant score PSI and quantiles,
  feedback hit-rate / served rank, and (on an Event Server) per-app
  ingest violations and event-mix PSI.
- default — the latest quality snapshot from the JSONL file the serving
  plane appends (``PIO_QUALITY_SNAPSHOTS``, next to the perf ledger).
- ``--diff`` — compare the latest snapshot against its predecessor (or
  against ``--baseline FILE``'s latest) via PSI between their serving
  sketches. Exit codes are pinned like ``pio perf diff``: **0** stable,
  **1** drift beyond ``--max-psi``, **2** engine error (missing or
  unreadable snapshots) — the CI drift gate.

Like ``pio top``/``pio perf`` this is a read-only, storage-free,
jax-free CLI; the console forwards to it verbatim.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional, Sequence

from ..obs.quality import (
    QualityConfig,
    SNAPSHOTS_ENV,
    load_snapshots,
    snapshot_psi,
)
from ..obs.sketch import QuantileSketch

EXIT_OK = 0
EXIT_DRIFT = 1
EXIT_ERROR = 2

#: default drift bar for ``--diff`` — the conventional "real
#: distribution change" PSI threshold (docs/observability.md#quality)
DEFAULT_MAX_PSI = 0.25

_VARIANTS = ("baseline", "candidate")


# -- live-node report ---------------------------------------------------------


def node_report(node: str, timeout: float = 5.0) -> Optional[dict]:
    """Scrape one node's ``/metrics`` → quality digest (None when the
    node is down). Shared by the CLI, the dashboard's ``/quality``
    panel, and the tier-1 drill."""
    from ..obs.top import fetch_metrics

    metrics = fetch_metrics(node, timeout=timeout)
    if metrics is None:
        return None
    out: dict = {"node": node, "up": True}

    def by_variant(name: str) -> Dict[str, float]:
        return {
            labels.get("variant", "-"): value
            for labels, value in metrics.get(name, [])
        }

    # -1 is the gauge's abstention sentinel (no pin / too few samples);
    # an abstaining variant is omitted, exactly like persisted snapshots
    psi = {
        variant: value
        for variant, value in by_variant("pio_quality_score_psi").items()
        if value >= 0
    }
    if psi:
        out["scorePsi"] = psi
    samples = by_variant("pio_quality_score_samples")
    if samples:
        out["scoreSamples"] = samples
    quantiles: Dict[str, Dict[str, float]] = {}
    for labels, value in metrics.get("pio_quality_score_quantile", []):
        variant = labels.get("variant", "-")
        quantiles.setdefault(variant, {})[labels.get("q", "?")] = value
    if quantiles:
        out["scoreQuantiles"] = quantiles

    feedback: dict = {}
    for labels, value in metrics.get(
        "pio_quality_feedback_events_total", []
    ):
        feedback[labels.get("outcome", "?")] = int(value)
    hit_rate = metrics.get("pio_quality_feedback_hit_rate")
    # a rate over zero joined events is undefined, not 0.0 — and only
    # hit/miss outcomes join; an unjoined backlog must not read as 0.0
    if hit_rate and (feedback.get("hit") or feedback.get("miss")):
        feedback["hitRate"] = hit_rate[0][1]
    mean_rank = metrics.get("pio_quality_feedback_mean_rank")
    if mean_rank and mean_rank[0][1]:
        feedback["meanServedRank"] = mean_rank[0][1]
    if feedback:
        out["feedback"] = feedback

    ingest: Dict[str, dict] = {}
    for labels, value in metrics.get(
        "pio_quality_ingest_events_total", []
    ):
        app = labels.get("app", "?")
        ingest.setdefault(app, {})["events"] = int(value)
    for labels, value in metrics.get(
        "pio_quality_ingest_violations_total", []
    ):
        app = labels.get("app", "?")
        ingest.setdefault(app, {}).setdefault("violations", {})[
            labels.get("kind", "?")
        ] = int(value)
    for labels, value in metrics.get("pio_quality_event_mix_psi", []):
        if value < 0:  # -1 sentinel: abstaining, not measured-stable
            continue
        app = labels.get("app", "?")
        ingest.setdefault(app, {})["mixPsi"] = value
    if ingest:
        out["ingest"] = ingest
    return out


# -- snapshot report ----------------------------------------------------------


def snapshot_report(snap: dict) -> dict:
    """One persisted snapshot → the same digest shape a node scrape
    yields (quantiles recomputed from the stored sketches)."""
    out: dict = {"source": snap.get("source", "?")}
    psi = snap.get("psi") or {}
    if psi:
        out["scorePsi"] = dict(psi)
    quantiles: Dict[str, Dict[str, float]] = {}
    samples: Dict[str, int] = {}
    for variant, doc in (snap.get("serving") or {}).items():
        try:
            sketch = QuantileSketch.from_dict(doc)
        except (TypeError, ValueError):
            continue
        samples[variant] = sketch.count
        quantiles[variant] = {
            f"{q:g}": round(sketch.quantile(q), 6)
            for q in (0.5, 0.9, 0.99)
        }
    if samples:
        out["scoreSamples"] = samples
    if quantiles:
        out["scoreQuantiles"] = quantiles
    feedback = snap.get("feedback") or {}
    if feedback:
        fb = dict(feedback)
        total = fb.get("total") or 0
        if total:
            fb["hitRate"] = round((fb.get("hits") or 0) / total, 4)
        out["feedback"] = fb
    return out


# -- rendering ---------------------------------------------------------------


def render_report(report: dict) -> str:
    lines: List[str] = []
    title = report.get("node") or report.get("source") or "quality"
    lines.append(f"quality [{title}]")
    psi = report.get("scorePsi") or {}
    samples = report.get("scoreSamples") or {}
    quantiles = report.get("scoreQuantiles") or {}
    for variant in _VARIANTS:
        if (
            variant not in psi
            and variant not in samples
            and variant not in quantiles
        ):
            continue
        qs = quantiles.get(variant, {})
        q_text = " ".join(
            f"p{float(q) * 100:g}={value:.4g}"
            for q, value in sorted(qs.items(), key=lambda kv: float(kv[0]))
        )
        value = psi.get(variant)
        psi_text = "-       " if value is None else f"{value:<8.4f}"
        lines.append(
            f"  {variant:<10} psi={psi_text} "
            f"samples={int(samples.get(variant, 0)):<7d} {q_text}".rstrip()
        )
    feedback = report.get("feedback")
    if feedback:
        hit_rate = feedback.get("hitRate")
        rank = feedback.get("meanServedRank")
        hits = feedback.get("hit", feedback.get("hits", 0))
        if "total" in feedback:  # snapshot shape: hits/total
            counts = f"hits={hits}/{feedback['total']} "
        else:  # node-scrape shape: hit/miss outcome counters
            counts = f"hits={hits} misses={feedback.get('miss', 0)} "
        lines.append(
            "  feedback   "
            + counts
            + (f"hitRate={hit_rate:.3f} " if hit_rate is not None else "")
            + (f"meanRank={rank:.2f}" if rank else "")
        )
    for app, stats in sorted((report.get("ingest") or {}).items()):
        violations = stats.get("violations") or {}
        v_text = " ".join(
            f"{kind}={n}" for kind, n in sorted(violations.items())
        )
        mix = stats.get("mixPsi")
        lines.append(
            f"  ingest app={app} events={stats.get('events', 0)} "
            + (f"mixPsi={mix:.4f} " if mix is not None else "")
            + v_text
        )
    if len(lines) == 1:
        lines.append("  (no quality signals yet)")
    return "\n".join(lines)


# -- diff (the CI drift gate) -------------------------------------------------


def run_diff(
    snapshots: Optional[str],
    baseline: Optional[str],
    max_psi: float,
    as_json: bool = False,
    min_samples: Optional[int] = None,
) -> int:
    """Latest snapshot vs its reference → 0 stable / 1 drift / 2 error."""
    if not snapshots:
        print(
            "error: --diff needs --snapshots FILE (or PIO_QUALITY_SNAPSHOTS)",
            file=sys.stderr,
        )
        return EXIT_ERROR
    current_all = load_snapshots(snapshots)
    if not current_all:
        print(
            f"error: no quality snapshots in {snapshots}", file=sys.stderr
        )
        return EXIT_ERROR
    current = current_all[-1]
    if baseline:
        reference_all = load_snapshots(baseline)
        if not reference_all:
            print(
                f"error: no quality snapshots in {baseline}",
                file=sys.stderr,
            )
            return EXIT_ERROR
        reference = reference_all[-1]
    else:
        if len(current_all) < 2:
            print(
                "error: --diff needs two snapshots (or --baseline FILE)",
                file=sys.stderr,
            )
            return EXIT_ERROR
        reference = current_all[-2]
    if min_samples is None:
        # the deployment's configured floor rides each snapshot; the
        # newest one speaks for the fleet's current config (older
        # snapshots may predate the field — fall back to the default)
        min_samples = (
            current.get("minPsiSamples")
            or reference.get("minPsiSamples")
            or QualityConfig.min_psi_samples
        )
    verdicts: dict = {}
    drifted = False
    for variant in _VARIANTS:
        value = snapshot_psi(
            reference, current, variant=variant, min_samples=min_samples
        )
        if value is None:
            continue
        verdicts[variant] = round(value, 6)
        if value > max_psi:
            drifted = True
    if not verdicts:
        print(
            "error: the snapshots share no comparable serving sketch",
            file=sys.stderr,
        )
        return EXIT_ERROR
    if as_json:
        print(
            json.dumps(
                {
                    "psi": verdicts,
                    "maxPsi": max_psi,
                    "drift": drifted,
                    "reference": reference.get("source"),
                    "current": current.get("source"),
                }
            )
        )
    else:
        for variant, value in sorted(verdicts.items()):
            marker = "DRIFT" if value > max_psi else "ok"
            print(
                f"{marker} {variant}: psi={value:.4f} "
                f"(bar {max_psi:.4f}) "
                f"{reference.get('source', '?')} -> "
                f"{current.get('source', '?')}"
            )
    return EXIT_DRIFT if drifted else EXIT_OK


# -- CLI glue ----------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="pio quality",
        description="model & data quality report + drift diff "
        "(docs/observability.md#quality)",
    )
    p.add_argument(
        "--node", default=None, metavar="HOST:PORT",
        help="scrape a live server's /metrics instead of snapshots",
    )
    p.add_argument(
        "--snapshots", default=None, metavar="FILE",
        help="quality-snapshot JSONL (default: $PIO_QUALITY_SNAPSHOTS)",
    )
    p.add_argument(
        "--diff", action="store_true",
        help="compare the two latest snapshots (or --baseline's latest "
        "vs --snapshots' latest); exit 1 on drift beyond --max-psi, "
        "2 on missing/unreadable snapshots",
    )
    p.add_argument(
        "--baseline", default=None, metavar="FILE",
        help="with --diff: take the reference snapshot from this file",
    )
    p.add_argument(
        "--max-psi", type=float, default=DEFAULT_MAX_PSI,
        help="drift bar for --diff (default %(default)s)",
    )
    p.add_argument(
        "--min-samples", type=int, default=None, metavar="N",
        help="with --diff: abstention floor per sketch side (default: "
        "the floor recorded in the newest snapshot, else "
        f"{QualityConfig.min_psi_samples})",
    )
    p.add_argument("--timeout", type=float, default=5.0)
    p.add_argument("--json", action="store_true")
    return p


def main(argv: Optional[Sequence[str]] = None) -> int:
    import os

    args = build_parser().parse_args(argv)
    snapshots = args.snapshots or os.environ.get(SNAPSHOTS_ENV)
    if args.diff:
        return run_diff(
            snapshots, args.baseline, args.max_psi, as_json=args.json,
            min_samples=args.min_samples,
        )
    if args.node:
        report = node_report(args.node, timeout=args.timeout)
        if report is None:
            print(f"error: no /metrics at {args.node}", file=sys.stderr)
            return EXIT_ERROR
    else:
        if not snapshots:
            print(
                "error: nothing to report — pass --node HOST:PORT or "
                "--snapshots FILE (or set PIO_QUALITY_SNAPSHOTS)",
                file=sys.stderr,
            )
            return EXIT_ERROR
        snaps = load_snapshots(snapshots)
        if not snaps:
            print(
                f"error: no quality snapshots in {snapshots}",
                file=sys.stderr,
            )
            return EXIT_ERROR
        report = snapshot_report(snaps[-1])
    if args.json:
        print(json.dumps(report, sort_keys=True))
    else:
        print(render_report(report))
    return EXIT_OK


if __name__ == "__main__":
    sys.exit(main())
