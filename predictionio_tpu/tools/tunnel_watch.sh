#!/bin/bash
# Persistent accelerator-tunnel watcher (VERDICT r3 item 1; r4 duty-cycle
# + single-instance + tiering fixes).
#
# The tunnel wedges for hours; rounds 2-4 lost their whole hardware
# windows. This loop probes on a fixed ~5-minute cadence — a failed
# probe costs only its timeout, while round 4's 20-minute cadence could
# miss a short window outright — holds a flock so a second watcher
# instance exits immediately (round 4's log shows a double start racing
# the queue), and on recovery runs the queue in two tiers: tier A first
# (≤5 min of device time: headline f32 baseline + the never-compiled
# kernel verdicts), then tier B (repeats, A/Bs, serving sweeps). Even a
# window that closes after a few minutes yields the highest-information
# records, and a re-wedge mid-tier-B never costs tier A's evidence.
#
# A clean tier-B run (rc=0) ends the watcher; anything else keeps
# watching and retries on later windows (tier B reuses tier-A records
# younger than 6 h instead of re-running them). The queue module is
# re-exec'd fresh each probe, so edits made while the watcher sleeps are
# picked up automatically. The watcher SCRIPT itself must not be edited
# while running (bash reads scripts incrementally) — restart instead,
# via ensure_watcher.sh, which is idempotent thanks to the flock.
#
# Usage: setsid nohup bash predictionio_tpu/tools/tunnel_watch.sh \
#   [engine_dir] [engine_dir_big] >/dev/null 2>&1 &
set -u
cd "$(dirname "$0")/../.."
ENGINE_DIR="${1:-/tmp/qs_r3/engine}"
ENGINE_DIR_BIG="${2:-}"
LOG=TUNNEL_WATCH.log
LOCK=.tunnel_watch.lock
DONE=.tunnel_watch.done   # written on final exit; ensure_watcher checks it
CYCLE_S=300        # target probe-start to probe-start period
MIN_SLEEP_S=20
MAX_ATTEMPTS=6     # cap on tunnel-up attempts that didn't finish tier B
attempts=0

# single instance: hold the lock for the watcher's whole lifetime
# (append-mode open — truncate only after the lock is ours)
exec 9>>"$LOCK"
if ! flock -n 9; then
  echo "$(date -u +%FT%TZ) watcher already running ($LOCK held) — exiting" \
    >> "$LOG"
  exit 0
fi
truncate -s 0 "$LOCK"
echo "$$" >&9
# starting a watcher re-arms it: a stale done-sentinel from a previous
# round must not make a cron'd ensure_watcher refuse restarts forever
rm -f "$DONE"

refresh_report() {
  # temp file + move on success only: a report crash must not truncate
  # a prior hardware window's report
  if python -m predictionio_tpu.tools.reval_report \
      > TPU_REVAL_REPORT.md.tmp 2>>"$LOG" 9>&-; then
    mv TPU_REVAL_REPORT.md.tmp TPU_REVAL_REPORT.md
  else
    echo "$(date -u +%FT%TZ) reval_report failed (kept old report)" >> "$LOG"
    rm -f TPU_REVAL_REPORT.md.tmp
  fi
}

echo "$(date -u +%FT%TZ) watcher start pid=$$ cycle=${CYCLE_S}s" \
  "(engine_dir=$ENGINE_DIR big=${ENGINE_DIR_BIG:-none})" >> "$LOG"
while true; do
  cycle_t0=$SECONDS
  status=$(timeout 170 python -c \
    "import bench; print(bench.probe_device(timeout_s=150))" \
    2>>"$LOG" 9>&- | tail -1)
  echo "$(date -u +%FT%TZ) probe=$status" >> "$LOG"
  if [ "$status" = "ok" ]; then
    echo "$(date -u +%FT%TZ) TUNNEL UP — tier A (golden-window records)" \
      >> "$LOG"
    python -m predictionio_tpu.tools.tpu_revalidate --tier a \
      --engine-dir "$ENGINE_DIR" \
      ${ENGINE_DIR_BIG:+--engine-dir-big "$ENGINE_DIR_BIG"} \
      >> "$LOG" 2>&1 9>&-
    rc_a=$?
    echo "$(date -u +%FT%TZ) tier A rc=$rc_a" >> "$LOG"
    if [ "$rc_a" = 2 ]; then
      # re-wedged between OUR probe and the queue's own probe (nothing
      # written): keep watching — dying here is the rounds-2/3 failure
      sleep 60
      continue
    fi
    refresh_report   # tier A alone may be all this window gives
    if [ "$rc_a" = 0 ]; then
      echo "$(date -u +%FT%TZ) tier B (full evidence queue)" >> "$LOG"
      python -m predictionio_tpu.tools.tpu_revalidate --tier b \
        --engine-dir "$ENGINE_DIR" \
        ${ENGINE_DIR_BIG:+--engine-dir-big "$ENGINE_DIR_BIG"} \
        >> "$LOG" 2>&1 9>&-
      rc_b=$?
      refresh_report
      echo "$(date -u +%FT%TZ) tier B rc=$rc_b" >> "$LOG"
      if [ "$rc_b" = 0 ]; then
        echo "$(date -u +%FT%TZ) queue complete — watcher exiting" >> "$LOG"
        echo "complete $(date -u +%FT%TZ)" > "$DONE"
        exit 0
      fi
      # rc_b=2 (re-wedged before tier B's own probe) writes no tier-B
      # records, but tier A DID spend device time this cycle — it must
      # count toward MAX_ATTEMPTS or a flappy tunnel loops tier A forever
    fi
    attempts=$((attempts + 1))
    if [ "$attempts" -ge "$MAX_ATTEMPTS" ]; then
      echo "$(date -u +%FT%TZ) $attempts incomplete attempts —" \
        "watcher exiting (evidence appended across all of them)" >> "$LOG"
      echo "exhausted $(date -u +%FT%TZ)" > "$DONE"
      exit 1
    fi
    echo "$(date -u +%FT%TZ) attempt $attempts incomplete;" \
      "watcher continues for another window" >> "$LOG"
  fi
  # fixed cadence regardless of probe outcome: sleep whatever remains of
  # the cycle (a fast 'failed' probe leaves ~CYCLE_S, a 170 s timeout
  # leaves ~130 s)
  elapsed=$((SECONDS - cycle_t0))
  sleep_s=$((CYCLE_S - elapsed))
  [ "$sleep_s" -lt "$MIN_SLEEP_S" ] && sleep_s=$MIN_SLEEP_S
  sleep "$sleep_s"
done
