#!/bin/bash
# Persistent accelerator-tunnel watcher (VERDICT r3 item 1).
#
# The tunnel wedges for hours; rounds 2 and 3 lost their whole hardware
# windows because nothing was probing when it recovered. This loop probes
# every PROBE_INTERVAL_S (default 20 min; 5 min after a fast "failed"),
# logs EVERY attempt to TUNNEL_WATCH.log, and the moment a probe succeeds
# runs the full revalidation queue unattended. A clean queue run (rc=0)
# ends the watcher; a run aborted or broken by a re-wedge keeps it
# watching and retries the whole queue on the next window (up to
# MAX_QUEUE_RUNS attempts — evidence appends across attempts and the
# report takes the newest record per step). The queue script is
# re-exec'd fresh each time, so edits to tpu_revalidate.py made while
# this watcher sleeps are picked up automatically.
#
# Usage: nohup bash predictionio_tpu/tools/tunnel_watch.sh \
#   [engine_dir] [engine_dir_big] &
set -u
cd "$(dirname "$0")/../.."
ENGINE_DIR="${1:-/tmp/qs_r3/engine}"
ENGINE_DIR_BIG="${2:-}"
LOG=TUNNEL_WATCH.log
OK_INTERVAL=1200   # 20 min between timeout probes
FAIL_INTERVAL=300  # 5 min after a fast "failed" (worth a quicker retry)
MAX_QUEUE_RUNS=5   # cap full-queue attempts (each appends evidence)
queue_runs=0

echo "$(date -u +%FT%TZ) watcher start (engine_dir=$ENGINE_DIR)" >> "$LOG"
while true; do
  status=$(timeout 170 python -c \
    "import bench; print(bench.probe_device(timeout_s=150))" 2>>"$LOG" | tail -1)
  echo "$(date -u +%FT%TZ) probe=$status" >> "$LOG"
  case "$status" in
    ok)
      echo "$(date -u +%FT%TZ) TUNNEL UP — running revalidation queue" >> "$LOG"
      python -m predictionio_tpu.tools.tpu_revalidate \
        --engine-dir "$ENGINE_DIR" \
        ${ENGINE_DIR_BIG:+--engine-dir-big "$ENGINE_DIR_BIG"} \
        >> "$LOG" 2>&1
      rc=$?
      if [ "$rc" = 2 ]; then
        # the tunnel wedged again between OUR probe and the queue's own
        # probe (rc=2 = aborted, nothing written): keep watching — dying
        # here is exactly the rounds-2/3 lost-window failure
        echo "$(date -u +%FT%TZ) revalidate rc=2 (re-wedged before start);"\
          " watcher continues" >> "$LOG"
        sleep "$FAIL_INTERVAL"
        continue
      fi
      queue_runs=$((queue_runs + 1))
      if [ "$rc" != 0 ] && [ "$queue_runs" -lt "$MAX_QUEUE_RUNS" ]; then
        # a mid-queue wedge (rc=1: baseline failed or fell back) leaves
        # partial evidence — summarize what landed NOW (this may be the
        # last window), then keep watching and retry the whole queue
        if python -m predictionio_tpu.tools.reval_report \
            > TPU_REVAL_REPORT.md.tmp 2>>"$LOG"; then
          mv TPU_REVAL_REPORT.md.tmp TPU_REVAL_REPORT.md
        else
          rm -f TPU_REVAL_REPORT.md.tmp
        fi
        echo "$(date -u +%FT%TZ) revalidate rc=$rc (attempt $queue_runs);"\
          " watcher continues for another window" >> "$LOG"
        sleep "$OK_INTERVAL"
        continue
      fi
      # write to a temp file and move only on success: a report crash
      # must not truncate a prior hardware window's report
      if python -m predictionio_tpu.tools.reval_report \
          > TPU_REVAL_REPORT.md.tmp 2>>"$LOG"; then
        mv TPU_REVAL_REPORT.md.tmp TPU_REVAL_REPORT.md
      else
        echo "$(date -u +%FT%TZ) reval_report failed (kept old report)" \
          >> "$LOG"
        rm -f TPU_REVAL_REPORT.md.tmp
      fi
      echo "$(date -u +%FT%TZ) revalidate rc=$rc — watcher exiting" >> "$LOG"
      exit $rc
      ;;
    failed) sleep "$FAIL_INTERVAL" ;;
    *)      sleep "$OK_INTERVAL" ;;
  esac
done
