"""Ingest benchmark: event log → streaming infeed → bucketized matrices.

Measures the host half of the training pipeline that the reference gets
from HBase region scans feeding executors
(``data/src/main/scala/io/prediction/data/storage/hbase/HBPEvents.scala:58-98``):
synthesizes N rate events into a native (C++) event log, then measures

* **ingest**: bulk append throughput into the log (events/s)
* **scan→arrays**: ``stream_ratings`` — chunked columnar scan + incremental
  id indexing → int32/float32 arrays (events/s)
* **bucketize**: COO → degree-bucketed padded CSR, both sides (events/s)
* **peak RSS** across the scan+bucketize phase, the bounded-memory claim

Run:  ``python -m predictionio_tpu.tools.ingestbench --events 20000000``
Prints one JSON line (diagnostics on stderr).
"""

from __future__ import annotations

import argparse
import json
import os
import resource
import sys
import tempfile
import time

import numpy as np


def _rss_mb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0


def run(n_events: int, chunk_rows: int, tmp_root: str) -> dict:
    import datetime as _dt

    from ..storage.event import UTC, Event

    def from_millis(ms: int) -> _dt.datetime:
        return _dt.datetime.fromtimestamp(ms / 1000.0, tz=UTC)
    from ..storage.native_events import NativeEventStore
    from ..workflow.infeed import stream_ratings
    from ..ops.als import bucketize

    n_users = max(64, n_events // 145)  # ML-20M-ish density
    n_items = max(32, n_events // 740)
    rng = np.random.default_rng(0)

    store = NativeEventStore(os.path.join(tmp_root, "events_native"))
    store.init(1)

    # -- ingest -----------------------------------------------------------
    t0 = time.monotonic()
    written = 0
    batch_n = 200_000
    base_ms = 1_750_000_000_000
    while written < n_events:
        b = min(batch_n, n_events - written)
        users = rng.integers(0, n_users, b)
        items = rng.integers(0, n_items, b)
        vals = rng.integers(1, 6, b)
        events = [
            Event(
                event="rate",
                entity_type="user",
                entity_id=f"u{users[j]}",
                target_entity_type="item",
                target_entity_id=f"i{items[j]}",
                properties={"rating": float(vals[j])},
                event_time=from_millis(base_ms + written + j),
            )
            for j in range(b)
        ]
        store.write(events, 1)
        written += b
        if written % 2_000_000 < batch_n:
            print(f"ingest: {written}/{n_events}", file=sys.stderr)
    ingest_s = time.monotonic() - t0

    rss_before_scan = _rss_mb()

    # -- scan → arrays ----------------------------------------------------
    t1 = time.monotonic()
    batch = stream_ratings(
        store, 1, {"rate": "rating"}, chunk_rows=chunk_rows
    )
    scan_s = time.monotonic() - t1
    nnz = len(batch.ratings)

    # -- bucketize both sides --------------------------------------------
    t2 = time.monotonic()
    nu, ni = len(batch.user_map), len(batch.item_map)
    by_user = bucketize(batch.users, batch.items, batch.ratings, nu, ni)
    by_item = bucketize(batch.items, batch.users, batch.ratings, ni, nu)
    bucketize_s = time.monotonic() - t2
    assert by_user.nnz == nnz and by_item.nnz == nnz

    store.close()
    return {
        "metric": "ingest_pipeline_events_per_s",
        "value": round(nnz / (scan_s + bucketize_s), 1),
        "unit": "events/s",
        "events": nnz,
        "ingest_events_per_s": round(written / ingest_s, 1),
        "scan_to_arrays_events_per_s": round(nnz / scan_s, 1),
        "bucketize_events_per_s": round(nnz / bucketize_s, 1),
        "ingest_s": round(ingest_s, 2),
        "scan_s": round(scan_s, 2),
        "bucketize_s": round(bucketize_s, 2),
        "peak_rss_mb": round(_rss_mb(), 1),
        "rss_before_scan_mb": round(rss_before_scan, 1),
        "chunk_rows": chunk_rows,
        "n_users": nu,
        "n_items": ni,
    }


def _writer_child(tmp_root: str, writer_id: str, n_events: int,
                  offset: int) -> None:
    """One ingest process appending to its private writer segment."""
    import datetime as _dt

    from ..storage.event import UTC, Event
    from ..storage.native_events import NativeEventStore

    rng = np.random.default_rng(hash(writer_id) % (1 << 32))
    store = NativeEventStore(
        os.path.join(tmp_root, "events_native"), writer_id=writer_id
    )
    store.init(1)
    base = _dt.datetime.fromtimestamp(1_750_000_000 + offset, tz=UTC)
    written = 0
    while written < n_events:
        b = min(200_000, n_events - written)
        users = rng.integers(0, 100_000, b)
        items = rng.integers(0, 20_000, b)
        vals = rng.integers(1, 6, b)
        store.write(
            [
                Event(
                    event="rate", entity_type="user",
                    entity_id=f"u{users[j]}",
                    target_entity_type="item",
                    target_entity_id=f"i{items[j]}",
                    properties={"rating": float(vals[j])},
                    event_time=base,
                )
                for j in range(b)
            ],
            1,
        )
        written += b
    store.close()


def _contention_child(tmp_root: str, writer_id, n_batches: int,
                      batch_events: int) -> None:
    """Pure-append loop for the lock-contention A/B: ONE batch is
    serialized up front (`_prepare_batch`), then the timed loop is
    nothing but ``evlog_append_batch`` calls — one flock + one write(2)
    each, no Python event construction or JSON encode in the loop. This
    is the measurement VERDICT r3 asked for: on a CPU-starved host the
    full ingest path serializes on Python work before writers can contend
    on the lock; hoisting serialization makes the loop I/O-bound so
    whatever flock signal exists can surface.

    Protocol: prints READY, waits for a line on stdin (start barrier),
    runs, prints one JSON line with its loop wall-clock.

    Invariant breach, deliberate: appending the SAME prepared batch
    ``n_batches`` times writes duplicate event ids, which violates the
    store's fresh-id routing assumption — the bench store directory is
    write-only throw-away state and must never be opened for reads."""
    import datetime as _dt

    from ..storage.event import UTC, Event
    from ..storage.native_events import NativeEventStore

    store = NativeEventStore(
        os.path.join(tmp_root, "events_native"), writer_id=writer_id
    )
    store.init(1)
    base = _dt.datetime.fromtimestamp(1_750_000_000, tz=UTC)
    rng = np.random.default_rng(hash(writer_id or "shared") % (1 << 32))
    users = rng.integers(0, 100_000, batch_events)
    items = rng.integers(0, 20_000, batch_events)
    events = [
        Event(
            event="rate", entity_type="user", entity_id=f"u{users[j]}",
            target_entity_type="item", target_entity_id=f"i{items[j]}",
            properties={"rating": 4.0}, event_time=base,
        )
        for j in range(batch_events)
    ]
    prepared = store._prepare_batch(events)
    # production routing: _writer_handle returns the private segment when
    # a writer_id is set (segmented mode), else the SAME primary log in
    # every process, appended under flock (shared mode)
    h = store._writer_handle(1)
    print("READY", flush=True)
    sys.stdin.readline()  # start barrier
    t0 = time.monotonic()
    for _ in range(n_batches):
        store._append_prepared(h, prepared)
    elapsed = time.monotonic() - t0
    store._lib.evlog_sync(h)
    store.close()
    print(json.dumps({"elapsed_s": elapsed,
                      "events": n_batches * batch_events}), flush=True)


def run_contention(n_events: int, batch_events: int, tmp_root: str) -> dict:
    """A/B: shared-flock (all writers on the primary log) vs segmented
    (private per-writer files) appends at 1/2/4 processes, serialization
    pre-hoisted. Reports aggregate events/s per configuration; the
    fdatasync is issued once per child at the end (the per-batch flock +
    write(2) is the contended op under test)."""
    import subprocess

    results: dict = {}
    for mode in ("shared", "segmented"):
        results[mode] = {}
        for writers in (1, 2, 4):
            sub = os.path.join(tmp_root, f"{mode}{writers}")
            os.makedirs(sub, exist_ok=True)
            per = n_events // writers
            n_batches = max(1, per // batch_events)
            procs = []
            for i in range(writers):
                wid = f"w{i}" if mode == "segmented" else None
                procs.append(subprocess.Popen(
                    [
                        sys.executable, "-c",
                        "from predictionio_tpu.tools.ingestbench import "
                        "_contention_child;"
                        f"_contention_child({sub!r}, {wid!r}, "
                        f"{n_batches}, {batch_events})",
                    ],
                    stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                    text=True,
                ))
            for p in procs:  # wait for every child to finish serializing
                line = p.stdout.readline().strip()
                if line != "READY":
                    # explicit check (not assert: -O would strip it AND
                    # its readline side effect, desynchronizing the A/B)
                    raise RuntimeError(
                        f"contention child failed before READY "
                        f"(got {line!r}); rc={p.poll()}"
                    )
            for p in procs:  # release the barrier
                p.stdin.write("GO\n")
                p.stdin.flush()
            stats = []
            for p in procs:
                line = p.stdout.readline()
                p.wait()
                if p.returncode != 0:
                    raise RuntimeError(f"contention child failed: {line}")
                stats.append(json.loads(line))
            total = sum(s["events"] for s in stats)
            slowest = max(s["elapsed_s"] for s in stats)
            results[mode][str(writers)] = {
                "events_per_s": round(total / slowest, 1),
                "events": total,
                "slowest_child_s": round(slowest, 3),
            }
    return {
        "metric": "ingest_contention_ab",
        "batch_events": batch_events,
        "results": results,
        "note": "pre-serialized payloads; per-batch cost is one flock + "
                "one write(2); fdatasync once per child at the end",
    }


def run_multiwriter(n_events: int, writers: int, tmp_root: str) -> dict:
    """N concurrent OS processes, each appending to its own segment of ONE
    app (the HBase region-parallel write analogue, HBPEvents.scala:166-184).
    Reports aggregate events/s and verifies the merged scan sees every
    segment's records."""
    import subprocess

    per = n_events // writers
    t0 = time.monotonic()
    procs = [
        subprocess.Popen(
            [
                sys.executable, "-c",
                "from predictionio_tpu.tools.ingestbench import _writer_child;"
                f"_writer_child({tmp_root!r}, 'w{i}', {per}, {i})",
            ],
        )
        for i in range(writers)
    ]
    for p in procs:
        p.wait()
    ingest_s = time.monotonic() - t0
    if any(p.returncode != 0 for p in procs):
        raise RuntimeError("a writer process failed")

    from ..storage.native_events import NativeEventStore

    store = NativeEventStore(os.path.join(tmp_root, "events_native"))
    t1 = time.monotonic()
    u, it, v, uids, iids = store.scan_ratings(1, {"rate": "rating"})
    scan_s = time.monotonic() - t1
    total = per * writers
    assert len(v) == total, f"merged scan saw {len(v)} of {total}"
    store.close()
    return {
        "metric": "multiwriter_ingest_events_per_s",
        "value": round(total / ingest_s, 1),
        "unit": "events/s",
        "writers": writers,
        "events": total,
        "ingest_s": round(ingest_s, 2),
        "merged_scan_events_per_s": round(total / scan_s, 1),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--events", type=int, default=20_000_000)
    ap.add_argument("--chunk-rows", type=int, default=1_000_000)
    ap.add_argument("--writers", type=int, default=0,
                    help="N concurrent writer processes appending to "
                         "private segments of one app (0 = single-process "
                         "full-pipeline bench)")
    ap.add_argument("--workdir", default=None,
                    help="scratch dir (default: a fresh tempdir, removed)")
    ap.add_argument("--contention", action="store_true",
                    help="shared-flock vs segmented append A/B with "
                         "pre-serialized payloads (1/2/4 processes)")
    ap.add_argument("--contention-batch", type=int, default=500,
                    help="events per append batch in --contention mode "
                         "(small batches = high lock-acquisition rate)")
    args = ap.parse_args(argv)

    def _go(d):
        if args.contention:
            return run_contention(args.events, args.contention_batch, d)
        if args.writers > 0:
            return run_multiwriter(args.events, args.writers, d)
        return run(args.events, args.chunk_rows, d)

    if args.workdir:
        os.makedirs(args.workdir, exist_ok=True)
        record = _go(args.workdir)
    else:
        with tempfile.TemporaryDirectory(prefix="pio-ingestbench-") as d:
            record = _go(d)
    print(json.dumps(record))
    return 0


if __name__ == "__main__":
    sys.exit(main())
