"""Single-purpose TPU revalidation steps (VERDICT r3 items 3 and 5).

Each subcommand runs ONE device experiment and prints ONE JSON line on
stdout; ``tpu_revalidate`` invokes them in subprocesses so a tunnel wedge
mid-step is a recorded timeout, not a dead queue. They are deliberately
tiny: the point is to exercise code paths that have never been COMPILED
on a TPU (Mosaic lowering inside shard_map, the fused gather+Gramian
kernel) with the one available chip, and to time the pure device-dispatch
serving cycle that the HTTP loadgen numbers fold into their wire costs.

Usage: ``python -m predictionio_tpu.tools._reval_steps <step>`` where
step is ``mesh_pallas`` | ``fused_smoke`` | ``dispatch_bench``.
"""

from __future__ import annotations

import json
import sys
import time


def _train_pair(cfg_kwargs_a: dict, cfg_kwargs_b: dict, mesh_for_a=False):
    """Train the same small problem under two configs; return factor pairs
    and max relative difference."""
    import numpy as np

    from ..ops.als import ALSConfig, als_train_coo
    from ..parallel.mesh import create_mesh

    rng = np.random.default_rng(11)
    nnz, n_u, n_i = 30_000, 900, 250
    w = 1.0 / np.arange(1, n_u + 1) ** 0.8
    u = rng.choice(n_u, size=nnz, p=w / w.sum()).astype(np.int32)
    i = rng.integers(0, n_i, nnz).astype(np.int32)
    v = rng.integers(1, 6, nnz).astype(np.float32)

    fa = als_train_coo(
        u, i, v, n_users=n_u, n_items=n_i, cfg=ALSConfig(**cfg_kwargs_a),
        mesh=create_mesh() if mesh_for_a else None,
    )
    fb = als_train_coo(
        u, i, v, n_users=n_u, n_items=n_i, cfg=ALSConfig(**cfg_kwargs_b)
    )
    diffs = []
    for x, y in ((fa.user_factors, fb.user_factors),
                 (fa.item_factors, fb.item_factors)):
        x, y = np.asarray(x), np.asarray(y)
        diffs.append(
            float(np.max(np.abs(x - y) / (np.abs(y) + 1e-6)))
        )
    return max(diffs)


def step_mesh_pallas() -> dict:
    """COMPILED (non-interpret) run of the shard_map-wrapped pallas solve
    on a real device mesh — the path `ops/als.py` routes under a mesh,
    which before this step had only ever executed in interpret mode on
    the CPU test mesh. Equality vs the chunked XLA solve."""
    import jax

    base = dict(rank=12, iterations=2, lambda_=0.05, seed=2)
    max_rel = _train_pair(
        dict(base, solve_mode="pallas"),
        dict(base, solve_mode="chunked"),
        mesh_for_a=True,
    )
    return {
        "step": "mesh_pallas_compiled",
        "backend": jax.default_backend(),
        "compiled": jax.default_backend() == "tpu",
        "n_mesh_devices": len(jax.devices()),
        "max_rel_vs_chunked": round(max_rel, 6),
        "ok": max_rel < 2e-2,
    }


def step_fused_smoke() -> dict:
    """COMPILED gramian_fused: kernel-level equality vs the einsum build
    at shapes that exercise K tiling and padding, plus a small end-to-end
    fused train vs the chunked solve. First Mosaic validation of the
    per-row-DMA gather kernel."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..ops.pallas_kernels import gramian_fused

    worst = 0.0
    for (b, k, n, r, seed) in (
        (32, 16, 500, 56, 0), (16, 512, 300, 56, 1), (8, 1024, 200, 24, 2),
        (25, 13, 77, 16, 3),
        # bench-realistic wide buckets: k=8192 hits the single-call SMEM
        # high-water mark ([4, 8192] int32 index block = the full
        # _FUSED_SMEM_IDX budget), k=32768 exercises the K-slice split —
        # both must survive Mosaic BEFORE the full-scale A/B commits
        (4, 8192, 300, 56, 4), (2, 32768, 300, 56, 5),
    ):
        rng = np.random.default_rng(seed)
        y = rng.standard_normal((n, r), dtype=np.float32)
        idx = rng.integers(0, n, (b, k)).astype(np.int32)
        w2 = (rng.random((b, k)) < 0.7).astype(np.float32)
        rhs = rng.standard_normal((b, k)).astype(np.float32) * w2
        ridge = rng.random(b).astype(np.float32)
        a, bv = gramian_fused(jnp.asarray(y), jnp.asarray(idx),
                              jnp.asarray(w2), jnp.asarray(rhs),
                              jnp.asarray(ridge))
        g = y[idx]
        a_ref = np.einsum("bkr,bk,bks->brs", g, w2, g) + (
            ridge[:, None, None] * np.eye(r, dtype=np.float32)
        )
        b_ref = np.einsum("bkr,bk->br", g, rhs)
        scale = float(np.max(np.abs(a_ref))) + 1e-6
        worst = max(
            worst,
            float(np.max(np.abs(np.asarray(a) - a_ref))) / scale,
            float(np.max(np.abs(np.asarray(bv) - b_ref))) / scale,
        )

    base = dict(rank=12, iterations=2, lambda_=0.05, seed=2)
    max_rel = _train_pair(
        dict(base, solve_mode="pallas", fused_gather=True),
        dict(base, solve_mode="chunked"),
    )
    return {
        "step": "fused_kernel_compiled",
        "backend": jax.default_backend(),
        "compiled": jax.default_backend() == "tpu",
        "kernel_max_rel": round(worst, 6),
        "train_max_rel_vs_chunked": round(max_rel, 6),
        "ok": worst < 1e-3 and max_rel < 2e-2,
    }


def step_dispatch_bench() -> dict:
    """Pure device-dispatch cycle for the serving hot op: batch-512 top-10
    over catalogs up to big-catalog shapes (60k/120k items — streaming
    kernel territory). Separates 'the device' from 'the wire' in the
    ≥10k QPS/chip question: in-process and HTTP loadgen numbers fold the
    host stack and the tunnel RTT into every cycle; this is the floor the
    chip itself sets per batch."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..ops.pallas_kernels import top_k_streaming

    import os

    reps = int(os.environ.get("PIO_DISPATCH_REPS", "50"))
    batch, rank, k = 512, 50, 10
    rng = np.random.default_rng(0)
    out = {
        "step": "dispatch_bench",
        "backend": jax.default_backend(),
        "batch": batch, "rank": rank, "k": k,
        "catalogs": {},
    }
    for n_items in (2_700, 27_000, 60_000, 120_000):
        items = jnp.asarray(
            rng.standard_normal((n_items, rank), dtype=np.float32)
        )
        q = jnp.asarray(
            rng.standard_normal((batch, rank), dtype=np.float32)
        )
        s, i = top_k_streaming(q, items, k)  # compile
        jax.block_until_ready((s, i))
        t0 = time.monotonic()
        for _ in range(reps):
            s, i = top_k_streaming(q, items, k)
        jax.block_until_ready((s, i))
        per_batch_ms = (time.monotonic() - t0) / reps * 1e3
        out["catalogs"][str(n_items)] = {
            "dispatch_ms_per_batch": round(per_batch_ms, 3),
            "implied_qps_at_depth1": round(batch / (per_batch_ms / 1e3), 0),
        }
    return out


def step_flash_pallas() -> dict:
    """COMPILED flash-attention kernel vs the XLA online-softmax path —
    first Mosaic validation, plus a timing rep at a serving-realistic
    shape."""
    import jax
    import numpy as np

    from ..ops.attention import flash_attention, flash_attention_pallas

    worst = 0.0
    for (b, h, lq, lk, d, causal, seed) in (
        (2, 4, 256, 256, 32, True, 0),
        (1, 2, 60, 60, 8, False, 1),
        (2, 8, 1024, 1024, 64, True, 2),
    ):
        rng = np.random.default_rng(seed)
        q = rng.normal(size=(b, h, lq, d)).astype(np.float32)
        k = rng.normal(size=(b, h, lk, d)).astype(np.float32)
        v = rng.normal(size=(b, h, lk, d)).astype(np.float32)
        got = np.asarray(flash_attention_pallas(q, k, v, causal=causal))
        ref = np.asarray(flash_attention(q, k, v, causal=causal))
        worst = max(worst, float(np.max(np.abs(got - ref))))

    rec = {
        "step": "flash_pallas",
        "backend": jax.default_backend(),
        "compiled": jax.default_backend() == "tpu",
        "max_abs_err": round(worst, 8),
        "ok": worst < 1e-3,
    }
    if jax.default_backend() == "tpu":
        # timing only where it means something (interpret mode off-TPU
        # would burn minutes to record incomparable numbers)
        q = np.random.default_rng(3).normal(
            size=(4, 8, 2048, 64)
        ).astype(np.float32)
        for name, fn in (("pallas", flash_attention_pallas),
                         ("xla", flash_attention)):
            out = fn(q, q, q, causal=True)
            jax.block_until_ready(out)
            t0 = time.monotonic()
            for _ in range(10):
                out = fn(q, q, q, causal=True)
            jax.block_until_ready(out)
            rec[f"{name}_ms_2048"] = round(
                (time.monotonic() - t0) / 10 * 1e3, 3
            )
    return rec


def step_implicit_gate() -> dict:
    """Ranking-quality gate for the IMPLICIT ALS path (VERDICT r4 item
    5). The queue's RMSE gate certifies levers on explicit mode only;
    implicit training (Hu-Koren confidence weighting — MLlib
    ``trainImplicit`` semantics, the similarproduct template's mode)
    exercises different code: the YᵀY base term, c−1 Gramian weights,
    c·p right-hand sides. This step trains a cluster-structured implicit
    dataset twice — reference f32 config, then the levered config from
    the same ``BENCH_*`` envs bench.py reads — and gates on
    precision@10 over held-out interactions. Without any lever env set
    it A/Bs bf16 gathers (the most likely adoption candidate); the
    queue always passes BENCH_GATHER_DTYPE explicitly so this
    standalone default cannot leak into a certification where bf16
    failed its explicit gate."""
    import os

    import numpy as np

    import jax

    from ..ops.als import ALSConfig, als_train_coo

    rng = np.random.default_rng(17)
    n_u, n_i, nnz, n_c = 20_000, 5_000, 1_500_000, 64
    # cluster-preference structure: most events hit the user's own item
    # cluster, the rest are uniform noise — learnable, cheap to generate
    uc = rng.integers(0, n_c, n_u)
    ic = rng.integers(0, n_c, n_i)
    users = rng.integers(0, n_u, nnz).astype(np.int64)
    in_cluster = rng.random(nnz) < 0.7
    items = rng.integers(0, n_i, nnz).astype(np.int64)
    by_cluster = [np.where(ic == c)[0] for c in range(n_c)]
    for c in range(n_c):
        m = in_cluster & (uc[users] == c)
        if m.any() and len(by_cluster[c]):
            items[m] = rng.choice(by_cluster[c], m.sum())

    holdout = rng.random(nnz) < 0.1
    tr_u, tr_i = users[~holdout], items[~holdout]
    # collapse duplicates into counts: value magnitude IS the implicit
    # confidence input (c = 1 + alpha·val)
    pair = tr_u * n_i + tr_i
    uniq, counts = np.unique(pair, return_counts=True)
    tr_u = (uniq // n_i).astype(np.int32)
    tr_i = (uniq % n_i).astype(np.int32)
    tr_v = counts.astype(np.float32)

    base = dict(rank=32, iterations=5, lambda_=0.05, alpha=10.0,
                implicit_prefs=True, seed=3)
    # tri-state lever envs mirror bench.py round 12: unset rides the
    # ALSConfig defaults (sort ON for bucketized inputs; fused resolves
    # with the solver), "0"/"1" force the leg explicitly
    sort_env = os.environ.get("BENCH_SORT_GATHER")
    fused_env = os.environ.get("BENCH_FUSED_GATHER")
    lever = dict(
        gather_dtype=os.environ.get("BENCH_GATHER_DTYPE", "bf16"),
        sort_gather_indices=None if sort_env is None else sort_env == "1",
        fused_gather=None if fused_env is None else fused_env == "1",
    )
    if lever["fused_gather"]:
        lever["solve_mode"] = "pallas"

    # holdout positives per user, minus train items (rank the unseen)
    ho_by_user: dict = {}
    for u, i in zip(users[holdout], items[holdout]):
        ho_by_user.setdefault(int(u), set()).add(int(i))
    train_by_user: dict = {}
    for u, i in zip(tr_u, tr_i):
        train_by_user.setdefault(int(u), set()).add(int(i))
    eval_users = [u for u in ho_by_user
                  if ho_by_user[u] - train_by_user.get(u, set())][:2000]

    def precision_at_10(cfg_kwargs: dict) -> float:
        f = als_train_coo(tr_u, tr_i, tr_v, n_users=n_u, n_items=n_i,
                          cfg=ALSConfig(**cfg_kwargs))
        uf = np.asarray(f.user_factors)
        yf = np.asarray(f.item_factors)
        scores = uf[eval_users] @ yf.T  # [2000, n_i] — small
        hits, total = 0, 0
        for row, u in enumerate(eval_users):
            s = scores[row]
            seen = train_by_user.get(u, set())
            if seen:
                s[list(seen)] = -np.inf  # rank only unseen items
            top = np.argpartition(-s, 10)[:10]
            want = ho_by_user[u] - seen
            hits += len(set(top.tolist()) & want)
            total += 10
        return hits / total

    p_ref = precision_at_10(dict(base))
    p_lever = precision_at_10(dict(base, **lever))
    delta = p_lever - p_ref
    return {
        "step": "implicit_gate",
        "backend": jax.default_backend(),
        "n_users": n_u, "n_items": n_i, "train_nnz": int(len(tr_v)),
        "eval_users": len(eval_users),
        "lever": {k: v for k, v in lever.items()},
        "p10_f32": round(p_ref, 5),
        "p10_lever": round(p_lever, 5),
        "delta": round(delta, 5),
        # ranking metrics are noisier than RMSE: absolute -0.005 bound
        "gate": "pass" if delta >= -0.005 else "FAIL",
        "ok": delta >= -0.005,
    }


def step_profile_trace() -> dict:
    """Capture a real profiler trace of the two hot paths (VERDICT r4
    item 7): one warm ALS training pass and a burst of serving top-k
    dispatches, under ``jax.profiler.trace``. The summary is parsed
    natively with ``jax.profiler.ProfileData`` (no TensorBoard needed)
    and recorded into the evidence file, so the HBM-utilization story
    can graduate from analytic byte accounting to measured op timings;
    the full trace stays on disk for TensorBoard's profile plugin."""
    import glob
    import os

    import numpy as np

    import jax
    import jax.numpy as jnp

    from ..ops.als import ALSConfig, als_train_coo
    from ..ops.pallas_kernels import top_k_streaming

    trace_dir = os.environ.get("PIO_PROFILE_DIR", "/tmp/pio-profile")
    os.makedirs(trace_dir, exist_ok=True)

    rng = np.random.default_rng(9)
    n_u, n_i, nnz = 60_000, 10_000, 2_000_000
    w = 1.0 / np.arange(1, n_u + 1) ** 0.8
    u = rng.choice(n_u, size=nnz, p=w / w.sum()).astype(np.int32)
    i = rng.integers(0, n_i, nnz).astype(np.int32)
    v = rng.integers(1, 6, nnz).astype(np.float32)
    cfg = ALSConfig(rank=32, iterations=2, lambda_=0.05, seed=4)

    items = jnp.asarray(
        rng.standard_normal((60_000, 50), dtype=np.float32)
    )
    q = jnp.asarray(rng.standard_normal((512, 50), dtype=np.float32))

    # warm both programs OUTSIDE the trace: the trace should show the
    # steady-state op mix, not one giant XlaCompile block
    als_train_coo(u, i, v, n_users=n_u, n_items=n_i, cfg=cfg)
    jax.block_until_ready(top_k_streaming(q, items, 10))

    with jax.profiler.trace(trace_dir):
        f = als_train_coo(u, i, v, n_users=n_u, n_items=n_i, cfg=cfg)
        jax.block_until_ready((f.user_factors, f.item_factors))
        for _ in range(20):
            s, idx = top_k_streaming(q, items, 10)
        jax.block_until_ready((s, idx))

    paths = sorted(
        glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"),
                  recursive=True),
        key=os.path.getmtime,
    )
    rec = {
        "step": "profile_trace",
        "backend": jax.default_backend(),
        "trace_dir": trace_dir,
    }
    if not paths:
        rec["error"] = "trace produced no .xplane.pb"
        return rec
    rec["xplane"] = paths[-1]
    try:
        pd = jax.profiler.ProfileData.from_file(paths[-1])
        planes = {}
        for plane in pd.planes:
            by_op: dict = {}
            total = 0.0
            for line in plane.lines:
                for ev in line.events:
                    d = ev.duration_ns or 0
                    by_op[ev.name] = by_op.get(ev.name, 0.0) + d
                    total += d
            top = sorted(by_op.items(), key=lambda kv: -kv[1])[:12]
            planes[plane.name] = {
                "total_ms": round(total / 1e6, 3),
                "top_ops_ms": {
                    k[:80]: round(ns / 1e6, 3) for k, ns in top
                },
            }
        # the device plane is the measurement; host planes are context
        rec["planes"] = {
            name: data for name, data in planes.items()
            if "TPU" in name or "/device" in name.lower()
        } or planes
    except Exception as exc:
        rec["parse_error"] = f"{type(exc).__name__}: {exc}"
    return rec


STEPS = {
    "mesh_pallas": step_mesh_pallas,
    "fused_smoke": step_fused_smoke,
    "dispatch_bench": step_dispatch_bench,
    "flash_pallas": step_flash_pallas,
    "implicit_gate": step_implicit_gate,
    "profile_trace": step_profile_trace,
}


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if len(argv) != 1 or argv[0] not in STEPS:
        print(f"usage: _reval_steps {{{'|'.join(STEPS)}}}", file=sys.stderr)
        return 2
    from ..utils.jax_cache import enable_compilation_cache

    enable_compilation_cache()
    rec = STEPS[argv[0]]()
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    sys.exit(main())
