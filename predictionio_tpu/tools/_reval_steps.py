"""Single-purpose TPU revalidation steps (VERDICT r3 items 3 and 5).

Each subcommand runs ONE device experiment and prints ONE JSON line on
stdout; ``tpu_revalidate`` invokes them in subprocesses so a tunnel wedge
mid-step is a recorded timeout, not a dead queue. They are deliberately
tiny: the point is to exercise code paths that have never been COMPILED
on a TPU (Mosaic lowering inside shard_map, the fused gather+Gramian
kernel) with the one available chip, and to time the pure device-dispatch
serving cycle that the HTTP loadgen numbers fold into their wire costs.

Usage: ``python -m predictionio_tpu.tools._reval_steps <step>`` where
step is ``mesh_pallas`` | ``fused_smoke`` | ``dispatch_bench``.
"""

from __future__ import annotations

import json
import sys
import time


def _train_pair(cfg_kwargs_a: dict, cfg_kwargs_b: dict, mesh_for_a=False):
    """Train the same small problem under two configs; return factor pairs
    and max relative difference."""
    import numpy as np

    from ..ops.als import ALSConfig, als_train_coo
    from ..parallel.mesh import create_mesh

    rng = np.random.default_rng(11)
    nnz, n_u, n_i = 30_000, 900, 250
    w = 1.0 / np.arange(1, n_u + 1) ** 0.8
    u = rng.choice(n_u, size=nnz, p=w / w.sum()).astype(np.int32)
    i = rng.integers(0, n_i, nnz).astype(np.int32)
    v = rng.integers(1, 6, nnz).astype(np.float32)

    fa = als_train_coo(
        u, i, v, n_users=n_u, n_items=n_i, cfg=ALSConfig(**cfg_kwargs_a),
        mesh=create_mesh() if mesh_for_a else None,
    )
    fb = als_train_coo(
        u, i, v, n_users=n_u, n_items=n_i, cfg=ALSConfig(**cfg_kwargs_b)
    )
    diffs = []
    for x, y in ((fa.user_factors, fb.user_factors),
                 (fa.item_factors, fb.item_factors)):
        x, y = np.asarray(x), np.asarray(y)
        diffs.append(
            float(np.max(np.abs(x - y) / (np.abs(y) + 1e-6)))
        )
    return max(diffs)


def step_mesh_pallas() -> dict:
    """COMPILED (non-interpret) run of the shard_map-wrapped pallas solve
    on a real device mesh — the path `ops/als.py` routes under a mesh,
    which before this step had only ever executed in interpret mode on
    the CPU test mesh. Equality vs the chunked XLA solve."""
    import jax

    base = dict(rank=12, iterations=2, lambda_=0.05, seed=2)
    max_rel = _train_pair(
        dict(base, solve_mode="pallas"),
        dict(base, solve_mode="chunked"),
        mesh_for_a=True,
    )
    return {
        "step": "mesh_pallas_compiled",
        "backend": jax.default_backend(),
        "compiled": jax.default_backend() == "tpu",
        "n_mesh_devices": len(jax.devices()),
        "max_rel_vs_chunked": round(max_rel, 6),
        "ok": max_rel < 2e-2,
    }


def step_fused_smoke() -> dict:
    """COMPILED gramian_fused: kernel-level equality vs the einsum build
    at shapes that exercise K tiling and padding, plus a small end-to-end
    fused train vs the chunked solve. First Mosaic validation of the
    per-row-DMA gather kernel."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..ops.pallas_kernels import gramian_fused

    worst = 0.0
    for (b, k, n, r, seed) in (
        (32, 16, 500, 56, 0), (16, 512, 300, 56, 1), (8, 1024, 200, 24, 2),
        (25, 13, 77, 16, 3),
        # bench-realistic wide buckets: k=8192 hits the single-call SMEM
        # high-water mark ([4, 8192] int32 index block = the full
        # _FUSED_SMEM_IDX budget), k=32768 exercises the K-slice split —
        # both must survive Mosaic BEFORE the full-scale A/B commits
        (4, 8192, 300, 56, 4), (2, 32768, 300, 56, 5),
    ):
        rng = np.random.default_rng(seed)
        y = rng.standard_normal((n, r), dtype=np.float32)
        idx = rng.integers(0, n, (b, k)).astype(np.int32)
        w2 = (rng.random((b, k)) < 0.7).astype(np.float32)
        rhs = rng.standard_normal((b, k)).astype(np.float32) * w2
        ridge = rng.random(b).astype(np.float32)
        a, bv = gramian_fused(jnp.asarray(y), jnp.asarray(idx),
                              jnp.asarray(w2), jnp.asarray(rhs),
                              jnp.asarray(ridge))
        g = y[idx]
        a_ref = np.einsum("bkr,bk,bks->brs", g, w2, g) + (
            ridge[:, None, None] * np.eye(r, dtype=np.float32)
        )
        b_ref = np.einsum("bkr,bk->br", g, rhs)
        scale = float(np.max(np.abs(a_ref))) + 1e-6
        worst = max(
            worst,
            float(np.max(np.abs(np.asarray(a) - a_ref))) / scale,
            float(np.max(np.abs(np.asarray(bv) - b_ref))) / scale,
        )

    base = dict(rank=12, iterations=2, lambda_=0.05, seed=2)
    max_rel = _train_pair(
        dict(base, solve_mode="pallas", fused_gather=True),
        dict(base, solve_mode="chunked"),
    )
    return {
        "step": "fused_kernel_compiled",
        "backend": jax.default_backend(),
        "compiled": jax.default_backend() == "tpu",
        "kernel_max_rel": round(worst, 6),
        "train_max_rel_vs_chunked": round(max_rel, 6),
        "ok": worst < 1e-3 and max_rel < 2e-2,
    }


def step_dispatch_bench() -> dict:
    """Pure device-dispatch cycle for the serving hot op: batch-512 top-10
    over catalogs up to big-catalog shapes (60k/120k items — streaming
    kernel territory). Separates 'the device' from 'the wire' in the
    ≥10k QPS/chip question: in-process and HTTP loadgen numbers fold the
    host stack and the tunnel RTT into every cycle; this is the floor the
    chip itself sets per batch."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..ops.pallas_kernels import top_k_streaming

    import os

    reps = int(os.environ.get("PIO_DISPATCH_REPS", "50"))
    batch, rank, k = 512, 50, 10
    rng = np.random.default_rng(0)
    out = {
        "step": "dispatch_bench",
        "backend": jax.default_backend(),
        "batch": batch, "rank": rank, "k": k,
        "catalogs": {},
    }
    for n_items in (2_700, 27_000, 60_000, 120_000):
        items = jnp.asarray(
            rng.standard_normal((n_items, rank), dtype=np.float32)
        )
        q = jnp.asarray(
            rng.standard_normal((batch, rank), dtype=np.float32)
        )
        s, i = top_k_streaming(q, items, k)  # compile
        jax.block_until_ready((s, i))
        t0 = time.monotonic()
        for _ in range(reps):
            s, i = top_k_streaming(q, items, k)
        jax.block_until_ready((s, i))
        per_batch_ms = (time.monotonic() - t0) / reps * 1e3
        out["catalogs"][str(n_items)] = {
            "dispatch_ms_per_batch": round(per_batch_ms, 3),
            "implied_qps_at_depth1": round(batch / (per_batch_ms / 1e3), 0),
        }
    return out


def step_flash_pallas() -> dict:
    """COMPILED flash-attention kernel vs the XLA online-softmax path —
    first Mosaic validation, plus a timing rep at a serving-realistic
    shape."""
    import jax
    import numpy as np

    from ..ops.attention import flash_attention, flash_attention_pallas

    worst = 0.0
    for (b, h, lq, lk, d, causal, seed) in (
        (2, 4, 256, 256, 32, True, 0),
        (1, 2, 60, 60, 8, False, 1),
        (2, 8, 1024, 1024, 64, True, 2),
    ):
        rng = np.random.default_rng(seed)
        q = rng.normal(size=(b, h, lq, d)).astype(np.float32)
        k = rng.normal(size=(b, h, lk, d)).astype(np.float32)
        v = rng.normal(size=(b, h, lk, d)).astype(np.float32)
        got = np.asarray(flash_attention_pallas(q, k, v, causal=causal))
        ref = np.asarray(flash_attention(q, k, v, causal=causal))
        worst = max(worst, float(np.max(np.abs(got - ref))))

    rec = {
        "step": "flash_pallas",
        "backend": jax.default_backend(),
        "compiled": jax.default_backend() == "tpu",
        "max_abs_err": round(worst, 8),
        "ok": worst < 1e-3,
    }
    if jax.default_backend() == "tpu":
        # timing only where it means something (interpret mode off-TPU
        # would burn minutes to record incomparable numbers)
        q = np.random.default_rng(3).normal(
            size=(4, 8, 2048, 64)
        ).astype(np.float32)
        for name, fn in (("pallas", flash_attention_pallas),
                         ("xla", flash_attention)):
            out = fn(q, q, q, causal=True)
            jax.block_until_ready(out)
            t0 = time.monotonic()
            for _ in range(10):
                out = fn(q, q, q, causal=True)
            jax.block_until_ready(out)
            rec[f"{name}_ms_2048"] = round(
                (time.monotonic() - t0) / 10 * 1e3, 3
            )
    return rec


STEPS = {
    "mesh_pallas": step_mesh_pallas,
    "fused_smoke": step_fused_smoke,
    "dispatch_bench": step_dispatch_bench,
    "flash_pallas": step_flash_pallas,
}


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if len(argv) != 1 or argv[0] not in STEPS:
        print(f"usage: _reval_steps {{{'|'.join(STEPS)}}}", file=sys.stderr)
        return 2
    from ..utils.jax_cache import enable_compilation_cache

    enable_compilation_cache()
    rec = STEPS[argv[0]]()
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    sys.exit(main())
