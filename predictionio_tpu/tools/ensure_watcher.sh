#!/bin/bash
# Idempotent tunnel-watcher starter (VERDICT r4: the watcher must be
# self-restarting). Safe to run from cron or any session: if a watcher
# already holds the lock this prints "running" and does nothing —
# checked here first so repeated invocations don't spam TUNNEL_WATCH.log
# with "already running" lines; the watcher's own flock still guards the
# start race.
#
# Usage: bash predictionio_tpu/tools/ensure_watcher.sh \
#   [engine_dir] [engine_dir_big]
set -u
cd "$(dirname "$0")/../.."
LOCK=.tunnel_watch.lock
DONE=.tunnel_watch.done
# finished watchers write the done-sentinel: without this check a cron'd
# ensure_watcher would restart after a CLEAN finish and re-spend the full
# device budget on every future window. Remove the file to re-arm.
if [ -f "$DONE" ]; then
  echo "done: $(cat "$DONE") (rm $DONE to re-arm)"
  exit 0
fi
# open append-mode: opening with '>' would truncate the pid the running
# watcher stored in the lockfile
exec 9>>"$LOCK"
if flock -n 9; then
  flock -u 9
  exec 9>&-
  setsid nohup bash predictionio_tpu/tools/tunnel_watch.sh "$@" \
    >/dev/null 2>&1 &
  echo "started (pid $!)"
else
  echo "running (pid $(cat "$LOCK" 2>/dev/null || echo '?'))"
fi
