"""Query server: REST deployment of trained engines.

Rebuild of ``core/src/main/scala/io/prediction/workflow/CreateServer.scala``:

- ``POST /queries.json`` — decode query, ``predict`` over every algorithm,
  ``serve`` combine, optional feedback loop (``CreateServer.scala:458-577``);
- ``GET /reload``       — hot-swap to the latest completed engine instance
  (``MasterActor`` ReloadServer, ``CreateServer.scala:300-321``);
- ``GET /stop``         — graceful shutdown (``CreateServer.scala:389-397``);
- ``GET /``             — status page with engine info and serving stats
  (``CreateServer.scala:421-456``; twirl ``index.scala.html``).

The reference's akka ``MasterActor``/``ServerActor`` pair and its
serve-time SparkContext collapse into one threaded HTTP server holding the
live model pytrees (factor tables stay resident in HBM between requests; a
reload swaps the table references under a lock — the TPU analogue of
respawning the server actor).

Feedback events mirror ``CreateServer.scala:505-565``: a ``predict`` event
with ``entityType=pio_pr``, a generated 64-char ``prId``, and properties
``{engineInstanceId, query, prediction}`` POSTed to the Event Server; when
the prediction carries a ``prId`` field the response is stamped with the
generated id.
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import html
import json
import logging
import random
import string
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, List, Optional, Sequence, Tuple
from urllib.parse import urlparse

import requests

from ..api.http import BackgroundHTTPServer, JsonHTTPHandler
from ..controller.engine import Engine, EngineParams
from ..storage import StorageRegistry, utcnow
from ..storage.metadata import STATUS_COMPLETED, EngineInstance
from .batching import MicroBatcher
from .context import WorkflowContext
from .core_workflow import load_models

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class ServerConfig:
    """``ServerConfig`` (``CreateServer.scala:71-98``); query port default
    8000 (``CreateServer.scala:76``)."""

    ip: str = "localhost"
    port: int = 8000
    engine_instance_id: Optional[str] = None  # None = latest COMPLETED
    engine_id: Optional[str] = None
    engine_version: Optional[str] = None
    engine_variant: str = "engine.json"
    feedback: bool = False
    event_server_ip: str = "localhost"
    event_server_port: int = 7070
    access_key: Optional[str] = None
    batch: str = ""
    # Micro-batching (the accelerator replacement for the reference's
    # per-request predictBase, CreateServer.scala:479-485): concurrent
    # queries are aggregated for <= batch_wait_ms into one batched device
    # dispatch. Worst-case added latency = batch_wait_ms; under load the
    # batch fills instantly and the wait never triggers.
    batching: bool = True
    # 512 keeps the padded top-k program set small (pad_pow2) while letting
    # a high-latency dispatch path (e.g. a remote-relay device) amortize
    # the round trip over a large batch; device time grows sub-linearly.
    # Memory envelope: scoring materializes a [batch, n_items] f32 matrix
    # PER IN-FLIGHT BATCH, so peak device memory scales with
    # batch_pipeline_depth × batch_max — at 10M items and depth 2,
    # 2×512×1e7×4 B ≈ 41 GB. Size batch_max to the catalog AND depth:
    # batch_max ≲ device_bytes / (batch_pipeline_depth × n_items × 4)
    # (e.g. 64 for 10M items at depth 2 on a 16 GB chip). The Pallas
    # streaming top-k (auto-selected for huge catalogs) sidesteps the
    # score matrix entirely.
    batch_max: int = 512
    batch_wait_ms: float = 1.0
    # In-flight batch pipelining: while one batch's results travel back
    # from the device, the next is already dispatched. Depth 2 hides one
    # full host↔device round trip (the binding resource on a tunneled or
    # remote-relay device); raise it when round_trip >> device_time. Peak
    # device memory scales with depth × the batch_max envelope above.
    batch_pipeline_depth: int = 2
    #: Remote error log: serving failures POST {message, query} here
    #: (``--log-url``, ``CreateServer.scala:409-420``). None = disabled.
    log_url: Optional[str] = None


# ---------------------------------------------------------------------------
# Query / prediction JSON codecs (per-algo querySerializer analogue,
# CreateServer.scala:475-478)
# ---------------------------------------------------------------------------


def decode_query(algorithms: Sequence[Any], payload: Any) -> Any:
    """Decode a JSON query using the first algorithm's declared query class
    (plain dicts pass through, like json4s ``DefaultFormats``)."""
    for algo in algorithms:
        cls = algo.query_class()
        if cls is not None:
            if dataclasses.is_dataclass(cls):
                fields = {f.name for f in dataclasses.fields(cls)}
                return cls(**{k: v for k, v in payload.items() if k in fields})
            return cls(**payload)
    return payload


def encode_result(obj: Any) -> Any:
    """Prediction → JSON-compatible structure.

    A result type may define ``to_json_dict`` to control its wire shape (the
    per-algo querySerializer analogue, ``CreateServer.scala:475-478``) —
    templates use it for the reference's camelCase field names."""
    # hot path: most nodes of a result tree are leaves
    if obj is None or type(obj) in (str, int, float, bool):
        return obj
    if hasattr(obj, "to_json_dict") and not isinstance(obj, type):
        return encode_result(obj.to_json_dict())
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {k: encode_result(v) for k, v in dataclasses.asdict(obj).items()}
    if isinstance(obj, dict):
        return {k: encode_result(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [encode_result(v) for v in obj]
    if not isinstance(obj, (str, bytes)):
        if hasattr(obj, "tolist"):
            return obj.tolist()  # numpy / jax arrays (any shape)
        if hasattr(obj, "item"):
            try:
                # pio: lint-ok[jit-host-sync-serving] encode_result IS the encode-time sync point the rule defers to — the one place a device scalar must become JSON
                return obj.item()  # other scalar wrappers
            except (TypeError, ValueError):
                pass
    return obj


def _gen_pr_id() -> str:
    """64 alphanumeric chars (``CreateServer.scala:513``)."""
    alphabet = string.ascii_letters + string.digits
    return "".join(random.choice(alphabet) for _ in range(64))


def _get_pr_id(obj: Any) -> Optional[str]:
    """The ``WithPrId`` protocol: a ``pr_id`` attribute or ``prId`` key."""
    if isinstance(obj, dict):
        return obj.get("prId") if "prId" in obj else None
    return getattr(obj, "pr_id", None)


def _has_pr_id(obj: Any) -> bool:
    return (isinstance(obj, dict) and "prId" in obj) or hasattr(obj, "pr_id")


# ---------------------------------------------------------------------------
# Deployment state (what MasterActor rebuilds on reload)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Deployment:
    """One live engine instance: algorithms + in-memory (HBM) models +
    serving combiner (``createServerActorWithEngine``,
    ``CreateServer.scala:184-248``)."""

    instance: EngineInstance
    engine_params: EngineParams
    algorithms: List[Any]
    models: List[Any]
    serving: Any


def prepare_deployment(
    engine: Engine,
    registry: StorageRegistry,
    config: ServerConfig,
    ctx: Optional[WorkflowContext] = None,
) -> Deployment:
    """Load the target engine instance and make its models live
    (``CreateServer.scala:184-248`` + ``Engine.prepareDeploy``)."""
    md = registry.get_metadata()
    if config.engine_instance_id:
        instance = md.engine_instance_get(config.engine_instance_id)
        if instance is None:
            raise KeyError(
                f"Engine instance {config.engine_instance_id} not found"
            )
    else:
        instance = md.engine_instance_get_latest_completed(
            engine_id=config.engine_id or "default",
            engine_version=config.engine_version or "1",
            engine_variant=config.engine_variant,
        )
        if instance is None:
            raise RuntimeError(
                "No completed engine instance found; run train first "
                "(Console.scala:742-780)"
            )
    if instance.status != STATUS_COMPLETED:
        raise RuntimeError(
            f"Engine instance {instance.id} has status {instance.status}, "
            "not COMPLETED"
        )

    ctx = ctx or WorkflowContext(mode="Serving", batch=config.batch)
    engine_params = engine.engine_instance_to_engine_params(instance)
    persisted = load_models(registry, instance.id)
    live_models = engine.prepare_deploy(ctx, engine_params, instance.id, persisted)
    algorithms = engine._algorithms(engine_params)
    serving = engine._serving(engine_params)
    return Deployment(
        instance=instance,
        engine_params=engine_params,
        algorithms=algorithms,
        models=live_models,
        serving=serving,
    )


# ---------------------------------------------------------------------------
# HTTP server
# ---------------------------------------------------------------------------


class QueryDecodeError(ValueError):
    """Query JSON does not fit the engine's query shape → 400, matching the
    reference's MappingException handling (``CreateServer.scala:578-585``)."""


class _QueryHandler(JsonHTTPHandler):
    server: "QueryServer"

    def do_POST(self) -> None:  # noqa: N802
        raw = self.read_body()
        path = urlparse(self.path).path
        if path != "/queries.json":
            self.respond(404, {"message": "Not Found"})
            return
        try:
            payload = json.loads(raw.decode("utf-8")) if raw else {}
        except ValueError as exc:
            self.respond(400, {"message": str(exc)})
            return
        try:
            result, status = self.server.handle_query(payload)
            self.respond(status, result)
        except QueryDecodeError as exc:
            # the reference remote-logs the bad-query branch too
            # (CreateServer.scala:583-590)
            self.server.post_error_log(str(exc), payload)
            self.respond(400, {"message": str(exc)})
        except Exception as exc:
            logger.exception("Query failed")
            self.server.post_error_log(str(exc), payload)
            self.respond(500, {"message": str(exc)})

    def do_GET(self) -> None:  # noqa: N802
        path = urlparse(self.path).path
        if path == "/":
            self.respond(200, self.server.status_html(), content_type="text/html")
        elif path == "/reload":
            try:
                self.server.reload()
                self.respond(200, {"message": "Reloaded"})
            except Exception as exc:
                logger.exception("Reload failed")
                self.respond(500, {"message": str(exc)})
        elif path == "/stop":
            self.respond(200, {"message": "Shutting down"})
            self.server.stop_async()
        else:
            self.respond(404, {"message": "Not Found"})


class QueryServer(BackgroundHTTPServer):
    """The serving process (``ServerActor`` + ``MasterActor``,
    ``CreateServer.scala:250-628``)."""

    def __init__(
        self,
        config: ServerConfig,
        engine: Engine,
        registry: StorageRegistry,
        deployment: Optional[Deployment] = None,
        ctx: Optional[WorkflowContext] = None,
    ):
        self.config = config
        self.engine = engine
        self.registry = registry
        self.ctx = ctx or WorkflowContext(mode="Serving", batch=config.batch)
        self._deploy_lock = threading.RLock()
        self.deployment = deployment or prepare_deployment(
            engine, registry, config, self.ctx
        )
        # Bounded async feedback delivery (CreateServer's fire-and-forget
        # future, without unbounded thread growth under load).
        self._feedback_pool = ThreadPoolExecutor(
            max_workers=2, thread_name_prefix="feedback"
        )
        # Micro-batching prediction dispatch (see ServerConfig.batching).
        # The deployment travels WITH each queued item, so a /reload
        # mid-batch is safe: in-flight queries finish on the model they
        # arrived under.
        self._batcher: Optional[MicroBatcher] = (
            MicroBatcher(
                self._predict_batch,
                max_batch=config.batch_max,
                max_wait_ms=config.batch_wait_ms,
                name="predict-batch",
                pipeline_depth=config.batch_pipeline_depth,
            )
            if config.batching
            else None
        )
        # Serving stats (CreateServer.scala:392-394,567-574)
        self._stats_lock = threading.Lock()
        self.server_start_time = utcnow()
        self.request_count = 0
        self.last_serving_sec = 0.0
        self.avg_serving_sec = 0.0
        super().__init__((config.ip, config.port), _QueryHandler)

    # -- query path (CreateServer.scala:458-577) --------------------------
    def handle_query(self, payload: Any) -> Tuple[Any, int]:
        started = time.monotonic()
        query_time = utcnow()
        with self._deploy_lock:
            dep = self.deployment
        try:
            query = decode_query(dep.algorithms, payload)
        except (TypeError, AttributeError, KeyError) as exc:
            raise QueryDecodeError(f"Invalid query: {exc}") from exc
        query = dep.serving.supplement(query)
        if self._batcher is not None:
            predictions = self._batcher.submit((dep, query))
        else:
            predictions = self._predict_one(dep, query)
        prediction = dep.serving.serve(query, predictions)
        result = encode_result(prediction)

        if self.config.feedback:
            result = self._send_feedback(dep, query_time, query, prediction, result)

        elapsed = time.monotonic() - started
        with self._stats_lock:
            self.last_serving_sec = elapsed
            self.avg_serving_sec = (
                self.avg_serving_sec * self.request_count + elapsed
            ) / (self.request_count + 1)
            self.request_count += 1
        return result, 200

    def post_error_log(self, message: str, payload: Any) -> None:
        """Fire-and-forget POST of a serving failure to ``log_url``
        (``CreateServer.scala:409-420`` — remote error reporting for
        fleet-monitored deployments). Rides the bounded feedback pool so
        an error storm against a slow sink cannot spawn unbounded
        threads, and never adds a failure of its own to the request."""
        url = self.config.log_url
        if not url:
            return
        # engine-instance identity so a shared fleet sink can attribute
        # the error (the reference posts {engineInstance, message},
        # CreateServer.scala:412-414)
        try:
            instance_id = self.deployment.instance.id
        except Exception:
            instance_id = None

        def send() -> None:
            try:
                requests.post(
                    url,
                    json={
                        "engineInstance": instance_id,
                        "message": message,
                        "query": payload,
                    },
                    timeout=10,
                )
            except Exception:
                logger.debug("error-log POST to %s failed", url, exc_info=True)

        try:
            self._feedback_pool.submit(send)
        except RuntimeError:
            # pool already shut down (/stop racing an in-flight failure):
            # the log post is best-effort; the response must still go out
            logger.debug("error-log skipped: feedback pool closed")

    @staticmethod
    def _predict_one(dep: Deployment, query: Any) -> List[Any]:
        """Unbatched per-query path (the reference's per-request
        ``predictBase`` loop, ``CreateServer.scala:479-485``)."""
        return [
            algo.predict(model, query)
            for algo, model in zip(dep.algorithms, dep.models)
        ]

    @staticmethod
    def _predict_batch(items: Sequence[Tuple[Deployment, Any]]) -> List[List[Any]]:
        """Batched prediction for micro-batched items ``(deployment,
        query)`` → per-item list of per-algorithm predictions.

        Queries are grouped by deployment (a reload mid-batch may leave
        two generations in one batch); within a group, each algorithm gets
        ONE ``batch_predict(model, [(idx, query)])`` call for the whole
        group — a single gather-dot top-k device dispatch for the TPU
        algorithms; the base-class default maps ``predict`` for the rest."""
        out: List[Any] = [None] * len(items)
        groups: dict = {}
        for pos, (dep, query) in enumerate(items):
            groups.setdefault(id(dep), (dep, []))[1].append((pos, query))
        for dep, indexed in groups.values():
            try:
                per_algo: List[dict] = []
                for algo, model in zip(dep.algorithms, dep.models):
                    per_algo.append(dict(algo.batch_predict(model, indexed)))
                for pos, _query in indexed:
                    out[pos] = [results[pos] for results in per_algo]
            except Exception:
                # Poison-query containment: one bad query must not 500 the
                # whole batch. Retry the group per-query; only the queries
                # that actually fail carry their exception (MicroBatcher's
                # per-item failure channel).
                for pos, query in indexed:
                    try:
                        out[pos] = QueryServer._predict_one(dep, query)
                    except Exception as exc:
                        out[pos] = exc
        return out  # every position was covered by exactly one group

    def _send_feedback(
        self,
        dep: Deployment,
        query_time: _dt.datetime,
        query: Any,
        prediction: Any,
        result: Any,
    ) -> Any:
        """Async ``predict`` event to the Event Server
        (``CreateServer.scala:505-565``)."""
        existing = _get_pr_id(prediction)
        new_pr_id = existing if existing else _gen_pr_id()
        data = {
            "event": "predict",
            "eventTime": query_time.isoformat(timespec="milliseconds"),
            "entityType": "pio_pr",
            "entityId": new_pr_id,
            "properties": {
                "engineInstanceId": dep.instance.id,
                "query": encode_result(query),
                "prediction": encode_result(prediction),
            },
        }
        query_pr_id = _get_pr_id(query)
        if query_pr_id is not None:
            data["prId"] = query_pr_id

        url = (
            f"http://{self.config.event_server_ip}:"
            f"{self.config.event_server_port}/events.json"
            f"?accessKey={self.config.access_key or ''}"
        )

        def post() -> None:
            try:
                resp = requests.post(url, json=data, timeout=10)
                if resp.status_code != 201:
                    logger.error(
                        "Feedback event failed. Status code: %s. Data: %s",
                        resp.status_code,
                        data,
                    )
            except Exception as exc:
                logger.error("Feedback event failed: %s", exc)

        self._feedback_pool.submit(post)

        # Stamp the generated prId into the response only for predictions
        # that carry a prId slot (CreateServer.scala:558-565).
        if _has_pr_id(prediction) and isinstance(result, dict):
            result = dict(result)
            result.pop("pr_id", None)  # replace the stale slot, don't duplicate
            result["prId"] = new_pr_id
        return result

    # -- lifecycle --------------------------------------------------------
    def server_close(self) -> None:
        if self._batcher is not None:
            self._batcher.close()  # fail queued requests fast, join thread
        self._feedback_pool.shutdown(wait=False)
        super().server_close()

    def reload(self) -> None:
        """Hot-swap to the latest completed instance
        (``CreateServer.scala:300-321``): the new tables are staged first,
        then the references swap under the lock."""
        cfg = dataclasses.replace(
            self.config,
            engine_instance_id=None,
            engine_id=self.deployment.instance.engine_id,
            engine_version=self.deployment.instance.engine_version,
            engine_variant=self.deployment.instance.engine_variant,
        )
        fresh = prepare_deployment(self.engine, self.registry, cfg, self.ctx)
        with self._deploy_lock:
            old = self.deployment.instance.id
            self.deployment = fresh
        logger.info(
            "Reloaded: engine instance %s -> %s", old, fresh.instance.id
        )

    # -- status page (CreateServer.scala:421-456) -------------------------
    def status_html(self) -> str:
        dep = self.deployment
        with self._stats_lock:
            rows = [
                ("Engine instance", dep.instance.id),
                ("Engine", f"{dep.instance.engine_id} {dep.instance.engine_version}"),
                ("Engine factory", dep.instance.engine_factory),
                ("Start time", str(self.server_start_time)),
                ("Algorithms", ", ".join(type(a).__name__ for a in dep.algorithms)),
                ("Models", ", ".join(type(m).__name__ for m in dep.models)),
                ("Serving", type(dep.serving).__name__),
                ("Feedback enabled", str(self.config.feedback)),
                ("Request count", str(self.request_count)),
                ("Average serving time", f"{self.avg_serving_sec * 1000:.3f} ms"),
                ("Last serving time", f"{self.last_serving_sec * 1000:.3f} ms"),
            ]
            if self._batcher is not None:
                bs = self._batcher.stats
                rows.append(
                    (
                        "Micro-batching",
                        f"{bs['batches']} batches, "
                        f"avg {bs['avg_batch']:.1f} queries/batch",
                    )
                )
        cells = "".join(
            f"<tr><th>{html.escape(k)}</th><td>{html.escape(v)}</td></tr>"
            for k, v in rows
        )
        return (
            "<!DOCTYPE html><html><head><title>"
            f"{html.escape(dep.instance.engine_id)} - predictionio_tpu engine "
            "server</title></head><body>"
            "<h1>PredictionIO-TPU Engine Server</h1>"
            f"<table>{cells}</table>"
            "<p>POST JSON queries to <code>/queries.json</code>; "
            "<a href=\"/reload\">reload</a> latest model.</p>"
            "</body></html>"
        )


def create_query_server(
    engine: Engine,
    config: ServerConfig = ServerConfig(),
    registry: Optional[StorageRegistry] = None,
    block: bool = True,
) -> QueryServer:
    """Deploy an engine (``CreateServer.main``, ``CreateServer.scala:100-182``)."""
    from ..storage.registry import get_registry
    from .version_check import check_upgrade

    check_upgrade("deployment", type(engine).__name__)  # CreateServer.scala:246
    registry = registry or get_registry()
    server = QueryServer(config, engine, registry)
    logger.info(
        "Query server: engine instance %s on %s:%d",
        server.deployment.instance.id,
        config.ip,
        server.bound_port,
    )
    if block:
        try:
            server.serve_forever()
        finally:
            server.server_close()
    else:
        server.start_background()
    return server
