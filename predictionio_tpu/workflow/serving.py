"""Query server: REST deployment of trained engines.

Rebuild of ``core/src/main/scala/io/prediction/workflow/CreateServer.scala``:

- ``POST /queries.json`` — decode query, ``predict`` over every algorithm,
  ``serve`` combine, optional feedback loop (``CreateServer.scala:458-577``);
- ``GET /reload``       — hot-swap to the latest completed engine instance
  (``MasterActor`` ReloadServer, ``CreateServer.scala:300-321``);
- ``GET /stop``         — graceful shutdown (``CreateServer.scala:389-397``);
- ``GET /``             — status page with engine info and serving stats
  (``CreateServer.scala:421-456``; twirl ``index.scala.html``).

The reference's akka ``MasterActor``/``ServerActor`` pair and its
serve-time SparkContext collapse into one threaded HTTP server holding the
live model pytrees (factor tables stay resident in HBM between requests; a
reload swaps the table references under a lock — the TPU analogue of
respawning the server actor).

Feedback events mirror ``CreateServer.scala:505-565``: a ``predict`` event
with ``entityType=pio_pr``, a generated 64-char ``prId``, and properties
``{engineInstanceId, query, prediction}`` POSTed to the Event Server; when
the prediction carries a ``prId`` field the response is stamped with the
generated id.

Resilience (``docs/robustness.md``): requests carry an optional
``X-PIO-Deadline-Ms`` budget checked at admission and again before the
MicroBatcher dispatch (an expired query never wastes a device slot);
admission is bounded (``PIO_SERVING_MAX_QUEUE`` in-flight queries, then
``503`` + ``Retry-After`` instead of unbounded thread pile-up); the
Event-Server feedback and ``--log-url`` POSTs ride a shared
``RetryPolicy`` (feedback events carry an ``idempotencyKey`` so the
retries cannot double-insert) behind per-sink ``CircuitBreaker``s; when
a breaker is open the server keeps answering from the HBM-resident
last-good model and reports ``degraded: true`` in its status.
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import html
import json
import logging
import os
import random
import string
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as FutureTimeoutError
from typing import Any, Callable, List, Optional, Sequence, Tuple
from urllib.parse import urlparse

import requests

from ..api.http import BackgroundHTTPServer, JsonHTTPHandler
from ..controller.engine import Engine, EngineParams
from ..obs.flight import record as flight_record
from ..obs.metrics import MetricsRegistry
from ..obs.trace import TRACE_HEADER, SpanContext, Tracer, current_context
from ..rollout.manager import RolloutError, RolloutManager
from ..rollout.plan import BASELINE, CANDIDATE, VARIANT_HEADER
from ..storage import StorageRegistry, utcnow
from ..storage.metadata import (
    ROLLOUT_SHADOW,
    STATUS_COMPLETED,
    EngineInstance,
)
from ..testing.faults import fault_point
from ..utils.resilience import (
    DEADLINE_HEADER,
    CircuitBreaker,
    CircuitOpen,
    Deadline,
    DeadlineExceeded,
    RetryPolicy,
    deadline_scope,
)
from .batching import MicroBatcher
from .context import WorkflowContext
from .core_workflow import load_models

logger = logging.getLogger(__name__)

#: Default in-flight admission cap (``PIO_SERVING_MAX_QUEUE`` overrides):
#: enough to keep batch_max-sized micro-batches formable under load,
#: small enough that a stalled device fails new arrivals in microseconds
#: instead of stacking handler threads until the process dies.
DEFAULT_MAX_QUEUE = 128


@dataclasses.dataclass(frozen=True)
class ServerConfig:
    """``ServerConfig`` (``CreateServer.scala:71-98``); query port default
    8000 (``CreateServer.scala:76``)."""

    ip: str = "localhost"
    port: int = 8000
    engine_instance_id: Optional[str] = None  # None = latest COMPLETED
    engine_id: Optional[str] = None
    engine_version: Optional[str] = None
    engine_variant: str = "engine.json"
    feedback: bool = False
    event_server_ip: str = "localhost"
    event_server_port: int = 7070
    access_key: Optional[str] = None
    batch: str = ""
    # Micro-batching (the accelerator replacement for the reference's
    # per-request predictBase, CreateServer.scala:479-485): concurrent
    # queries are aggregated for <= batch_wait_ms into one batched device
    # dispatch. Worst-case added latency = batch_wait_ms; under load the
    # batch fills instantly and the wait never triggers.
    batching: bool = True
    # 512 keeps the padded top-k program set small (pad_pow2) while letting
    # a high-latency dispatch path (e.g. a remote-relay device) amortize
    # the round trip over a large batch; device time grows sub-linearly.
    # Memory envelope: scoring materializes a [batch, n_items] f32 matrix
    # PER IN-FLIGHT BATCH, so peak device memory scales with
    # batch_pipeline_depth × batch_max — at 10M items and depth 2,
    # 2×512×1e7×4 B ≈ 41 GB. Size batch_max to the catalog AND depth:
    # batch_max ≲ device_bytes / (batch_pipeline_depth × n_items × 4)
    # (e.g. 64 for 10M items at depth 2 on a 16 GB chip). The fused
    # streaming top-k (auto-selected on TPU past 64 MB of would-be
    # scores — ops.scoring.STREAMING_TOPK_BYTES; /status.json topkPath
    # reports the resolved path) sidesteps the score matrix entirely.
    batch_max: int = 512
    batch_wait_ms: float = 1.0
    # In-flight batch pipelining: while one batch's results travel back
    # from the device, the next is already dispatched. Depth 2 hides one
    # full host↔device round trip (the binding resource on a tunneled or
    # remote-relay device); raise it when round_trip >> device_time. Peak
    # device memory scales with depth × the batch_max envelope above.
    batch_pipeline_depth: int = 2
    #: Remote error log: serving failures POST {message, query} here
    #: (``--log-url``, ``CreateServer.scala:409-420``). None = disabled.
    log_url: Optional[str] = None
    #: Bounded admission: max queries in flight (handler threads admitted
    #: past the front door) before new arrivals shed with 503 +
    #: Retry-After. None = ``PIO_SERVING_MAX_QUEUE`` env (default
    #: ``DEFAULT_MAX_QUEUE``); 0 disables shedding (unbounded, the
    #: pre-resilience behavior).
    max_queue: Optional[int] = None
    #: Continuous-learning loop: a ``ContinuousConfig``
    #: (``predictionio_tpu/continuous``) attaches a changefeed-driven
    #: fold-in controller to this server — candidates auto-submit
    #: through the rollout plane (docs/continuous.md). None = disabled.
    continuous: Optional[Any] = None
    #: Quality-observability knobs: a ``QualityConfig``
    #: (``predictionio_tpu/obs/quality``) for the served-score drift /
    #: feedback-join monitor every query server carries
    #: (docs/observability.md#quality). None = defaults.
    quality: Optional[Any] = None
    #: Fleet-health knobs: a ``HealthConfig``
    #: (``predictionio_tpu/obs/slo``) for the SLO burn-rate engine,
    #: stall watchdog and flight recorder every server carries
    #: (docs/slo.md). None = env defaults.
    health: Optional[Any] = None
    #: Sharded-model serving (docs/fleet.md): with ``shard_count > 1``
    #: this server holds only partition ``shard_index`` of the item
    #: factors (item row ``i`` lives on shard ``i % shard_count``) and
    #: answers with its *local* top-k; a ``pio router --sharded`` tier
    #: fans queries out to every shard and k-way-merges the answers into
    #: the exact global top-k. Every algorithm in the engine must
    #: implement ``shard_model`` — deploy fails loudly otherwise. The
    #: shard spec rides ``dataclasses.replace`` into rollout candidate
    #: deployments, so a canary on a sharded fleet is sharded
    #: identically.
    shard_index: int = 0
    shard_count: int = 1


# ---------------------------------------------------------------------------
# Query / prediction JSON codecs (per-algo querySerializer analogue,
# CreateServer.scala:475-478)
# ---------------------------------------------------------------------------


def decode_query(algorithms: Sequence[Any], payload: Any) -> Any:
    """Decode a JSON query using the first algorithm's declared query class
    (plain dicts pass through, like json4s ``DefaultFormats``)."""
    for algo in algorithms:
        cls = algo.query_class()
        if cls is not None:
            if dataclasses.is_dataclass(cls):
                fields = {f.name for f in dataclasses.fields(cls)}
                return cls(**{k: v for k, v in payload.items() if k in fields})
            return cls(**payload)
    return payload


def encode_result(obj: Any) -> Any:
    """Prediction → JSON-compatible structure.

    A result type may define ``to_json_dict`` to control its wire shape (the
    per-algo querySerializer analogue, ``CreateServer.scala:475-478``) —
    templates use it for the reference's camelCase field names."""
    # hot path: most nodes of a result tree are leaves
    if obj is None or type(obj) in (str, int, float, bool):
        return obj
    if hasattr(obj, "to_json_dict") and not isinstance(obj, type):
        return encode_result(obj.to_json_dict())
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {k: encode_result(v) for k, v in dataclasses.asdict(obj).items()}
    if isinstance(obj, dict):
        return {k: encode_result(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [encode_result(v) for v in obj]
    if not isinstance(obj, (str, bytes)):
        if hasattr(obj, "tolist"):
            return obj.tolist()  # numpy / jax arrays (any shape)
        if hasattr(obj, "item"):
            try:
                # pio: lint-ok[jit-host-sync-serving] encode_result IS the encode-time sync point the rule defers to — the one place a device scalar must become JSON
                return obj.item()  # other scalar wrappers
            except (TypeError, ValueError):
                pass
    return obj


def _gen_pr_id() -> str:
    """64 alphanumeric chars (``CreateServer.scala:513``)."""
    alphabet = string.ascii_letters + string.digits
    return "".join(random.choice(alphabet) for _ in range(64))


def _get_pr_id(obj: Any) -> Optional[str]:
    """The ``WithPrId`` protocol: a ``pr_id`` attribute or ``prId`` key."""
    if isinstance(obj, dict):
        return obj.get("prId") if "prId" in obj else None
    return getattr(obj, "pr_id", None)


def _has_pr_id(obj: Any) -> bool:
    return (isinstance(obj, dict) and "prId" in obj) or hasattr(obj, "pr_id")


# ---------------------------------------------------------------------------
# Serving stats (CreateServer.scala:392-394,567-574, grown with the
# resilience counters the status page reports)
# ---------------------------------------------------------------------------


class ServingStats:
    """Thread-safe serving counters, backed by the obs metrics plane.

    Beyond the reference's request count / serving times, every
    resilience outcome is *counted*, not just logged: shed admissions,
    expired deadlines, retries, feedback/error-log delivery failures and
    breaker-skipped deliveries — a fleet monitor reads these off
    ``GET /`` instead of scraping logs.

    Request latency feeds a log-scale registry histogram
    (``pio_serving_request_seconds``), so :meth:`snapshot` reports
    p50/p95/p99 — last/avg alone are blind to exactly the tail behavior
    that matters at millions of users (a 2x p99 regression moves the
    average by noise). Every pre-existing camelCase wire key is
    preserved; the percentiles are additive."""

    _COUNTERS = (
        "shed",
        "deadline_expired",
        "retries",
        "feedback_sent",
        "feedback_failures",
        "feedback_skipped",
        "error_log_failures",
        "error_log_skipped",
    )

    def __init__(self, metrics: Optional[MetricsRegistry] = None):
        # standalone construction (tests, loadgen) gets a private
        # registry; servers pass theirs so /metrics sees the same series
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        self._hist = self.metrics.histogram(
            "pio_serving_request_seconds",
            "End-to-end /queries.json latency",
        )
        self._events = self.metrics.counter(
            "pio_serving_events_total",
            "Serving resilience outcomes",
            labelnames=("kind",),
        )
        self._lock = threading.Lock()
        self.request_count = 0
        self.last_serving_sec = 0.0
        self.avg_serving_sec = 0.0
        for name in self._COUNTERS:
            setattr(self, name, 0)

    def record_request(self, elapsed_s: float) -> None:
        with self._lock:
            self.last_serving_sec = elapsed_s
            self.avg_serving_sec = (
                self.avg_serving_sec * self.request_count + elapsed_s
            ) / (self.request_count + 1)
            self.request_count += 1
        self._hist.observe(elapsed_s)

    def inc(self, counter: str) -> None:
        if counter not in self._COUNTERS:
            raise ValueError(f"unknown serving counter {counter!r}")
        with self._lock:
            setattr(self, counter, getattr(self, counter) + 1)
        self._events.inc(1, kind=counter)  # kind is a closed set: safe label

    def percentile_ms(self, q: float) -> float:
        return round(self._hist.percentile(q) * 1000.0, 3)

    def snapshot(self) -> dict:
        with self._lock:
            out = {
                "requests": self.request_count,
                "lastServingMs": round(self.last_serving_sec * 1000, 3),
                "avgServingMs": round(self.avg_serving_sec * 1000, 3),
            }
            for name in self._COUNTERS:
                # camelCase the wire names to match the rest of the API
                parts = name.split("_")
                key = parts[0] + "".join(p.title() for p in parts[1:])
                out[key] = getattr(self, name)
        # histogram-estimated tail latency (outside the lock: the
        # histogram has its own)
        out["p50Ms"] = self.percentile_ms(0.50)
        out["p95Ms"] = self.percentile_ms(0.95)
        out["p99Ms"] = self.percentile_ms(0.99)
        return out


# ---------------------------------------------------------------------------
# Deployment state (what MasterActor rebuilds on reload)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Deployment:
    """One live engine instance: algorithms + in-memory (HBM) models +
    serving combiner (``createServerActorWithEngine``,
    ``CreateServer.scala:184-248``)."""

    instance: EngineInstance
    engine_params: EngineParams
    algorithms: List[Any]
    models: List[Any]
    serving: Any


def prepare_deployment(
    engine: Engine,
    registry: StorageRegistry,
    config: ServerConfig,
    ctx: Optional[WorkflowContext] = None,
) -> Deployment:
    """Load the target engine instance and make its models live
    (``CreateServer.scala:184-248`` + ``Engine.prepareDeploy``)."""
    md = registry.get_metadata()
    if config.engine_instance_id:
        instance = md.engine_instance_get(config.engine_instance_id)
        if instance is None:
            raise KeyError(
                f"Engine instance {config.engine_instance_id} not found"
            )
    else:
        # positional args: survives the metadata RPC wire ({method, args},
        # no kwargs channel) so deploy works on remote/HA storage
        instance = md.engine_instance_get_latest_completed(
            config.engine_id or "default",
            config.engine_version or "1",
            config.engine_variant,
        )
        if instance is None:
            raise RuntimeError(
                "No completed engine instance found; run train first "
                "(Console.scala:742-780)"
            )
    if instance.status != STATUS_COMPLETED:
        raise RuntimeError(
            f"Engine instance {instance.id} has status {instance.status}, "
            "not COMPLETED"
        )

    ctx = ctx or WorkflowContext(mode="Serving", batch=config.batch)
    engine_params = engine.engine_instance_to_engine_params(instance)
    persisted = load_models(registry, instance.id)
    live_models = engine.prepare_deploy(ctx, engine_params, instance.id, persisted)
    algorithms = engine._algorithms(engine_params)
    serving = engine._serving(engine_params)
    if config.shard_count > 1:
        live_models = _shard_models(algorithms, live_models, config)
    return Deployment(
        instance=instance,
        engine_params=engine_params,
        algorithms=algorithms,
        models=live_models,
        serving=serving,
    )


def _shard_models(
    algorithms: Sequence[Any], models: List[Any], config: ServerConfig
) -> List[Any]:
    """Replace each live model with its ``shard_index``-of-``shard_count``
    partition (docs/fleet.md). Every algorithm must opt in via a
    ``shard_model(model, shard_index, shard_count)`` method: a server
    that silently held the full catalog on a sharded fleet would make
    the router's merged top-k wrong (duplicated items), so a
    non-shardable algorithm fails the deploy, not the first query."""
    if not (0 <= config.shard_index < config.shard_count):
        raise ValueError(
            f"shard_index {config.shard_index} out of range for "
            f"shard_count {config.shard_count}"
        )
    sharded: List[Any] = []
    for algo, model in zip(algorithms, models):
        shard = getattr(algo, "shard_model", None)
        if shard is None:
            raise ValueError(
                f"{type(algo).__name__} does not implement shard_model; "
                "this engine cannot serve in sharded mode (docs/fleet.md)"
            )
        sharded.append(shard(model, config.shard_index, config.shard_count))
    return sharded


# ---------------------------------------------------------------------------
# HTTP server
# ---------------------------------------------------------------------------


class QueryDecodeError(ValueError):
    """Query JSON does not fit the engine's query shape → 400, matching the
    reference's MappingException handling (``CreateServer.scala:578-585``)."""


class _QueryHandler(JsonHTTPHandler):
    server: "QueryServer"

    #: every response of this server carries a variant label (closed
    #: {-, baseline, candidate} vocabulary; "-" = no rollout involved)
    #: so canary/shadow traffic is attributable on the shared
    #: ``pio_http_responses_total`` series (docs/rollouts.md)
    response_label_defaults = {"variant": "-"}

    def do_POST(self) -> None:  # noqa: N802
        self.response_labels = None  # handler instances persist per-connection
        raw = self.read_body()
        path = urlparse(self.path).path
        if path == "/queries.json":
            self._handle_queries(raw)
        elif path == "/reload":
            # reload is a state-changing op: POST is the proper verb
            # (GET kept below for CreateServer parity, deprecated —
            # docs/serving.md)
            self._handle_reload()
        elif path in ("/rollout/start", "/rollout/promote", "/rollout/abort"):
            self._handle_rollout(path, raw)
        elif path in (
            "/continuous/start",
            "/continuous/pause",
            "/continuous/trigger",
        ):
            self._handle_continuous(path, raw)
        else:
            self.respond(404, {"message": "Not Found"})

    def _handle_queries(self, raw: bytes) -> None:
        try:
            payload = json.loads(raw.decode("utf-8")) if raw else {}
        except ValueError as exc:
            self.respond(400, {"message": str(exc)})
            return
        # Bounded admission BEFORE any engine work: at the cap the
        # overload answer is an instant 503 + Retry-After, not another
        # handler thread piling onto a saturated device (the shed-don't-
        # queue discipline of the ads-serving paper in PAPERS.md).
        if not self.server.admit():
            self.server.stats.inc("shed")
            self.respond(
                503,
                {"message": "server overloaded; shedding load"},
                headers={"Retry-After": self.server.retry_after_s()},
            )
            return
        deadline = Deadline.from_header(
            self.headers.get(DEADLINE_HEADER), clock=self.server.clock
        )
        span = None
        # Mutable out-channel for the serving variant: handle_query fills
        # it, the admission span records it as a tag (the dict is read at
        # span close), and the response counter labels it.
        info: dict = {"variant": "-"}
        try:
            if deadline is not None:
                # admission-stage check: a budget that is already gone
                # spends zero decode/supplement work
                deadline.check("admission")
            # Admission span: joins the client's X-PIO-Trace id (or roots
            # a fresh trace) and becomes ambient for the request, so the
            # engine's supplement/serve storage calls and the batcher
            # spans all land in the same trace (docs/observability.md).
            with self.server.tracer.server_span(
                "POST /queries.json",
                header_value=self.headers.get(TRACE_HEADER),
                tags=info,
            ) as span:
                result, status = self.server.handle_query(
                    payload, deadline, info=info
                )
            self.response_labels = {"variant": info["variant"]}
            # VARIANT_HEADER echoes the serving variant to the client —
            # the router tier's fleet-consistency check compares it
            # against its own pure-function assignment (docs/fleet.md),
            # and a chaos drill can assert stickiness across a backend
            # kill without scraping metrics.
            self.respond(
                status,
                result,
                headers={
                    TRACE_HEADER: span.trace_id,
                    VARIANT_HEADER: info["variant"],
                },
            )
        except DeadlineExceeded as exc:
            self.response_labels = {"variant": info["variant"]}
            self.server.stats.inc("deadline_expired")
            self.respond(504, {"message": str(exc), "stage": exc.stage})
        except QueryDecodeError as exc:
            # the reference remote-logs the bad-query branch too
            # (CreateServer.scala:583-590)
            self.response_labels = {"variant": info["variant"]}
            self.server.post_error_log(str(exc), payload, trace_ctx=span)
            self.respond(400, {"message": str(exc)})
        except Exception as exc:
            logger.exception("Query failed")
            self.response_labels = {"variant": info["variant"]}
            self.server.post_error_log(str(exc), payload, trace_ctx=span)
            self.respond(500, {"message": str(exc)})
        finally:
            self.server.release()

    def _handle_reload(self) -> None:
        rollout = self.server.rollout
        if rollout is not None and rollout.active:
            self.respond(
                409,
                {
                    "message": (
                        f"rollout {rollout.plan.id} in progress "
                        f"(stage {rollout.stage}); promote or abort it "
                        "before reloading"
                    ),
                },
            )
            return
        try:
            self.server.reload()
            self.respond(200, {"message": "Reloaded"})
        except Exception as exc:
            logger.exception("Reload failed")
            self.respond(500, {"message": str(exc)})

    def _handle_rollout(self, path: str, raw: bytes) -> None:
        """``POST /rollout/start|promote|abort`` (docs/rollouts.md)."""
        rollout = self.server.rollout
        try:
            body = json.loads(raw.decode("utf-8")) if raw else {}
        except ValueError as exc:
            self.respond(400, {"message": str(exc)})
            return
        if not isinstance(body, dict):
            self.respond(400, {"message": "expected a JSON object body"})
            return
        try:
            if path == "/rollout/start":
                out = rollout.start(
                    candidate_instance_id=body.get("instanceId"),
                    percent=body.get("percent"),
                    gates=body.get("gates"),
                )
            elif path == "/rollout/promote":
                out = rollout.promote(body.get("reason", "manual promote"))
            else:
                out = rollout.abort(body.get("reason", "manual abort"))
            self.respond(200, out)
        except RolloutError as exc:
            self.respond(409, {"message": str(exc)})
        except ValueError as exc:  # e.g. an unknown gate option
            self.respond(400, {"message": str(exc)})
        except Exception as exc:
            logger.exception("rollout %s failed", path)
            self.respond(500, {"message": str(exc)})

    def _handle_continuous(self, path: str, raw: bytes) -> None:
        """``POST /continuous/start|pause|trigger`` (docs/continuous.md)."""
        continuous = self.server.continuous
        if continuous is None:
            self.respond(
                409,
                {
                    "message": (
                        "no continuous controller attached; deploy with "
                        "--continuous-app (docs/continuous.md)"
                    ),
                },
            )
            return
        try:
            body = json.loads(raw.decode("utf-8")) if raw else {}
        except ValueError as exc:
            self.respond(400, {"message": str(exc)})
            return
        if not isinstance(body, dict):
            self.respond(400, {"message": "expected a JSON object body"})
            return
        try:
            if path == "/continuous/start":
                continuous.start()
                out = continuous.status()
            elif path == "/continuous/pause":
                out = continuous.pause()
            else:
                out = continuous.trigger(full=bool(body.get("full")))
            self.respond(200, out)
        except Exception as exc:
            logger.exception("continuous %s failed", path)
            self.respond(500, {"message": str(exc)})

    def do_GET(self) -> None:  # noqa: N802
        self.response_labels = None  # handler instances persist per-connection
        path = urlparse(self.path).path
        if self.serve_obs(path):  # /metrics + /traces.json
            return
        if path == "/" or path == "/status.json":
            # content negotiation: browsers keep the HTML status page,
            # monitors GET /status.json (or Accept: application/json)
            # for the machine-readable twin with breaker states and
            # shed counters
            accept = self.headers.get("Accept", "")
            if path == "/status.json" or "application/json" in accept:
                self.respond(200, self.server.status_json())
            else:
                self.respond(
                    200, self.server.status_html(), content_type="text/html"
                )
        elif path == "/rollout.json":
            self.respond(200, self.server.rollout.status())
        elif path == "/shard.json":
            # shard metadata for the router tier / fleet tooling
            # (docs/fleet.md): which partition this server holds
            self.respond(200, self.server.shard_json())
        elif path == "/continuous.json":
            continuous = self.server.continuous
            if continuous is None:
                self.respond(200, {"enabled": False})
            else:
                self.respond(200, continuous.status())
        elif path == "/reload":
            # deprecated spelling (state change behind a GET), kept for
            # PredictionIO CreateServer parity — use POST /reload
            self._handle_reload()
        elif path == "/stop":
            self.respond(200, {"message": "Shutting down"})
            self.server.stop_async()
        else:
            self.respond(404, {"message": "Not Found"})


class QueryServer(BackgroundHTTPServer):
    """The serving process (``ServerActor`` + ``MasterActor``,
    ``CreateServer.scala:250-628``)."""

    def __init__(
        self,
        config: ServerConfig,
        engine: Engine,
        registry: StorageRegistry,
        deployment: Optional[Deployment] = None,
        ctx: Optional[WorkflowContext] = None,
        clock: Callable[[], float] = time.monotonic,
        retry_policy: Optional[RetryPolicy] = None,
        feedback_breaker: Optional[CircuitBreaker] = None,
        error_log_breaker: Optional[CircuitBreaker] = None,
        reload_breaker: Optional[CircuitBreaker] = None,
    ):
        self.config = config
        self.engine = engine
        self.registry = registry
        self.ctx = ctx or WorkflowContext(mode="Serving", batch=config.batch)
        self._deploy_lock = threading.RLock()
        self.deployment = deployment or prepare_deployment(
            engine, registry, config, self.ctx
        )
        # Resilience plumbing (docs/robustness.md). The clock and policy
        # objects are injectable so the whole fault suite runs without a
        # wall-clock sleep; defaults come from the PIO_BREAKER_* env.
        self.clock = clock
        # Observability plane (docs/observability.md): one registry +
        # tracer per server process, exposed on /metrics + /traces.json.
        metrics = MetricsRegistry(clock=clock)
        self.stats = ServingStats(metrics)
        # Quality-observability plane (docs/observability.md#quality):
        # per-variant served-score sketches (drift vs a baseline snapshot
        # pinned at model LIVE) and the feedback join the continuous
        # plane feeds — pio_quality_* on /metrics, `pio quality` reads
        # them fleet-wide.
        from ..obs.quality import QualityMonitor

        self.quality = QualityMonitor(
            metrics, clock=clock, config=config.quality
        )
        # Jit boundary telemetry (docs/observability.md#profiling): the
        # process telemetry mirrors onto this registry so /metrics shows
        # pio_jit_compiles_total / pio_jit_retraces_total — bind() replays
        # totals, so the deploy-time serving compiles that happened
        # before this registry existed are not lost.
        from ..obs.profile import default_telemetry

        default_telemetry().bind(metrics)
        default_telemetry().attach_monitoring()
        # Quantized-serving gate outcomes (docs/quantization.md#gate):
        # the quant module counts runs/refusals process-wide; callback
        # gauges export them so a refusal is a visible series on
        # /metrics, not just a stack trace in the deploy log.
        from ..quant import gate_counts

        metrics.gauge_callback(
            "pio_quant_gate_runs_total",
            lambda: gate_counts().get("runs", 0),
            "Quantized-serving exactness gate evaluations",
        )
        metrics.gauge_callback(
            "pio_quant_gate_refusals_total",
            lambda: gate_counts().get("refusals", 0),
            "Quantized-serving tables refused by the exactness gate",
        )
        self._retry = retry_policy or RetryPolicy(
            attempts=3,
            base_delay_s=0.05,
            max_delay_s=1.0,
            on_retry=lambda _i: self.stats.inc("retries"),
        )
        self.feedback_breaker = feedback_breaker or CircuitBreaker.from_env(
            "event-server", clock=clock
        )
        self.error_log_breaker = error_log_breaker or CircuitBreaker.from_env(
            "error-log", clock=clock
        )
        self.reload_breaker = reload_breaker or CircuitBreaker.from_env(
            "reload", clock=clock
        )
        if config.max_queue is not None:
            self._max_queue = config.max_queue
        else:
            self._max_queue = int(
                os.environ.get("PIO_SERVING_MAX_QUEUE", str(DEFAULT_MAX_QUEUE))
            )
        self._admission_lock = threading.Lock()
        self._inflight = 0
        # Bounded async feedback delivery (CreateServer's fire-and-forget
        # future, without unbounded thread growth under load).
        self._feedback_pool = ThreadPoolExecutor(
            max_workers=2, thread_name_prefix="feedback"
        )
        # Micro-batching prediction dispatch (see ServerConfig.batching).
        # The deployment travels WITH each queued item, so a /reload
        # mid-batch is safe: in-flight queries finish on the model they
        # arrived under.
        tracer = Tracer("query-server", clock=clock)
        self._batcher: Optional[MicroBatcher] = (
            MicroBatcher(
                self._predict_batch,
                max_batch=config.batch_max,
                max_wait_ms=config.batch_wait_ms,
                name="predict-batch",
                pipeline_depth=config.batch_pipeline_depth,
                metrics=metrics,
                tracer=tracer,
                clock=clock,
            )
            if config.batching
            else None
        )
        # Serving stats (CreateServer.scala:392-394,567-574 + resilience)
        self.server_start_time = utcnow()
        # breaker states + lifetime opens, pulled at scrape time
        for dep, breaker in (
            ("event-server", self.feedback_breaker),
            ("error-log", self.error_log_breaker),
            ("reload", self.reload_breaker),
        ):
            metrics.gauge_callback(
                "pio_breaker_state",
                (lambda b=breaker: b.state_value),
                "Breaker state (0 closed, 1 half-open, 2 open)",
                labels={"dep": dep},
            )
            # monotonic, but exposed as a gauge (the callback pull
            # model) — so no `_total` suffix, like pio_changefeed_seq
            metrics.gauge_callback(
                "pio_breaker_opens",
                (lambda b=breaker: b.open_count),
                "Lifetime breaker open transitions",
                labels={"dep": dep},
            )
        # Observer-fault accounting (docs/slo.md): every swallowed
        # observer/monitor exception is COUNTED, never just debug-logged
        # — a quality monitor that starts throwing on every query is
        # invisible in logs and a flat line on this counter is the
        # proof the observers are healthy (the obs-swallowed-observer
        # lint rule pins the pattern).
        self._observer_errors = metrics.counter(
            "pio_observer_errors_total",
            "Swallowed observer/monitor exceptions by site",
            labelnames=("site",),
        )
        super().__init__(
            (config.ip, config.port),
            _QueryHandler,
            metrics=metrics,
            tracer=tracer,
            health_kind="query",
            health_config=config.health,
        )
        self._export_train_phases()
        # Rollout plane (docs/rollouts.md): the manager owns any staged
        # deploy of this engine. resume() re-resolves an active plan
        # from metadata, so a server restarted mid-canary keeps the
        # exact same sticky split; a broken plan degrades to plain
        # baseline serving, never a failed boot.
        self.rollout = RolloutManager(self)
        try:
            self.rollout.resume()
        except Exception:
            logger.exception(
                "rollout resume failed; serving the baseline only"
            )
        # Continuous-learning plane (docs/continuous.md): the controller
        # resumes its durable cursor and any in-flight candidate on
        # construction; a broken loop degrades to plain serving, never a
        # failed boot (the loop is an optimization, the server is not).
        self.continuous = None
        if config.continuous is not None:
            try:
                from ..continuous.controller import ContinuousController

                self.continuous = ContinuousController(self, config.continuous)
                if config.continuous.autostart:
                    self.continuous.start()
            except Exception:
                self.continuous = None
                logger.exception(
                    "continuous controller failed to attach; serving "
                    "without the continuous-learning loop"
                )

    # Pre-resilience attribute surface, kept for callers/tests that read
    # the counters straight off the server object.
    @property
    def request_count(self) -> int:
        return self.stats.request_count

    @property
    def last_serving_sec(self) -> float:
        return self.stats.last_serving_sec

    @property
    def avg_serving_sec(self) -> float:
        return self.stats.avg_serving_sec

    # -- admission (bounded queue → shed, never pile up) -------------------
    def admit(self) -> bool:
        if self._max_queue <= 0:  # 0 = unbounded (explicit opt-out)
            return True
        with self._admission_lock:
            if self._inflight >= self._max_queue:
                return False
            self._inflight += 1
            return True

    def release(self) -> None:
        if self._max_queue <= 0:
            return
        with self._admission_lock:
            self._inflight = max(0, self._inflight - 1)

    def retry_after_s(self) -> int:
        """Retry-After for a shed request: one worst-case batch drain,
        floored at 1 s (the resolution HTTP gives us)."""
        drain = self.stats.avg_serving_sec * 2
        return max(1, int(drain + 0.999))

    @property
    def degraded(self) -> bool:
        """True while any dependency breaker is not closed — the server
        still answers (from the HBM-resident last-good model), but a
        fleet monitor should know the feedback/reload plane is impaired."""
        return any(
            b.state != CircuitBreaker.CLOSED
            for b in (
                self.feedback_breaker,
                self.error_log_breaker,
                self.reload_breaker,
            )
        )

    # -- query path (CreateServer.scala:458-577) --------------------------
    def handle_query(
        self,
        payload: Any,
        deadline: Optional[Deadline] = None,
        info: Optional[dict] = None,
    ) -> Tuple[Any, int]:
        """One query end to end. ``info`` (when given) is filled with the
        serving ``variant`` (and ``fallback`` on candidate containment)
        — the handler forwards it into span tags and response labels."""
        # Stall watchdog (docs/slo.md): every in-flight request is
        # tracked with its deadline budget — a request still running at
        # a multiple of that budget is a wedge the watchdog dumps
        # forensics for, whether or not the client is still waiting.
        watchdog = self.health.watchdog if self.health is not None else None
        token = (
            watchdog.enter(
                "serving.request",
                budget_s=(
                    deadline.remaining_s() if deadline is not None else None
                ),
            )
            if watchdog is not None
            else None
        )
        try:
            return self._handle_query_tracked(payload, deadline, info)
        finally:
            if watchdog is not None:
                watchdog.exit(token)

    def _handle_query_tracked(
        self,
        payload: Any,
        deadline: Optional[Deadline] = None,
        info: Optional[dict] = None,
    ) -> Tuple[Any, int]:
        started = time.monotonic()
        query_time = utcnow()
        rollout = self.rollout
        if rollout is not None:
            # land any transition whose metadata write failed — terminal
            # transitions have no later observe() to ride
            rollout.retry_pending_persist()
        rollout_active = rollout is not None and rollout.active
        variant = BASELINE
        variant_started = started
        dep = None
        if rollout_active:
            # Deterministic sticky split (docs/rollouts.md): CANARY
            # routes the plan's percent of entity keys to the candidate;
            # SHADOW always serves baseline (the duplicate is async).
            variant = rollout.variant_for(payload)
            if variant == CANDIDATE:
                dep = rollout.candidate_deployment()
                if dep is None:  # rollback won a race: serve baseline
                    variant = BASELINE
        if dep is None:
            with self._deploy_lock:
                dep = self.deployment
        if info is not None and rollout_active:
            info["variant"] = variant
        try:
            query, prediction = self._serve_one(dep, payload, deadline, variant)
        except DeadlineExceeded as exc:
            # An exhausted budget cannot be re-served from the baseline,
            # but a serving variant that burns client deadlines must feed
            # its error window, or a too-slow canary never rolls back.
            # Only the batch-wait stage is the variant's doing — a budget
            # already gone at admission/dispatch is the client's. Both
            # variants record, so the delta gate stays a *delta*.
            if rollout_active and exc.stage == "batch-wait":
                rollout.observe(variant, time.monotonic() - started, ok=False)
            raise
        except Exception:
            if variant != CANDIDATE:
                # Baseline failures count too: errors the whole fleet is
                # suffering (shared dependency down, malformed client
                # traffic) must raise BOTH windows' error rates, or the
                # delta gate degenerates into an absolute candidate
                # threshold and rolls back a healthy canary.
                if rollout_active:
                    rollout.observe(
                        BASELINE, time.monotonic() - started, ok=False
                    )
                raise
            # Canary containment: a sick candidate is a *rollout* signal
            # (counted against its error gate), never a client error —
            # the same request is re-served from the resident baseline.
            # QueryDecodeError included: a query the candidate's
            # algorithms cannot decode is a candidate defect.
            rollout.observe(CANDIDATE, time.monotonic() - started, ok=False)
            logger.exception(
                "candidate %s failed; serving baseline", dep.instance.id
            )
            variant = BASELINE
            variant_started = time.monotonic()  # gate windows see only
            # the baseline's own work, not the failed candidate attempt
            if info is not None:
                info["variant"] = variant
                info["fallback"] = True
            with self._deploy_lock:
                dep = self.deployment
            try:
                query, prediction = self._serve_one(
                    dep, payload, deadline, variant
                )
            except Exception:
                if rollout_active:  # the fallback itself failed: baseline's
                    rollout.observe(
                        BASELINE, time.monotonic() - variant_started, ok=False
                    )
                raise
        result = encode_result(prediction)

        # Quality plane: score distribution + the served-list record the
        # feedback join reads. BEFORE the prId stamp, like the shadow
        # duplicate — the signals describe the model's answer. Swallowed
        # on error but COUNTED (docs/slo.md): observability must never
        # fail a query, and a failing observer must never be invisible.
        try:
            self.quality.observe_result(variant, payload, result)
        except Exception:
            self._observer_errors.inc(1, site="serving.quality")
            logger.debug("quality observe failed", exc_info=True)

        # Shadow duplication BEFORE the feedback prId stamp: divergence
        # must compare model outputs, not the per-request id noise.
        if rollout_active and rollout.stage == ROLLOUT_SHADOW:
            rollout.submit_shadow(payload, result)

        if self.config.feedback:
            result = self._send_feedback(
                dep, query_time, query, prediction, result, variant
            )

        now = time.monotonic()
        if rollout_active:
            rollout.observe(variant, now - variant_started, ok=True)
        self.stats.record_request(now - started)
        return result, 200

    def _serve_one(
        self,
        dep: Deployment,
        payload: Any,
        deadline: Optional[Deadline],
        variant: str,
    ) -> Tuple[Any, Any]:
        """Decode → supplement → (batched) predict → combine against ONE
        deployment; the shared path under the live request, the canary
        fallback retry, and a shadow duplicate. Returns
        ``(query, prediction)``."""
        with deadline_scope(deadline):
            try:
                query = decode_query(dep.algorithms, payload)
            except (TypeError, AttributeError, KeyError) as exc:
                raise QueryDecodeError(f"Invalid query: {exc}") from exc
            query = dep.serving.supplement(query)
            if deadline is not None:
                # the load-shed moment that matters most: an expired query
                # must never occupy a device slot (ISSUE 2 tentpole)
                deadline.check("dispatch")
            # chaos hook (docs/slo.md): the loadgen --brownout scenario
            # wedges the predict path here — fault-injected latency and
            # refusals, not a kill — proving the stall watchdog and the
            # SLO burn alerts on a backend that is sick, not dead
            fault_point("serving.predict", instance=dep.instance.id)
            if variant == CANDIDATE:
                # chaos hook: the loadgen --rollout scenario fails the
                # candidate exactly here, proving auto-rollback with
                # zero client-visible failures (docs/rollouts.md)
                fault_point("serving.candidate", instance=dep.instance.id)
            if self._batcher is not None:
                try:
                    predictions = self._batcher.submit(
                        (dep, query),
                        timeout=(
                            deadline.remaining_s()
                            if deadline is not None
                            else None
                        ),
                    )
                except FutureTimeoutError:
                    raise DeadlineExceeded(
                        "deadline exceeded waiting for batched dispatch",
                        stage="batch-wait",
                    ) from None
            else:
                predictions = self._predict_one(dep, query)
            prediction = dep.serving.serve(query, predictions)
        return query, prediction

    def _post_json(
        self,
        site: str,
        url: str,
        data: Any,
        trace_ctx: Optional[SpanContext] = None,
    ) -> None:
        """One retried JSON POST to a sink (the shared delivery path of
        the feedback and error-log planes). Raises on final failure so
        the caller's breaker records ONE failure per logical delivery,
        not one per attempt. Retrying a *write* is safe here because
        both sinks dedupe: feedback events carry an ``idempotencyKey``
        and the error log is an append-only diagnostic stream.

        ``trace_ctx`` is the originating request's span context, captured
        *before* the hop onto the feedback pool thread (contextvars do
        not follow): the delivery records a child span and forwards the
        trace id so the Event Server's spans join the same trace."""
        headers = {}
        if trace_ctx is not None:
            headers[TRACE_HEADER] = trace_ctx.trace_id

        def attempt() -> None:
            fault_point(site, url=url)
            resp = requests.post(url, json=data, timeout=10, headers=headers)
            if resp.status_code not in (200, 201):
                raise RuntimeError(
                    f"{site} POST -> HTTP {resp.status_code}"
                )

        if trace_ctx is None:
            self._retry.call(attempt)
            return
        with self.tracer.span(site, parent=trace_ctx):
            self._retry.call(attempt)

    def post_error_log(
        self,
        message: str,
        payload: Any,
        trace_ctx: Optional[SpanContext] = None,
    ) -> None:
        """Fire-and-forget POST of a serving failure to ``log_url``
        (``CreateServer.scala:409-420`` — remote error reporting for
        fleet-monitored deployments). Rides the bounded feedback pool so
        an error storm against a slow sink cannot spawn unbounded
        threads, and never adds a failure of its own to the request; a
        dead sink trips ``error_log_breaker`` so the storm stops paying
        connect timeouts entirely."""
        url = self.config.log_url
        if not url:
            return
        # engine-instance identity so a shared fleet sink can attribute
        # the error (the reference posts {engineInstance, message},
        # CreateServer.scala:412-414)
        try:
            instance_id = self.deployment.instance.id
        except Exception:
            instance_id = None
        data = {
            "engineInstance": instance_id,
            "message": message,
            "query": payload,
        }
        if trace_ctx is None:
            trace_ctx = current_context()  # captured before the thread hop

        def send() -> None:
            try:
                self.error_log_breaker.call(
                    self._post_json, "serving.error_log", url, data,
                    trace_ctx=trace_ctx,
                )
            except CircuitOpen:
                self.stats.inc("error_log_skipped")
            except Exception:
                self.stats.inc("error_log_failures")
                logger.debug("error-log POST to %s failed", url, exc_info=True)

        try:
            self._feedback_pool.submit(send)
        except RuntimeError:
            # pool already shut down (/stop racing an in-flight failure):
            # the log post is best-effort; the response must still go out
            logger.debug("error-log skipped: feedback pool closed")

    @staticmethod
    def _predict_one(dep: Deployment, query: Any) -> List[Any]:
        """Unbatched per-query path (the reference's per-request
        ``predictBase`` loop, ``CreateServer.scala:479-485``)."""
        return [
            algo.predict(model, query)
            for algo, model in zip(dep.algorithms, dep.models)
        ]

    @staticmethod
    def _predict_batch(items: Sequence[Tuple[Deployment, Any]]) -> List[List[Any]]:
        """Batched prediction for micro-batched items ``(deployment,
        query)`` → per-item list of per-algorithm predictions.

        Queries are grouped by deployment (a reload mid-batch may leave
        two generations in one batch); within a group, each algorithm gets
        ONE ``batch_predict(model, [(idx, query)])`` call for the whole
        group — a single gather-dot top-k device dispatch for the TPU
        algorithms; the base-class default maps ``predict`` for the rest."""
        out: List[Any] = [None] * len(items)
        groups: dict = {}
        for pos, (dep, query) in enumerate(items):
            groups.setdefault(id(dep), (dep, []))[1].append((pos, query))
        for dep, indexed in groups.values():
            try:
                per_algo: List[dict] = []
                for algo, model in zip(dep.algorithms, dep.models):
                    per_algo.append(dict(algo.batch_predict(model, indexed)))
                for pos, _query in indexed:
                    out[pos] = [results[pos] for results in per_algo]
            except Exception:
                # Poison-query containment: one bad query must not 500 the
                # whole batch. Retry the group per-query; only the queries
                # that actually fail carry their exception (MicroBatcher's
                # per-item failure channel).
                for pos, query in indexed:
                    try:
                        out[pos] = QueryServer._predict_one(dep, query)
                    except Exception as exc:
                        out[pos] = exc
        return out  # every position was covered by exactly one group

    def _send_feedback(
        self,
        dep: Deployment,
        query_time: _dt.datetime,
        query: Any,
        prediction: Any,
        result: Any,
        variant: str = BASELINE,
    ) -> Any:
        """Async ``predict`` event to the Event Server
        (``CreateServer.scala:505-565``). The event carries the serving
        ``variant`` so offline evaluation can score canary vs. baseline
        straight from the event store (docs/rollouts.md)."""
        existing = _get_pr_id(prediction)
        new_pr_id = existing if existing else _gen_pr_id()
        data = {
            "event": "predict",
            "eventTime": query_time.isoformat(timespec="milliseconds"),
            "entityType": "pio_pr",
            "entityId": new_pr_id,
            "properties": {
                "engineInstanceId": dep.instance.id,
                "query": encode_result(query),
                "prediction": encode_result(prediction),
                "variant": variant,
            },
            # prId is unique per prediction, so it doubles as the event's
            # idempotency key: the RetryPolicy may replay this POST after
            # an ambiguous failure and the Event Server still inserts
            # exactly one event (docs/robustness.md).
            "idempotencyKey": new_pr_id,
        }
        query_pr_id = _get_pr_id(query)
        if query_pr_id is not None:
            data["prId"] = query_pr_id

        url = (
            f"http://{self.config.event_server_ip}:"
            f"{self.config.event_server_port}/events.json"
            f"?accessKey={self.config.access_key or ''}"
        )

        self._feedback_pool.submit(
            self._deliver_feedback, url, data, current_context()
        )

        # Stamp the generated prId into the response only for predictions
        # that carry a prId slot (CreateServer.scala:558-565).
        if _has_pr_id(prediction) and isinstance(result, dict):
            result = dict(result)
            result.pop("pr_id", None)  # replace the stale slot, don't duplicate
            result["prId"] = new_pr_id
        return result

    def _deliver_feedback(
        self,
        url: str,
        data: dict,
        trace_ctx: Optional[SpanContext] = None,
    ) -> None:
        """Breaker-guarded, retried feedback delivery (pool thread).

        While the Event Server is down the breaker opens after
        ``failure_threshold`` deliveries and subsequent feedback is
        *skipped* (counted, not attempted): queries keep serving from the
        resident model at full speed instead of each paying a connect
        timeout — the degraded mode ``GET /`` surfaces."""
        try:
            self.feedback_breaker.call(
                self._post_json, "serving.feedback", url, data,
                trace_ctx=trace_ctx,
            )
            self.stats.inc("feedback_sent")
        except CircuitOpen:
            self.stats.inc("feedback_skipped")
        except Exception as exc:
            self.stats.inc("feedback_failures")
            logger.error("Feedback event failed: %s", exc)

    # -- lifecycle --------------------------------------------------------
    def server_close(self) -> None:
        if self._batcher is not None:
            self._batcher.close()  # fail queued requests fast, join thread
        self._feedback_pool.shutdown(wait=False)
        if getattr(self, "continuous", None) is not None:
            self.continuous.stop()
        if getattr(self, "rollout", None) is not None:
            self.rollout.close()
        super().server_close()

    def _adopt_deployment(self, dep: Deployment) -> None:
        """Install ``dep`` as THE serving deployment (rollout go-live,
        docs/rollouts.md). The retired deployment's last server-side
        reference dies with the swap, so its model buffers are
        reclaimable; in-flight queries finish on the deployment they
        were routed to (they hold their own reference through the
        micro-batch items)."""
        with self._deploy_lock:
            old = self.deployment.instance.id
            self.deployment = dep
        self._export_train_phases()
        # re-pin the quality baseline: drift must be measured against the
        # distribution of the model NOW serving, not its predecessor's
        # (the closing state persists as a snapshot first)
        try:
            self.quality.model_live(dep.instance.id)
        except Exception:
            self._observer_errors.inc(1, site="serving.quality")
            logger.debug("quality re-pin failed", exc_info=True)
        flight_record(
            "deploy", "serving.adopt",
            fromInstance=old, toInstance=dep.instance.id,
        )
        logger.info(
            "Deployment swapped: engine instance %s -> %s",
            old, dep.instance.id,
        )

    def reload(self) -> None:
        """Hot-swap to the latest completed instance
        (``CreateServer.scala:300-321``): the new tables are staged first,
        then the references swap under the lock.

        Refused while a rollout is in flight: the latest completed
        instance IS the rollout's candidate, and loading it as the
        baseline would corrupt the split — promote or abort instead
        (docs/rollouts.md).

        Failures (storage down, corrupt instance) ride
        ``reload_breaker``: the resident last-good tables keep serving
        (degradation is nearly free — they never left HBM), repeated
        failures open the breaker so reload storms fast-fail, and the
        status page shows ``degraded: true`` until a probe reload
        succeeds."""
        rollout = getattr(self, "rollout", None)
        if rollout is not None and rollout.active:
            raise RuntimeError(
                f"rollout {rollout.plan.id} in progress (stage "
                f"{rollout.stage}); promote or abort it before reloading"
            )
        cfg = dataclasses.replace(
            self.config,
            engine_instance_id=None,
            engine_id=self.deployment.instance.engine_id,
            engine_version=self.deployment.instance.engine_version,
            engine_variant=self.deployment.instance.engine_variant,
        )
        fresh = self.reload_breaker.call(
            prepare_deployment, self.engine, self.registry, cfg, self.ctx
        )
        with self._deploy_lock:
            old = self.deployment.instance.id
            self.deployment = fresh
        self._export_train_phases()
        # a reload is a model go-live too: re-pin the drift baseline
        try:
            self.quality.model_live(fresh.instance.id)
        except Exception:
            self._observer_errors.inc(1, site="serving.quality")
            logger.debug("quality re-pin failed", exc_info=True)
        flight_record(
            "deploy", "serving.reload",
            fromInstance=old, toInstance=fresh.instance.id,
        )
        logger.info(
            "Reloaded: engine instance %s -> %s", old, fresh.instance.id
        )

    def _export_train_phases(self) -> None:
        """Re-export the deployed instance's persisted training phase
        timings as gauges (``pio top`` reads them off ``/metrics``).
        Phase names are read/prepare/train[i] — bounded by algo count.
        The previous export is cleared first: after a ``/reload`` the
        series must describe the instance actually deployed, not linger
        from the one it replaced (including when the new record carries
        no phases at all)."""
        from ..utils.profiling import phases_from_env

        phases = phases_from_env(self.deployment.instance.env)
        gauge = self.metrics.gauge(
            "pio_train_phase_seconds",
            "Wall-clock of each training phase of the deployed instance",
            labelnames=("phase",),
        )
        gauge.clear()
        for name, seconds in phases.items():
            gauge.set(seconds, phase=name)

    def shard_json(self) -> dict:
        """``GET /shard.json``: which item-factor partition this server
        holds (docs/fleet.md). ``items`` counts rows per model where the
        model exposes an ``item_factors`` table (the recommender
        templates); other models report None — the route is metadata,
        not a capability probe."""
        with self._deploy_lock:
            dep = self.deployment
        return {
            "sharded": self.config.shard_count > 1,
            "shardIndex": self.config.shard_index,
            "shardCount": self.config.shard_count,
            "engineInstance": dep.instance.id,
            "models": [
                {
                    "type": type(m).__name__,
                    "items": (
                        len(m.item_factors)
                        if getattr(m, "item_factors", None) is not None
                        else None
                    ),
                }
                for m in dep.models
            ],
        }

    # -- status page (CreateServer.scala:421-456) -------------------------
    def status_json(self) -> dict:
        """Machine-readable status: the HTML page's facts plus breaker
        states, shed/deadline counters and the degraded flag (``GET
        /status.json``, or ``GET /`` with ``Accept: application/json``)."""
        dep = self.deployment
        out = {
            "status": "degraded" if self.degraded else "alive",
            "degraded": self.degraded,
            "engineInstance": dep.instance.id,
            "engine": {
                "id": dep.instance.engine_id,
                "version": dep.instance.engine_version,
                "factory": dep.instance.engine_factory,
            },
            "startTime": str(self.server_start_time),
            "feedback": self.config.feedback,
            "maxQueue": self._max_queue,
            "stats": self.stats.snapshot(),
            "breakers": {
                "eventServer": self.feedback_breaker.snapshot(),
                "errorLog": self.error_log_breaker.snapshot(),
                "reload": self.reload_breaker.snapshot(),
            },
        }
        if self.config.shard_count > 1:
            out["shard"] = {
                "index": self.config.shard_index,
                "count": self.config.shard_count,
            }
        # resolved serving top-k path per algorithm ("streaming" = the
        # fused device-resident Pallas kernel, "dense" = XLA score +
        # lax.top_k; None until the first query) — the serve-side lever
        # record, matching the train side's resolved-flag discipline
        # (docs/performance.md#levers)
        topk = {
            f"{idx}:{type(algo).__name__}": algo.topk_path
            for idx, algo in enumerate(dep.algorithms)
            if getattr(algo, "topk_path", None) is not None
        }
        if topk:
            out["topkPath"] = topk
        # quantized-serving gate status per algorithm (table dtype,
        # bytes, compression ratio, gate matchRate — set at model
        # attach, docs/quantization.md): present only while the
        # quantized_serving lever is resolved ON, same shape the
        # profile dicts carry
        quant = {
            f"{idx}:{type(algo).__name__}": algo.quant_status
            for idx, algo in enumerate(dep.algorithms)
            if getattr(algo, "quant_status", None) is not None
        }
        if quant:
            from ..quant import gate_counts

            out["quantServing"] = quant
            out["quantGate"] = gate_counts()
        if self._batcher is not None:
            out["batching"] = self._batcher.stats
        if getattr(self, "quality", None) is not None:
            out["quality"] = self.quality.summary()
        if getattr(self, "rollout", None) is not None:
            out["rollout"] = self.rollout.status()
        if getattr(self, "continuous", None) is not None:
            out["continuous"] = self.continuous.status()
        from ..utils.profiling import phases_from_env

        phases = phases_from_env(dep.instance.env)
        if phases:
            out["trainPhases"] = phases
        return out

    def status_html(self) -> str:
        dep = self.deployment
        stats = self.stats.snapshot()
        rows = [
            ("Engine instance", dep.instance.id),
            ("Engine", f"{dep.instance.engine_id} {dep.instance.engine_version}"),
            ("Engine factory", dep.instance.engine_factory),
            ("Start time", str(self.server_start_time)),
            ("Algorithms", ", ".join(type(a).__name__ for a in dep.algorithms)),
            ("Models", ", ".join(type(m).__name__ for m in dep.models)),
            ("Serving", type(dep.serving).__name__),
            ("Feedback enabled", str(self.config.feedback)),
            ("Request count", str(stats["requests"])),
            ("Average serving time", f"{stats['avgServingMs']:.3f} ms"),
            ("Last serving time", f"{stats['lastServingMs']:.3f} ms"),
            ("Degraded", str(self.degraded)),
            (
                "Rollout",
                (
                    f"{self.rollout.plan.id} stage={self.rollout.stage}"
                    if getattr(self, "rollout", None) is not None
                    and self.rollout.plan is not None
                    else "none"
                ),
            ),
            ("Shed requests", str(stats["shed"])),
            ("Expired deadlines", str(stats["deadlineExpired"])),
            (
                "Breakers",
                ", ".join(
                    f"{name}={b.state}"
                    for name, b in (
                        ("event-server", self.feedback_breaker),
                        ("error-log", self.error_log_breaker),
                        ("reload", self.reload_breaker),
                    )
                ),
            ),
        ]
        if self._batcher is not None:
            bs = self._batcher.stats
            rows.append(
                (
                    "Micro-batching",
                    f"{bs['batches']} batches, "
                    f"avg {bs['avg_batch']:.1f} queries/batch",
                )
            )
        cells = "".join(
            f"<tr><th>{html.escape(k)}</th><td>{html.escape(v)}</td></tr>"
            for k, v in rows
        )
        return (
            "<!DOCTYPE html><html><head><title>"
            f"{html.escape(dep.instance.engine_id)} - predictionio_tpu engine "
            "server</title></head><body>"
            "<h1>PredictionIO-TPU Engine Server</h1>"
            f"<table>{cells}</table>"
            "<p>POST JSON queries to <code>/queries.json</code>; "
            "<a href=\"/reload\">reload</a> latest model.</p>"
            "</body></html>"
        )


def create_query_server(
    engine: Engine,
    config: ServerConfig = ServerConfig(),
    registry: Optional[StorageRegistry] = None,
    block: bool = True,
) -> QueryServer:
    """Deploy an engine (``CreateServer.main``, ``CreateServer.scala:100-182``)."""
    from ..storage.registry import get_registry
    from .version_check import check_upgrade

    check_upgrade("deployment", type(engine).__name__)  # CreateServer.scala:246
    registry = registry or get_registry()
    server = QueryServer(config, engine, registry)
    logger.info(
        "Query server: engine instance %s on %s:%d",
        server.deployment.instance.id,
        config.ip,
        server.bound_port,
    )
    if block:
        try:
            server.serve_forever()
        finally:
            server.server_close()
    else:
        server.start_background()
    return server
