"""Step-level checkpoint/resume for training runs.

The reference checkpoints only whole trained models (Kryo blob per
EngineInstance, ``CoreWorkflow.scala:71-73``) — a crash mid-ALS means
retraining from scratch (SURVEY §5 "Checkpoint / resume"). Here training
loops save their state pytree every N steps and resume from the newest
valid step: strictly better, same external API.

Format: one directory per step (``step_<n>/``) holding an ``arrays.npz``
with '/'-joined pytree paths as keys, a ``meta.json`` with user metadata,
and a ``_COMPLETE`` marker written last — a checkpoint without the marker
(crash mid-save) is ignored and cleaned up on the next save. No dependency
on checkpoint-library APIs; any pytree of numpy/jax arrays round-trips.
"""

from __future__ import annotations

import io
import json
import os
import re
import shutil
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..utils.durability import atomic_write_bytes, fsync_dir as _fsync_dir

_STEP_RE = re.compile(r"^step_(\d+)$")
_SEP = "/"


def _flatten(tree: Any, prefix: str = "") -> Dict[str, np.ndarray]:
    """Pytree (nested dict/list/tuple of arrays) → {path: array}."""
    out: Dict[str, np.ndarray] = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            key = str(k)
            if _SEP in key:
                raise ValueError(f"checkpoint dict keys may not contain '/': {key!r}")
            out.update(_flatten(v, f"{prefix}{key}{_SEP}"))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            out.update(_flatten(v, f"{prefix}{i}{_SEP}"))
    else:
        out[prefix.rstrip(_SEP)] = np.asarray(tree)
    return out


def _unflatten_into(like: Any, flat: Dict[str, np.ndarray], prefix: str = "") -> Any:
    """Rebuild ``like``'s structure with arrays from ``flat``."""
    if isinstance(like, dict):
        return {
            k: _unflatten_into(v, flat, f"{prefix}{k}{_SEP}")
            for k, v in like.items()
        }
    if isinstance(like, tuple):
        return tuple(
            _unflatten_into(v, flat, f"{prefix}{i}{_SEP}")
            for i, v in enumerate(like)
        )
    if isinstance(like, list):
        return [
            _unflatten_into(v, flat, f"{prefix}{i}{_SEP}")
            for i, v in enumerate(like)
        ]
    key = prefix.rstrip(_SEP)
    if key not in flat:
        raise KeyError(f"checkpoint missing array {key!r}")
    return flat[key]


class CheckpointManager:
    """Save/restore/prune step checkpoints under one run directory.

    Retention: ``keep_last=N`` prunes all but the newest N *complete*
    steps after each successful save, so long runs cannot fill the disk;
    ``None`` (the default) keeps everything. ``keep`` is the historical
    alias for the same knob."""

    def __init__(
        self,
        directory: str,
        keep: Optional[int] = None,
        keep_last: Optional[int] = None,
    ):
        self.directory = directory
        self.keep = keep_last if keep_last is not None else keep
        os.makedirs(directory, exist_ok=True)

    # -- introspection ----------------------------------------------------
    def all_steps(self) -> List[int]:
        steps = []
        for name in os.listdir(self.directory):
            m = _STEP_RE.match(name)
            if m and os.path.exists(
                os.path.join(self.directory, name, "_COMPLETE")
            ):
                steps.append(int(m.group(1)))
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step}")

    # -- save -------------------------------------------------------------
    def save(self, step: int, tree: Any, metadata: Optional[dict] = None) -> None:
        d = self._step_dir(step)
        if os.path.exists(d):
            shutil.rmtree(d)  # replace an incomplete/old attempt
        os.makedirs(d)
        flat = _flatten(tree)
        # Durability ordering: every data file commits atomically
        # (tmp + fsync + rename, utils/durability.atomic_write_bytes)
        # BEFORE the _COMPLETE marker, or a power loss can leave a
        # durable marker pointing at garbage.
        buf = io.BytesIO()
        np.savez(buf, **flat)
        atomic_write_bytes(os.path.join(d, "arrays.npz"), buf.getvalue())
        atomic_write_bytes(
            os.path.join(d, "meta.json"),
            json.dumps(metadata or {}).encode("utf-8"),
        )
        with open(os.path.join(d, "_COMPLETE"), "w") as f:
            f.write("ok")
            f.flush()
            os.fsync(f.fileno())
        _fsync_dir(d)
        # the step_N dirent itself lives in the parent directory
        _fsync_dir(self.directory)
        self._prune()

    def _prune(self) -> None:
        steps = self.all_steps()
        doomed = (
            steps[: max(0, len(steps) - self.keep)]
            if self.keep is not None
            else []
        )
        for s in doomed:
            # Crash-safe deletion order: drop the _COMPLETE marker first
            # (and make the drop durable) so a crash mid-rmtree can never
            # leave a half-deleted directory that still LOOKS complete —
            # restore(step) on it would load garbage. Without the marker
            # the leftovers are just an incomplete dir, swept below on
            # the next save.
            d = self._step_dir(s)
            try:
                os.remove(os.path.join(d, "_COMPLETE"))
            except OSError:
                pass
            _fsync_dir(d)
            shutil.rmtree(d, ignore_errors=True)
        # drop incomplete directories (crashed saves)
        for name in os.listdir(self.directory):
            m = _STEP_RE.match(name)
            if m and not os.path.exists(
                os.path.join(self.directory, name, "_COMPLETE")
            ):
                if int(m.group(1)) not in steps:
                    shutil.rmtree(
                        os.path.join(self.directory, name), ignore_errors=True
                    )

    # -- restore ----------------------------------------------------------
    def restore(
        self, step: Optional[int] = None, like: Any = None
    ) -> Tuple[int, Any, dict]:
        """(step, pytree, metadata). ``like`` gives the structure to rebuild
        (arrays in ``like`` are placeholders); without it a flat
        {path: array} dict is returned."""
        step = step if step is not None else self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {self.directory}")
        d = self._step_dir(step)
        if not os.path.exists(os.path.join(d, "_COMPLETE")):
            raise FileNotFoundError(f"checkpoint step {step} is incomplete")
        with np.load(os.path.join(d, "arrays.npz")) as z:
            flat = {k: z[k] for k in z.files}
        with open(os.path.join(d, "meta.json")) as f:
            metadata = json.load(f)
        tree = _unflatten_into(like, flat) if like is not None else flat
        return step, tree, metadata
