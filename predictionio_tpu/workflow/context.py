"""WorkflowContext: per-run compute context.

Rebuild of ``core/src/main/scala/io/prediction/workflow/WorkflowContext.scala:78-97``
— where the reference constructs a SparkContext ("PredictionIO <mode>:
<batch>" app name, executor env injection), a run here gets a device mesh,
mode/batch labels, and the PIO_* env passthrough. The context is handed to
every DASE component (the ``sc`` argument of the reference's ``*Base``
methods).
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Sequence

from ..parallel.mesh import (
    DATA_AXIS,
    MeshConfig,
    create_mesh,
    data_sharding,
    replicated,
)


def pio_env_vars(env: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """Env vars starting with PIO_ (``WorkflowUtils.pioEnvVars``,
    ``WorkflowUtils.scala:212-217``)."""
    source = env if env is not None else dict(os.environ)
    return {k: v for k, v in source.items() if k.startswith("PIO_")}


class WorkflowContext:
    """Compute context: mode + batch labels, env, and a lazily-built mesh."""

    def __init__(
        self,
        mode: str = "Training",
        batch: str = "",
        executor_env: Optional[Dict[str, str]] = None,
        mesh_config: Optional[MeshConfig] = None,
        devices: Optional[Sequence] = None,
    ):
        self.mode = mode
        self.batch = batch
        self.env = dict(
            executor_env if executor_env is not None else pio_env_vars()
        )
        self._mesh_config = mesh_config
        self._devices = devices
        self._mesh = None
        #: per-run phase timings (read/prepare/train/...), always available
        from ..utils.profiling import StepTimer

        self.timer = StepTimer()
        #: set by the training workflow to the run's checkpoint directory;
        #: algorithms with step checkpointing call ``checkpoint_manager()``
        #: (single-device pytree checkpoints) or ``checkpoint_store()``
        #: (the sharded canonical-row store, docs/checkpoint.md)
        self.checkpoint_dir: Optional[str] = None
        #: the workflow run's checkpoint-cadence override (``pio train
        #: --checkpoint-every`` / the continuous controller's retrain
        #: config); sits between the engine params' explicit value and
        #: the ``PIO_CKPT_EVERY`` env in ``ckpt.resolve_every``
        self.checkpoint_every: Optional[int] = None

    def checkpoint_store(
        self,
        subdir: Optional[str] = None,
        keep_last: Optional[int] = None,
        keep_every: Optional[int] = None,
    ):
        """``ckpt.CheckpointStore`` for this run, or None when the
        workflow did not assign a checkpoint directory. Same ``subdir``
        namespacing contract as :meth:`checkpoint_manager`; retention
        defaults resolve from ``PIO_CKPT_KEEP_LAST``/``_KEEP_EVERY``."""
        if not self.checkpoint_dir:
            return None
        from ..ckpt import CheckpointStore, resolve_retention

        kl, ke = resolve_retention(keep_last, keep_every)
        d = self.checkpoint_dir
        if subdir:
            d = os.path.join(d, subdir)
        return CheckpointStore(d, keep_last=kl, keep_every=ke)

    def checkpoint_manager(self, subdir: Optional[str] = None, keep: int = 3):
        """CheckpointManager for this run, or None when the workflow did not
        assign a checkpoint directory (e.g. bare Engine.train in tests).

        ``subdir`` namespaces independent training loops sharing one run —
        e.g. each algorithm of a multi-algorithm engine — so one loop never
        resumes from another's state."""
        if not self.checkpoint_dir:
            return None
        from .checkpoint import CheckpointManager

        d = self.checkpoint_dir
        if subdir:
            d = os.path.join(d, subdir)
        return CheckpointManager(d, keep=keep)

    @property
    def app_name(self) -> str:
        # "PredictionIO <mode>: <batch>" (WorkflowContext.scala:82-84)
        return f"PredictionIO {self.mode}: {self.batch}"

    @property
    def mesh(self):
        if self._mesh is None:
            self._mesh = create_mesh(self._mesh_config, self._devices)
        return self._mesh

    # -- sharding shortcuts used by DASE components ------------------------
    def data_sharding(self, axis: str = DATA_AXIS):
        return data_sharding(self.mesh, axis=axis)

    def replicated(self):
        return replicated(self.mesh)

    def slices(self, n: int) -> list:
        """Split this context into up to ``n`` contexts over independent
        mesh slices (hyperparameter-sweep parallelism, SURVEY §2.8 row 5).
        Each slice context shares the timer/env/checkpoint settings but
        owns a disjoint device subset, so concurrent evals dispatch onto
        disjoint hardware."""
        from ..parallel.mesh import slice_mesh

        meshes = slice_mesh(self.mesh, n)
        if len(meshes) == 1:
            return [self]
        out = []
        for m in meshes:
            child = WorkflowContext.__new__(WorkflowContext)
            child.__dict__.update(self.__dict__)
            child._mesh = m
            out.append(child)
        return out

    def stop(self) -> None:
        """SparkContext.stop analogue — release the mesh."""
        self._mesh = None
