"""Workflow runtime (≙ reference L4, ``workflow/``; SURVEY §2.1)."""

from .context import WorkflowContext, pio_env_vars
from .core_workflow import load_models, run_evaluation, run_train

__all__ = [
    "WorkflowContext",
    "load_models",
    "pio_env_vars",
    "run_evaluation",
    "run_train",
]
