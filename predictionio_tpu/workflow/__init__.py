"""Workflow runtime (≙ reference L4, ``workflow/``; SURVEY §2.1)."""

from .context import WorkflowContext, pio_env_vars
from .core_workflow import load_models, run_evaluation, run_train
from .serving import (
    Deployment,
    QueryServer,
    ServerConfig,
    create_query_server,
    prepare_deployment,
)

__all__ = [
    "Deployment",
    "QueryServer",
    "ServerConfig",
    "WorkflowContext",
    "create_query_server",
    "prepare_deployment",
    "load_models",
    "pio_env_vars",
    "run_evaluation",
    "run_train",
]
