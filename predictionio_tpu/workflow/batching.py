"""Micro-batching aggregator for the serving hot path.

The reference serves each query with its own ``predictBase`` call
(``core/src/main/scala/io/prediction/workflow/CreateServer.scala:479-485``)
— fine on a JVM thread pool doing CPU dot-products, fatal on an
accelerator: a batch-1 device dispatch per HTTP request leaves the MXU
idle and pays full dispatch latency per query. SURVEY §7 flags "batched
query aggregation into the gather-dot kernel without killing tail
latency" as the hard part of the ≥10k QPS target.

:class:`MicroBatcher` is the aggregator: concurrent request threads
``submit()`` work items; a single dispatcher thread collects whatever has
arrived within ``max_wait_ms`` (or up to ``max_batch``), invokes the
batched processor ONCE, and fans results back to the waiting threads.
Under load, batches fill instantly (wait ≈ 0 — the next batch forms while
the previous one is on the device); at low rates a lone query pays at
most ``max_wait_ms`` extra latency. This is the classic accelerator-
serving pattern (cf. TF Serving's batching layer), sized so tail latency
stays bounded: p99 <= device_time(max_batch) + max_wait_ms.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, List, Optional, Sequence

__all__ = ["MicroBatcher"]


class MicroBatcher:
    """Aggregate concurrent ``submit()`` calls into batched processor runs.

    ``process``: callable taking a list of items and returning a list of
    results of the same length (index-aligned). It runs on the dispatcher
    thread. A result element that is an ``Exception`` instance fails only
    its own request; an exception *raised* by ``process`` fails every
    request in that batch (and only that batch).

    ``default_timeout_s`` bounds each ``submit()`` wait; size it to cover
    worst-case first-dispatch latency (an XLA compile for a fresh shape
    bucket can cost tens of seconds on TPU).
    """

    def __init__(
        self,
        process: Callable[[Sequence[Any]], Sequence[Any]],
        max_batch: int = 64,
        max_wait_ms: float = 1.0,
        name: str = "microbatch",
        default_timeout_s: float = 120.0,
    ):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self._process = process
        self._max_batch = max_batch
        self._max_wait_s = max(0.0, max_wait_ms) / 1000.0
        self._default_timeout_s = default_timeout_s
        self._lock = threading.Lock()
        self._nonempty = threading.Condition(self._lock)
        self._items: List[Any] = []
        self._futures: List[Future] = []
        self._closed = False
        self._batches = 0
        self._submitted = 0
        self._dispatcher = threading.Thread(
            target=self._run, name=name, daemon=True
        )
        self._dispatcher.start()

    # -- client side ------------------------------------------------------
    def submit(self, item: Any, timeout: Optional[float] = None) -> Any:
        """Block until the batched processor has handled ``item``; returns
        its index-aligned result (or raises that item's exception)."""
        fut: Future = Future()
        with self._nonempty:
            if self._closed:
                raise RuntimeError("MicroBatcher is closed")
            self._items.append(item)
            self._futures.append(fut)
            self._submitted += 1
            self._nonempty.notify()
        return fut.result(
            timeout=timeout if timeout is not None else self._default_timeout_s
        )

    # -- dispatcher -------------------------------------------------------
    def _take_batch(self) -> tuple:
        """Wait for at least one item, linger up to max_wait for more (or
        until the batch is full), then drain. Returns ([], []) on close."""
        with self._nonempty:
            while not self._items and not self._closed:
                self._nonempty.wait(0.1)
            if self._closed and not self._items:
                return (), ()
            if self._max_wait_s > 0:
                deadline = time.monotonic() + self._max_wait_s
                while len(self._items) < self._max_batch:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._nonempty.wait(remaining)
            items = self._items[: self._max_batch]
            futures = self._futures[: self._max_batch]
            del self._items[: self._max_batch]
            del self._futures[: self._max_batch]
            return items, futures

    def _run(self) -> None:
        while True:
            items, futures = self._take_batch()
            if not items:
                if self._closed:
                    return
                continue
            try:
                results = self._process(items)
                if len(results) != len(items):
                    raise RuntimeError(
                        f"batch processor returned {len(results)} results "
                        f"for {len(items)} items"
                    )
            except Exception as exc:
                for fut in futures:
                    if not fut.done():
                        fut.set_exception(exc)
                continue
            self._batches += 1
            for fut, result in zip(futures, results):
                if fut.done():
                    continue
                if isinstance(result, Exception):
                    fut.set_exception(result)  # per-item failure channel
                else:
                    fut.set_result(result)

    # -- lifecycle / stats ------------------------------------------------
    def close(self) -> None:
        with self._nonempty:
            self._closed = True
            self._nonempty.notify_all()
        self._dispatcher.join(timeout=5.0)
        # fail anything still queued
        with self._nonempty:
            for fut in self._futures:
                if not fut.done():
                    fut.set_exception(RuntimeError("MicroBatcher closed"))
            self._items.clear()
            self._futures.clear()

    @property
    def stats(self) -> dict:
        with self._lock:
            return {
                "submitted": self._submitted,
                "batches": self._batches,
                "avg_batch": (
                    self._submitted / self._batches if self._batches else 0.0
                ),
            }
