"""Micro-batching aggregator for the serving hot path.

The reference serves each query with its own ``predictBase`` call
(``core/src/main/scala/io/prediction/workflow/CreateServer.scala:479-485``)
— fine on a JVM thread pool doing CPU dot-products, fatal on an
accelerator: a batch-1 device dispatch per HTTP request leaves the MXU
idle and pays full dispatch latency per query. SURVEY §7 flags "batched
query aggregation into the gather-dot kernel without killing tail
latency" as the hard part of the ≥10k QPS target.

:class:`MicroBatcher` is the aggregator: concurrent request threads
``submit()`` work items; a single dispatcher thread collects whatever has
arrived within ``max_wait_ms`` (or up to ``max_batch``), hands the batch
to a worker thread, and immediately forms the next batch. Up to
``pipeline_depth`` batches are in flight at once: while batch *k*'s
results travel back from the device, batch *k+1* is already dispatched —
on a high-latency host↔device path (the tunneled dev chip pays ~69 ms
round trip) a single in-flight batch caps throughput at
``max_batch / round_trip`` with the device idle between batches, which is
exactly the ceiling round 2 measured at 2,250 QPS. Pipelining multiplies
that by the depth until device compute (not the wire) is the binding
resource. At low rates a lone query pays at most ``max_wait_ms`` extra
latency. This is the classic accelerator-serving pattern (cf. TF
Serving's batching layer), sized so tail latency stays bounded:
p99 <= pipeline_depth * device_time(max_batch) + max_wait_ms.

The processor must be thread-safe under ``pipeline_depth`` concurrent
calls (jitted JAX dispatch is; the serving processor is a pure function
of its items). Batches may COMPLETE out of order; per-item futures make
that invisible to callers.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, List, Optional, Sequence, Tuple

from ..obs.metrics import MetricsRegistry
from ..obs.trace import Tracer, current_context

__all__ = ["MicroBatcher"]


class MicroBatcher:
    """Aggregate concurrent ``submit()`` calls into batched processor runs.

    ``process``: callable taking a list of items and returning a list of
    results of the same length (index-aligned). It runs on the dispatcher
    thread. A result element that is an ``Exception`` instance fails only
    its own request; an exception *raised* by ``process`` fails every
    request in that batch (and only that batch).

    ``default_timeout_s`` bounds each ``submit()`` wait; size it to cover
    worst-case first-dispatch latency (an XLA compile for a fresh shape
    bucket can cost tens of seconds on TPU).

    ``pipeline_depth`` is the number of batches allowed in flight at once
    (>=1). Depth 1 reproduces the strictly serial round-2 behavior; depth
    >=2 overlaps device round trips and is the default.

    Observability (``docs/observability.md``): with a ``metrics``
    registry attached, every flush records batch size, the flush reason
    (``full`` / ``wait`` / ``close``) and per-item queue wait, and the
    live queue depth is exported as a gauge — the signals that say
    whether the aggregator is forming real batches or just adding
    ``max_wait_ms`` of latency. With a ``tracer`` attached, each item
    whose submitting thread carried a span context gets two child spans:
    ``batch.queue-wait`` (submit → dispatch) and ``batch.device`` (the
    processor call) — the queue-time-vs-device-time split that explains
    a slow query. ``clock`` is injectable for sleep-free tests.
    """

    def __init__(
        self,
        process: Callable[[Sequence[Any]], Sequence[Any]],
        max_batch: int = 64,
        max_wait_ms: float = 1.0,
        name: str = "microbatch",
        default_timeout_s: float = 120.0,
        pipeline_depth: int = 2,
        metrics: Optional[MetricsRegistry] = None,
        tracer: Optional[Tracer] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if pipeline_depth < 1:
            raise ValueError(
                f"pipeline_depth must be >= 1, got {pipeline_depth}"
            )
        self._process = process
        self._max_batch = max_batch
        self._max_wait_s = max(0.0, max_wait_ms) / 1000.0
        self._default_timeout_s = default_timeout_s
        self._pipeline_depth = pipeline_depth
        self._clock = clock
        self._tracer = tracer
        self._obs_size = self._obs_wait = self._obs_flush = None
        self._obs_items = self._obs_failures = None
        if metrics is not None:
            self._obs_size = metrics.histogram(
                "pio_batch_size",
                "Queries per dispatched micro-batch",
                buckets=[2.0 ** i for i in range(11)],  # 1..1024
            )
            self._obs_wait = metrics.histogram(
                "pio_batch_queue_wait_seconds",
                "Per-item wait between submit and batch dispatch",
            )
            self._obs_flush = metrics.counter(
                "pio_batch_flush_total",
                "Batch flushes by trigger",
                labelnames=("reason",),
            )
            self._obs_items = metrics.counter(
                "pio_batch_items_total", "Items dispatched through batches"
            )
            self._obs_failures = metrics.counter(
                "pio_batch_failures_total",
                "Batches whose processor raised (all items failed)",
            )
        self._lock = threading.Lock()
        self._nonempty = threading.Condition(self._lock)
        self._items: List[Any] = []
        self._futures: List[Future] = []
        #: parallel to _items: (enqueue_ts, submitter SpanContext or None)
        self._meta: List[Tuple[float, Any]] = []
        self._closed = False
        if metrics is not None:
            # registered only now: the registry is shared, so a scrape
            # can fire the callback the instant it registers — the lock
            # and the queue it reads must already exist
            metrics.gauge_callback(
                "pio_batch_queue_depth",
                self._queue_depth,
                "Items waiting for the next batch",
            )
        self._batches = 0
        self._submitted = 0
        self._inflight_hwm = 0  # high-water mark of concurrent batches
        self._inflight = 0
        self._slots = threading.Semaphore(pipeline_depth)
        # Dedicated daemon workers (NOT a ThreadPoolExecutor: its threads
        # are joined at interpreter exit, so a batch hung on a dead device
        # would wedge process shutdown; daemons get left behind instead).
        self._work: "queue.Queue" = queue.Queue()
        self._workers = [
            threading.Thread(
                target=self._worker, name=f"{name}-exec-{i}", daemon=True
            )
            for i in range(pipeline_depth)
        ]
        for w in self._workers:
            w.start()
        self._dispatcher = threading.Thread(
            target=self._run, name=name, daemon=True
        )
        self._dispatcher.start()

    def _queue_depth(self) -> int:
        """Scrape-thread gauge callback: reads the queue under the same
        lock the request/dispatcher threads mutate it under."""
        with self._lock:
            return len(self._items)

    # -- client side ------------------------------------------------------
    def submit(self, item: Any, timeout: Optional[float] = None) -> Any:
        """Block until the batched processor has handled ``item``; returns
        its index-aligned result (or raises that item's exception)."""
        fut: Future = Future()
        # capture the submitter's trace context OUTSIDE the lock: the
        # dispatcher/worker threads that emit this item's spans have no
        # access to the submitting thread's contextvars
        span_ctx = current_context() if self._tracer is not None else None
        with self._nonempty:
            if self._closed:
                raise RuntimeError("MicroBatcher is closed")
            self._items.append(item)
            self._futures.append(fut)
            self._meta.append((self._clock(), span_ctx))
            self._submitted += 1
            self._nonempty.notify()
        return fut.result(
            timeout=timeout if timeout is not None else self._default_timeout_s
        )

    # -- dispatcher -------------------------------------------------------
    def _take_batch(self) -> tuple:
        """Wait for at least one item, linger up to max_wait for more (or
        until the batch is full), then drain. Returns ((), (), (), "")
        on close."""
        with self._nonempty:
            while not self._items and not self._closed:
                self._nonempty.wait(0.1)
            if self._closed and not self._items:
                return (), (), (), ""
            if self._max_wait_s > 0:
                deadline = time.monotonic() + self._max_wait_s
                while len(self._items) < self._max_batch:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._nonempty.wait(remaining)
            # flush reason, for the metrics plane: a fleet of "wait"
            # flushes at size 1 means batching is pure added latency
            if len(self._items) >= self._max_batch:
                reason = "full"
            elif self._closed:
                reason = "close"
            else:
                reason = "wait"
            items = self._items[: self._max_batch]
            futures = self._futures[: self._max_batch]
            metas = self._meta[: self._max_batch]
            del self._items[: self._max_batch]
            del self._futures[: self._max_batch]
            del self._meta[: self._max_batch]
            return items, futures, metas, reason

    def _run(self) -> None:
        while True:
            # Acquire a pipeline slot BEFORE draining the queue: the batch
            # is formed as late as possible, so while all slots are busy
            # (device round trips in flight) arrivals keep topping up the
            # next batch to max_batch instead of dispatching undersized.
            self._slots.acquire()
            items, futures, metas, reason = self._take_batch()
            if not items:
                self._slots.release()
                with self._lock:
                    closed = self._closed
                if closed:
                    return
                continue
            with self._lock:
                self._inflight += 1
                self._inflight_hwm = max(self._inflight_hwm, self._inflight)
            self._work.put((items, futures, metas, reason))

    def _worker(self) -> None:
        while True:
            task = self._work.get()
            if task is None:  # close() sentinel
                return
            self._execute(*task)

    def _record_obs(
        self,
        metas: Sequence[Tuple[float, Any]],
        reason: str,
        dispatch_ts: float,
        device_s: float,
        batch_size: int,
    ) -> None:
        """Metrics + spans for one executed batch (see class docstring)."""
        if self._obs_size is not None:
            self._obs_size.observe(batch_size)
            self._obs_flush.inc(1, reason=reason)
            self._obs_items.inc(batch_size)
        for enqueue_ts, span_ctx in metas:
            wait_s = max(0.0, dispatch_ts - enqueue_ts)
            if self._obs_wait is not None:
                self._obs_wait.observe(wait_s)
            if self._tracer is not None and span_ctx is not None:
                wall = self._tracer.wall()
                tags = {"batch_size": batch_size, "flush": reason}
                self._tracer.record(
                    "batch.queue-wait",
                    self._tracer.child_context(span_ctx),
                    span_ctx.span_id,
                    start_wall=wall - wait_s - device_s,
                    duration_s=wait_s,
                    tags=tags,
                )
                self._tracer.record(
                    "batch.device",
                    self._tracer.child_context(span_ctx),
                    span_ctx.span_id,
                    start_wall=wall - device_s,
                    duration_s=device_s,
                    tags=tags,
                )

    def _execute(
        self,
        items: Sequence[Any],
        futures: Sequence[Future],
        metas: Sequence[Tuple[float, Any]] = (),
        reason: str = "",
    ) -> None:
        """Run one batch on an executor thread and fan results out. Runs
        concurrently with up to ``pipeline_depth - 1`` sibling batches."""
        dispatch_ts = self._clock()
        recorded = False

        def record() -> None:
            # Metrics/spans for every executed batch, FAILED ones
            # included — an erroring device is exactly when the batch
            # signals matter, so a raise must not zero the flush counts.
            # Swallowed on error: observability must never wedge the
            # pipeline slot or kill the worker thread.
            try:
                self._record_obs(
                    metas,
                    reason,
                    dispatch_ts,
                    self._clock() - dispatch_ts,
                    len(items),
                )
            except Exception:
                pass

        # Observability is recorded BEFORE the result fan-out on both
        # paths: set_result()/set_exception() unblocks the submitting
        # thread, which may answer its client — and a client (or an e2e
        # test) that then reads /traces.json must find this batch's
        # spans already there. Recording after the fan-out raced exactly
        # that read (the PR-8/9 batch-span flake).
        try:
            try:
                results = self._process(items)
                if len(results) != len(items):
                    raise RuntimeError(
                        f"batch processor returned {len(results)} results "
                        f"for {len(items)} items"
                    )
            except Exception as exc:
                if self._obs_failures is not None:
                    self._obs_failures.inc(1)
                record()
                recorded = True
                for fut in futures:
                    if not fut.done():
                        fut.set_exception(exc)
                return
            with self._lock:
                self._batches += 1
            record()
            recorded = True
            for fut, result in zip(futures, results):
                if fut.done():
                    continue
                if isinstance(result, Exception):
                    fut.set_exception(result)  # per-item failure channel
                else:
                    fut.set_result(result)
        finally:
            if not recorded:  # a raise before the fan-out still records
                record()
            with self._lock:
                self._inflight -= 1
            self._slots.release()

    # -- lifecycle / stats ------------------------------------------------
    def close(self, grace_s: float = 5.0) -> None:
        # ONE deadline shared by the dispatcher join and the in-flight
        # wait: close() is bounded by grace_s total, not per phase.
        deadline = time.monotonic() + grace_s
        with self._nonempty:
            self._closed = True
            self._nonempty.notify_all()
        self._dispatcher.join(timeout=max(0.0, deadline - time.monotonic()))
        # Bounded wait for in-flight batches (their callers still block on
        # the results). A batch hung on a dead device must not hang /stop
        # or hot-swap forever: after the grace period the daemon workers
        # are left behind and hung submitters hit their submit() timeout.
        while time.monotonic() < deadline:
            with self._lock:
                if self._inflight == 0:
                    break
            time.sleep(0.005)
        for _ in self._workers:
            self._work.put(None)  # tidy exit for idle workers
        # fail anything still queued
        with self._nonempty:
            for fut in self._futures:
                if not fut.done():
                    fut.set_exception(RuntimeError("MicroBatcher closed"))
            self._items.clear()
            self._futures.clear()
            self._meta.clear()

    @property
    def stats(self) -> dict:
        with self._lock:
            return {
                "submitted": self._submitted,
                "batches": self._batches,
                "avg_batch": (
                    self._submitted / self._batches if self._batches else 0.0
                ),
                "pipeline_depth": self._pipeline_depth,
                "inflight_hwm": self._inflight_hwm,
            }
