"""Streaming training infeed: event store → device-ready index arrays.

The reference feeds training through ``newAPIHadoopRDD`` region splits —
events stream from HBase regionservers into executor partitions without any
single host holding the whole dataset
(``data/src/main/scala/io/prediction/data/storage/hbase/HBPEvents.scala:58-98``).
This module is the TPU-native analogue for the host side of that pipe: the
chunked columnar scan (``EventStore.scan_columnar_iter``) streams bounded
column chunks, each chunk is translated to dense int32 indices on the fly
(incremental BiMap build), and only the final index/value arrays — 12
bytes/rating — are retained. No per-event objects, no full-app Python
string lists: peak host memory is one chunk of decoded strings plus the
numeric output, instead of the 3× materialization of a read-all →
map-all → bucketize pipeline.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ..storage.bimap import BiMap
from ..storage.events import EventFilter, EventStore


class StreamingIndexer:
    """Incremental ``BiMap.string_int``: dense indices in arrival order.

    Feeding chunks through :meth:`index_chunk` produces exactly the ids a
    one-shot ``BiMap.string_int(all_keys)`` would assign, without ever
    holding ``all_keys``.
    """

    def __init__(self):
        self._map: Dict[str, int] = {}

    def __len__(self) -> int:
        return len(self._map)

    def index_chunk(self, keys: Sequence[str]) -> np.ndarray:
        """Translate one chunk, assigning fresh indices to unseen keys."""
        m = self._map
        out = np.empty(len(keys), dtype=np.int32)
        for j, k in enumerate(keys):
            v = m.get(k)
            if v is None:
                v = len(m)
                m[k] = v
            out[j] = v
        return out

    def to_bimap(self) -> BiMap:
        return BiMap(self._map)


#: Value rule for one event name: a float (fixed value, e.g. implicit
#: "buy" → 4.0) or a property name to read (required on the event).
ValueRule = Dict[str, object]


def _extract_chunk(cols: dict, value_rules: ValueRule):
    """One column chunk → (user ids, target ids, values), applying the
    per-event value rules and skipping target-less events."""
    uids: List[str] = []
    tids: List[str] = []
    vals: List[float] = []
    for ev, uid, tid, props in zip(
        cols["event"], cols["entity_id"],
        cols["target_entity_id"], cols["properties"],
    ):
        if tid is None:
            continue
        rule = value_rules[ev]
        if isinstance(rule, str):
            if rule not in props:
                raise ValueError(
                    f"{ev!r} event for {uid}->{tid} has no {rule!r} property"
                )
            vals.append(float(props[rule]))
        else:
            vals.append(float(rule))
        uids.append(uid)
        tids.append(tid)
    return uids, tids, vals


@dataclasses.dataclass
class RatingBatch:
    """Final product of a streaming read."""

    users: np.ndarray  # int32 [nnz]
    items: np.ndarray  # int32 [nnz]
    ratings: np.ndarray  # float32 [nnz]
    user_map: BiMap
    item_map: BiMap


def stream_ratings(
    store: EventStore,
    app_id: int,
    value_rules: ValueRule,
    chunk_rows: int = 1_000_000,
    on_chunk: Optional[Callable[[np.ndarray, np.ndarray, np.ndarray], None]] = None,
    hashed_users: int = 0,
) -> RatingBatch:
    """Stream (entity → target, value) events into dense rating arrays.

    ``value_rules`` maps each event name to either a fixed float or the name
    of a required float property (the recommendation template's
    rate-vs-buy rule, ``DataSource.scala:25-55``). Events without a target
    entity are skipped. ``on_chunk`` (optional) observes each translated
    chunk — the hook a sharded device infeed attaches to.

    ``hashed_users`` (a power-of-two capacity) switches the user side to
    the O(1)-host-memory :class:`~predictionio_tpu.storage.bimap.
    HashedIdMap` — the big-ID path for catalogs whose unique-user dict
    would not fit one host (the exact BiMap costs ~194 B/id; see the
    HashedIdMap docstring for the aliasing trade-off). Items keep the
    exact map: serving must decode item indices back to ids.
    """
    # Native fast path: the event log's C++ ratings scan does the whole
    # loop below in one pass (ratings.cc) — only the unique-id strings
    # cross into Python. Constraint: one distinct property name.
    n_props = len({r for r in value_rules.values() if isinstance(r, str)})
    if (
        not hashed_users
        and on_chunk is None
        and n_props <= 1
        and hasattr(store, "scan_ratings")
    ):
        from ..storage.native_events import NativeScanUnsupported

        try:
            users, items, vals, user_ids, item_ids = store.scan_ratings(
                app_id, value_rules
            )
        except NativeScanUnsupported:
            # the native scan declined (e.g. writer segments + primary-log
            # deletes): the generic chunked path below is always exact.
            # Plain ValueError (bad data) still propagates.
            pass
        else:
            return RatingBatch(
                users=users,
                items=items,
                ratings=vals,
                user_map=BiMap({k: i for i, k in enumerate(user_ids)}),
                item_map=BiMap({k: i for i, k in enumerate(item_ids)}),
            )

    if hashed_users:
        from ..storage.bimap import HashedIdMap

        user_map = HashedIdMap(hashed_users)
        index_users = user_map.map_array
        finish_user_map = lambda: user_map  # noqa: E731
    else:
        user_ix = StreamingIndexer()
        index_users = user_ix.index_chunk
        finish_user_map = user_ix.to_bimap
    item_ix = StreamingIndexer()
    u_parts: List[np.ndarray] = []
    i_parts: List[np.ndarray] = []
    v_parts: List[np.ndarray] = []

    flt = EventFilter(event_names=list(value_rules))
    for cols in store.scan_columnar_iter(app_id, flt, chunk_rows=chunk_rows):
        uids, tids, vals = _extract_chunk(cols, value_rules)
        if not uids:
            continue
        u = index_users(uids)
        i = item_ix.index_chunk(tids)
        v = np.asarray(vals, dtype=np.float32)
        if on_chunk is not None:
            on_chunk(u, i, v)
        u_parts.append(u)
        i_parts.append(i)
        v_parts.append(v)

    empty_i = np.zeros(0, dtype=np.int32)
    return RatingBatch(
        users=np.concatenate(u_parts) if u_parts else empty_i,
        items=np.concatenate(i_parts) if i_parts else empty_i,
        ratings=(
            np.concatenate(v_parts)
            if v_parts
            else np.zeros(0, dtype=np.float32)
        ),
        user_map=finish_user_map(),
        item_map=item_ix.to_bimap(),
    )
