"""Reflective loading of user engine factories / evaluations.

Rebuild of ``core/src/main/scala/io/prediction/workflow/WorkflowUtils.scala``:
``getEngine`` / ``getEvaluation`` / ``getEngineParamsGenerator``
(``WorkflowUtils.scala:61-117``) resolve a user-supplied class name against the
classpath, trying Scala-object and Java-class conventions.  The TPU-native
equivalent resolves a dotted path (``pkg.module:attr`` or ``pkg.module.attr``)
against ``sys.path``, with the engine project directory prepended so an
``engine.py`` next to ``engine.json`` is importable — the analogue of the
reference registering built jars on the classpath
(``RegisterEngine.scala:30-120``).
"""

from __future__ import annotations

import importlib
import logging
import os
import sys
from typing import Any, Optional

logger = logging.getLogger(__name__)


class EngineFactoryError(Exception):
    """Factory path did not resolve (``WorkflowUtils.scala:84-91``)."""


def load_object(path: str, search_dir: Optional[str] = None) -> Any:
    """Resolve ``module:attr`` (preferred) or dotted ``module.attr``.

    ``search_dir`` (the engine project directory) is prepended to ``sys.path``
    for the import, mirroring the reference's engine-jar classpath injection.
    """
    if not path:
        raise EngineFactoryError("empty factory path")
    if search_dir:
        search_dir = os.path.abspath(search_dir)
        # Stays on sys.path for the process lifetime: the engine's own
        # module-level imports of sibling files must keep working after load
        # (the reference keeps engine jars on the classpath the same way).
        if search_dir not in sys.path:
            sys.path.insert(0, search_dir)
    if ":" in path:
        mod_name, _, attr = path.partition(":")
        try:
            module = _import_module(mod_name, search_dir)
        except ImportError as exc:
            raise EngineFactoryError(f"could not import {mod_name!r}: {exc}") from exc
        try:
            return _get_attr_chain(module, attr)
        except AttributeError as exc:
            raise EngineFactoryError(f"{path}: {exc}") from exc
    # Dotted form: try progressively shorter module prefixes
    # (``WorkflowUtils.getEngine`` tries object-then-class the same way).
    parts = path.split(".")
    for split in range(len(parts) - 1, 0, -1):
        mod_name = ".".join(parts[:split])
        try:
            module = _import_module(mod_name, search_dir)
        except ImportError:
            continue
        try:
            return _get_attr_chain(module, ".".join(parts[split:]))
        except AttributeError:
            continue
    # Whole path may itself be a module exposing a callable engine factory.
    try:
        return _import_module(path, search_dir)
    except ImportError as exc:
        raise EngineFactoryError(
            f"could not resolve {path!r} (searched sys.path"
            + (f" + {search_dir!r}" if search_dir else "")
            + ")"
        ) from exc


def _import_module(mod_name: str, search_dir: Optional[str]) -> Any:
    """Import ``mod_name``, preferring a file inside ``search_dir``.

    Engine projects all tend to name their module ``engine`` (the scaffolds
    do), so a plain ``import engine`` would collide in ``sys.modules`` across
    projects.  Project-local modules are therefore loaded by file location
    under a per-directory unique name — the analogue of the reference giving
    each engine its own jar on an isolated classpath entry
    (``RegisterEngine.scala:46-120``).
    """
    if search_dir:
        candidate = os.path.join(search_dir, *mod_name.split(".")) + ".py"
        if os.path.exists(candidate):
            import hashlib
            import importlib.util

            # Flat (dot-free) synthetic name: pickle resolves a class's
            # ``__module__`` via ``__import__``, which for a dotted name
            # imports the (nonexistent) parent package but for a flat name
            # hits the sys.modules entry directly — so models defined in a
            # project-local engine.py pickle/unpickle cleanly.  The tag is a
            # digest of the project path, deterministic across processes:
            # deploy re-registers the same name before unpickling.
            tag = hashlib.sha1(search_dir.encode("utf-8")).hexdigest()[:12]
            unique = f"_pio_engine_{tag}_{mod_name.replace('.', '_')}"
            if unique in sys.modules:
                return sys.modules[unique]
            spec = importlib.util.spec_from_file_location(unique, candidate)
            assert spec is not None and spec.loader is not None
            module = importlib.util.module_from_spec(spec)
            sys.modules[unique] = module
            spec.loader.exec_module(module)
            return module
    return importlib.import_module(mod_name)


def _get_attr_chain(obj: Any, attr_path: str) -> Any:
    for attr in attr_path.split("."):
        obj = getattr(obj, attr)
    return obj


def _instantiate(obj: Any) -> Any:
    """A factory may be the instance itself, a zero-arg callable, or a class."""
    if callable(obj):
        return obj()
    return obj


def get_engine(factory: str, search_dir: Optional[str] = None):
    """``WorkflowUtils.getEngine`` (``WorkflowUtils.scala:61-91``)."""
    from ..controller.engine import Engine

    obj = _instantiate(load_object(factory, search_dir))
    if not isinstance(obj, Engine):
        raise EngineFactoryError(
            f"{factory!r} resolved to {type(obj).__name__}, not an Engine"
        )
    return obj


def get_evaluation(path: str, search_dir: Optional[str] = None):
    """``WorkflowUtils.getEvaluation`` (``WorkflowUtils.scala:93-103``)."""
    from ..controller.evaluation import Evaluation

    obj = _instantiate(load_object(path, search_dir))
    if not isinstance(obj, Evaluation):
        raise EngineFactoryError(
            f"{path!r} resolved to {type(obj).__name__}, not an Evaluation"
        )
    return obj


def get_engine_params_generator(path: str, search_dir: Optional[str] = None):
    """``WorkflowUtils.getEngineParamsGenerator``
    (``WorkflowUtils.scala:105-117``)."""
    from ..controller.evaluation import EngineParamsGenerator

    obj = _instantiate(load_object(path, search_dir))
    if not isinstance(obj, EngineParamsGenerator):
        raise EngineFactoryError(
            f"{path!r} resolved to {type(obj).__name__}, "
            "not an EngineParamsGenerator"
        )
    return obj


def apply_runtime_conf(variant) -> dict:
    """Apply an engine variant's embedded runtime configuration — the
    analogue of engine.json's ``sparkConf`` block
    (``WorkflowUtils.extractSparkConf``, ``WorkflowUtils.scala:321-339``,
    consumed at SparkContext creation, ``WorkflowContext.scala:78-96``).

    ``engine.json`` may carry::

        "runtimeConf": {
          "env":       {"PIO_PROFILE_DIR": "/tmp/prof"},   # process env
          "platform":  "cpu",                               # JAX_PLATFORMS
          "xla_flags": "--xla_force_host_platform_device_count=8",
          "jax":       {"jax_enable_x64": true}             # jax.config
        }

    Like the reference's sparkConf, settings bind at runtime start-up:
    ``env``/``platform``/``xla_flags`` fully apply only when the driver is
    a fresh process (``--spawn``); ``jax`` config keys apply immediately.
    Returns the dict of applied settings (for logging / tests).
    """
    conf = (variant or {}).get("runtimeConf") or {}
    applied: dict = {}
    for key, value in (conf.get("env") or {}).items():
        os.environ[key] = str(value)
        applied.setdefault("env", {})[key] = str(value)
    if conf.get("xla_flags"):
        # Flag-NAME-aware merge: a requested flag replaces any existing
        # setting of the same flag (token/substring comparisons either
        # leave contradictory duplicates or treat "…count=1" as present
        # because "…count=16" is).
        def flag_name(token: str) -> str:
            return token.split("=", 1)[0]

        requested = conf["xla_flags"].split()
        names = {flag_name(t) for t in requested}
        kept = [
            t
            for t in os.environ.get("XLA_FLAGS", "").split()
            if flag_name(t) not in names
        ]
        os.environ["XLA_FLAGS"] = " ".join(kept + requested)
        applied["xla_flags"] = conf["xla_flags"]
    if conf.get("platform"):
        os.environ["JAX_PLATFORMS"] = conf["platform"]
        try:
            import jax

            jax.config.update("jax_platforms", conf["platform"])
        except Exception:
            pass  # jax not importable yet: the env var carries it
        applied["platform"] = conf["platform"]
    jax_conf = conf.get("jax") or {}
    if jax_conf:
        import jax

        for key, value in jax_conf.items():
            jax.config.update(key, value)
            applied.setdefault("jax", {})[key] = value
    if applied:
        logger.info("applied runtimeConf: %s", applied)
    return applied


def modify_logging(verbose: bool) -> None:
    """``WorkflowUtils.modifyLogging`` (``WorkflowUtils.scala:278-289``)."""
    level = logging.DEBUG if verbose else logging.INFO
    logging.getLogger("predictionio_tpu").setLevel(level)
    logging.basicConfig(level=level)
