"""Core train/eval workflows.

Rebuild of ``core/src/main/scala/io/prediction/workflow/CoreWorkflow.scala:43-144``
and ``EvaluationWorkflow.scala:68-81``: bootstrap a context, run the engine,
persist models / evaluation results, and flip instance status
INIT → COMPLETED (or EVALUATING → EVALCOMPLETED). The reference Kryo-blobs
models into the ``Models`` store; here the persisted model list is pickled.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import pickle
import re
import shutil
from typing import Any, List, Optional

from ..controller.engine import (
    Engine,
    EngineParams,
    WorkflowParams,
    serialize_engine_params,
)
from ..controller.evaluation import EngineParamsGenerator, Evaluation
from ..storage import (
    STATUS_COMPLETED,
    STATUS_EVALCOMPLETED,
    STATUS_EVALUATING,
    Model,
    StorageRegistry,
    new_engine_instance,
    utcnow,
)
from ..storage.metadata import EvaluationInstance
from .context import WorkflowContext, pio_env_vars

logger = logging.getLogger(__name__)


def run_train(
    engine: Engine,
    engine_params: EngineParams,
    registry: StorageRegistry,
    engine_id: str = "default",
    engine_version: str = "1",
    engine_variant: str = "engine.json",
    engine_factory: str = "",
    workflow_params: WorkflowParams = WorkflowParams(),
    ctx: Optional[WorkflowContext] = None,
) -> str:
    """Train and persist; returns the engine instance id
    (``CoreWorkflow.runTrain``, ``CoreWorkflow.scala:43-93``)."""
    from .version_check import check_upgrade

    check_upgrade("training", engine_factory)  # CoreWorkflow.scala:51
    md = registry.get_metadata()
    params_cols = serialize_engine_params(engine_params)
    instance = new_engine_instance(
        engine_id=engine_id,
        engine_version=engine_version,
        engine_variant=engine_variant,
        engine_factory=engine_factory,
        batch=workflow_params.batch,
        env=pio_env_vars(),
        **params_cols,
    )
    instance_id = md.engine_instance_insert(instance)

    ctx = ctx or WorkflowContext(mode="Training", batch=workflow_params.batch)
    if ctx.checkpoint_every is None:
        # per-run cadence override (`pio train --checkpoint-every`, the
        # continuous controller's retrain config) — sits between the
        # engine params and PIO_CKPT_EVERY in ckpt.resolve_every
        ctx.checkpoint_every = getattr(
            workflow_params, "checkpoint_every", None
        )
    derived_checkpoint_dir = False
    if ctx.checkpoint_dir is None:
        explicit_dir = os.environ.get("PIO_CKPT_DIR")
        if explicit_dir:
            # an operator-pinned checkpoint root (docs/checkpoint.md):
            # NOT deleted on success — its retention is the store's GC
            ctx.checkpoint_dir = explicit_dir
        else:
            from ..storage.registry import base_dir

            # Stable across reruns of the same workflow (NOT the per-run
            # instance id): a crashed run's rerun finds and resumes these
            # checkpoints; a successful run deletes them below.
            slug = (
                re.sub(r"[^A-Za-z0-9_.-]", "_", workflow_params.batch)
                or "default"
            )
            ctx.checkpoint_dir = os.path.join(
                base_dir(), "checkpoints", engine_id, engine_version, slug
            )
            derived_checkpoint_dir = True
    try:
        from ..obs.profile import default_telemetry
        from ..utils.profiling import device_trace

        telemetry = default_telemetry()
        jit_before = telemetry.snapshot()
        import time as _time

        train_t0 = _time.monotonic()
        with device_trace(os.environ.get("PIO_PROFILE_DIR")):
            models = engine.train(ctx, engine_params, workflow_params)
        train_wall_s = _time.monotonic() - train_t0
        logger.info("train phases: %s", ctx.timer.format_summary())
        persisted = engine.make_serializable_models(
            ctx, engine_params, instance_id, models
        )
        registry.get_models().insert(
            Model(id=instance_id, models=pickle.dumps(persisted))
        )
        stored = md.engine_instance_get(instance_id)
        assert stored is not None
        # Persist the per-phase wall-clock summary with the completed
        # record: the StepTimer dies with this process, but the timings
        # belong to the instance — the query server re-exports them as
        # pio_train_phase_seconds gauges and the dashboard lists them
        # (docs/observability.md).
        from ..utils.profiling import (
            TRAIN_PHASES_ENV_KEY,
            TRAIN_PROFILE_ENV_KEY,
            phases_to_env,
            profile_to_env,
        )

        env = dict(stored.env)
        env[TRAIN_PHASES_ENV_KEY] = phases_to_env(ctx.timer.summary())
        # Compile/retrace profile of THIS run (delta, not process totals:
        # a long-lived embedding process may train many instances), so
        # `pio profile` can report a completed instance's compile
        # behavior after the training process is gone.
        jit_delta = telemetry.delta_since(jit_before)
        jit_delta["train_wall_s"] = round(train_wall_s, 3)
        env[TRAIN_PROFILE_ENV_KEY] = profile_to_env(jit_delta)
        md.engine_instance_update(
            dataclasses.replace(
                stored, status=STATUS_COMPLETED, end_time=utcnow(), env=env
            )
        )
        _append_perf_ledger(
            instance_id, train_wall_s, ctx.timer.summary(), jit_delta
        )
        logger.info("Training completed; engine instance %s", instance_id)
        if derived_checkpoint_dir:
            # resume data is only for crashed runs — a completed run clears
            # it (bounds disk). Only the path THIS function derived is
            # deleted; a caller-supplied directory may be shared.
            shutil.rmtree(ctx.checkpoint_dir, ignore_errors=True)
        return instance_id
    except KeyboardInterrupt:
        # CoreWorkflow.scala:83-88: interruptions leave the INIT row behind.
        logger.warning("Training interrupted; instance %s stays INIT", instance_id)
        raise
    finally:
        ctx.stop()


def _append_perf_ledger(
    instance_id: str,
    train_wall_s: float,
    phase_summary: dict,
    jit_delta: dict,
) -> None:
    """Opt-in durable perf record for this training run
    (``PIO_PERF_LEDGER=path``, docs/performance.md#perf-ledger).
    Best-effort: ledger trouble must never fail a finished train."""
    path = os.environ.get("PIO_PERF_LEDGER")
    if not path:
        return
    try:
        from ..obs import perfledger

        device = None
        try:
            import jax

            device = str(jax.devices()[0])
        except Exception:
            pass
        perfledger.append_record(
            path,
            perfledger.make_record(
                source="train",
                metric="train_wall_s",
                value=train_wall_s,
                device=device,
                phases={
                    name: round(s["total_s"], 4)
                    for name, s in phase_summary.items()
                },
                extra={"instanceId": instance_id, "jit": jit_delta},
            ),
        )
    except Exception:
        logger.exception("perf-ledger append failed (ignored)")


def load_models(registry: StorageRegistry, instance_id: str) -> List[Any]:
    """Persisted model list for an instance (``CreateServer.scala:196-198``)."""
    blob = registry.get_models().get(instance_id)
    if blob is None:
        raise KeyError(f"No model data for engine instance {instance_id}")
    return pickle.loads(blob.models)


def run_evaluation(
    evaluation: Evaluation,
    engine_params_generator: EngineParamsGenerator,
    registry: StorageRegistry,
    workflow_params: WorkflowParams = WorkflowParams(),
    ctx: Optional[WorkflowContext] = None,
) -> str:
    """Full evaluation run (``CoreWorkflow.runEvaluation``,
    ``CoreWorkflow.scala:95-144`` + ``EvaluationWorkflow.scala:68-81``)."""
    from .version_check import check_upgrade

    check_upgrade("evaluation", type(evaluation).__name__)  # :108
    md = registry.get_metadata()
    now = utcnow()
    instance = EvaluationInstance(
        id="",
        status=STATUS_EVALUATING,
        start_time=now,
        end_time=now,
        evaluation_class=type(evaluation).__name__,
        engine_params_generator_class=type(engine_params_generator).__name__,
        batch=workflow_params.batch,
        env=pio_env_vars(),
    )
    instance_id = md.evaluation_instance_insert(instance)

    ctx = ctx or WorkflowContext(mode="Evaluation", batch=workflow_params.batch)
    try:
        engine, evaluator = evaluation.engine_evaluator
        params_list = engine_params_generator.engine_params_list
        # sweep parallelism: candidates ride independent mesh slices
        # (SURVEY §2.8 row 5); auto = one slice per candidate, bounded by
        # the mesh's data-axis size inside ctx.slices
        parallelism = (
            workflow_params.eval_parallelism
            if workflow_params.eval_parallelism > 0
            else len(params_list)
        )
        engine_eval_data = engine.batch_eval(
            ctx, params_list, workflow_params, parallelism=parallelism
        )
        result = evaluator.evaluate_base(
            ctx, evaluation, engine_eval_data, workflow_params,
            parallelism=parallelism,
        )
        stored = md.evaluation_instance_get(instance_id)
        assert stored is not None
        md.evaluation_instance_update(
            dataclasses.replace(
                stored,
                status=STATUS_EVALCOMPLETED,
                end_time=utcnow(),
                evaluator_results=result.one_liner(),
                evaluator_results_html=result.to_html(),
                evaluator_results_json=result.to_json(),
            )
        )
        logger.info("Evaluation completed; instance %s", instance_id)
        return instance_id
    finally:
        ctx.stop()
