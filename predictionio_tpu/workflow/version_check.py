"""Upgrade check: the ``UpgradeCheckRunner`` analogue.

The reference fires a background thread from every train/eval/deploy/build
that fetches ``http://direct.prediction.io/<version>/<component>.json`` and
ignores the result (the upgrade logic is a literal ``// TODO`` —
``core/src/main/scala/io/prediction/workflow/WorkflowUtils.scala:392-413``,
invoked from ``CoreWorkflow.scala:51,108``, ``CreateServer.scala:246`` and
``Console.scala:842-844``). This analogue completes the TODO: when the
version index is reachable and advertises a newer release, an INFO line
says so; every failure mode (no network, 404, bad JSON, slow host) is a
DEBUG line at most. The check never blocks the caller (daemon thread, short
timeout).

Unlike the reference, the check is **opt-in**: it only runs when
``PIO_VERSIONS_HOST`` names an index the operator controls. The reference's
hard-coded ``direct.prediction.io`` belongs to a defunct project — a
default-on request to a lapsed domain from every production process is a
takeover target, not a feature. ``PIO_NO_UPGRADE_CHECK=1`` force-disables
even a configured host.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import urllib.request
from typing import Optional, Tuple

log = logging.getLogger(__name__)

#: No default host: the check is opt-in via PIO_VERSIONS_HOST (trailing
#: slash optional). The reference hard-coded plain-http
#: ``direct.prediction.io`` (``WorkflowUtils.scala:396``) — a domain this
#: project does not control; defaulting to it would point every production
#: train/eval/deploy process at whoever registers it next.
DEFAULT_VERSIONS_HOST = ""

_TIMEOUT_S = 3.0
#: Response size cap: the index is a tiny JSON document; never buffer an
#: arbitrarily large body from a (potentially hijacked) remote host.
_MAX_BODY = 1 << 16


def _parse_version(v: str) -> Optional[Tuple[int, ...]]:
    """Dotted version → int tuple; None when unparseable (pre-release tags
    compare as their numeric prefix: "0.9.2-SNAPSHOT" → (0, 9, 2))."""
    parts = []
    for piece in str(v).split("."):
        digits = ""
        for ch in piece:
            if not ch.isdigit():
                break
            digits += ch
        if not digits:
            break
        parts.append(int(digits))
    return tuple(parts) if parts else None


def check_url(component: str, engine: str = "", version: str = "",
              host: str = "") -> str:
    """The reference's URL scheme (``WorkflowUtils.scala:399-404``)."""
    if not version:
        from .. import __version__ as version
    host = (host or os.environ.get("PIO_VERSIONS_HOST")
            or DEFAULT_VERSIONS_HOST).rstrip("/")
    if engine:
        return f"{host}/{version}/{component}/{engine}.json"
    return f"{host}/{version}/{component}.json"


def _run_check(component: str, engine: str) -> Optional[str]:
    """Fetch + compare. Returns the newer-version string when an upgrade is
    advertised, else None. Never raises."""
    from .. import __version__

    url = check_url(component, engine, __version__)
    try:
        with urllib.request.urlopen(url, timeout=_TIMEOUT_S) as resp:
            data = json.loads(resp.read(_MAX_BODY).decode("utf-8"))
    except Exception as exc:  # any failure: a debug line, nothing more
        log.debug("upgrade metainfo not available (%s): %s", url, exc)
        return None
    latest = data.get("version") if isinstance(data, dict) else None
    if not latest:
        return None
    # Sanitize before the string reaches a log line: printable ASCII only,
    # clamped — a hijacked index must not inject control chars into logs.
    latest = "".join(
        ch for ch in str(latest)[:64] if ch.isprintable() and ord(ch) < 128
    )
    cur, new = _parse_version(__version__), _parse_version(latest)
    if cur is not None and new is not None and new > cur:
        log.info(
            "A newer version %s is available (running %s) — component %s",
            latest, __version__, component or "core",
        )
        return str(latest)
    return None


def check_upgrade(component: str = "core", engine: str = "") -> Optional[threading.Thread]:
    """Fire-and-forget upgrade check (``WorkflowUtils.checkUpgrade``).

    Returns the daemon thread (tests join it) or None when skipped: the
    check only runs when ``PIO_VERSIONS_HOST`` is configured (opt-in), and
    ``PIO_NO_UPGRADE_CHECK=1`` disables it even then.
    """
    if os.environ.get("PIO_NO_UPGRADE_CHECK") == "1":
        return None
    if not (os.environ.get("PIO_VERSIONS_HOST") or DEFAULT_VERSIONS_HOST):
        return None
    t = threading.Thread(
        target=_run_check, args=(component, engine),
        name="pio-upgrade-check", daemon=True,
    )
    t.start()
    return t
