"""Shared cache tier: one sidecar cache, many routers — advisory by
construction (docs/fleet.md#shared-cache-tier).

The PR-14 response cache is per-router-process: at fleet scale every
router replica pays its own miss storm for the same Zipfian head. This
module adds the middle level of the fleet's memory hierarchy — a
stdlib sidecar cache server that router replicas consult between their
local LRU and the backend fan-out, so a hot key is computed once per
*fleet* instead of once per router (the shared, staleness-bounded
serving cache the ads-serving infrastructure in PAPERS.md treats as
table stakes).

The robustness contract, in one sentence: **the sidecar can make the
fleet faster, it can never make it wrong.**

- Every entry carries the PR-14 **epoch** (rollout plan + serving
  instance). A lookup under a different epoch is a miss and drops the
  entry — server-side in :class:`~predictionio_tpu.fleet.cache.
  ResponseCache` and re-checked client-side (a skewed sidecar answer is
  dropped locally, never served).
- The client is **advisory**: any doubt — timeout, protocol error,
  open breaker, epoch skew — degrades to a miss, never a stale serve,
  and every degrade is *recorded* (an outcome counter + ``lastError``
  on the status surface; the ``robust-fallback-swallows`` lint rule
  pins this path as its clean exemplar). Killing the tier therefore
  degrades the fleet to exactly the per-router PR-14 behavior.
- A :class:`~predictionio_tpu.utils.resilience.CircuitBreaker` guards
  the sidecar socket: a dead sidecar costs a handful of timeouts, then
  every lookup is an instant local miss until the cooldown probe.

The sidecar also answers ``GET /cache/top`` — the hottest entries by
hit count — which restarting routers use to pre-fill their local LRU
(**cache warming**: a deploy never exposes the backends to the full
hot set again).
"""

from __future__ import annotations

import http.client
import json
import logging
import os
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple
from urllib.parse import parse_qs, urlsplit

from ..api.http import BackgroundHTTPServer, JsonHTTPHandler
from ..utils.resilience import CircuitBreaker, CircuitOpen
from .cache import CacheEntry, ResponseCache

logger = logging.getLogger(__name__)

__all__ = [
    "SHARED_OUTCOMES",
    "SharedCacheClient",
    "SharedCacheServer",
]

#: client outcome vocabulary — closed, safe as a metric label
#: (``pio_router_shared_cache_total{outcome}``): "hit"/"negative_hit"/
#: "miss" are the sidecar's answers; "epoch_skew" is an answer the
#: client dropped locally (entry filled under another epoch);
#: "open"/"error" are degrades (breaker short-circuit / any transport
#: or protocol failure); "put"/"put_error" account the fill path.
SHARED_OUTCOMES = (
    "hit",
    "negative_hit",
    "miss",
    "epoch_skew",
    "open",
    "error",
    "put",
    "put_error",
)


class SharedCacheHandler(JsonHTTPHandler):
    """The sidecar's wire surface — same HTTP discipline as the storage
    nodes (JSON bodies, keep-alive, obs routes)."""

    def do_GET(self) -> None:  # noqa: N802 (stdlib casing)
        parts = urlsplit(self.path)
        if self.serve_obs(parts.path):
            return
        if parts.path == "/status.json":
            self.respond(200, self.server.status_json())
        elif parts.path == "/cache/top":
            query = parse_qs(parts.query)
            try:
                n = int(query.get("n", ["50"])[0])
            except ValueError:
                self.respond(400, {"error": "n must be an integer"})
                return
            # byte budget for the warming export: explicit maxBytes
            # query param wins, PIO_SHAREDCACHE_WARM_BYTES is the fleet
            # default, unset = unbounded (docs/cli.md)
            raw_budget = query.get("maxBytes", [None])[0]
            if raw_budget is None:
                raw_budget = os.environ.get("PIO_SHAREDCACHE_WARM_BYTES")
            max_bytes: Optional[int] = None
            if raw_budget not in (None, ""):
                try:
                    max_bytes = int(raw_budget)
                except ValueError:
                    self.respond(
                        400, {"error": "maxBytes must be an integer"}
                    )
                    return
            self.respond(
                200,
                {
                    "entries": self.server.cache.export_top(
                        n, max_bytes=max_bytes
                    )
                },
            )
        else:
            self.respond(404, {"error": f"no route {parts.path}"})

    def do_POST(self) -> None:  # noqa: N802
        raw = self.read_body()
        try:
            body = json.loads(raw.decode("utf-8")) if raw else {}
        except (ValueError, UnicodeDecodeError):
            self.respond(400, {"error": "invalid JSON body"})
            return
        if not isinstance(body, dict):
            self.respond(400, {"error": "body must be a JSON object"})
            return
        if self.path == "/cache/lookup":
            self.respond(200, self.server.lookup(body))
        elif self.path == "/cache/put":
            self.respond(200, self.server.put(body))
        elif self.path == "/cache/flush":
            self.respond(
                200,
                {
                    "flushed": self.server.cache.flush(
                        variant=body.get("variant"),
                        reason=str(body.get("reason", "explicit")),
                    )
                },
            )
        else:
            self.respond(404, {"error": f"no route {self.path}"})


class SharedCacheServer(BackgroundHTTPServer):
    """The sidecar: a :class:`ResponseCache` behind HTTP.

    Deliberately dumb — it stores what routers hand it and answers
    epoch-checked reads; *all* policy (what to cache, negative TTLs,
    when to flush) lives in the routers. A dumb tier has nothing to
    disagree with the routers about."""

    def __init__(
        self,
        ip: str = "127.0.0.1",
        port: int = 0,
        max_entries: int = 8192,
        ttl_s: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        super().__init__((ip, port), SharedCacheHandler)
        self.cache = ResponseCache(
            max_entries=max_entries,
            ttl_s=ttl_s,
            clock=clock,
            on_invalidate=self._on_invalidate,
        )
        self._lookups = self.metrics.counter(
            "pio_sharedcache_lookups_total",
            "Sidecar lookups by outcome",
            labelnames=("outcome",),
        )
        self._invalidations = self.metrics.counter(
            "pio_sharedcache_invalidations_total",
            "Sidecar entries dropped, by reason",
            labelnames=("reason",),
        )
        self.metrics.gauge_callback(
            "pio_sharedcache_entries",
            lambda: float(len(self.cache)),
            help="Live sidecar cache entries",
        )

    def _on_invalidate(self, reason: str, count: int) -> None:
        self._invalidations.inc(count, reason=reason)

    # -- ops (handler thread) ---------------------------------------------
    def lookup(self, body: dict) -> dict:
        key = (str(body.get("variant", "-")), str(body.get("query", "")))
        epoch = str(body.get("epoch", ""))
        entry = self.cache.get(key, epoch)
        if entry is None:
            self._lookups.inc(1, outcome="miss")
            return {"found": False}
        self._lookups.inc(
            1, outcome="negative_hit" if entry.negative else "hit"
        )
        return {
            "found": True,
            "body": entry.body,
            "servedVariant": entry.variant,
            "epoch": entry.epoch,
            "negative": entry.negative,
        }

    def put(self, body: dict) -> dict:
        key = (str(body.get("variant", "-")), str(body.get("query", "")))
        ttl_s = body.get("ttlS")
        self.cache.put(
            key,
            body.get("body"),
            body.get("servedVariant"),
            str(body.get("epoch", "")),
            ttl_s=float(ttl_s) if ttl_s is not None else None,
            negative=bool(body.get("negative", False)),
        )
        return {"stored": True}

    def status_json(self) -> dict:
        return {"server": "sharedcache", "cache": self.cache.snapshot()}


class SharedCacheClient:
    """The router-side advisory client.

    Degrade contract (the ``robust-fallback-swallows`` clean exemplar):
    every path that turns a sidecar problem into a miss goes through
    :meth:`_record_degrade`, which counts the outcome, keeps the last
    error on the status surface and logs at debug — a degraded tier is
    *visible*, never silent. The return value of a degrade is always
    ``None`` (= miss): the one thing this client never does is guess.
    """

    def __init__(
        self,
        addr: str,
        timeout_s: float = 0.25,
        breaker: Optional[CircuitBreaker] = None,
        on_outcome: Optional[Callable[[str], None]] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.addr = addr
        host, _, port = addr.partition(":")
        self._host = host
        self._port = int(port)
        self.timeout_s = float(timeout_s)
        self.breaker = (
            breaker
            if breaker is not None
            else CircuitBreaker.from_env(f"sharedcache-{addr}", clock=clock)
        )
        self._on_outcome = on_outcome
        self._local = threading.local()
        self._lock = threading.Lock()
        self.outcomes: Dict[str, int] = {}
        self.last_error: Optional[str] = None

    # -- accounting --------------------------------------------------------
    def _count(self, outcome: str) -> None:
        with self._lock:
            self.outcomes[outcome] = self.outcomes.get(outcome, 0) + 1
        if self._on_outcome is not None:
            try:
                self._on_outcome(outcome)
            except Exception:
                pass  # observability must never fail a lookup

    def _record_degrade(self, outcome: str, exc: BaseException) -> None:
        """Advisory degrade: record the failure (counter + status
        surface + debug log) and answer a miss. Never raises."""
        self._count(outcome)
        with self._lock:
            self.last_error = f"{type(exc).__name__}: {exc}"
        logger.debug(
            "shared cache %s degraded to miss (%s): %s",
            self.addr, outcome, exc,
        )
        return None

    # -- transport ---------------------------------------------------------
    def _conn(self) -> http.client.HTTPConnection:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = http.client.HTTPConnection(
                self._host, self._port, timeout=self.timeout_s
            )
            self._local.conn = conn
        return conn

    def _drop_conn(self) -> None:
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            self._local.conn = None
            try:
                conn.close()
            except Exception:
                pass

    def _request(
        self,
        method: str,
        path: str,
        payload: Optional[dict] = None,
        timeout_s: Optional[float] = None,
    ) -> Any:
        """One keep-alive request → parsed JSON; raises on ANY problem
        (non-200, bad JSON, socket error) — callers translate into a
        recorded degrade. A failed connection is dropped so the next
        call starts clean."""
        conn = self._conn()
        conn.timeout = (
            self.timeout_s if timeout_s is None else float(timeout_s)
        )
        if conn.sock is not None:
            conn.sock.settimeout(conn.timeout)
        try:
            body = (
                json.dumps(payload).encode("utf-8")
                if payload is not None
                else None
            )
            conn.request(
                method, path, body=body,
                headers={"Content-Type": "application/json"},
            )
            resp = conn.getresponse()
            raw = resp.read()
            if resp.status != 200:
                raise RuntimeError(
                    f"sidecar answered {resp.status} on {path}"
                )
            return json.loads(raw.decode("utf-8"))
        except Exception:
            self._drop_conn()
            raise

    # -- the tier ----------------------------------------------------------
    def lookup(
        self,
        key: Tuple[str, str],
        epoch: str,
        budget_s: Optional[float] = None,
    ) -> Optional[CacheEntry]:
        """The shared tier's answer for ``key`` under ``epoch`` — a
        :class:`CacheEntry` on a hit, ``None`` on a miss *or any doubt*.
        ``budget_s`` caps the lookup below the request's remaining
        deadline so the tier can never blow the caller's budget."""
        try:
            self.breaker.before_call()
        except CircuitOpen as exc:
            return self._record_degrade("open", exc)
        timeout = self.timeout_s
        if budget_s is not None:
            timeout = max(0.001, min(timeout, float(budget_s)))
        try:
            out = self._request(
                "POST",
                "/cache/lookup",
                {"variant": key[0], "query": key[1], "epoch": epoch},
                timeout_s=timeout,
            )
        except Exception as exc:
            self.breaker.record_failure()
            return self._record_degrade("error", exc)
        self.breaker.record_success()
        if not out.get("found"):
            self._count("miss")
            return None
        if str(out.get("epoch")) != epoch:
            # skewed sidecar (should not happen: the server checks too)
            # — drop locally, never serve across epochs
            self._count("epoch_skew")
            return None
        negative = bool(out.get("negative", False))
        self._count("negative_hit" if negative else "hit")
        return CacheEntry(
            body=out.get("body"),
            variant=out.get("servedVariant"),
            epoch=epoch,
            stored_at=0.0,  # freshness is the sidecar's concern
            negative=negative,
        )

    def put(
        self,
        key: Tuple[str, str],
        body: Any,
        variant: Optional[str],
        epoch: str,
        ttl_s: Optional[float] = None,
        negative: bool = False,
    ) -> bool:
        """Offer one filled response to the tier; best-effort (False =
        not stored, recorded)."""
        try:
            self.breaker.before_call()
        except CircuitOpen as exc:
            self._record_degrade("open", exc)
            return False
        try:
            self._request(
                "POST",
                "/cache/put",
                {
                    "variant": key[0],
                    "query": key[1],
                    "body": body,
                    "servedVariant": variant,
                    "epoch": epoch,
                    "ttlS": ttl_s,
                    "negative": negative,
                },
            )
        except Exception as exc:
            self.breaker.record_failure()
            self._record_degrade("put_error", exc)
            return False
        self.breaker.record_success()
        self._count("put")
        return True

    def flush(self, reason: str = "epoch") -> Optional[int]:
        """Ask the sidecar to drop everything (routers push this on an
        epoch move so the tier converges without waiting out reads).
        Best-effort: ``None`` = the ask didn't land (recorded)."""
        try:
            self.breaker.before_call()
        except CircuitOpen as exc:
            self._record_degrade("open", exc)
            return None
        try:
            out = self._request(
                "POST", "/cache/flush", {"reason": reason}
            )
        except Exception as exc:
            self.breaker.record_failure()
            self._record_degrade("error", exc)
            return None
        self.breaker.record_success()
        return int(out.get("flushed", 0))

    def top(self, n: int = 50, max_bytes: Optional[int] = None) -> list:
        """The sidecar's hottest entries (the warming export); an empty
        list on any doubt (recorded) — warming is opportunistic.
        ``max_bytes`` forwards a byte budget for the export (the sidecar
        applies its own ``PIO_SHAREDCACHE_WARM_BYTES`` default when this
        is None)."""
        try:
            self.breaker.before_call()
        except CircuitOpen as exc:
            self._record_degrade("open", exc)
            return []
        path = f"/cache/top?n={int(n)}"
        if max_bytes is not None:
            path += f"&maxBytes={int(max_bytes)}"
        try:
            out = self._request("GET", path)
        except Exception as exc:
            self.breaker.record_failure()
            self._record_degrade("error", exc)
            return []
        self.breaker.record_success()
        entries = out.get("entries")
        return entries if isinstance(entries, list) else []

    def status(self) -> dict:
        """The ``/router.json`` sharedCache block."""
        with self._lock:
            return {
                "addr": self.addr,
                "timeoutS": self.timeout_s,
                "breaker": self.breaker.snapshot(),
                "outcomes": dict(self.outcomes),
                "lastError": self.last_error,
            }
