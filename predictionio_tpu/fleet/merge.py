"""Exact top-k merge over per-shard candidate lists.

The sharded serving mode partitions the item-factor matrix across query
servers (``docs/fleet.md``); each shard answers a query with its *local*
top-k. Because every item lives on exactly one shard and scores are
computed against the full user factors, the global top-k is a subset of
the union of local top-ks — so merging the per-shard lists reproduces
the unsharded answer *exactly*, not approximately (the serving-side
analogue of the sharded-embedding gather in Tensor Casting / the
sharded-factor layout in ALX, PAPERS.md).

Determinism contract: merge order is ``(-score, item_id)`` — score
descending, ties broken by item id ascending — so any router replica
merging the same shard answers produces byte-identical output. Pure,
stdlib-only module (the ``rollout/plan.py`` discipline): testable in
isolation, provably stable across restarts.
"""

from __future__ import annotations

import heapq
from typing import Any, Dict, List, Optional, Sequence

__all__ = [
    "merge_item_scores",
    "merge_predictions",
    "merged_matches_reference",
]


def _sort_key(entry: Dict[str, Any]):
    # score descending, then item id ascending: a total order, so equal
    # scores cannot flap between merges or router replicas
    return (-float(entry.get("score", 0.0)), str(entry.get("item", "")))


def merge_item_scores(
    shard_lists: Sequence[Sequence[Dict[str, Any]]],
    k: Optional[int] = None,
) -> List[Dict[str, Any]]:
    """K-way merge of per-shard ``[{"item", "score"}, ...]`` lists into
    the exact global top-``k`` (all entries when ``k`` is None).

    Each shard list is first ordered by the merge key (shards already
    return descending scores, but the merge must not *depend* on it —
    a misbehaving shard degrades to a sort, never to a wrong answer),
    then consumed through a heap so the common case is O(total · log S).
    """
    runs = [sorted(entries, key=_sort_key) for entries in shard_lists if entries]
    merged = heapq.merge(*runs, key=_sort_key)
    if k is None:
        return list(merged)
    out: List[Dict[str, Any]] = []
    for entry in merged:
        out.append(entry)
        if len(out) >= k:
            break
    return out


def merge_predictions(
    shard_results: Sequence[Any], k: Optional[int] = None
) -> Any:
    """Merge per-shard *encoded* prediction bodies (the ``/queries.json``
    response JSON) into one.

    Recognizes the templates' shared ``{"itemScores": [...]}`` wire
    shape (``models/wire.py``) and merges those lists exactly; any other
    shape cannot be sharded meaningfully, so the first shard's answer
    passes through unchanged — with a loud ``ValueError`` when shards
    *disagree* on non-mergeable bodies (silently picking one would turn
    a misconfigured fleet into quietly wrong answers)."""
    results = [r for r in shard_results if r is not None]
    if not results:
        return None
    if all(isinstance(r, dict) and "itemScores" in r for r in results):
        merged = dict(results[0])
        merged["itemScores"] = merge_item_scores(
            [r["itemScores"] for r in results], k
        )
        return merged
    first = results[0]
    if any(r != first for r in results[1:]):
        raise ValueError(
            "shard responses disagree and carry no itemScores list to "
            "merge; this engine's result shape cannot be served sharded"
        )
    return first


def merged_matches_reference(
    merged: Any, reference: Any, rtol: float = 1e-5, atol: float = 1e-6
) -> bool:
    """The f32 ranking-equality contract shared by sharded serving and
    the fused top-k kernels: identical item *ranking* (the top-k and its
    order — exact), scores equal to f32 reassociation tolerance. The
    item set/order is what "exact top-k" means; scores carry last-ulp
    noise because XLA's matmul accumulation order depends on the matrix
    shape, so a 6-item shard and a 12-item catalog — or a streamed tile
    and a dense row — round differently (docs/fleet.md; the ROUND7
    sort-gather analysis). Lives here, next to the merge whose exactness
    it defines, so every consumer (the fleet chaos drill, the fused
    top-k equivalence tests) pins the SAME contract. Stdlib-only like
    the rest of the module (``|a-b| <= atol + rtol*|b|``, numpy
    ``allclose`` semantics)."""
    if not (isinstance(merged, dict) and isinstance(reference, dict)):
        return merged == reference
    got = merged.get("itemScores")
    want = reference.get("itemScores")
    if got is None or want is None:
        return merged == reference
    got_items = [e.get("item") for e in got]
    want_items = [e.get("item") for e in want]
    if got_items != want_items:
        # Two items whose scores differ by LESS than the tolerance can
        # legitimately swap rank between two computations of the same
        # top-k (the same noise, applied to a near-tie). Accept a
        # permutation only when the item SETS agree and the positionwise
        # scores still align — which confines any swap to within a tied
        # window; a genuinely different item in the list still fails.
        if set(got_items) != set(want_items):
            return False
    if len(got) != len(want):
        return False
    for a, b in zip(got, want):
        ga, gb = float(a.get("score", 0.0)), float(b.get("score", 0.0))
        if not abs(ga - gb) <= atol + rtol * abs(gb):
            return False
    return True
