"""Exact top-k merge over per-shard candidate lists.

The sharded serving mode partitions the item-factor matrix across query
servers (``docs/fleet.md``); each shard answers a query with its *local*
top-k. Because every item lives on exactly one shard and scores are
computed against the full user factors, the global top-k is a subset of
the union of local top-ks — so merging the per-shard lists reproduces
the unsharded answer *exactly*, not approximately (the serving-side
analogue of the sharded-embedding gather in Tensor Casting / the
sharded-factor layout in ALX, PAPERS.md).

Determinism contract: merge order is ``(-score, item_id)`` — score
descending, ties broken by item id ascending — so any router replica
merging the same shard answers produces byte-identical output. Pure,
stdlib-only module (the ``rollout/plan.py`` discipline): testable in
isolation, provably stable across restarts.
"""

from __future__ import annotations

import heapq
from typing import Any, Dict, List, Optional, Sequence

__all__ = ["merge_item_scores", "merge_predictions"]


def _sort_key(entry: Dict[str, Any]):
    # score descending, then item id ascending: a total order, so equal
    # scores cannot flap between merges or router replicas
    return (-float(entry.get("score", 0.0)), str(entry.get("item", "")))


def merge_item_scores(
    shard_lists: Sequence[Sequence[Dict[str, Any]]],
    k: Optional[int] = None,
) -> List[Dict[str, Any]]:
    """K-way merge of per-shard ``[{"item", "score"}, ...]`` lists into
    the exact global top-``k`` (all entries when ``k`` is None).

    Each shard list is first ordered by the merge key (shards already
    return descending scores, but the merge must not *depend* on it —
    a misbehaving shard degrades to a sort, never to a wrong answer),
    then consumed through a heap so the common case is O(total · log S).
    """
    runs = [sorted(entries, key=_sort_key) for entries in shard_lists if entries]
    merged = heapq.merge(*runs, key=_sort_key)
    if k is None:
        return list(merged)
    out: List[Dict[str, Any]] = []
    for entry in merged:
        out.append(entry)
        if len(out) >= k:
            break
    return out


def merge_predictions(
    shard_results: Sequence[Any], k: Optional[int] = None
) -> Any:
    """Merge per-shard *encoded* prediction bodies (the ``/queries.json``
    response JSON) into one.

    Recognizes the templates' shared ``{"itemScores": [...]}`` wire
    shape (``models/wire.py``) and merges those lists exactly; any other
    shape cannot be sharded meaningfully, so the first shard's answer
    passes through unchanged — with a loud ``ValueError`` when shards
    *disagree* on non-mergeable bodies (silently picking one would turn
    a misconfigured fleet into quietly wrong answers)."""
    results = [r for r in shard_results if r is not None]
    if not results:
        return None
    if all(isinstance(r, dict) and "itemScores" in r for r in results):
        merged = dict(results[0])
        merged["itemScores"] = merge_item_scores(
            [r["itemScores"] for r in results], k
        )
        return merged
    first = results[0]
    if any(r != first for r in results[1:]):
        raise ValueError(
            "shard responses disagree and carry no itemScores list to "
            "merge; this engine's result shape cannot be served sharded"
        )
    return first
