"""``pio router``: the L7 tier that fronts N query servers.

One query server tops out at one process; the ROADMAP north star is
heavy traffic from millions of users. The router is the horizontal
story (``docs/fleet.md``):

- **Consistent routing, zero coordination.** Replica affinity rides the
  same pure SHA-256 ``salt|key → bucket`` split the canary plane uses
  (:func:`~predictionio_tpu.rollout.plan.bucket_for_key`): the same
  entity key lands on the same backend from *any* router replica, and
  canary variant assignment needs no router participation at all — each
  query server computes it from the replicated ``RolloutPlan`` with the
  same pure function, so a request retried on another replica gets the
  byte-identical variant. The router *verifies* that invariant per
  request (``pio_router_variant_mismatch_total`` — it reads the active
  plan through the replicated ``rollout_plan_get_active`` and compares
  its own assignment against the backend's ``X-PIO-Variant`` echo).
- **Per-app admission quotas.** The PR-2 bounded-admission discipline,
  one level up: each app (the ``X-PIO-App`` header) gets an in-flight
  cap at the router, so one tenant's surge sheds with 503 + Retry-After
  instead of starving the fleet.
- **Breaker-guarded health + retry-on-another-replica.** One
  :class:`~predictionio_tpu.utils.resilience.CircuitBreaker` per
  backend; a dead or shedding backend fails the read over to the next
  replica *inside the same request* (no backoff sleeps — the retry
  target is a different process), with the deadline budget split across
  the remaining attempts so the schedule always fits the client's
  budget.
- **Sharded-model scatter/gather.** With ``sharded=True`` each backend
  holds one partition of the item factors (``ServerConfig.shard_index``
  / ``shard_count``); the router fans a query out to every shard
  concurrently and k-way-merges the local top-ks into the exact global
  top-k (:mod:`~predictionio_tpu.fleet.merge`).

No jax anywhere: a router node is pure stdlib + the shared resilience
and obs planes.
"""

from __future__ import annotations

import dataclasses
import http.client
import json
import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple
from urllib.parse import urlparse

from ..api.http import BackgroundHTTPServer, JsonHTTPHandler
from ..obs.trace import TRACE_HEADER, Tracer
from ..rollout.plan import (
    BASELINE,
    VARIANT_HEADER,
    bucket_for_key,
    sticky_key,
    variant_for_key,
)
from ..utils.resilience import (
    DEADLINE_HEADER,
    CircuitBreaker,
    CircuitOpen,
    Deadline,
    DeadlineExceeded,
)
from .merge import merge_predictions

logger = logging.getLogger(__name__)

__all__ = [
    "APP_HEADER",
    "VARIANT_HEADER",
    "RouterBadRequest",
    "RouterConfig",
    "RouterServer",
    "create_router",
]


class RouterBadRequest(ValueError):
    """The client's request body is malformed → 400 (never retried)."""


class FleetOverloaded(RuntimeError):
    """Every replica shed the read (per-backend 503s): fleet-wide
    backpressure, not a routing failure. Surfaces to the client as
    503 + Retry-After — a well-behaved client must back off, exactly as
    it would against a single shedding server; a generic 502 here would
    make clients retry immediately into the overload."""

    def __init__(self, message: str, retry_after_s: int = 1):
        super().__init__(message)
        self.retry_after_s = retry_after_s

#: app identity a quota is keyed on; absent header = the "-" default app
APP_HEADER = "X-PIO-App"

#: rollout stages in which a plan routes/labels traffic (mirrors
#: storage.metadata ROLLOUT_SHADOW/ROLLOUT_CANARY without importing the
#: storage plane into the hot path)
_ACTIVE_STAGES = ("SHADOW", "CANARY")


@dataclasses.dataclass(frozen=True)
class RouterConfig:
    """``pio router`` surface (docs/fleet.md, docs/cli.md)."""

    ip: str = "localhost"
    port: int = 8700
    #: backend query servers, ``host:port`` each. In sharded mode the
    #: POSITION is the shard index — backend i must serve shard i of
    #: len(backends).
    backends: Tuple[str, ...] = ()
    #: replicated (False): any backend answers any query, affinity by
    #: bucket, failover to the next replica. Sharded (True): every
    #: backend holds one item-factor partition; queries fan out to all
    #: and merge.
    sharded: bool = False
    #: per-app in-flight caps ({app: max}); apps not listed fall back to
    #: ``default_quota`` (0 = unbounded)
    quotas: Dict[str, int] = dataclasses.field(default_factory=dict)
    default_quota: int = 0
    #: per-leg socket timeout (always capped by the request deadline)
    timeout_s: float = 10.0
    #: max distinct replicas tried per read (replicated mode);
    #: 0 = every configured backend
    max_attempts: int = 0
    #: salt for the replica-affinity bucket — any value shared by all
    #: router replicas keeps them consistent; it is deliberately NOT the
    #: rollout salt, so starting a canary never reshuffles which backend
    #: a user's requests land on
    routing_salt: str = "pio-router"
    #: top-k used for the sharded merge when the query carries no "num"
    #: field: must match the engine's query-class default (the bundled
    #: templates all default to 10), or the merged answer's length
    #: diverges from the unsharded server's — each shard fills the
    #: default independently and the router cannot see it
    default_num: int = 10
    #: engine identity whose active RolloutPlan the router mirrors for
    #: the variant-consistency check (None = first backend's instance,
    #: discovered lazily; the check is skipped without a registry)
    engine_id: Optional[str] = None
    engine_version: Optional[str] = None
    engine_variant: str = "engine.json"
    #: seconds an active-plan read is cached before re-reading metadata
    plan_refresh_s: float = 2.0


class _RouterHandler(JsonHTTPHandler):
    server: "RouterServer"

    def do_POST(self) -> None:  # noqa: N802
        raw = self.read_body()
        path = urlparse(self.path).path
        if path != "/queries.json":
            self.respond(404, {"message": "Not Found"})
            return
        app = (self.headers.get(APP_HEADER) or "-").strip() or "-"
        if not self.server.admit(app):
            self.server.count_request("shed")
            self.server.count_shed(app)
            self.respond(
                503,
                {"message": f"app {app!r} over its router quota"},
                headers={"Retry-After": 1},
            )
            return
        deadline = Deadline.from_header(
            self.headers.get(DEADLINE_HEADER), clock=self.server.clock
        )
        started = self.server.clock()
        try:
            if deadline is not None:
                deadline.check("router-admission")
            with self.server.tracer.server_span(
                "POST /queries.json",
                header_value=self.headers.get(TRACE_HEADER),
                tags={"router": "1"},
            ) as span:
                status, body, variant = self.server.route_query(
                    raw, deadline, trace_id=span.trace_id
                )
            headers = {TRACE_HEADER: span.trace_id}
            if variant is not None:
                headers[VARIANT_HEADER] = variant
            self.server.count_request("ok" if status == 200 else "error")
            self.respond(status, body, headers=headers)
        except DeadlineExceeded as exc:
            self.server.count_request("deadline")
            self.respond(504, {"message": str(exc), "stage": exc.stage})
        except RouterBadRequest as exc:
            self.server.count_request("bad_request")
            self.respond(400, {"message": str(exc)})
        except FleetOverloaded as exc:
            # fleet-wide backpressure relays as a shed, never a 502:
            # clients that honor Retry-After must keep backing off
            self.server.count_request("shed")
            self.server.count_shed(app)
            self.respond(
                503,
                {"message": str(exc)},
                headers={"Retry-After": exc.retry_after_s},
            )
        except Exception as exc:
            logger.exception("router query failed")
            self.server.count_request("error")
            self.respond(502, {"message": str(exc)})
        finally:
            self.server.observe_latency(self.server.clock() - started)
            self.server.release(app)

    def do_GET(self) -> None:  # noqa: N802
        path = urlparse(self.path).path
        if self.serve_obs(path):  # /metrics + /traces.json
            return
        if path in ("/", "/status.json", "/router.json"):
            self.respond(200, self.server.status_json())
        elif path == "/stop":
            self.respond(200, {"message": "Shutting down"})
            self.server.stop_async()
        else:
            self.respond(404, {"message": "Not Found"})


class RouterServer(BackgroundHTTPServer):
    """The router process: stateless but for quota counters, breaker
    state and the cached plan read — everything a replica needs to agree
    with its peers is a pure function of (config, replicated plan)."""

    def __init__(
        self,
        config: RouterConfig,
        registry=None,
        clock: Callable[[], float] = time.monotonic,
    ):
        if not config.backends:
            raise ValueError("router needs at least one backend (host:port)")
        self.config = config
        self.registry = registry
        self.clock = clock
        self.backends: Tuple[str, ...] = tuple(config.backends)
        # one breaker per backend: health is judged per process, and an
        # open breaker takes the backend out of the rotation until its
        # cooldown admits a probe
        self.breakers: Dict[str, CircuitBreaker] = {
            b: CircuitBreaker.from_env(f"backend-{b}", clock=clock)
            for b in self.backends
        }
        #: guards the mutable tables below (quota in-flight counts, the
        #: cached plan, the lazily-discovered engine identity); every
        #: cross-thread reader — handler threads, gauge callbacks —
        #: takes it, and nothing blocking runs under it
        self._lock = threading.Lock()
        self._inflight: Dict[str, int] = {}
        self._plan: Optional[Any] = None
        self._plan_read_at: Optional[float] = None
        self._engine_key: Optional[Tuple[str, str, str]] = (
            (config.engine_id, config.engine_version or "1", config.engine_variant)
            if config.engine_id
            else None
        )
        # per-(worker thread, backend) persistent connections: handler
        # and fan-out threads each keep their own socket per backend, so
        # keep-alive reuse never interleaves two requests on one socket
        self._conns = threading.local()

        metrics_clock = clock
        from ..obs.metrics import MetricsRegistry

        metrics = MetricsRegistry(clock=metrics_clock)
        self._requests = metrics.counter(
            "pio_router_requests_total",
            "Routed requests by outcome",
            labelnames=("outcome",),
        )
        self._retries = metrics.counter(
            "pio_router_retries_total",
            "Reads retried on another replica, by failed backend",
            labelnames=("backend",),
        )
        self._shed = metrics.counter(
            "pio_router_shed_total",
            "Requests shed at the router quota, by app",
            labelnames=("app",),
        )
        self._backend_events = metrics.counter(
            "pio_router_backend_events_total",
            "Per-backend leg outcomes",
            labelnames=("backend", "kind"),
        )
        self._hist = metrics.histogram(
            "pio_router_request_seconds",
            "End-to-end routed request latency",
        )
        self._variant_mismatch = metrics.counter(
            "pio_router_variant_mismatch_total",
            "Requests whose backend variant disagreed with the router's "
            "own pure-function assignment (must stay 0)",
        )
        metrics.gauge_callback(
            "pio_router_backends_up",
            self._backends_up,
            "Backends whose breaker currently admits traffic",
        )
        metrics.gauge(
            "pio_router_sharded", "1 when serving in sharded-model mode"
        ).set(1 if config.sharded else 0)
        super().__init__(
            (config.ip, config.port),
            _RouterHandler,
            metrics=metrics,
            tracer=Tracer("router", clock=clock),
            health_kind="router",
        )

    # -- admission (per-app quotas) ---------------------------------------
    def quota_for(self, app: str) -> int:
        return self.config.quotas.get(app, self.config.default_quota)

    def admit(self, app: str) -> bool:
        quota = self.quota_for(app)
        with self._lock:
            inflight = self._inflight.get(app, 0)
            if quota > 0 and inflight >= quota:
                return False
            self._inflight[app] = inflight + 1
            return True

    def release(self, app: str) -> None:
        with self._lock:
            remaining = max(0, self._inflight.get(app, 0) - 1)
            if remaining:
                self._inflight[app] = remaining
            else:
                # drop drained apps: X-PIO-App is client-controlled, and
                # a table keyed by every value ever seen would grow
                # without bound on this long-lived front tier (the shed
                # counter is safe — the metrics registry caps label
                # cardinality into "_overflow")
                self._inflight.pop(app, None)

    # -- metrics hooks (handler-facing; the registry is thread-safe) ------
    def count_request(self, outcome: str) -> None:
        self._requests.inc(1, outcome=outcome)

    def count_shed(self, app: str) -> None:
        self._shed.inc(1, app=app)

    def observe_latency(self, elapsed_s: float) -> None:
        self._hist.observe(max(0.0, elapsed_s))

    def _backends_up(self) -> int:
        return sum(
            1
            for b in self.breakers.values()
            if b.state != CircuitBreaker.OPEN
        )

    # -- fleet-consistent plan view ---------------------------------------
    def active_plan(self):
        """The engine's active RolloutPlan via the replicated
        ``rollout_plan_get_active`` read, cached ``plan_refresh_s``.
        Any failure (no registry, metadata outage, unknown engine)
        degrades to None — the consistency check is an alarm, never a
        serving dependency."""
        if self.registry is None:
            return None
        with self._lock:
            fresh = (
                self._plan_read_at is not None
                and self.clock() - self._plan_read_at
                < self.config.plan_refresh_s
            )
            if fresh:
                return self._plan
            engine_key = self._engine_key
        plan = None
        try:
            md = self.registry.get_metadata()
            if engine_key is None:
                engine_key = self._discover_engine_key(md)
            if engine_key is not None:
                plan = md.rollout_plan_get_active(*engine_key)
        except Exception:
            logger.debug("router plan read failed", exc_info=True)
            plan = None
        with self._lock:
            self._plan = plan
            self._plan_read_at = self.clock()
            if engine_key is not None:
                self._engine_key = engine_key
        return plan

    def _discover_engine_key(self, md) -> Optional[Tuple[str, str, str]]:
        """Without an explicit --engine-id, mirror whatever engine the
        fleet's latest completed instance belongs to."""
        try:
            instances = md.engine_instance_get_all()
        except Exception:
            return None
        completed = [i for i in instances if i.status == "COMPLETED"]
        if not completed:
            return None
        latest = max(completed, key=lambda i: i.start_time)
        return (latest.engine_id, latest.engine_version, latest.engine_variant)

    def variant_preview(self, payload: Any) -> Optional[str]:
        """The router's own (pure-function) variant assignment for this
        payload under the active plan — what any query server must also
        compute. None when no plan is active/readable."""
        plan = self.active_plan()
        if plan is None or plan.stage not in _ACTIVE_STAGES:
            return None
        if plan.stage != "CANARY":
            return BASELINE
        return variant_for_key(plan.salt, sticky_key(payload), plan.percent)

    # -- routing ----------------------------------------------------------
    def route_query(
        self,
        raw: bytes,
        deadline: Optional[Deadline],
        trace_id: Optional[str] = None,
    ) -> Tuple[int, Any, Optional[str]]:
        """One client request end to end → ``(status, body, variant)``.
        Raises DeadlineExceeded/ValueError for the handler's 504/400."""
        try:
            payload = json.loads(raw.decode("utf-8")) if raw else {}
        except ValueError as exc:
            raise RouterBadRequest(f"invalid query JSON: {exc}") from exc
        # stall watchdog (docs/slo.md): a routed request that outlives a
        # multiple of its budget — every failover leg wedged — is a
        # fleet-level stall worth a flight dump
        watchdog = self.health.watchdog if self.health is not None else None
        token = (
            watchdog.enter(
                "router.request",
                budget_s=(
                    deadline.remaining_s() if deadline is not None else None
                ),
            )
            if watchdog is not None
            else None
        )
        try:
            if self.config.sharded:
                status, body, variant = self._route_sharded(
                    raw, payload, deadline, trace_id
                )
            else:
                status, body, variant = self._route_replicated(
                    raw, payload, deadline, trace_id
                )
        finally:
            if watchdog is not None:
                watchdog.exit(token)
        if status == 200:
            self._check_variant(payload, variant)
        return status, body, variant

    def _check_variant(self, payload: Any, served: Optional[str]) -> None:
        expected = self.variant_preview(payload)
        if expected is None or served in (None, "", "-"):
            return  # no active plan, or a backend predating the header
        if served != expected:
            self._variant_mismatch.inc(1)
            logger.warning(
                "variant mismatch: router computed %s, backend served %s "
                "(sticky split drifted — check plan replication)",
                expected, served,
            )

    def _ordered_replicas(self, payload: Any) -> List[str]:
        """Affinity-first rotation: the sticky bucket picks the home
        replica, failover walks the rest in ring order. Pure function of
        (routing_salt, key, backend list) — every router replica
        produces the same order."""
        start = bucket_for_key(
            self.config.routing_salt, sticky_key(payload)
        ) % len(self.backends)
        ring = self.backends[start:] + self.backends[:start]
        admitting = [
            b for b in ring
            if self.breakers[b].state != CircuitBreaker.OPEN
        ]
        # every breaker open: trying the ring beats a guaranteed 502 (and
        # before_call below re-checks each breaker's cooldown properly)
        return admitting or list(ring)

    def _route_replicated(
        self,
        raw: bytes,
        payload: Any,
        deadline: Optional[Deadline],
        trace_id: Optional[str],
    ) -> Tuple[int, Any, Optional[str]]:
        replicas = self._ordered_replicas(payload)
        if self.config.max_attempts > 0:
            replicas = replicas[: self.config.max_attempts]
        last_error: Optional[str] = None
        all_shed = bool(replicas)
        for i, backend in enumerate(replicas):
            if deadline is not None:
                deadline.check("router-retry")
            attempts_left = len(replicas) - i
            breaker = self.breakers[backend]
            try:
                breaker.before_call()
            except CircuitOpen:
                self._backend_events.inc(1, backend=backend, kind="open_skip")
                all_shed = False
                continue
            try:
                status, body, headers = self._leg(
                    backend, raw, deadline, attempts_left, trace_id
                )
            except Exception as exc:
                breaker.record_failure()
                self._backend_events.inc(1, backend=backend, kind="error")
                if i + 1 < len(replicas):
                    self._retries.inc(1, backend=backend)
                last_error = f"{backend}: {exc}"
                all_shed = False
                continue
            if status == 503 or (status >= 500 and status != 504):
                # a shedding or erroring backend: the read belongs on
                # another replica (bounded-admission discipline says the
                # *fleet* answers even when one member cannot). 504 is
                # excluded: an expired deadline is the CLIENT's budget,
                # not backend sickness — it must neither trip the
                # breaker nor burn a failover leg it cannot afford.
                breaker.record_failure()
                self._backend_events.inc(1, backend=backend, kind="error")
                if i + 1 < len(replicas):
                    self._retries.inc(1, backend=backend)
                last_error = f"{backend}: HTTP {status}"
                if status != 503:
                    all_shed = False
                continue
            breaker.record_success()
            self._backend_events.inc(1, backend=backend, kind="ok")
            return status, body, headers.get(VARIANT_HEADER.lower())
        if all_shed:
            # every replica answered 503: fleet-wide backpressure, not a
            # routing failure — relay the shed so clients back off
            raise FleetOverloaded(
                f"all {len(replicas)} replicas are shedding load"
            )
        raise RuntimeError(
            f"no backend could serve the read (tried {len(replicas)}): "
            f"{last_error or 'all breakers open'}"
        )

    def _route_sharded(
        self,
        raw: bytes,
        payload: Any,
        deadline: Optional[Deadline],
        trace_id: Optional[str],
    ) -> Tuple[int, Any, Optional[str]]:
        """Scatter to every shard, gather, merge exactly. All legs run
        concurrently, each under the full remaining budget (they are
        parallel — splitting it would punish fan-out width). Legs get
        per-request threads, not a shared pool: a pool sized to the
        shard count would serialize concurrent client requests behind
        each other's slowest leg (head-of-line blocking — one backend
        stalling to its socket timeout would inflate every queued
        request). ThreadingHTTPServer already spawns per connection;
        N short-lived leg threads per request is the same discipline."""
        results: List = [None] * len(self.backends)

        def run_leg(idx: int, backend: str) -> None:
            try:
                results[idx] = self._shard_leg(
                    backend, raw, deadline, trace_id
                )
            finally:
                # ephemeral thread: its thread-local conns die with it —
                # close deterministically instead of leaking the socket
                # to GC (TIME_WAIT/fd churn under sustained fan-out)
                self._close_thread_conns()

        threads = [
            threading.Thread(
                target=run_leg, args=(i, b), daemon=True,
                name=f"router-leg-{i}",
            )
            for i, b in enumerate(self.backends)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        bodies: List[Any] = []
        variant: Optional[str] = None
        errors: List[str] = []
        for backend, (ok, value, leg_variant) in zip(
            self.backends, results
        ):
            if ok:
                bodies.append(value)
                if variant is None:
                    variant = leg_variant
            else:
                errors.append(f"{backend}: {value}")
        if errors:
            # a missing shard makes an exact merge impossible: fail the
            # read loudly instead of returning a silently truncated
            # catalog (docs/fleet.md#failure-modes)
            raise RuntimeError(
                f"{len(errors)}/{len(self.backends)} shards failed: "
                + "; ".join(errors)
            )
        k = payload.get("num") if isinstance(payload, dict) else None
        if not isinstance(k, int):
            # the engine's query class filled its default on every shard
            # (each returned up to default_num); merging untruncated
            # would hand the client shard_count × the unsharded count
            k = self.config.default_num
        merged = merge_predictions(bodies, k)
        return 200, merged, variant

    def _shard_leg(
        self,
        backend: str,
        raw: bytes,
        deadline: Optional[Deadline],
        trace_id: Optional[str],
    ) -> Tuple[bool, Any, Optional[str]]:
        """One shard fan-out leg (pool thread) → (ok, body|error, variant)."""
        breaker = self.breakers[backend]
        try:
            breaker.before_call()
        except CircuitOpen as exc:
            self._backend_events.inc(1, backend=backend, kind="open_skip")
            return False, str(exc), None
        try:
            status, body, headers = self._leg(
                backend, raw, deadline, 1, trace_id
            )
            if status != 200:
                raise RuntimeError(f"HTTP {status}")
        except Exception as exc:
            breaker.record_failure()
            self._backend_events.inc(1, backend=backend, kind="error")
            return False, str(exc), None
        breaker.record_success()
        self._backend_events.inc(1, backend=backend, kind="ok")
        return True, body, headers.get(VARIANT_HEADER.lower())

    # -- one backend leg --------------------------------------------------
    def _leg_timeout(
        self, deadline: Optional[Deadline], attempts_left: int
    ) -> float:
        """Budget split across the retry schedule: with ``attempts_left``
        sequential tries remaining, this leg may spend at most an even
        share of what's left — so a hung first replica can never eat the
        whole budget and leave the failover zero time."""
        timeout = self.config.timeout_s
        if deadline is not None:
            share = deadline.remaining_s() / max(1, attempts_left)
            timeout = max(0.001, min(timeout, share))
        return timeout

    def _leg(
        self,
        backend: str,
        raw: bytes,
        deadline: Optional[Deadline],
        attempts_left: int,
        trace_id: Optional[str],
    ) -> Tuple[int, Any, Dict[str, str]]:
        """One HTTP POST to one backend → (status, parsed body, headers).
        Propagates the trace id and the *remaining* deadline budget."""
        timeout = self._leg_timeout(deadline, attempts_left)
        headers = {"Content-Type": "application/json"}
        if trace_id:
            headers[TRACE_HEADER] = trace_id
        if deadline is not None:
            headers[DEADLINE_HEADER] = deadline.header_value()
        leg_tags: Dict[str, object] = {"backend": backend}
        with self.tracer.span("router.backend", tags=leg_tags):
            conn = self._conn(backend, timeout)
            conn.timeout = timeout
            if conn.sock is not None:  # reused keep-alive socket
                conn.sock.settimeout(timeout)
            try:
                conn.request("POST", "/queries.json", body=raw, headers=headers)
                resp = conn.getresponse()
                body_bytes = resp.read()
                resp_headers = {
                    k.lower(): v for k, v in resp.getheaders()
                }
                status = resp.status
            except Exception:
                self._drop_conn(backend)
                raise
            leg_tags["status"] = status  # recorded at span close
        try:
            body = json.loads(body_bytes.decode("utf-8")) if body_bytes else {}
        except ValueError:
            body = {"message": body_bytes.decode("utf-8", "replace")}
        return status, body, resp_headers

    def _conn(self, backend: str, timeout: float) -> http.client.HTTPConnection:
        pool = getattr(self._conns, "pool", None)
        if pool is None:
            pool = self._conns.pool = {}
        conn = pool.get(backend)
        if conn is None:
            host, _, port = backend.partition(":")
            conn = http.client.HTTPConnection(
                host, int(port or 80), timeout=timeout
            )
            pool[backend] = conn
        return conn

    def _drop_conn(self, backend: str) -> None:
        pool = getattr(self._conns, "pool", None)
        if pool is None:
            return
        conn = pool.pop(backend, None)
        if conn is not None:
            try:
                conn.close()
            except Exception:
                pass

    def _close_thread_conns(self) -> None:
        """Close every connection this thread pooled (per-request
        fan-out threads call it on exit; long-lived handler threads
        keep theirs for keep-alive reuse)."""
        pool = getattr(self._conns, "pool", None)
        if not pool:
            return
        for conn in pool.values():
            try:
                conn.close()
            except Exception:
                pass
        pool.clear()

    # -- status -----------------------------------------------------------
    def status_json(self) -> dict:
        with self._lock:
            inflight = {
                app: n for app, n in self._inflight.items() if n > 0
            }
            plan = self._plan
        out: dict = {
            "role": "router",
            "sharded": self.config.sharded,
            "backends": [
                {
                    "backend": b,
                    "breaker": self.breakers[b].snapshot(),
                }
                for b in self.backends
            ],
            "backendsUp": self._backends_up(),
            "quotas": dict(self.config.quotas),
            "defaultQuota": self.config.default_quota,
            "inflight": inflight,
        }
        if plan is not None:
            out["rolloutPlan"] = {
                "id": plan.id,
                "stage": plan.stage,
                "percent": plan.percent,
                "salt": plan.salt,
            }
        return out



def create_router(
    config: RouterConfig,
    registry=None,
    block: bool = True,
) -> RouterServer:
    """``pio router`` entry point (docs/cli.md)."""
    server = RouterServer(config, registry=registry)
    logger.info(
        "router: %s mode, %d backends, on %s:%d",
        "sharded" if config.sharded else "replicated",
        len(config.backends),
        config.ip,
        server.bound_port,
    )
    if block:
        try:
            server.serve_forever()
        finally:
            server.server_close()
    else:
        server.start_background()
    return server
