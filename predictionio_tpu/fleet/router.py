"""``pio router``: the L7 tier that fronts N query servers.

One query server tops out at one process; the ROADMAP north star is
heavy traffic from millions of users. The router is the horizontal
story (``docs/fleet.md``):

- **Consistent routing, zero coordination.** Replica affinity rides the
  same pure SHA-256 ``salt|key → bucket`` split the canary plane uses
  (:func:`~predictionio_tpu.rollout.plan.bucket_for_key`): the same
  entity key lands on the same backend from *any* router replica, and
  canary variant assignment needs no router participation at all — each
  query server computes it from the replicated ``RolloutPlan`` with the
  same pure function, so a request retried on another replica gets the
  byte-identical variant. The router *verifies* that invariant per
  request (``pio_router_variant_mismatch_total`` — it reads the active
  plan through the replicated ``rollout_plan_get_active`` and compares
  its own assignment against the backend's ``X-PIO-Variant`` echo).
- **Per-app admission quotas.** The PR-2 bounded-admission discipline,
  one level up: each app (the ``X-PIO-App`` header) gets an in-flight
  cap at the router, so one tenant's surge sheds with 503 + Retry-After
  instead of starving the fleet.
- **Breaker-guarded health + retry-on-another-replica.** One
  :class:`~predictionio_tpu.utils.resilience.CircuitBreaker` per
  backend; a dead or shedding backend fails the read over to the next
  replica *inside the same request* (no backoff sleeps — the retry
  target is a different process), with the deadline budget split across
  the remaining attempts so the schedule always fits the client's
  budget.
- **Sharded-model scatter/gather.** With ``sharded=True`` each backend
  holds one partition of the item factors (``ServerConfig.shard_index``
  / ``shard_count``); the router fans a query out to every shard
  concurrently and k-way-merges the local top-ks into the exact global
  top-k (:mod:`~predictionio_tpu.fleet.merge`).

No jax anywhere: a router node is pure stdlib + the shared resilience
and obs planes.
"""

from __future__ import annotations

import dataclasses
import http.client
import json
import logging
import os
import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple
from urllib.parse import urlparse

from ..api.http import BackgroundHTTPServer, JsonHTTPHandler
from ..obs.trace import TRACE_HEADER, Tracer
from ..rollout.plan import (
    BASELINE,
    VARIANT_HEADER,
    bucket_for_key,
    plan_epoch,
    sticky_key,
    variant_for_key,
)
from ..utils.resilience import (
    DEADLINE_HEADER,
    CircuitBreaker,
    CircuitOpen,
    Deadline,
    DeadlineExceeded,
)
from .cache import CACHE_HEADER, ResponseCache, SingleFlight, canonical_query
from .merge import merge_predictions

logger = logging.getLogger(__name__)

__all__ = [
    "APP_HEADER",
    "CACHE_HEADER",
    "VARIANT_HEADER",
    "RouterBadRequest",
    "RouterConfig",
    "RouterServer",
    "ShardUnavailable",
    "create_router",
]


class RouterBadRequest(ValueError):
    """The client's request body is malformed → 400 (never retried)."""


class FleetOverloaded(RuntimeError):
    """Every replica shed the read (per-backend 503s): fleet-wide
    backpressure, not a routing failure. Surfaces to the client as
    503 + Retry-After — a well-behaved client must back off, exactly as
    it would against a single shedding server; a generic 502 here would
    make clients retry immediately into the overload."""

    def __init__(self, message: str, retry_after_s: int = 1):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class ShardUnavailable(RuntimeError):
    """A sharded read lost at least one whole shard — every replica of
    it dead or shedding — so an exact merge is impossible. Surfaces as
    502 NAMING the shard index(es): "3 shards failed" tells an operator
    nothing, "shard 1 has no live replica" names the keyspace to heal
    (docs/fleet.md#failure-modes)."""

    def __init__(self, shards: Sequence[int], detail: str):
        self.shards = tuple(shards)
        noun = "shard" if len(self.shards) == 1 else "shards"
        ids = ", ".join(str(s) for s in self.shards)
        super().__init__(
            f"{noun} {ids}: no live replica answered ({detail})"
        )


#: app identity a quota is keyed on; absent header = the "-" default app
APP_HEADER = "X-PIO-App"

#: rollout stages in which a plan routes/labels traffic (mirrors
#: storage.metadata ROLLOUT_SHADOW/ROLLOUT_CANARY without importing the
#: storage plane into the hot path)
_ACTIVE_STAGES = ("SHADOW", "CANARY")


@dataclasses.dataclass(frozen=True)
class RouterConfig:
    """``pio router`` surface (docs/fleet.md, docs/cli.md)."""

    ip: str = "localhost"
    port: int = 8700
    #: backend query servers, ``host:port`` each. In sharded mode the
    #: POSITION is the shard index — backend i must serve shard i of
    #: len(backends).
    backends: Tuple[str, ...] = ()
    #: replicated (False): any backend answers any query, affinity by
    #: bucket, failover to the next replica. Sharded (True): every
    #: backend holds one item-factor partition; queries fan out to all
    #: and merge.
    sharded: bool = False
    #: with ``sharded``: each shard is served by this many consecutive
    #: backends (backend i serves shard i // replicas_per_shard), and a
    #: shard leg fails over inside its replica group exactly like the
    #: replicated mode does across the whole ring — a sharded fleet
    #: survives a backend kill (docs/fleet.md#replicas-per-shard)
    replicas_per_shard: int = 1
    #: per-app in-flight caps ({app: max}); apps not listed fall back to
    #: ``default_quota`` (0 = unbounded)
    quotas: Dict[str, int] = dataclasses.field(default_factory=dict)
    default_quota: int = 0
    #: per-leg socket timeout (always capped by the request deadline)
    timeout_s: float = 10.0
    #: max distinct replicas tried per read (replicated mode);
    #: 0 = every configured backend
    max_attempts: int = 0
    #: salt for the replica-affinity bucket — any value shared by all
    #: router replicas keeps them consistent; it is deliberately NOT the
    #: rollout salt, so starting a canary never reshuffles which backend
    #: a user's requests land on
    routing_salt: str = "pio-router"
    #: top-k used for the sharded merge when the query carries no "num"
    #: field: must match the engine's query-class default (the bundled
    #: templates all default to 10), or the merged answer's length
    #: diverges from the unsharded server's — each shard fills the
    #: default independently and the router cannot see it
    default_num: int = 10
    #: engine identity whose active RolloutPlan the router mirrors for
    #: the variant-consistency check (None = first backend's instance,
    #: discovered lazily; the check is skipped without a registry)
    engine_id: Optional[str] = None
    engine_version: Optional[str] = None
    engine_variant: str = "engine.json"
    #: seconds an active-plan read is cached before re-reading metadata.
    #: Also the response cache's invalidation-observation cadence: an
    #: epoch change (rollout stage / model swap) is seen at most this
    #: long after the durable write (0 = re-read every request)
    plan_refresh_s: float = 2.0
    #: router response cache (docs/fleet.md#cache). Tri-state like the
    #: PR-12 kernel levers: None resolves from PIO_ROUTER_CACHE
    #: (default ON — serve from memory is the point); explicit
    #: False/True overrides the env
    cache_enabled: Optional[bool] = None
    #: LRU bound; None resolves from PIO_ROUTER_CACHE_MAX (default 2048)
    cache_max_entries: Optional[int] = None
    #: per-entry freshness budget; None resolves from
    #: PIO_ROUTER_CACHE_TTL_S (default 30 s). The TTL is a *backstop* —
    #: correctness comes from epoch invalidation, the TTL just bounds
    #: staleness against signals the epoch cannot see
    cache_ttl_s: Optional[float] = None
    #: coalesce concurrent identical sharded fan-outs onto one in-flight
    #: scatter/gather; None resolves from PIO_ROUTER_COALESCE
    #: (default ON in sharded mode)
    coalesce: Optional[bool] = None
    #: long-lived fan-out worker threads per shard whose keep-alive
    #: connections distinct concurrent queries share; beyond the bound a
    #: leg spills to an ephemeral thread (never head-of-line blocks).
    #: None resolves from PIO_ROUTER_LEG_WORKERS (default 2);
    #: 0 = per-request threads only (the pre-cache behavior)
    leg_workers: Optional[int] = None
    #: shared cache tier sidecar, ``host:port``
    #: (docs/fleet.md#shared-cache-tier). None resolves from
    #: PIO_ROUTER_SHARED_CACHE (default: no tier). Requires the local
    #: cache to be enabled — the tier is the middle level of the same
    #: hierarchy, not a replacement for it. Advisory by construction:
    #: any sidecar doubt is a miss, never a stale serve.
    shared_cache: Optional[str] = None
    #: per-call sidecar timeout; None resolves from
    #: PIO_ROUTER_SHARED_TIMEOUT_S (default 0.25 s — the tier must never
    #: cost a meaningful share of a request budget)
    shared_timeout_s: Optional[float] = None
    #: pre-fill the local LRU from the sidecar's top-keys export at
    #: startup (cache warming on deploy); None resolves from
    #: PIO_ROUTER_SHARED_WARM (default ON when a tier is configured)
    shared_warm: Optional[bool] = None
    #: TTL for negative entries (known-empty 200 results); None resolves
    #: from PIO_ROUTER_NEGATIVE_TTL_S (default 5 s; 0 disables negative
    #: caching). Deliberately short: "nothing matched" goes stale the
    #: moment new data lands, and no epoch sees data-only changes
    negative_ttl_s: Optional[float] = None
    #: request hedging (docs/fleet.md#hedging): after a p9x-derived
    #: delay, issue ONE hedge leg to the next replica from the
    #: *remaining* deadline budget; first response wins. None resolves
    #: from PIO_ROUTER_HEDGE (default ON — it only ever fires on the
    #: observed tail)
    hedge_enabled: Optional[bool] = None
    #: the "9x" in p9x: which latency percentile of recent successful
    #: legs sets the hedge delay
    hedge_percentile: float = 95.0
    #: floor for the hedge delay — a sub-millisecond p95 must not turn
    #: hedging into double-send-everything
    hedge_min_delay_s: float = 0.005
    #: minimum remaining deadline budget a hedge leg needs; below it the
    #: hedge is denied (counted, never fired) — a doomed duplicate helps
    #: nobody
    hedge_leg_min_s: float = 0.05
    #: metadata changefeed to subscribe to for PUSHED epoch invalidation
    #: (a storage server base URL, e.g. ``http://host:port``). None
    #: resolves from PIO_ROUTER_META_FEED (default: poll only). With a
    #: live subscription the poll below stretches to ``push_watchdog_s``
    #: — staleness drops to ~push latency and the per-request metadata
    #: read disappears; a dead/wedged subscriber falls back to
    #: ``plan_refresh_s`` polling automatically (never a frozen epoch)
    meta_feed: Optional[str] = None
    #: subscriber tail interval (near-zero staleness knob)
    push_poll_s: float = 0.05
    #: poll cadence while the push plane is healthy — a watchdog, not
    #: the staleness bound
    push_watchdog_s: float = 30.0


class _RouterHandler(JsonHTTPHandler):
    server: "RouterServer"

    def do_POST(self) -> None:  # noqa: N802
        raw = self.read_body()
        path = urlparse(self.path).path
        if path != "/queries.json":
            self.respond(404, {"message": "Not Found"})
            return
        app = (self.headers.get(APP_HEADER) or "-").strip() or "-"
        if not self.server.admit(app):
            self.server.count_request("shed")
            self.server.count_shed(app)
            self.respond(
                503,
                {"message": f"app {app!r} over its router quota"},
                headers={"Retry-After": 1},
            )
            return
        deadline = Deadline.from_header(
            self.headers.get(DEADLINE_HEADER), clock=self.server.clock
        )
        started = self.server.clock()
        # the routed work runs inside the quota slot; the response WRITE
        # does not — the slot is released before the client can observe
        # the answer, so "my request returned" implies "my slot is
        # free" (a slow client draining a response must not hold fan-out
        # concurrency hostage either)
        out: Tuple[int, Any, Dict[str, Any]]
        try:
            if deadline is not None:
                deadline.check("router-admission")
            info: Dict[str, str] = {}
            with self.server.tracer.server_span(
                "POST /queries.json",
                header_value=self.headers.get(TRACE_HEADER),
                tags={"router": "1"},
            ) as span:
                status, body, variant = self.server.route_query(
                    raw, deadline, trace_id=span.trace_id, info=info
                )
            headers: Dict[str, Any] = {TRACE_HEADER: span.trace_id}
            if variant is not None:
                headers[VARIANT_HEADER] = variant
            if info.get("cache"):
                # hit bodies are byte-identical to the miss that filled
                # them; only the trace id (above) and this verdict
                # header differ (docs/fleet.md#cache)
                headers[CACHE_HEADER] = info["cache"]
            self.server.count_request("ok" if status == 200 else "error")
            out = (status, body, headers)
        except DeadlineExceeded as exc:
            self.server.count_request("deadline")
            out = (504, {"message": str(exc), "stage": exc.stage}, {})
        except RouterBadRequest as exc:
            self.server.count_request("bad_request")
            out = (400, {"message": str(exc)}, {})
        except FleetOverloaded as exc:
            # fleet-wide backpressure relays as a shed, never a 502:
            # clients that honor Retry-After must keep backing off
            self.server.count_request("shed")
            self.server.count_shed(app)
            out = (
                503,
                {"message": str(exc)},
                {"Retry-After": exc.retry_after_s},
            )
        except Exception as exc:
            logger.exception("router query failed")
            self.server.count_request("error")
            out = (502, {"message": str(exc)}, {})
        finally:
            self.server.observe_latency(self.server.clock() - started)
            self.server.release(app)
        self.respond(out[0], out[1], headers=out[2])

    def do_GET(self) -> None:  # noqa: N802
        path = urlparse(self.path).path
        if self.serve_obs(path):  # /metrics + /traces.json
            return
        if path in ("/", "/status.json", "/router.json"):
            self.respond(200, self.server.status_json())
        elif path == "/stop":
            self.respond(200, {"message": "Shutting down"})
            self.server.stop_async()
        else:
            self.respond(404, {"message": "Not Found"})


class _CountDownLatch:
    """Join point for a scatter/gather round: ``wait`` returns once
    every leg has counted down, whichever thread (pool worker or
    ephemeral) ran it."""

    def __init__(self, count: int):
        self._count = count
        self._cond = threading.Condition()

    def count_down(self) -> None:
        with self._cond:
            self._count -= 1
            if self._count <= 0:
                self._cond.notify_all()

    def wait(self) -> None:
        with self._cond:
            while self._count > 0:
                self._cond.wait()


class _ShardLegPool:
    """Bounded long-lived leg workers for ONE shard: distinct concurrent
    queries share the workers' keep-alive connections instead of paying
    a fresh socket per fan-out leg. Admission-aware — a leg arriving
    while every worker may already be occupied (``unfinished_tasks >=
    workers``) spills to an ephemeral thread instead of queueing, so
    the pool can never head-of-line-block a request behind another
    request's slowest leg (the failure mode that kept PR 9 on
    per-request threads)."""

    _STOP = object()

    def __init__(
        self,
        name: str,
        workers: int,
        on_thread_exit: Callable[[], None],
    ):
        self.name = name
        self.workers = max(1, workers)
        self._on_thread_exit = on_thread_exit
        self._q: "queue.Queue" = queue.Queue()
        self._threads: List[threading.Thread] = []
        self._lock = threading.Lock()
        self._stopped = False

    def submit(self, task: Callable[[], None]) -> None:
        with self._lock:
            if not self._stopped and not self._threads:
                # lazy start: a router that never fans out (unit tests,
                # replicated mode misconfig) spawns nothing
                for i in range(self.workers):
                    t = threading.Thread(
                        target=self._run, daemon=True,
                        name=f"router-{self.name}-leg{i}",
                    )
                    t.start()
                    self._threads.append(t)
            # spill the moment every worker could be busy: a leg must
            # never QUEUE behind another request's slow leg (queued =
            # head-of-line blocked while its deadline ticks); the pool
            # only buys connection reuse for legs a worker can take now
            spill = (
                self._stopped
                or self._q.unfinished_tasks >= self.workers
            )
        if spill:
            threading.Thread(
                target=self._spill_run, args=(task,), daemon=True,
                name=f"router-{self.name}-spill",
            ).start()
        else:
            self._q.put(task)

    def _spill_run(self, task: Callable[[], None]) -> None:
        try:
            task()
        finally:
            self._on_thread_exit()

    def _run(self) -> None:
        while True:
            task = self._q.get()
            try:
                if task is self._STOP:
                    return
                task()
            finally:
                self._q.task_done()
                if task is self._STOP:
                    self._on_thread_exit()

    def stop(self) -> None:
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
            threads = list(self._threads)
        for _ in threads:
            self._q.put(self._STOP)


class _HedgeTracker:
    """The p9x estimator behind request hedging (docs/fleet.md#hedging,
    the tail-at-scale discipline in PAPERS.md): a bounded ring of recent
    *successful* leg latencies; :meth:`delay_s` answers the configured
    percentile (floored at ``min_delay_s``) once the window is warm, or
    None while it is not — a cold router never hedges, because it has
    no tail to read."""

    def __init__(
        self,
        percentile: float = 95.0,
        window: int = 128,
        min_samples: int = 16,
        min_delay_s: float = 0.005,
    ):
        from collections import deque

        self.percentile = min(99.9, max(50.0, float(percentile)))
        self.min_samples = max(2, int(min_samples))
        self.min_delay_s = float(min_delay_s)
        self._lat: "deque" = deque(maxlen=max(self.min_samples, int(window)))
        self._lock = threading.Lock()

    def observe(self, elapsed_s: float) -> None:
        with self._lock:
            self._lat.append(max(0.0, float(elapsed_s)))

    def delay_s(self) -> Optional[float]:
        with self._lock:
            if len(self._lat) < self.min_samples:
                return None
            lat = sorted(self._lat)
        idx = min(len(lat) - 1, int(len(lat) * self.percentile / 100.0))
        return max(self.min_delay_s, lat[idx])

    def snapshot(self) -> dict:
        with self._lock:
            samples = len(self._lat)
        delay = self.delay_s()
        return {
            "enabled": True,
            "percentile": self.percentile,
            "samples": samples,
            "delayS": round(delay, 6) if delay is not None else None,
        }


def _is_empty_result(body: Any) -> bool:
    """A *known-empty* 200: a dict with at least one list field, all of
    them empty (``{"itemScores": []}`` — the engines' "nothing matched"
    shape). These are negative-cached under a short TTL: misses for
    unknown entities are the classic cache-punch-through, but "nothing"
    goes stale the moment new data lands, and no epoch sees data-only
    changes — hence the separate, short fuse."""
    if not isinstance(body, dict) or not body:
        return False
    lists = [v for v in body.values() if isinstance(v, list)]
    return bool(lists) and all(not v for v in lists)


class RouterServer(BackgroundHTTPServer):
    """The router process: stateless but for quota counters, breaker
    state and the cached plan read — everything a replica needs to agree
    with its peers is a pure function of (config, replicated plan)."""

    def __init__(
        self,
        config: RouterConfig,
        registry=None,
        clock: Callable[[], float] = time.monotonic,
        meta_feed=None,
    ):
        """``meta_feed`` — an already-constructed changefeed source
        (``LocalFeed``/``RemoteFeed`` protocol) for pushed invalidation;
        overrides ``config.meta_feed`` (which names a storage server by
        URL). In-process fleets and drills inject their oplog here."""
        if not config.backends:
            raise ValueError("router needs at least one backend (host:port)")
        if config.replicas_per_shard < 1:
            raise ValueError("replicas-per-shard must be >= 1")
        if config.replicas_per_shard > 1 and not config.sharded:
            raise ValueError(
                "replicas-per-shard only applies to --sharded (replicated "
                "mode already treats every backend as a replica)"
            )
        if config.sharded and (
            len(config.backends) % config.replicas_per_shard
        ):
            raise ValueError(
                f"{len(config.backends)} backends do not divide into "
                f"replica groups of {config.replicas_per_shard}: backend i "
                "serves shard i // replicas_per_shard, so the list length "
                "must be shard_count * replicas_per_shard"
            )
        self.config = config
        self.registry = registry
        self.clock = clock
        self.backends: Tuple[str, ...] = tuple(config.backends)
        #: number of model partitions the fleet serves (sharded mode)
        self.shard_count = (
            len(self.backends) // config.replicas_per_shard
            if config.sharded
            else 1
        )
        # dead-shard metric labels, minted once from config: a closed
        # 0..shard_count-1 vocabulary, never interpolated per request
        self._shard_labels = tuple(
            f"shard-{i}" for i in range(self.shard_count)
        )
        # one breaker per backend: health is judged per process, and an
        # open breaker takes the backend out of the rotation until its
        # cooldown admits a probe
        self.breakers: Dict[str, CircuitBreaker] = {
            b: CircuitBreaker.from_env(f"backend-{b}", clock=clock)
            for b in self.backends
        }
        #: guards the mutable tables below (quota in-flight counts, the
        #: cached plan, the lazily-discovered engine identity); every
        #: cross-thread reader — handler threads, gauge callbacks —
        #: takes it, and nothing blocking runs under it
        self._lock = threading.Lock()
        self._inflight: Dict[str, int] = {}
        self._plan: Optional[Any] = None
        self._plan_read_at: Optional[float] = None
        self._engine_key: Optional[Tuple[str, str, str]] = (
            (config.engine_id, config.engine_version or "1", config.engine_variant)
            if config.engine_id
            else None
        )
        self._epoch: Optional[str] = None
        # per-(worker thread, backend) persistent connections: handler
        # and fan-out threads each keep their own socket per backend, so
        # keep-alive reuse never interleaves two requests on one socket
        self._conns = threading.local()

        # -- the serving-tier memory hierarchy (docs/fleet.md#cache) ------
        # tri-state resolution, PR-12 lever style: explicit config wins,
        # else env, else the fast default (ON)
        cache_on = config.cache_enabled
        if cache_on is None:
            cache_on = os.environ.get("PIO_ROUTER_CACHE", "1") != "0"
        max_entries = config.cache_max_entries
        if max_entries is None:
            max_entries = int(os.environ.get("PIO_ROUTER_CACHE_MAX", "2048"))
        ttl_s = config.cache_ttl_s
        if ttl_s is None:
            ttl_s = float(os.environ.get("PIO_ROUTER_CACHE_TTL_S", "30"))
        self._cache: Optional[ResponseCache] = (
            ResponseCache(
                max_entries=max_entries,
                ttl_s=ttl_s,
                clock=clock,
                on_invalidate=self._count_invalidation,
            )
            if cache_on and max_entries > 0 and ttl_s > 0
            else None
        )
        coalesce = config.coalesce
        if coalesce is None:
            coalesce = os.environ.get("PIO_ROUTER_COALESCE", "1") != "0"
        self._singleflight: Optional[SingleFlight] = (
            SingleFlight() if (coalesce and config.sharded) else None
        )
        leg_workers = config.leg_workers
        if leg_workers is None:
            leg_workers = int(os.environ.get("PIO_ROUTER_LEG_WORKERS", "2"))
        self._leg_pools: Dict[int, _ShardLegPool] = (
            {
                shard: _ShardLegPool(
                    f"shard{shard}", leg_workers, self._close_thread_conns
                )
                for shard in range(self.shard_count)
            }
            if config.sharded and leg_workers > 0
            else {}
        )
        # shared cache tier levers (docs/fleet.md#shared-cache-tier);
        # the client itself is built after the metrics block so its
        # outcome callback lands on a live counter
        shared_addr = config.shared_cache
        if shared_addr is None:
            shared_addr = (
                os.environ.get("PIO_ROUTER_SHARED_CACHE", "").strip() or None
            )
        shared_timeout = config.shared_timeout_s
        if shared_timeout is None:
            shared_timeout = float(
                os.environ.get("PIO_ROUTER_SHARED_TIMEOUT_S", "0.25")
            )
        shared_warm = config.shared_warm
        if shared_warm is None:
            shared_warm = os.environ.get("PIO_ROUTER_SHARED_WARM", "1") != "0"
        self._shared_warm = bool(shared_warm)
        negative_ttl = config.negative_ttl_s
        if negative_ttl is None:
            negative_ttl = float(
                os.environ.get("PIO_ROUTER_NEGATIVE_TTL_S", "5")
            )
        self._negative_ttl_s = max(0.0, negative_ttl)
        hedge_on = config.hedge_enabled
        if hedge_on is None:
            hedge_on = os.environ.get("PIO_ROUTER_HEDGE", "1") != "0"
        self._hedge: Optional[_HedgeTracker] = (
            _HedgeTracker(
                percentile=config.hedge_percentile,
                min_delay_s=config.hedge_min_delay_s,
            )
            if hedge_on
            else None
        )
        self._hedge_leg_min_s = float(config.hedge_leg_min_s)
        meta_feed_url = config.meta_feed
        if meta_feed_url is None:
            meta_feed_url = (
                os.environ.get("PIO_ROUTER_META_FEED", "").strip() or None
            )
        self._warmed_entries = 0
        self._refresh_forced = False
        self._shared = None
        self._subscriber = None

        metrics_clock = clock
        from ..obs.metrics import MetricsRegistry

        metrics = MetricsRegistry(clock=metrics_clock)
        self._requests = metrics.counter(
            "pio_router_requests_total",
            "Routed requests by outcome",
            labelnames=("outcome",),
        )
        self._retries = metrics.counter(
            "pio_router_retries_total",
            "Reads retried on another replica, by failed backend",
            labelnames=("backend",),
        )
        self._shed = metrics.counter(
            "pio_router_shed_total",
            "Requests shed at the router quota, by app",
            labelnames=("app",),
        )
        self._backend_events = metrics.counter(
            "pio_router_backend_events_total",
            "Per-backend leg outcomes",
            labelnames=("backend", "kind"),
        )
        self._hist = metrics.histogram(
            "pio_router_request_seconds",
            "End-to-end routed request latency",
        )
        self._variant_mismatch = metrics.counter(
            "pio_router_variant_mismatch_total",
            "Requests whose backend variant disagreed with the router's "
            "own pure-function assignment (must stay 0)",
        )
        self._cache_hits = metrics.counter(
            "pio_router_cache_hits_total",
            "Queries answered from the router response cache",
        )
        self._cache_misses = metrics.counter(
            "pio_router_cache_misses_total",
            "Cache lookups that went to the backends",
        )
        self._cache_invalidations = metrics.counter(
            "pio_router_cache_invalidations_total",
            "Cache entries dropped, by reason (epoch = rollout/model "
            "swap flush, ttl, capacity, explicit)",
            labelnames=("reason",),
        )
        self._coalesced = metrics.counter(
            "pio_router_coalesced_total",
            "Sharded fan-outs answered by joining another request's "
            "in-flight scatter/gather (single-flight)",
        )
        self._shared_counter = metrics.counter(
            "pio_router_shared_cache_total",
            "Shared cache tier client outcomes (hit/negative_hit/miss/"
            "epoch_skew/open/error/put/put_error — degrades are "
            "recorded, never silent)",
            labelnames=("outcome",),
        )
        self._hedges = metrics.counter(
            "pio_router_hedges_total",
            "Request hedging outcomes (fired/primary_won/hedge_won/"
            "loser_cancelled/budget_denied/breaker_denied)",
            labelnames=("outcome",),
        )
        self._epoch_events = metrics.counter(
            "pio_router_epoch_events_total",
            "Epoch-moving cache flushes by how the move was observed "
            "(push = changefeed subscription, poll = refresh cadence)",
            labelnames=("source",),
        )
        metrics.gauge_callback(
            "pio_router_push_alive",
            lambda: (
                1.0
                if self._subscriber is not None and self._subscriber.alive()
                else 0.0
            ),
            "1 while the pushed-invalidation subscriber is demonstrably "
            "live (0 = poll fallback)",
        )
        metrics.gauge_callback(
            "pio_router_cache_entries",
            lambda: len(self._cache) if self._cache is not None else 0,
            "Live entries in the router response cache",
        )
        metrics.gauge_callback(
            "pio_router_backends_up",
            self._backends_up,
            "Backends whose breaker currently admits traffic",
        )
        metrics.gauge(
            "pio_router_sharded", "1 when serving in sharded-model mode"
        ).set(1 if config.sharded else 0)
        super().__init__(
            (config.ip, config.port),
            _RouterHandler,
            metrics=metrics,
            tracer=Tracer("router", clock=clock),
            health_kind="router",
        )
        # -- shared tier + pushed invalidation (after the bind: a failed
        # construction must not leave client threads behind) -------------
        if shared_addr is not None and self._cache is not None:
            from .sharedcache import SharedCacheClient

            self._shared = SharedCacheClient(
                shared_addr,
                timeout_s=shared_timeout,
                on_outcome=self._count_shared,
                clock=clock,
            )
        if meta_feed is None and meta_feed_url is not None:
            from ..continuous.watcher import RemoteFeed

            meta_feed = RemoteFeed(meta_feed_url, timeout=5.0)
        if meta_feed is not None:
            from ..continuous.watcher import ChangefeedSubscriber

            self._subscriber = ChangefeedSubscriber(
                meta_feed,
                self._on_meta_ops,
                poll_s=config.push_poll_s,
                clock=clock,
                name=f"router-{self.bound_port}-subscriber",
            ).start()
        if self._shared is not None and self._shared_warm:
            threading.Thread(
                target=self._warm_safely, daemon=True,
                name=f"router-{self.bound_port}-warm",
            ).start()

    # -- live ring update (fleet/autoscale.py) ----------------------------
    def resize_replicas(
        self, backends: Sequence[str], replicas_per_shard: int
    ) -> dict:
        """Autoscaler actuation: swap in a new backend ring with the
        SAME shard count but a different replicas-per-shard — the one
        ring change that is safe live, because shard labels, leg pools
        and the shard→replica-group function all key on shard index.
        New backends get fresh breakers; departing backends keep their
        (now idle) breaker entries so an in-flight leg racing the swap
        still finds its state. Loud on anything that would change the
        shard count — that is a partition/shard migration, not a
        resize."""
        backends = tuple(backends)
        if not self.config.sharded:
            raise ValueError(
                "resize_replicas applies to sharded mode (replicated "
                "mode scales by just adding backends to the ring)"
            )
        if replicas_per_shard < 1:
            raise ValueError("replicas-per-shard must be >= 1")
        if len(backends) != self.shard_count * replicas_per_shard:
            raise ValueError(
                f"{len(backends)} backends do not give {self.shard_count} "
                f"shards x {replicas_per_shard} replicas — a resize must "
                "keep the shard count; migrate to change it"
            )
        with self._lock:
            for b in backends:
                if b not in self.breakers:
                    self.breakers[b] = CircuitBreaker.from_env(
                        f"backend-{b}", clock=self.clock
                    )
            self.config = dataclasses.replace(
                self.config,
                backends=backends,
                replicas_per_shard=replicas_per_shard,
            )
            self.backends = backends
        return {
            "backends": list(backends),
            "replicasPerShard": replicas_per_shard,
            "shardCount": self.shard_count,
        }

    # -- admission (per-app quotas) ---------------------------------------
    def quota_for(self, app: str) -> int:
        return self.config.quotas.get(app, self.config.default_quota)

    def admit(self, app: str) -> bool:
        quota = self.quota_for(app)
        with self._lock:
            inflight = self._inflight.get(app, 0)
            if quota > 0 and inflight >= quota:
                return False
            self._inflight[app] = inflight + 1
            return True

    def release(self, app: str) -> None:
        with self._lock:
            remaining = max(0, self._inflight.get(app, 0) - 1)
            if remaining:
                self._inflight[app] = remaining
            else:
                # drop drained apps: X-PIO-App is client-controlled, and
                # a table keyed by every value ever seen would grow
                # without bound on this long-lived front tier (the shed
                # counter is safe — the metrics registry caps label
                # cardinality into "_overflow")
                self._inflight.pop(app, None)

    # -- metrics hooks (handler-facing; the registry is thread-safe) ------
    def count_request(self, outcome: str) -> None:
        self._requests.inc(1, outcome=outcome)

    def count_shed(self, app: str) -> None:
        self._shed.inc(1, app=app)

    def _count_invalidation(self, reason: str, count: int) -> None:
        self._cache_invalidations.inc(count, reason=reason)

    def _count_shared(self, outcome: str) -> None:
        self._shared_counter.inc(1, outcome=outcome)

    # -- shared tier: warming (docs/fleet.md#shared-cache-tier) -----------
    def warm_from_shared(self, n: int = 256) -> int:
        """Pre-fill the local LRU from the sidecar's top-keys export —
        cache warming on deploy: a restarting router re-learns the hot
        set from the tier instead of exposing the backends to it. Only
        entries under the CURRENT epoch are imported (a stale export
        must not seed a stale cache); negative entries keep their short
        fuse. Returns how many entries landed."""
        shared, cache = self._shared, self._cache
        if shared is None or cache is None:
            return 0
        epoch = self.current_epoch()
        warmed = 0
        for item in shared.top(n):
            if not isinstance(item, dict):
                continue
            if str(item.get("epoch")) != epoch:
                continue
            key = (
                str(item.get("variant", "-")),
                str(item.get("query", "")),
            )
            negative = bool(item.get("negative", False))
            if negative and self._negative_ttl_s <= 0:
                continue
            cache.put(
                key,
                item.get("body"),
                item.get("servedVariant"),
                epoch,
                ttl_s=self._negative_ttl_s if negative else None,
                negative=negative,
            )
            warmed += 1
        with self._lock:
            self._warmed_entries += warmed
        return warmed

    def _warm_safely(self) -> None:
        try:
            warmed = self.warm_from_shared()
            if warmed:
                logger.info(
                    "warmed %d cache entries from the shared tier", warmed
                )
        except Exception:
            # warming is opportunistic: a cold start is the status quo
            # ante, never a boot failure (the client records transport
            # degrades itself)
            logger.debug("cache warming failed", exc_info=True)

    # -- pushed invalidation (docs/fleet.md#shared-cache-tier) ------------
    def _on_meta_ops(self, ops: List[dict], gap: bool) -> None:
        """Changefeed subscriber callback: an epoch-relevant op — or a
        feed gap, an unknown window that MAY have held one — forces the
        next plan read instead of waiting out the refresh cadence."""
        from ..storage.changefeed import op_moves_epoch

        if gap or any(op_moves_epoch(op) for op in ops):
            self._force_epoch_refresh()

    def _force_epoch_refresh(self) -> None:
        with self._lock:
            self._plan_read_at = None
            self._refresh_forced = True
        self.active_plan()

    def observe_latency(self, elapsed_s: float) -> None:
        self._hist.observe(max(0.0, elapsed_s))

    def _backends_up(self) -> int:
        # snapshot under the lock: resize_replicas (autoscaler
        # actuation) grows this table concurrently with scrapes
        with self._lock:
            breakers = list(self.breakers.values())
        return sum(
            1 for b in breakers if b.state != CircuitBreaker.OPEN
        )

    # -- fleet-consistent plan view ---------------------------------------
    def active_plan(self):
        """The engine's active RolloutPlan via the replicated
        ``rollout_plan_get_active`` read, cached ``plan_refresh_s``.
        Any failure (no registry, metadata outage, unknown engine)
        degrades to None — the consistency check is an alarm, never a
        serving dependency.

        Every refresh also derives the CACHE EPOCH — ``plan_epoch``
        over the active plan plus the latest completed instance id for
        the engine key — and an observed epoch move flushes the response
        cache on the spot (docs/fleet.md#cache): a rollout stage change
        or a model swap (a new instance landing through the continuous
        plane and replicated metadata) invalidates within one
        ``plan_refresh_s`` of the durable write. Reads that cannot
        complete keep the PRIOR epoch: "metadata unreachable" must not
        flap the epoch and stampede the backends with a cold cache.

        With a LIVE changefeed subscriber the poll stretches to
        ``push_watchdog_s`` — epoch moves arrive pushed, and the poll
        is only the watchdog behind the push plane. The stretch is
        re-decided on :meth:`ChangefeedSubscriber.alive` at *every*
        read: a dead or wedged subscriber silently restores the old
        cadence, so the epoch can never freeze behind a stuck push
        plane (docs/fleet.md#shared-cache-tier)."""
        if self.registry is None:
            return None
        with self._lock:
            interval = self.config.plan_refresh_s
            if self._subscriber is not None and self._subscriber.alive():
                interval = max(interval, self.config.push_watchdog_s)
            fresh = (
                self._plan_read_at is not None
                and self.clock() - self._plan_read_at < interval
            )
            if fresh:
                return self._plan
            forced = self._refresh_forced
            self._refresh_forced = False
            engine_key = self._engine_key
        plan = None
        epoch: Optional[str] = None
        try:
            md = self.registry.get_metadata()
            if engine_key is None:
                engine_key = self._discover_engine_key(md)
            if engine_key is not None:
                plan = md.rollout_plan_get_active(*engine_key)
                latest = md.engine_instance_get_latest_completed(*engine_key)
                epoch = plan_epoch(plan) + "#" + (
                    latest.id if latest is not None else "-"
                )
        except Exception:
            logger.debug("router plan read failed", exc_info=True)
            plan = None
            epoch = None
        flush_from: Optional[str] = None
        with self._lock:
            self._plan = plan
            self._plan_read_at = self.clock()
            if engine_key is not None:
                self._engine_key = engine_key
            if epoch is not None:
                if self._epoch is not None and self._epoch != epoch:
                    flush_from = self._epoch
                self._epoch = epoch
        if flush_from is not None and self._cache is not None:
            dropped = self._cache.flush(reason="epoch")
            self._epoch_events.inc(1, source="push" if forced else "poll")
            logger.info(
                "rollout/model epoch moved (%s); flushed %d cached "
                "responses",
                "pushed invalidation" if forced else "poll",
                dropped,
            )
            if self._shared is not None:
                # the sidecar flush rides a fire-and-forget thread: the
                # LOCAL flush is the correctness event (and every shared
                # read is epoch-checked anyway) — a slow sidecar must
                # not stall whoever observed the epoch move
                threading.Thread(
                    target=self._shared.flush,
                    kwargs={"reason": "epoch"},
                    daemon=True,
                    name="router-shared-flush",
                ).start()
        return plan

    def current_epoch(self) -> str:
        """The epoch cache entries are stamped/validated with. Refreshes
        through :meth:`active_plan` on the same cadence; "-" without a
        registry (TTL is then the only staleness bound — documented in
        docs/fleet.md#cache)."""
        self.active_plan()
        with self._lock:
            return self._epoch or "-"

    def _discover_engine_key(self, md) -> Optional[Tuple[str, str, str]]:
        """Without an explicit --engine-id, mirror whatever engine the
        fleet's latest completed instance belongs to."""
        try:
            instances = md.engine_instance_get_all()
        except Exception:
            return None
        completed = [i for i in instances if i.status == "COMPLETED"]
        if not completed:
            return None
        latest = max(completed, key=lambda i: i.start_time)
        return (latest.engine_id, latest.engine_version, latest.engine_variant)

    def variant_preview(self, payload: Any) -> Optional[str]:
        """The router's own (pure-function) variant assignment for this
        payload under the active plan — what any query server must also
        compute. None when no plan is active/readable."""
        plan = self.active_plan()
        if plan is None or plan.stage not in _ACTIVE_STAGES:
            return None
        if plan.stage != "CANARY":
            return BASELINE
        return variant_for_key(plan.salt, sticky_key(payload), plan.percent)

    # -- routing ----------------------------------------------------------
    def route_query(
        self,
        raw: bytes,
        deadline: Optional[Deadline],
        trace_id: Optional[str] = None,
        info: Optional[Dict[str, str]] = None,
    ) -> Tuple[int, Any, Optional[str]]:
        """One client request end to end → ``(status, body, variant)``.
        Raises DeadlineExceeded/ValueError for the handler's 504/400.
        ``info`` (when given) reports the cache verdict for the
        ``X-PIO-Cache`` response header.

        The memory hierarchy, in order (docs/fleet.md#cache): the
        response cache answers a hit without touching a backend (body
        byte-identical to the miss that filled it, variant still
        verified); a sharded miss coalesces onto any identical scatter/
        gather already in flight; only then does a fresh fan-out run."""
        try:
            payload = json.loads(raw.decode("utf-8")) if raw else {}
        except ValueError as exc:
            raise RouterBadRequest(f"invalid query JSON: {exc}") from exc
        qkey: Optional[Tuple[str, str]] = None
        epoch = "-"
        expected: Optional[str] = ""  # "" = not computed (recompute later)
        if self._cache is not None or self._singleflight is not None:
            # ONE key for both tiers: the router's own pure-function
            # variant assignment ("-" when no plan routes traffic) over
            # the canonical byte form of the query
            expected = self.variant_preview(payload)
            qkey = (expected or "-", canonical_query(payload))
        if self._cache is not None and qkey is not None:
            epoch = self.current_epoch()
            entry = self._cache.get(qkey, epoch)
            if entry is not None:
                self._cache_hits.inc(1)
                if info is not None:
                    info["cache"] = "hit"
                # a hit still verifies the variant contract: the entry
                # was served under some variant, and the router's own
                # assignment must still agree with it (reusing the
                # assignment the key was built from — no second read)
                self._check_variant(payload, entry.variant, expected)
                return 200, entry.body, entry.variant
            self._cache_misses.inc(1)
            if info is not None:
                info["cache"] = "miss"
            if self._shared is not None:
                # the shared tier sits BETWEEN the local LRU and the
                # fan-out (docs/fleet.md#shared-cache-tier). The lookup
                # spends at most half the remaining budget — the
                # sidecar may make this request faster, never later —
                # and any doubt (timeout, open breaker, epoch skew)
                # comes back as None: an advisory miss, handled by the
                # fan-out below exactly as if the tier did not exist.
                shared_entry = self._shared.lookup(
                    qkey,
                    epoch,
                    budget_s=(
                        deadline.remaining_s() / 2.0
                        if deadline is not None
                        else None
                    ),
                )
                if shared_entry is not None:
                    if info is not None:
                        info["cache"] = "hit-shared"
                    self._check_variant(
                        payload, shared_entry.variant, expected
                    )
                    # promote into the local LRU so the NEXT identical
                    # read is a local hit (negative entries keep their
                    # short fuse)
                    self._cache.put(
                        qkey,
                        shared_entry.body,
                        shared_entry.variant,
                        epoch,
                        ttl_s=(
                            self._negative_ttl_s
                            if shared_entry.negative
                            else None
                        ),
                        negative=shared_entry.negative,
                    )
                    return 200, shared_entry.body, shared_entry.variant
        # stall watchdog (docs/slo.md): a routed request that outlives a
        # multiple of its budget — every failover leg wedged — is a
        # fleet-level stall worth a flight dump
        watchdog = self.health.watchdog if self.health is not None else None
        token = (
            watchdog.enter(
                "router.request",
                budget_s=(
                    deadline.remaining_s() if deadline is not None else None
                ),
            )
            if watchdog is not None
            else None
        )
        try:
            if self.config.sharded:
                status, body, variant = self._sharded_singleflight(
                    qkey, raw, payload, deadline, trace_id
                )
            else:
                status, body, variant = self._route_replicated(
                    raw, payload, deadline, trace_id
                )
        finally:
            if watchdog is not None:
                watchdog.exit(token)
        if status == 200:
            self._check_variant(payload, variant, expected)
            if self._cache is not None and qkey is not None:
                # negative caching: a known-empty answer is still an
                # answer — cache it on a short fuse so a hammered
                # missing key stops reaching the backends, without a
                # late-arriving model having to wait out the full TTL
                negative = (
                    self._negative_ttl_s > 0 and _is_empty_result(body)
                )
                ttl = self._negative_ttl_s if negative else None
                # filled under the epoch observed BEFORE the backend
                # call: if the plan moved mid-request, the very next
                # refresh observes the new epoch and drops this entry
                self._cache.put(
                    qkey, body, variant, epoch, ttl_s=ttl, negative=negative
                )
                if self._shared is not None:
                    # share the fill synchronously: the client's answer
                    # is already paid for, and a dead sidecar costs at
                    # most one fast-failing put before its breaker opens
                    self._shared.put(
                        qkey, body, variant, epoch,
                        ttl_s=ttl, negative=negative,
                    )
        return status, body, variant

    def _sharded_singleflight(
        self,
        qkey: Optional[Tuple[str, str]],
        raw: bytes,
        payload: Any,
        deadline: Optional[Deadline],
        trace_id: Optional[str],
    ) -> Tuple[int, Any, Optional[str]]:
        """Sharded dispatch behind the single-flight gate: concurrent
        identical queries share ONE in-flight scatter/gather. Followers
        never inherit a leader's *deadline* failure (that was its
        budget, not theirs) — they fall back to their own fan-out."""

        def scatter() -> Tuple[int, Any, Optional[str]]:
            return self._route_sharded(raw, payload, deadline, trace_id)

        if self._singleflight is None or qkey is None:
            return scatter()
        try:
            result, shared = self._singleflight.do(
                qkey,
                scatter,
                timeout_s=(
                    deadline.remaining_s() if deadline is not None else None
                ),
                share_error=lambda exc: not isinstance(
                    exc, DeadlineExceeded
                ),
            )
        except TimeoutError:
            raise DeadlineExceeded(
                "deadline exceeded waiting for the coalesced fan-out",
                stage="router-coalesce",
            ) from None
        if shared:
            self._coalesced.inc(1)
        return result

    def _check_variant(
        self,
        payload: Any,
        served: Optional[str],
        expected: Optional[str] = "",
    ) -> None:
        """``expected=""`` (the default sentinel) recomputes the
        router's own assignment; callers that already computed it for
        the cache key pass it through — the hot hit path must not pay
        a second plan read + bucket hash."""
        if expected == "":
            expected = self.variant_preview(payload)
        if expected is None or served in (None, "", "-"):
            return  # no active plan, or a backend predating the header
        if served != expected:
            self._variant_mismatch.inc(1)
            logger.warning(
                "variant mismatch: router computed %s, backend served %s "
                "(sticky split drifted — check plan replication)",
                expected, served,
            )

    def _ordered_replicas(self, payload: Any) -> List[str]:
        """Affinity-first rotation: the sticky bucket picks the home
        replica, failover walks the rest in ring order. Pure function of
        (routing_salt, key, backend list) — every router replica
        produces the same order."""
        start = bucket_for_key(
            self.config.routing_salt, sticky_key(payload)
        ) % len(self.backends)
        ring = self.backends[start:] + self.backends[:start]
        admitting = [
            b for b in ring
            if self.breakers[b].state != CircuitBreaker.OPEN
        ]
        # every breaker open: trying the ring beats a guaranteed 502 (and
        # before_call below re-checks each breaker's cooldown properly)
        return admitting or list(ring)

    def _attempt_leg(
        self,
        backend: str,
        raw: bytes,
        deadline: Optional[Deadline],
        attempts_left: int,
        trace_id: Optional[str],
        has_next: bool,
    ) -> Tuple[str, Any]:
        """One ring position with ALL its bookkeeping: breaker
        admission, the HTTP leg, the breaker verdict, per-backend event
        counts, the retry count (only when a next position exists to
        retry onto), and the hedge tracker's latency sample on success.
        Returns ``("ok", (status, body, headers))`` — which includes
        non-retryable answers (4xx, 504) that pass through to the
        client; ``("failed", (message, shed))`` where ``shed`` is True
        iff the backend answered 503; or ``("skip", message)`` for an
        open breaker (the replica was never tried).

        504 is never a failure here: an expired deadline is the
        CLIENT's budget, not backend sickness — it must neither trip
        the breaker nor burn a failover leg it cannot afford."""
        breaker = self.breakers[backend]
        try:
            breaker.before_call()
        except CircuitOpen as exc:
            self._backend_events.inc(1, backend=backend, kind="open_skip")
            return "skip", f"{backend}: {exc}"
        started = self.clock()
        try:
            status, body, headers = self._leg(
                backend, raw, deadline, attempts_left, trace_id
            )
        except Exception as exc:
            breaker.record_failure()
            self._backend_events.inc(1, backend=backend, kind="error")
            if has_next:
                self._retries.inc(1, backend=backend)
            return "failed", (f"{backend}: {exc}", False)
        if status == 503 or (status >= 500 and status != 504):
            # a shedding or erroring backend: the read belongs on
            # another replica (bounded-admission discipline says the
            # *fleet* answers even when one member cannot)
            breaker.record_failure()
            self._backend_events.inc(1, backend=backend, kind="error")
            if has_next:
                self._retries.inc(1, backend=backend)
            return "failed", (f"{backend}: HTTP {status}", status == 503)
        breaker.record_success()
        self._backend_events.inc(1, backend=backend, kind="ok")
        if self._hedge is not None:
            self._hedge.observe(self.clock() - started)
        return "ok", (status, body, headers)

    def _q_wait(
        self,
        q: "queue.SimpleQueue",
        deadline: Optional[Deadline],
    ) -> Tuple[str, Tuple[str, Any]]:
        """Block for the next hedge-race verdict within the remaining
        deadline budget (forever without a deadline — the legs
        themselves are timeout-bounded, so 'forever' is bounded too)."""
        timeout = (
            max(0.0, deadline.remaining_s()) if deadline is not None else None
        )
        try:
            return q.get(timeout=timeout)
        except queue.Empty:
            raise DeadlineExceeded(
                "deadline exceeded waiting for the hedged leg",
                stage="router-hedge",
            ) from None

    def _hedged_first(
        self,
        replicas: Sequence[str],
        raw: bytes,
        deadline: Optional[Deadline],
        trace_id: Optional[str],
    ) -> Tuple[int, List[Tuple[str, Any]]]:
        """The ring's FIRST position, hedged when the tail tracker says
        so (docs/fleet.md#hedging; the tail-at-scale discipline in
        PAPERS.md): the primary leg launches immediately; if no answer
        lands within the p9x delay, ONE hedge leg fires at the next
        replica and the first response wins — the loser is abandoned
        and counted, its keep-alive connection dying with its thread.

        The hedge leg is funded from the budget REMAINING at fire time
        (its ``attempts_left`` split is computed then, against what the
        primary already spent), and never fires at all when that
        remainder is under ``hedge_leg_min_s`` or the next replica's
        breaker is open. Ineligible calls (tracker cold, hedging off, a
        lone replica) degrade to the plain sequential attempt.

        Returns ``(consumed, verdicts)``: how many ring positions were
        used (1 or 2) and the verdicts to fold into the walk."""
        delay = self._hedge.delay_s() if self._hedge is not None else None
        if delay is None or len(replicas) < 2:
            verdict = self._attempt_leg(
                replicas[0], raw, deadline, len(replicas), trace_id,
                len(replicas) > 1,
            )
            return 1, [verdict]
        q: "queue.SimpleQueue" = queue.SimpleQueue()

        def run(
            tag: str, backend: str, attempts_left: int, has_next: bool
        ) -> None:
            try:
                verdict = self._attempt_leg(
                    backend, raw, deadline, attempts_left, trace_id,
                    has_next,
                )
            except BaseException as exc:  # belt: a leg never goes silent
                verdict = ("failed", (f"{backend}: {exc}", False))
            finally:
                self._close_thread_conns()
            q.put((tag, verdict))

        threading.Thread(
            target=run, args=("primary", replicas[0], len(replicas), True),
            daemon=True, name="router-hedge-primary",
        ).start()
        try:
            first = q.get(timeout=delay)
        except queue.Empty:
            first = None
        if first is not None:
            # answered inside the p9x window: no hedge, no extra cost —
            # the common case by construction
            return 1, [first[1]]
        remaining = deadline.remaining_s() if deadline is not None else None
        if remaining is not None and remaining < self._hedge_leg_min_s:
            # too little budget left to fund a second leg: a hedge now
            # would only split starvation two ways
            self._hedges.inc(1, outcome="budget_denied")
            return 1, [self._q_wait(q, deadline)[1]]
        if self.breakers[replicas[1]].state == CircuitBreaker.OPEN:
            self._hedges.inc(1, outcome="breaker_denied")
            return 1, [self._q_wait(q, deadline)[1]]
        self._hedges.inc(1, outcome="fired")
        threading.Thread(
            target=run,
            args=(
                "hedge", replicas[1], max(1, len(replicas) - 1),
                len(replicas) > 2,
            ),
            daemon=True, name="router-hedge-leg",
        ).start()
        tag, verdict = self._q_wait(q, deadline)
        if verdict[0] == "ok":
            self._hedges.inc(
                1, outcome="hedge_won" if tag == "hedge" else "primary_won"
            )
            self._hedges.inc(1, outcome="loser_cancelled")
            return 2, [verdict]
        tag2, verdict2 = self._q_wait(q, deadline)
        if verdict2[0] == "ok":
            self._hedges.inc(
                1, outcome="hedge_won" if tag2 == "hedge" else "primary_won"
            )
            return 2, [verdict2]
        return 2, [verdict, verdict2]

    def _walk_ring(
        self,
        replicas: Sequence[str],
        raw: bytes,
        deadline: Optional[Deadline],
        trace_id: Optional[str],
        stage: str,
    ) -> Tuple[str, Any]:
        """Walk one failover ring in order — the ONE status discipline
        both routing modes share (503/5xx fail over and trip the
        breaker; 504 and 4xx pass through; open breakers skip). The
        first position runs through :meth:`_hedged_first` and may
        consume two ring positions when the hedge fires. Returns
        ``("ok", (status, body, variant))`` or ``("failed", (details,
        all_shed))`` where ``details`` is the ordered ``(kind,
        message)`` trail and ``all_shed`` is True iff every tried
        replica answered 503."""
        details: List[Tuple[str, str]] = []
        all_shed = bool(replicas)
        i = 0
        while i < len(replicas):
            if deadline is not None:
                deadline.check(stage)
            if i == 0:
                consumed, verdicts = self._hedged_first(
                    replicas, raw, deadline, trace_id
                )
                i += consumed
            else:
                verdicts = [
                    self._attempt_leg(
                        replicas[i], raw, deadline, len(replicas) - i,
                        trace_id, i + 1 < len(replicas),
                    )
                ]
                i += 1
            for kind, value in verdicts:
                if kind == "ok":
                    status, body, headers = value
                    return "ok", (
                        status, body, headers.get(VARIANT_HEADER.lower())
                    )
                if kind == "skip":
                    details.append(("skip", value))
                    all_shed = False
                else:
                    msg, shed = value
                    details.append(("failed", msg))
                    if not shed:
                        all_shed = False
        return "failed", (details, all_shed)

    def _route_replicated(
        self,
        raw: bytes,
        payload: Any,
        deadline: Optional[Deadline],
        trace_id: Optional[str],
    ) -> Tuple[int, Any, Optional[str]]:
        replicas = self._ordered_replicas(payload)
        if self.config.max_attempts > 0:
            replicas = replicas[: self.config.max_attempts]
        kind, value = self._walk_ring(
            replicas, raw, deadline, trace_id, "router-retry"
        )
        if kind == "ok":
            return value
        details, all_shed = value
        if all_shed:
            # every replica answered 503: fleet-wide backpressure, not a
            # routing failure — relay the shed so clients back off
            raise FleetOverloaded(
                f"all {len(replicas)} replicas are shedding load"
            )
        failed = [msg for k, msg in details if k == "failed"]
        last_error = failed[-1] if failed else None
        raise RuntimeError(
            f"no backend could serve the read (tried {len(replicas)}): "
            f"{last_error or 'all breakers open'}"
        )

    def _shard_replicas(self, shard: int) -> Tuple[str, ...]:
        """The consecutive backend group serving ``shard``."""
        r = self.config.replicas_per_shard
        return self.backends[shard * r:(shard + 1) * r]

    def _ordered_shard_replicas(self, shard: int, key: str) -> List[str]:
        """Affinity-first rotation WITHIN one shard's replica group —
        the replicated mode's ring discipline, scoped to the shard: the
        sticky bucket picks the home replica, failover walks the rest,
        open breakers leave the rotation (but an all-open group still
        tries the ring — before_call re-checks cooldowns properly)."""
        replicas = self._shard_replicas(shard)
        start = bucket_for_key(self.config.routing_salt, key) % len(replicas)
        ring = list(replicas[start:] + replicas[:start])
        admitting = [
            b for b in ring
            if self.breakers[b].state != CircuitBreaker.OPEN
        ]
        return admitting or ring

    def _route_sharded(
        self,
        raw: bytes,
        payload: Any,
        deadline: Optional[Deadline],
        trace_id: Optional[str],
    ) -> Tuple[int, Any, Optional[str]]:
        """Scatter to every shard, gather, merge exactly. Shard legs run
        concurrently, each under the full remaining budget (they are
        parallel — splitting across shards would punish fan-out width);
        WITHIN a shard a leg fails over across the replica group
        sequentially, splitting its budget like the replicated mode.

        Legs run on the per-shard worker pools when configured
        (``leg_workers``): a few long-lived threads per shard whose
        keep-alive connections distinct concurrent queries share —
        admission-aware, because a leg arriving while the pool's backlog
        exceeds its bound spills to an ephemeral thread instead of
        queueing behind a slow leg (the PR-9 head-of-line lesson: a
        fixed pool must never serialize requests behind each other's
        slowest leg)."""
        results: List = [None] * self.shard_count
        key = sticky_key(payload)
        latch = _CountDownLatch(self.shard_count)

        def run_leg(shard: int) -> None:
            try:
                results[shard] = self._shard_leg(
                    shard, key, raw, deadline, trace_id
                )
            except Exception as exc:  # belt: a leg never leaves a hole
                results[shard] = ("dead", (f"leg failed: {exc}", False))
            finally:
                latch.count_down()

        for shard in range(self.shard_count):
            pool = self._leg_pools.get(shard)
            if pool is not None:
                pool.submit(lambda s=shard: run_leg(s))
            else:
                threading.Thread(
                    target=self._ephemeral_leg, args=(run_leg, shard),
                    daemon=True, name=f"router-leg-{shard}",
                ).start()
        latch.wait()
        bodies: List[Any] = []
        variant: Optional[str] = None
        dead: List[int] = []
        details: List[str] = []
        all_dead_shed = True
        for shard, (kind, value) in enumerate(results):
            if kind == "ok":
                status, body, leg_variant = value
                if status != 200:
                    # a non-retryable backend answer (expired client
                    # deadline, 4xx) passes through like the replicated
                    # mode — it is the client's outcome, not shard death
                    return status, body, leg_variant
                bodies.append(body)
                if variant is None:
                    variant = leg_variant
            else:
                errors, all_shed = value
                dead.append(shard)
                details.append(f"shard {shard}: {errors}")
                all_dead_shed = all_dead_shed and all_shed
        if dead:
            if all_dead_shed:
                # every replica of every failed shard answered 503:
                # fleet backpressure, not shard death — relay the shed
                # so well-behaved clients back off (the replicated
                # mode's FleetOverloaded discipline)
                raise FleetOverloaded(
                    "sharded read shed: every replica of "
                    + ", ".join(f"shard {s}" for s in dead)
                    + " is shedding load"
                )
            # a missing shard makes an exact merge impossible: fail the
            # read loudly — NAMING the shard — instead of returning a
            # silently truncated catalog (docs/fleet.md#failure-modes),
            # and count the dead-shard kind distinctly from ordinary
            # per-backend errors
            for shard in dead:
                self._backend_events.inc(
                    1, backend=self._shard_labels[shard], kind="dead_shard"
                )
            raise ShardUnavailable(dead, "; ".join(details))
        k = payload.get("num") if isinstance(payload, dict) else None
        if not isinstance(k, int):
            # the engine's query class filled its default on every shard
            # (each returned up to default_num); merging untruncated
            # would hand the client shard_count × the unsharded count
            k = self.config.default_num
        merged = merge_predictions(bodies, k)
        return 200, merged, variant

    def _ephemeral_leg(self, run_leg, shard: int) -> None:
        try:
            run_leg(shard)
        finally:
            # ephemeral thread: its thread-local conns die with it —
            # close deterministically instead of leaking the socket
            # to GC (TIME_WAIT/fd churn under sustained fan-out)
            self._close_thread_conns()

    def _shard_leg(
        self,
        shard: int,
        key: str,
        raw: bytes,
        deadline: Optional[Deadline],
        trace_id: Optional[str],
    ) -> Tuple[str, Any]:
        """One shard's fan-out leg: try the shard's replicas
        affinity-first, failing a dead, erroring or shedding replica
        over to the next — the SAME status discipline as the replicated
        ring, applied inside the replica group: 503/5xx fail over and
        trip the breaker; 504 does NEITHER (an expired deadline is the
        client's budget, not backend sickness) and non-retryable
        answers (504, 4xx) pass through. Returns
        ``("ok", (status, body, variant))`` or
        ``("dead", (error detail, all_replicas_shed))``."""
        replicas = self._ordered_shard_replicas(shard, key)
        kind, value = self._walk_ring(
            replicas, raw, deadline, trace_id, "shard-retry"
        )
        if kind == "ok":
            return "ok", value
        details, all_shed = value
        joined = "; ".join(msg for _, msg in details)
        return "dead", (joined or "no replica configured", all_shed)

    # -- one backend leg --------------------------------------------------
    def _leg_timeout(
        self, deadline: Optional[Deadline], attempts_left: int
    ) -> float:
        """Budget split across the retry schedule: with ``attempts_left``
        sequential tries remaining, this leg may spend at most an even
        share of what's left — so a hung first replica can never eat the
        whole budget and leave the failover zero time."""
        timeout = self.config.timeout_s
        if deadline is not None:
            share = deadline.remaining_s() / max(1, attempts_left)
            timeout = max(0.001, min(timeout, share))
        return timeout

    def _leg(
        self,
        backend: str,
        raw: bytes,
        deadline: Optional[Deadline],
        attempts_left: int,
        trace_id: Optional[str],
    ) -> Tuple[int, Any, Dict[str, str]]:
        """One HTTP POST to one backend → (status, parsed body, headers).
        Propagates the trace id and the *remaining* deadline budget."""
        timeout = self._leg_timeout(deadline, attempts_left)
        headers = {"Content-Type": "application/json"}
        if trace_id:
            headers[TRACE_HEADER] = trace_id
        if deadline is not None:
            headers[DEADLINE_HEADER] = deadline.header_value()
        leg_tags: Dict[str, object] = {"backend": backend}
        with self.tracer.span("router.backend", tags=leg_tags):
            conn = self._conn(backend, timeout)
            conn.timeout = timeout
            if conn.sock is not None:  # reused keep-alive socket
                conn.sock.settimeout(timeout)
            try:
                conn.request("POST", "/queries.json", body=raw, headers=headers)
                resp = conn.getresponse()
                body_bytes = resp.read()
                resp_headers = {
                    k.lower(): v for k, v in resp.getheaders()
                }
                status = resp.status
            except Exception:
                self._drop_conn(backend)
                raise
            leg_tags["status"] = status  # recorded at span close
        try:
            body = json.loads(body_bytes.decode("utf-8")) if body_bytes else {}
        except ValueError:
            body = {"message": body_bytes.decode("utf-8", "replace")}
        return status, body, resp_headers

    def _conn(self, backend: str, timeout: float) -> http.client.HTTPConnection:
        pool = getattr(self._conns, "pool", None)
        if pool is None:
            pool = self._conns.pool = {}
        conn = pool.get(backend)
        if conn is None:
            host, _, port = backend.partition(":")
            conn = http.client.HTTPConnection(
                host, int(port or 80), timeout=timeout
            )
            pool[backend] = conn
        return conn

    def _drop_conn(self, backend: str) -> None:
        pool = getattr(self._conns, "pool", None)
        if pool is None:
            return
        conn = pool.pop(backend, None)
        if conn is not None:
            try:
                conn.close()
            except Exception:
                pass

    def _close_thread_conns(self) -> None:
        """Close every connection this thread pooled (per-request
        fan-out threads call it on exit; long-lived handler threads
        keep theirs for keep-alive reuse)."""
        pool = getattr(self._conns, "pool", None)
        if not pool:
            return
        for conn in pool.values():
            try:
                conn.close()
            except Exception:
                pass
        pool.clear()

    def server_close(self) -> None:
        if self._subscriber is not None:
            self._subscriber.stop()
        for pool in self._leg_pools.values():
            pool.stop()
        super().server_close()

    # -- status -----------------------------------------------------------
    def status_json(self) -> dict:
        with self._lock:
            inflight = {
                app: n for app, n in self._inflight.items() if n > 0
            }
            plan = self._plan
        out: dict = {
            "role": "router",
            "sharded": self.config.sharded,
            "shardCount": self.shard_count if self.config.sharded else None,
            "replicasPerShard": (
                self.config.replicas_per_shard if self.config.sharded
                else None
            ),
            "backends": [
                {
                    "backend": b,
                    "breaker": self.breakers[b].snapshot(),
                    **(
                        {"shard": i // self.config.replicas_per_shard}
                        if self.config.sharded
                        else {}
                    ),
                }
                for i, b in enumerate(self.backends)
            ],
            "backendsUp": self._backends_up(),
            "quotas": dict(self.config.quotas),
            "defaultQuota": self.config.default_quota,
            "inflight": inflight,
            "cache": (
                self._cache.snapshot()
                if self._cache is not None
                else {"enabled": False}
            ),
        }
        if self._cache is not None:
            out["cache"]["enabled"] = True
        if self._shared is not None:
            with self._lock:
                warmed = self._warmed_entries
            shared = self._shared.status()
            shared["enabled"] = True
            shared["warmedEntries"] = warmed
            shared["negativeTtlS"] = self._negative_ttl_s
            out["sharedCache"] = shared
        else:
            out["sharedCache"] = {"enabled": False}
        if self._subscriber is not None:
            out["subscriber"] = self._subscriber.status()
            out["epochSource"] = (
                "push" if self._subscriber.alive() else "poll"
            )
        else:
            out["epochSource"] = "poll"
        out["hedging"] = (
            self._hedge.snapshot()
            if self._hedge is not None
            else {"enabled": False}
        )
        if plan is not None:
            out["rolloutPlan"] = {
                "id": plan.id,
                "stage": plan.stage,
                "percent": plan.percent,
                "salt": plan.salt,
            }
        return out



def create_router(
    config: RouterConfig,
    registry=None,
    block: bool = True,
) -> RouterServer:
    """``pio router`` entry point (docs/cli.md)."""
    server = RouterServer(config, registry=registry)
    logger.info(
        "router: %s mode, %d backends, on %s:%d",
        "sharded" if config.sharded else "replicated",
        len(config.backends),
        config.ip,
        server.bound_port,
    )
    if block:
        try:
            server.serve_forever()
        finally:
            server.server_close()
    else:
        server.start_background()
    return server
