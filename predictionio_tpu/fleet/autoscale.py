"""SLO-driven fleet autoscaling: bounded, hysteresis-damped, ledgered.

The ROADMAP's "Elastic fleet" motion needs a control loop, not a human
watching dashboards: the PR-11 SLO engine already computes burn rates,
the PR-13/14 fleets already export per-partition shed counters and
per-backend breaker state — this module closes the loop
(``docs/robustness.md#autoscaler``). Design constraints, in order:

**Bounded.** At most ONE action per tick; replica targets clamped to
``[min_replicas, max_replicas]``; a partition migration only ever
recommends ``N+1`` (never a jump) and never past ``max_partitions``.
An autoscaler that can emit unbounded actions is an outage machine
with extra steps — the Google ads-serving paper's elasticity loops
(PAPERS.md) are all clamped this way.

**Hysteresis-damped.** Scaling up takes ``up_ticks`` *consecutive* hot
ticks; scaling down takes ``down_ticks`` consecutive calm ticks
(asymmetric — flapping wastes more than a spare replica costs); after
ANY action a ``cooldown_ticks`` refractory window holds, because the
action's effect takes time to show in the very signals being read.

**Ledgered.** Every decision — actions AND holds — goes through the
flight recorder (``obs/flight.py``), and executed/dry-run actions count
in ``pio_autoscale_actions_total{action,dry_run}``. An autoscaler whose
reasoning cannot be reconstructed after the fact is untrustable.

**Dry-run by default.** ``AutoscaleConfig.dry_run`` is True unless the
operator sets ``PIO_AUTOSCALE_DRY_RUN=0`` (or ``--execute``): the loop
decides and ledgers but calls no actuator. Trust is earned from the
ledger first.

The class consumes an :class:`AutoscaleSignals` snapshot per tick and
never scrapes anything itself — adapters (:class:`SignalSource` for an
in-process fleet, :func:`signals_from_dict` for ``pio autoscale
--signals``) own the plumbing, the loop owns only the decision.
"""

from __future__ import annotations

import dataclasses
import os
import time
from collections import deque
from typing import Callable, Dict, List, Mapping, Optional, Tuple

from ..obs import flight
from ..obs.metrics import MetricsRegistry

__all__ = [
    "ACTIONS",
    "AutoscaleAction",
    "AutoscaleConfig",
    "AutoscaleSignals",
    "FleetAutoscaler",
    "SignalSource",
    "signals_from_dict",
]

#: the closed action vocabulary (and the metric's ``action`` label set)
ACTIONS = ("add_replica", "remove_replica", "migrate_partitions", "hold")


def _env_int(env: Mapping[str, str], name: str, default: int) -> int:
    try:
        return int(env.get(name, default))
    except (TypeError, ValueError):
        return default


@dataclasses.dataclass(frozen=True)
class AutoscaleConfig:
    """``pio autoscale`` surface (docs/cli.md). Every field resolves
    from a ``PIO_AUTOSCALE_*`` env var in :meth:`from_env`."""

    #: decide + ledger but execute nothing (the default posture)
    dry_run: bool = True
    min_replicas: int = 1
    max_replicas: int = 4
    #: consecutive hot ticks before a scale-up action
    up_ticks: int = 2
    #: consecutive calm ticks before a scale-down action (asymmetric:
    #: flapping costs more than a spare replica)
    down_ticks: int = 6
    #: refractory ticks after any action — its effect must have time to
    #: reach the signals before the loop reads them again
    cooldown_ticks: int = 5
    #: a raw burn rate at/above this marks the tick hot even when the
    #: engine's own fire state machine has not latched yet (matches
    #: SLOObjective.burn_threshold's default)
    burn_threshold: float = 8.0
    #: per-tick ingest sheds (summed over partitions) that mark ingest
    #: pressure — the signal that recommends a partition migration
    shed_threshold: float = 1.0
    max_partitions: int = 8

    @classmethod
    def from_env(
        cls, env: Optional[Mapping[str, str]] = None, **overrides
    ) -> "AutoscaleConfig":
        env = os.environ if env is None else env
        fields = dict(
            dry_run=env.get("PIO_AUTOSCALE_DRY_RUN", "1") != "0",
            min_replicas=_env_int(env, "PIO_AUTOSCALE_MIN_REPLICAS", 1),
            max_replicas=_env_int(env, "PIO_AUTOSCALE_MAX_REPLICAS", 4),
            up_ticks=_env_int(env, "PIO_AUTOSCALE_UP_TICKS", 2),
            down_ticks=_env_int(env, "PIO_AUTOSCALE_DOWN_TICKS", 6),
            cooldown_ticks=_env_int(env, "PIO_AUTOSCALE_COOLDOWN_TICKS", 5),
            max_partitions=_env_int(env, "PIO_AUTOSCALE_MAX_PARTITIONS", 8),
        )
        fields.update(overrides)
        return cls(**fields)


@dataclasses.dataclass(frozen=True)
class AutoscaleSignals:
    """One tick's read of the fleet. Rates are per-tick deltas, not
    cumulative counters — :class:`SignalSource` owns that subtraction."""

    replicas_per_shard: int = 1
    shard_count: int = 1
    partition_count: int = 1
    #: SLO entries currently FIRING (names from SLOEngine.firing())
    firing: Tuple[str, ...] = ()
    #: objective name -> fast-window burn rate (abstentions omitted)
    burn: Mapping[str, float] = dataclasses.field(default_factory=dict)
    #: router backends whose breaker is currently open
    breaker_open_backends: int = 0
    #: shard index -> shed/error legs this tick (router view)
    shard_pressure: Mapping[int, float] = dataclasses.field(
        default_factory=dict
    )
    #: partition index -> ingest sheds this tick (event-server view)
    partition_shed: Mapping[int, float] = dataclasses.field(
        default_factory=dict
    )


@dataclasses.dataclass(frozen=True)
class AutoscaleAction:
    """One emitted decision. ``executed`` is only ever True when the
    actuator ran and returned; a dry-run action is a recommendation."""

    kind: str
    reason: str
    target: Optional[int] = None  # shard index / new replica or N count
    dry_run: bool = True
    executed: bool = False
    error: Optional[str] = None

    def to_json(self) -> dict:
        out = dataclasses.asdict(self)
        return {k: v for k, v in out.items() if v is not None}


class FleetAutoscaler:
    """The control loop: feed one :class:`AutoscaleSignals` per tick to
    :meth:`observe`, get back the (at most one) action it took. The
    ``actuator`` — ``callable(AutoscaleAction) -> None`` — is whatever
    can actually move the fleet (the drill wires a ring resize +
    migration start; production wires provisioning); it is only called
    outside dry-run, and its failure marks the action, never raises."""

    def __init__(
        self,
        config: Optional[AutoscaleConfig] = None,
        actuator: Optional[Callable[[AutoscaleAction], None]] = None,
        clock: Callable[[], float] = time.monotonic,
        metrics: Optional[MetricsRegistry] = None,
    ):
        self.config = config if config is not None else AutoscaleConfig.from_env()
        self.actuator = actuator
        self.clock = clock
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        self._actions_total = self.metrics.counter(
            "pio_autoscale_actions_total",
            "Autoscaler decisions by action kind and dry-run posture",
            labelnames=("action", "dry_run"),
        )
        self.tick_count = 0
        self._hot = 0
        self._calm = 0
        self._ingest_hot = 0
        self._cooldown = 0
        #: recent decisions (actions and holds), newest last — the
        #: in-memory tail of the flight-recorder ledger for status/CLI
        self.history: deque = deque(maxlen=128)

    # -- signal classification -------------------------------------------
    def _serving_hot(self, s: AutoscaleSignals) -> Optional[str]:
        if s.firing:
            return f"SLO firing: {', '.join(sorted(s.firing))}"
        burned = [
            name for name, rate in sorted(s.burn.items())
            if rate is not None and rate >= self.config.burn_threshold
        ]
        if burned:
            return f"burn rate over {self.config.burn_threshold}: " + ", ".join(burned)
        if s.breaker_open_backends > 0:
            return f"{s.breaker_open_backends} backend breaker(s) open"
        shed = [
            str(i) for i, v in sorted(s.shard_pressure.items()) if v > 0
        ]
        if shed:
            return f"shard shed pressure on shard(s) {', '.join(shed)}"
        return None

    def _ingest_pressure(self, s: AutoscaleSignals) -> Optional[str]:
        total = sum(v for v in s.partition_shed.values() if v)
        if total >= self.config.shed_threshold:
            worst = max(s.partition_shed, key=lambda k: s.partition_shed[k])
            return (
                f"{total:.0f} ingest shed(s) this tick "
                f"(worst partition {worst})"
            )
        return None

    def _worst_shard(self, s: AutoscaleSignals) -> Optional[int]:
        if not s.shard_pressure:
            return None
        return max(s.shard_pressure, key=lambda k: s.shard_pressure[k])

    # -- the tick ---------------------------------------------------------
    def observe(self, signals: AutoscaleSignals) -> List[AutoscaleAction]:
        """One control tick. Returns the emitted actions (0 or 1) —
        holds are ledgered but not returned."""
        cfg = self.config
        self.tick_count += 1
        hot_reason = self._serving_hot(signals)
        ingest_reason = self._ingest_pressure(signals)
        if hot_reason:
            self._hot += 1
            self._calm = 0
        else:
            self._hot = 0
            self._calm += 1
        self._ingest_hot = self._ingest_hot + 1 if ingest_reason else 0

        if self._cooldown > 0:
            self._cooldown -= 1
            return self._hold(
                f"cooldown ({self._cooldown} tick(s) left)", signals
            )

        # scale-up beats scale-out beats scale-down: serving pain is
        # user-visible now, ingest pain sheds (bounded) until migrated
        if hot_reason and self._hot >= cfg.up_ticks:
            if signals.replicas_per_shard < cfg.max_replicas:
                return self._act(
                    AutoscaleAction(
                        kind="add_replica",
                        reason=hot_reason,
                        target=signals.replicas_per_shard + 1,
                        dry_run=cfg.dry_run,
                    ),
                    signals,
                )
            return self._hold(
                f"hot ({hot_reason}) but already at max_replicas="
                f"{cfg.max_replicas}",
                signals,
            )
        if ingest_reason and self._ingest_hot >= cfg.up_ticks:
            if signals.partition_count < cfg.max_partitions:
                return self._act(
                    AutoscaleAction(
                        kind="migrate_partitions",
                        reason=ingest_reason,
                        target=signals.partition_count + 1,
                        dry_run=cfg.dry_run,
                    ),
                    signals,
                )
            return self._hold(
                f"ingest pressure ({ingest_reason}) but already at "
                f"max_partitions={cfg.max_partitions}",
                signals,
            )
        if (
            not hot_reason
            and self._calm >= cfg.down_ticks
            and signals.replicas_per_shard > cfg.min_replicas
        ):
            return self._act(
                AutoscaleAction(
                    kind="remove_replica",
                    reason=f"calm for {self._calm} tick(s)",
                    target=signals.replicas_per_shard - 1,
                    dry_run=cfg.dry_run,
                ),
                signals,
            )
        return self._hold(
            hot_reason
            and f"hot ({self._hot}/{cfg.up_ticks} tick(s)): {hot_reason}"
            or f"calm ({self._calm}/{cfg.down_ticks} tick(s))",
            signals,
        )

    # -- emit / ledger ----------------------------------------------------
    def _ledger(self, action: AutoscaleAction, signals: AutoscaleSignals):
        entry = {
            "tick": self.tick_count,
            "action": action.to_json(),
            "replicasPerShard": signals.replicas_per_shard,
            "partitionCount": signals.partition_count,
        }
        self.history.append(entry)
        flight.record(
            "autoscale",
            "fleet.autoscale.decide",
            tick=self.tick_count,
            action=action.kind,
            reason=action.reason,
            target=action.target,
            dryRun=action.dry_run,
            executed=action.executed,
            error=action.error,
        )

    def _hold(
        self, reason: str, signals: AutoscaleSignals
    ) -> List[AutoscaleAction]:
        self._ledger(
            AutoscaleAction(
                kind="hold", reason=reason, dry_run=self.config.dry_run
            ),
            signals,
        )
        return []

    def _act(
        self, action: AutoscaleAction, signals: AutoscaleSignals
    ) -> List[AutoscaleAction]:
        if not action.dry_run and self.actuator is not None:
            try:
                self.actuator(action)
                action = dataclasses.replace(action, executed=True)
            except Exception as exc:
                action = dataclasses.replace(action, error=str(exc))
        self._actions_total.inc(
            1, action=action.kind, dry_run="1" if action.dry_run else "0"
        )
        self._cooldown = self.config.cooldown_ticks
        self._hot = 0
        self._calm = 0
        self._ingest_hot = 0
        self._ledger(action, signals)
        return [action]

    def decisions(self) -> List[dict]:
        return list(self.history)


class SignalSource:
    """In-process adapter: turns an :class:`~predictionio_tpu.obs.slo
    .SLOEngine`, a :class:`~predictionio_tpu.fleet.router.RouterServer`
    and/or an event server into per-tick :class:`AutoscaleSignals`.
    Counters are cumulative, the loop wants deltas — this object keeps
    the previous totals and subtracts."""

    def __init__(self, slo_engine=None, router=None, event_server=None):
        self._slo = slo_engine
        self._router = router
        self._event_server = event_server
        self._prev_shard: Dict[int, float] = {}
        self._prev_partition: Dict[int, float] = {}

    def _shard_pressure(self, status: dict) -> Dict[int, float]:
        """Per-shard shed/error legs since the last sample, read off the
        router's per-backend event counter."""
        if self._router is None:
            return {}
        rps = max(1, self._router.config.replicas_per_shard)
        totals: Dict[int, float] = {}
        for labels, value in self._router._backend_events.samples():
            if labels.get("kind") not in ("error", "open_skip", "dead_shard"):
                continue
            backend = labels.get("backend") or ""
            if backend.startswith("shard-"):
                # dead-shard legs are already labelled by shard
                try:
                    shard = int(backend.split("-", 1)[1])
                except ValueError:
                    continue
            else:
                try:
                    shard = self._router.backends.index(backend) // rps
                except ValueError:
                    continue
            totals[shard] = totals.get(shard, 0.0) + float(value)
        out = {
            shard: max(0.0, total - self._prev_shard.get(shard, 0.0))
            for shard, total in totals.items()
        }
        self._prev_shard = totals
        return out

    def _partition_shed(self) -> Dict[int, float]:
        if self._event_server is None:
            return {}
        counter = getattr(self._event_server, "_partition_shed_total", None)
        if counter is None:
            return {}
        totals: Dict[int, float] = {}
        for labels, value in counter.samples():
            try:
                totals[int(labels.get("partition", -1))] = float(value)
            except (TypeError, ValueError):
                continue
        out = {
            part: max(0.0, total - self._prev_partition.get(part, 0.0))
            for part, total in totals.items()
        }
        self._prev_partition = totals
        return out

    def sample(self) -> AutoscaleSignals:
        firing: Tuple[str, ...] = ()
        burn: Dict[str, float] = {}
        if self._slo is not None:
            summary = self._slo.summary()
            firing = tuple(
                o["name"] for o in summary["objectives"]
                if o["state"] == "FIRING"
            )
            burn = {
                o["name"]: o["burnFast"]
                for o in summary["objectives"]
                if o.get("burnFast") is not None
            }
        replicas, shards, breakers_open = 1, 1, 0
        status: dict = {}
        if self._router is not None:
            status = self._router.status_json()
            replicas = status.get("replicasPerShard") or 1
            shards = status.get("shardCount") or 1
            breakers_open = sum(
                1 for b in status.get("backends", ())
                if (b.get("breaker") or {}).get("state") == "open"
            )
        partition_count = 1
        if self._event_server is not None:
            events = getattr(self._event_server, "events", None)
            partition_count = getattr(events, "partition_count", 1)
        return AutoscaleSignals(
            replicas_per_shard=replicas,
            shard_count=shards,
            partition_count=partition_count,
            firing=firing,
            burn=burn,
            breaker_open_backends=breakers_open,
            shard_pressure=self._shard_pressure(status),
            partition_shed=self._partition_shed(),
        )


def signals_from_dict(d: Mapping) -> AutoscaleSignals:
    """``pio autoscale --signals FILE`` adapter: a JSON snapshot (the
    shape ``AutoscaleSignals`` prints) → one tick's signals. Unknown
    keys are ignored so operators can annotate the file."""

    def _int_keys(m) -> Dict[int, float]:
        out: Dict[int, float] = {}
        for k, v in (m or {}).items():
            try:
                out[int(k)] = float(v)
            except (TypeError, ValueError):
                continue
        return out

    return AutoscaleSignals(
        replicas_per_shard=int(d.get("replicasPerShard", 1)),
        shard_count=int(d.get("shardCount", 1)),
        partition_count=int(d.get("partitionCount", 1)),
        firing=tuple(d.get("firing", ())),
        burn={
            str(k): float(v) for k, v in (d.get("burn") or {}).items()
            if v is not None
        },
        breaker_open_backends=int(d.get("breakerOpenBackends", 0)),
        shard_pressure=_int_keys(d.get("shardPressure")),
        partition_shed=_int_keys(d.get("partitionShed")),
    )
