"""Router response cache + single-flight: serve from memory, not matmul.

At millions of users the head of the query distribution is Zipfian — the
same handful of (engine, variant, query) triples arrives over and over —
so the fastest top-k is the one never recomputed (docs/fleet.md#cache;
the memory-over-recompute discipline of the ads-serving infrastructure
in PAPERS.md). This module is the pure, stdlib-only storage half of
that tier (the ``rollout/plan.py`` discipline: injected clock, no HTTP,
no jax — testable in isolation):

- :func:`canonical_query` — ONE canonical byte form per logical query,
  so ``{"user": "u1", "num": 5}`` and ``{"num": 5, "user": "u1"}`` share
  a cache line.
- :class:`ResponseCache` — bounded LRU + TTL storage keyed by
  ``(variant, canonical query)``, every entry stamped with the **epoch**
  (:func:`~predictionio_tpu.rollout.plan.plan_epoch` + the serving model
  instance) it was filled under. A lookup whose current epoch disagrees
  with the entry's drops the entry — a cached answer can never outlive
  the rollout stage or the model that produced it, *by construction*,
  not by timer.
- :class:`SingleFlight` — coalesces concurrent identical calls onto one
  in-flight execution, so N simultaneous sharded queries for the same
  key cost ONE scatter/gather instead of N.

The router (:mod:`~predictionio_tpu.fleet.router`) owns the policy:
when to look up, what the epoch is, and how invalidations surface as
``pio_router_cache_*`` metrics.
"""

from __future__ import annotations

import json
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional, Tuple

__all__ = [
    "CACHE_HEADER",
    "CacheEntry",
    "ResponseCache",
    "SingleFlight",
    "canonical_query",
]

#: response header naming the router cache's verdict for this request
#: ("hit" / "miss"; absent when the cache is disabled). Headers only —
#: the BODY of a hit is byte-identical to the miss that filled it
#: (docs/fleet.md#cache).
CACHE_HEADER = "X-PIO-Cache"

#: invalidation reasons — a closed vocabulary, safe as a metric label
#: (docs/observability.md#metric-catalog): "epoch" = rollout stage /
#: model swap flush, "ttl" = entry outlived its freshness budget,
#: "capacity" = LRU eviction at the bound, "explicit" = operator flush.
INVALIDATION_REASONS = ("epoch", "ttl", "capacity", "explicit")


def canonical_query(payload: Any) -> str:
    """The one canonical string form of a query payload: key-sorted,
    separator-free JSON — byte-stable across clients that serialize the
    same logical query differently. Unserializable payloads degrade to
    ``repr`` (still deterministic within a process; such shapes are
    exotic enough that a missed cache line beats a wrong shared one)."""
    try:
        return json.dumps(
            payload, sort_keys=True, separators=(",", ":"), default=str
        )
    except (TypeError, ValueError):
        return repr(payload)


class CacheEntry:
    """One cached response: the parsed 200 body, the variant header it
    was served under, and the epoch it was filled at. ``ttl_s`` — when
    set — overrides the cache-wide TTL (the negative-caching lever:
    known-empty results live on a much shorter fuse, docs/fleet.md
    #shared-cache-tier); ``negative`` marks such entries so owners can
    label the hit. ``hits`` counts reads served from this entry — the
    popularity signal behind the shared tier's top-keys export."""

    __slots__ = ("body", "variant", "epoch", "stored_at", "ttl_s",
                 "negative", "hits")

    def __init__(
        self, body: Any, variant: Optional[str], epoch: str, stored_at: float,
        ttl_s: Optional[float] = None, negative: bool = False,
    ):
        self.body = body
        self.variant = variant
        self.epoch = epoch
        self.stored_at = stored_at
        self.ttl_s = ttl_s
        self.negative = negative
        self.hits = 0


class ResponseCache:
    """Bounded LRU + TTL response store with epoch-checked reads.

    One lock over one OrderedDict; nothing blocking runs under it (the
    package's lock discipline). ``on_invalidate(reason, count)`` — when
    given — is called for every eviction class, so the owner can mirror
    the counts into labeled metrics without this module importing the
    metrics plane.
    """

    def __init__(
        self,
        max_entries: int = 2048,
        ttl_s: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
        on_invalidate: Optional[Callable[[str, int], None]] = None,
    ):
        if max_entries <= 0:
            raise ValueError("max_entries must be positive (the whole "
                             "point is a BOUNDED cache)")
        if ttl_s <= 0:
            raise ValueError("ttl_s must be positive")
        self.max_entries = int(max_entries)
        self.ttl_s = float(ttl_s)
        self.clock = clock
        self._on_invalidate = on_invalidate
        self._lock = threading.Lock()
        self._cache: "OrderedDict[Tuple[str, str], CacheEntry]" = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.invalidations: Dict[str, int] = {}

    # -- internal ----------------------------------------------------------
    def _note_invalidation(self, reason: str, count: int) -> None:
        """Caller holds the lock for the bookkeeping; the owner callback
        runs OUTSIDE it (callers pass the counts out) — see call sites."""
        self.invalidations[reason] = self.invalidations.get(reason, 0) + count

    def _emit(self, reason: str, count: int) -> None:
        if count and self._on_invalidate is not None:
            try:
                self._on_invalidate(reason, count)
            except Exception:
                pass  # observability must never fail a lookup

    # -- read/write --------------------------------------------------------
    def get(
        self, key: Tuple[str, str], epoch: str
    ) -> Optional[CacheEntry]:
        """The live entry for ``key`` under the CURRENT ``epoch``, or
        None. An entry past its TTL or filled under another epoch is
        dropped on the spot (and counted) — a stale read is never an
        answer."""
        dropped: Optional[str] = None
        with self._lock:
            entry = self._cache.get(key)
            if entry is None:
                self.misses += 1
                return None
            ttl = entry.ttl_s if entry.ttl_s is not None else self.ttl_s
            if self.clock() - entry.stored_at > ttl:
                del self._cache[key]
                self._note_invalidation("ttl", 1)
                self.misses += 1
                dropped = "ttl"
            elif entry.epoch != epoch:
                del self._cache[key]
                self._note_invalidation("epoch", 1)
                self.misses += 1
                dropped = "epoch"
            else:
                self._cache.move_to_end(key)
                self.hits += 1
                entry.hits += 1
        if dropped is not None:
            self._emit(dropped, 1)
            return None
        return entry

    def put(
        self,
        key: Tuple[str, str],
        body: Any,
        variant: Optional[str],
        epoch: str,
        ttl_s: Optional[float] = None,
        negative: bool = False,
    ) -> None:
        """Store one 200 response under the epoch it was computed at.
        Beyond ``max_entries`` the least-recently-used entry is evicted
        (counted as a "capacity" invalidation). ``ttl_s`` overrides the
        cache-wide TTL for this entry; ``negative`` marks a known-empty
        result (callers pair it with a short TTL)."""
        evicted = 0
        with self._lock:
            self._cache[key] = CacheEntry(
                body=body, variant=variant, epoch=epoch,
                stored_at=self.clock(),
                ttl_s=float(ttl_s) if ttl_s is not None else None,
                negative=bool(negative),
            )
            self._cache.move_to_end(key)
            while len(self._cache) > self.max_entries:
                self._cache.popitem(last=False)
                evicted += 1
            if evicted:
                self._note_invalidation("capacity", evicted)
        self._emit("capacity", evicted)

    def flush(
        self, variant: Optional[str] = None, reason: str = "epoch"
    ) -> int:
        """Drop every entry (or every entry of one ``variant``) and
        return how many were dropped. The router calls this when the
        observed epoch moves — a rollout stage change or a model swap
        flushes the keyspace the moment it is seen, instead of letting
        each entry die lazily at its next read."""
        with self._lock:
            if variant is None:
                count = len(self._cache)
                self._cache.clear()
            else:
                doomed = [k for k in self._cache if k[0] == variant]
                for k in doomed:
                    del self._cache[k]
                count = len(doomed)
            if count:
                self._note_invalidation(reason, count)
        self._emit(reason, count)
        return count

    def export_top(
        self, n: int = 50, max_bytes: Optional[int] = None
    ) -> list:
        """The ``n`` most-hit live entries, hottest first — the warming
        export (docs/fleet.md#shared-cache-tier): a restarting router
        pre-fills its local LRU from this list so the backends never see
        the full hot set again. Entries past their TTL are skipped (not
        dropped — export is a read, never a mutation); negative entries
        ride along with their flag so the importer keeps the short
        fuse.

        ``max_bytes`` caps the export by payload size (body + query
        bytes): one giant blob with many hits no longer crowds the whole
        warming budget out — an entry that would overflow the remaining
        budget is skipped and the scan continues, so smaller but still
        hot entries behind it make the cut (``PIO_SHAREDCACHE_WARM_BYTES``
        sets the fleet default — docs/cli.md)."""
        now = self.clock()
        with self._lock:
            live = [
                (key, entry)
                for key, entry in self._cache.items()
                if now - entry.stored_at <= (
                    entry.ttl_s if entry.ttl_s is not None else self.ttl_s
                )
            ]
        live.sort(key=lambda item: item[1].hits, reverse=True)
        out: list = []
        remaining = None if max_bytes is None else max(0, int(max_bytes))
        for key, entry in live:
            if len(out) >= max(0, int(n)):
                break
            if remaining is not None:
                # cost = what the wire carries: serialized body + query
                try:
                    body_len = len(
                        json.dumps(
                            entry.body, separators=(",", ":"), default=str
                        )
                    )
                except (TypeError, ValueError):
                    body_len = len(repr(entry.body))
                cost = body_len + len(key[1])
                if cost > remaining:
                    continue  # too big for what's left; keep scanning
                remaining -= cost
            out.append(
                {
                    "variant": key[0],
                    "query": key[1],
                    "body": entry.body,
                    "servedVariant": entry.variant,
                    "epoch": entry.epoch,
                    "hits": entry.hits,
                    "negative": entry.negative,
                }
            )
        return out

    # -- introspection -----------------------------------------------------
    def __len__(self) -> int:
        with self._lock:
            return len(self._cache)

    def snapshot(self) -> dict:
        """The ``/router.json`` cache block."""
        with self._lock:
            return {
                "entries": len(self._cache),
                "maxEntries": self.max_entries,
                "ttlS": self.ttl_s,
                "hits": self.hits,
                "misses": self.misses,
                "invalidations": dict(self.invalidations),
            }


class _Flight:
    __slots__ = ("done", "value", "error")

    def __init__(self):
        self.done = threading.Event()
        self.value: Any = None
        self.error: Optional[BaseException] = None


class SingleFlight:
    """Coalesce concurrent calls for the same key onto one execution.

    ``do(key, fn)`` → ``(value, shared)``: the first caller for a key
    becomes the *leader* and runs ``fn``; callers arriving while the
    leader is in flight wait and receive the leader's result
    (``shared=True``) without executing anything. The leader's exception
    propagates to followers too — with one exception: a follower never
    inherits the leader's *deadline* failure (that was the leader's
    budget, not the follower's — see the router's 504 discipline), it
    falls back to its own execution instead. A follower whose own
    ``timeout_s`` expires first raises :class:`TimeoutError`.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._flights: Dict[Any, _Flight] = {}

    def do(
        self,
        key: Any,
        fn: Callable[[], Any],
        timeout_s: Optional[float] = None,
        share_error: Callable[[BaseException], bool] = lambda exc: True,
    ) -> Tuple[Any, bool]:
        with self._lock:
            flight = self._flights.get(key)
            if flight is None:
                flight = _Flight()
                self._flights[key] = flight
                leader = True
            else:
                leader = False
        if leader:
            try:
                flight.value = fn()
            except BaseException as exc:
                flight.error = exc
                raise
            finally:
                with self._lock:
                    self._flights.pop(key, None)
                flight.done.set()
            return flight.value, False
        if not flight.done.wait(timeout_s):
            raise TimeoutError(
                "coalesced request timed out waiting for the in-flight leg"
            )
        if flight.error is not None:
            if share_error(flight.error):
                raise flight.error
            # the leader's failure was caller-specific (e.g. ITS deadline
            # expired) — run our own leg rather than inherit it
            return fn(), False
        return flight.value, True
