"""Serving fleet: the horizontal story for the query tier.

One query server process was the ceiling through PR 8; this package is
the router tier that fronts N of them (``docs/fleet.md``):

- :mod:`~predictionio_tpu.fleet.router` — ``pio router``: consistent
  replica affinity and fleet-wide canary stickiness (both riding the
  pure ``rollout/plan.py`` SHA-256 bucket split), per-app admission
  quotas, breaker-guarded backend health with retry-on-another-replica,
  and the sharded-model scatter/gather serving mode.
- :mod:`~predictionio_tpu.fleet.merge` — exact global top-k from
  per-shard top-k candidates (k-way merge on score, ties broken by item
  id for determinism).

Like the rollout plane's :mod:`~predictionio_tpu.rollout.plan`, the
routing arithmetic is pure; the router server itself is stdlib + the
shared resilience/obs planes — no jax import anywhere in the package,
so a router node needs no accelerator runtime.
"""

from .merge import merge_item_scores, merge_predictions
from .router import RouterConfig, RouterServer, create_router

__all__ = [
    "RouterConfig",
    "RouterServer",
    "create_router",
    "merge_item_scores",
    "merge_predictions",
]
