"""Serving fleet: the horizontal story for the query tier.

One query server process was the ceiling through PR 8; this package is
the router tier that fronts N of them (``docs/fleet.md``):

- :mod:`~predictionio_tpu.fleet.router` — ``pio router``: consistent
  replica affinity and fleet-wide canary stickiness (both riding the
  pure ``rollout/plan.py`` SHA-256 bucket split), per-app admission
  quotas, breaker-guarded backend health with retry-on-another-replica,
  and the sharded-model scatter/gather serving mode with
  replicas-per-shard failover.
- :mod:`~predictionio_tpu.fleet.merge` — exact global top-k from
  per-shard top-k candidates (k-way merge on score, ties broken by item
  id for determinism).
- :mod:`~predictionio_tpu.fleet.cache` — the serving-tier memory
  hierarchy (``docs/fleet.md#cache``): a bounded LRU+TTL response cache
  with epoch-checked reads (a cached answer can never outlive the
  rollout stage or model that produced it) and the single-flight gate
  that coalesces concurrent identical scatter/gathers.

Like the rollout plane's :mod:`~predictionio_tpu.rollout.plan`, the
routing and cache arithmetic is pure; the router server itself is
stdlib + the shared resilience/obs planes — no jax import anywhere in
the package, so a router node needs no accelerator runtime.
"""

from .cache import CACHE_HEADER, ResponseCache, SingleFlight, canonical_query
from .merge import merge_item_scores, merge_predictions
from .router import (
    RouterConfig,
    RouterServer,
    ShardUnavailable,
    create_router,
)

__all__ = [
    "CACHE_HEADER",
    "ResponseCache",
    "RouterConfig",
    "RouterServer",
    "ShardUnavailable",
    "SingleFlight",
    "canonical_query",
    "create_router",
    "merge_item_scores",
    "merge_predictions",
]
