"""Checkpoint subsystem: async sharded step-resume (docs/checkpoint.md).

A preemption costs minutes, not the run: the sharded ALS trainer
(``ops/als_sharded.py``) snapshots both factor tables in CANONICAL
(global, unpermuted) row order every ``checkpoint_every`` iterations, a
background :class:`CheckpointWriter` commits each snapshot atomically
(per-file tmp + fsync + rename with SHA-256, ``manifest.json`` LAST),
and resume re-deals the canonical rows through the balancer at ANY
shard count — N→M lands within the PR-12 reassociation tolerances of
the uninterrupted run.

Failure discipline, in one line each:

- crash mid-write       → no manifest → the step never existed
- corrupt file on load  → loud skip to the previous valid step, counted
- mismatched recipe     → loud :class:`CheckpointMismatch` refusal
- disk can't keep up    → snapshot dropped + counted, loop never stalls

Operator surface: ``pio ckpt ls|verify|gc`` (:mod:`.cli`), the
``PIO_CKPT_*`` envs (:mod:`.settings`), and the ``ckptResume`` bench
block with the ``train_ckpt_overhead_ratio`` ledger metric.
"""

from .settings import (  # noqa: F401
    DIR_ENV,
    EVERY_ENV,
    KEEP_EVERY_ENV,
    KEEP_LAST_ENV,
    QUEUE_ENV,
    RESUME_ENV,
    resolve_every,
    resolve_queue_depth,
    resolve_resume,
    resolve_retention,
)
from .store import (  # noqa: F401
    MANIFEST,
    CheckpointCorrupt,
    CheckpointError,
    CheckpointMismatch,
    CheckpointStore,
    LoadedCheckpoint,
    sha256_bytes,
)
from .writer import CheckpointWriter  # noqa: F401
