"""Checkpoint lever resolution: explicit > workflow > env > default.

The PR-12 tri-state discipline, extended one notch: the engine params
carry the explicit value, the workflow run (``pio train
--checkpoint-every`` / ``--resume``, or the continuous controller's
retrain config) carries a per-run override, the ``PIO_CKPT_*`` envs
carry the fleet default. Whatever resolves is what the profile records
— resolved, not requested — and invalid values fail loudly at resolve
time, never as a silently ignored flag.

Envs (docs/cli.md#environment):

- ``PIO_CKPT_EVERY``      checkpoint cadence in iterations (0 = off)
- ``PIO_CKPT_RESUME``     0 = clear existing checkpoints, train fresh
- ``PIO_CKPT_KEEP_LAST``  GC: newest committed steps kept (default 3)
- ``PIO_CKPT_KEEP_EVERY`` GC: also keep steps divisible by J (0 = off)
- ``PIO_CKPT_QUEUE``      writer queue depth (default 2)
- ``PIO_CKPT_DIR``        explicit checkpoint root for the run (kept on
  success, unlike the derived per-run directory)
"""

from __future__ import annotations

import os
from typing import Mapping, Optional

EVERY_ENV = "PIO_CKPT_EVERY"
RESUME_ENV = "PIO_CKPT_RESUME"
KEEP_LAST_ENV = "PIO_CKPT_KEEP_LAST"
KEEP_EVERY_ENV = "PIO_CKPT_KEEP_EVERY"
QUEUE_ENV = "PIO_CKPT_QUEUE"
DIR_ENV = "PIO_CKPT_DIR"


def _env_int(env: Mapping[str, str], name: str) -> Optional[int]:
    raw = env.get(name)
    if raw is None or raw.strip() == "":
        return None
    try:
        return int(raw)
    except ValueError:
        raise ValueError(
            f"{name}={raw!r} is not an integer — unset it or pass a "
            "whole number of iterations"
        ) from None


def resolve_every(
    explicit: Optional[int] = None,
    workflow: Optional[int] = None,
    env: Optional[Mapping[str, str]] = None,
) -> int:
    """Checkpoint cadence: engine params > workflow run > env > 0."""
    env = os.environ if env is None else env
    for source, value in (
        ("checkpoint_every", explicit),
        ("--checkpoint-every", workflow),
        (EVERY_ENV, _env_int(env, EVERY_ENV)),
    ):
        if value is not None:
            if value < 0:
                raise ValueError(
                    f"{source}={value} must be >= 0 (0 disables "
                    "checkpointing)"
                )
            return int(value)
    return 0


def resolve_resume(
    explicit: Optional[bool] = None,
    env: Optional[Mapping[str, str]] = None,
) -> bool:
    """Resume toggle: explicit (``--resume``/``--no-resume``) > env >
    True. Default ON — a rerun after a crash picks up the latest valid
    checkpoint; the config-identity refusal guards against resuming
    foreign state."""
    if explicit is not None:
        return bool(explicit)
    env = os.environ if env is None else env
    raw = env.get(RESUME_ENV)
    if raw is None or raw.strip() == "":
        return True
    return raw.strip() not in ("0", "false", "no", "off")


def resolve_retention(
    keep_last: Optional[int] = None,
    keep_every: Optional[int] = None,
    env: Optional[Mapping[str, str]] = None,
) -> tuple:
    """GC policy: explicit > env > (3, 0)."""
    env = os.environ if env is None else env
    if keep_last is None:
        keep_last = _env_int(env, KEEP_LAST_ENV)
    if keep_every is None:
        keep_every = _env_int(env, KEEP_EVERY_ENV)
    return (3 if keep_last is None else keep_last,
            0 if keep_every is None else keep_every)


def resolve_queue_depth(
    explicit: Optional[int] = None,
    env: Optional[Mapping[str, str]] = None,
) -> int:
    env = os.environ if env is None else env
    value = explicit if explicit is not None else _env_int(env, QUEUE_ENV)
    return 2 if value is None else value
