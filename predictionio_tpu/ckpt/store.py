"""On-disk checkpoint store: atomic per-file writes, manifest-last commit.

The commit protocol (docs/checkpoint.md#commit-protocol) has exactly one
durable transition per checkpoint:

    step_00000007/x.npy.tmp      write + fsync
    step_00000007/x.npy          os.replace (atomic_write_bytes)
    step_00000007/y.npy          ... every array file the same way ...
    step_00000007/manifest.json  LAST — atomic_write_bytes again

A step directory without a parseable ``manifest.json`` is *not a
checkpoint*: it is garbage left by a crash, invisible to
:meth:`CheckpointStore.steps` and therefore to resume. A crash at ANY
point of the sequence above leaves either (a) no manifest — the step
never existed — or (b) a complete manifest whose every file was already
fsync'd under its final name. There is no window in which a loadable
half-checkpoint exists, which is the property the preemption drill
(bench.py ``ckptResume``) kills processes to prove.

Integrity is per file: the manifest records a SHA-256 for every array
file, verified on load. A mismatch is a *loud skip* — the corrupt step
is logged at ERROR, counted in :attr:`CheckpointStore.corrupt_skipped`,
and resume falls back to the previous valid step. A checkpoint whose
recorded config identity disagrees with the resuming run's is a *loud
refusal* (:class:`CheckpointMismatch`): silently training on foreign
factors diverges without a trace, the failure mode PR-12's lever
discipline exists to prevent.

Retention (docs/checkpoint.md#gc-policy): ``keep_last`` newest committed
steps always survive; ``keep_every`` > 0 additionally pins every step
divisible by it (the coarse history a post-mortem replays). Deletion
removes the manifest FIRST and fsyncs the root, so a crash mid-GC
demotes the step to garbage instead of leaving a manifest pointing at
missing files.
"""

from __future__ import annotations

import dataclasses
import hashlib
import io
import json
import logging
import os
import re
import shutil
from typing import Dict, Iterable, List, Optional

import numpy as np

from ..utils.durability import atomic_write_bytes, fsync_dir

logger = logging.getLogger("pio.ckpt")

MANIFEST = "manifest.json"
SCHEMA_VERSION = 1
_STEP_RE = re.compile(r"^step_(\d{8})$")


class CheckpointError(Exception):
    """Base class for checkpoint failures."""


class CheckpointCorrupt(CheckpointError):
    """A committed step failed integrity verification (bad manifest,
    missing file, checksum mismatch). Resume SKIPS it — loudly,
    counted — and falls back to the previous valid step."""


class CheckpointMismatch(CheckpointError):
    """The checkpoint's recorded config identity disagrees with the
    resuming run. This never degrades to a skip: resuming different
    math on old factors is silent divergence, so it refuses."""


def sha256_bytes(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _step_dirname(step: int) -> str:
    return f"step_{step:08d}"


def _npy_bytes(array: np.ndarray) -> bytes:
    buf = io.BytesIO()
    np.save(buf, np.ascontiguousarray(array))
    return buf.getvalue()


@dataclasses.dataclass
class LoadedCheckpoint:
    """One verified checkpoint: arrays by name, the manifest's ``meta``
    dict (config identity + ``iteration``), and the committed step."""

    step: int
    arrays: Dict[str, np.ndarray]
    meta: dict


class CheckpointStore:
    """Directory of committed checkpoints under ``root``.

    One writer at a time (the background :class:`~.writer.CheckpointWriter`
    thread); any number of readers. ``keep_last``/``keep_every`` set the
    GC policy applied after every save (and by ``pio ckpt gc``).
    """

    def __init__(
        self,
        root: str,
        keep_last: int = 3,
        keep_every: int = 0,
    ) -> None:
        if keep_last < 1:
            raise ValueError(f"keep_last must be >= 1, got {keep_last}")
        if keep_every < 0:
            raise ValueError(f"keep_every must be >= 0, got {keep_every}")
        self.root = root
        self.keep_last = keep_last
        self.keep_every = keep_every
        #: corrupt steps skipped by :meth:`load` over this store's
        #: lifetime — the counter the resume path and the drill report
        self.corrupt_skipped = 0

    # -- listing ----------------------------------------------------------

    def steps(self) -> List[int]:
        """Committed steps (manifest present), ascending. Step dirs
        without a manifest are crash garbage and not listed."""
        if not os.path.isdir(self.root):
            return []
        out = []
        for name in os.listdir(self.root):
            m = _STEP_RE.match(name)
            if m and os.path.isfile(
                os.path.join(self.root, name, MANIFEST)
            ):
                out.append(int(m.group(1)))
        return sorted(out)

    def uncommitted(self) -> List[str]:
        """Step dirs with NO manifest: crash leftovers, never loadable."""
        if not os.path.isdir(self.root):
            return []
        return sorted(
            name for name in os.listdir(self.root)
            if _STEP_RE.match(name)
            and not os.path.isfile(os.path.join(self.root, name, MANIFEST))
        )

    def step_dir(self, step: int) -> str:
        return os.path.join(self.root, _step_dirname(step))

    # -- save (the clean exemplar for robust-nonatomic-checkpoint) --------

    def save(self, step: int, arrays: Dict[str, np.ndarray], meta: dict) -> str:
        """Commit one checkpoint: every array file atomically
        (tmp + fsync + rename, per-file SHA-256), manifest LAST. Returns
        the step directory. Runs GC after the commit."""
        if step < 0:
            raise ValueError(f"checkpoint step must be >= 0, got {step}")
        d = self.step_dir(step)
        if os.path.isdir(d):
            # a half-written twin from a crashed predecessor (same step,
            # no manifest) — or a re-save of a committed step: both
            # restart from an empty directory so stale files can never
            # shadow the new manifest's contents
            shutil.rmtree(d)
        os.makedirs(d, exist_ok=True)
        files = self._save_files(d, arrays)
        self._commit_manifest(d, step, files, meta)
        self.gc()
        return d

    def _save_files(
        self, d: str, arrays: Dict[str, np.ndarray]
    ) -> Dict[str, dict]:
        files: Dict[str, dict] = {}
        for name, array in arrays.items():
            data = _npy_bytes(array)
            fname = f"{name}.npy"
            atomic_write_bytes(os.path.join(d, fname), data)
            files[fname] = {
                "sha256": sha256_bytes(data),
                "bytes": len(data),
            }
        return files

    def _commit_manifest(
        self, d: str, step: int, files: Dict[str, dict], meta: dict
    ) -> None:
        manifest = {
            "schema": SCHEMA_VERSION,
            "step": int(step),
            "files": files,
            "meta": dict(meta),
        }
        atomic_write_bytes(
            os.path.join(d, MANIFEST),
            json.dumps(manifest, indent=2, sort_keys=True).encode("utf-8"),
        )
        # the rename inside atomic_write_bytes fsyncs the step dir; the
        # root must be durable too or the whole step dir can vanish
        fsync_dir(self.root)

    # -- load / verify ----------------------------------------------------

    def read_manifest(self, step: int) -> dict:
        path = os.path.join(self.step_dir(step), MANIFEST)
        try:
            with open(path, "rb") as fh:
                manifest = json.loads(fh.read().decode("utf-8"))
        except (OSError, ValueError) as exc:
            raise CheckpointCorrupt(
                f"step {step}: unreadable manifest ({exc})"
            ) from exc
        if not isinstance(manifest, dict) or "files" not in manifest:
            raise CheckpointCorrupt(
                f"step {step}: manifest is not a checkpoint manifest"
            )
        if manifest.get("schema") != SCHEMA_VERSION:
            raise CheckpointCorrupt(
                f"step {step}: manifest schema "
                f"{manifest.get('schema')!r} != {SCHEMA_VERSION}"
            )
        return manifest

    def verify_step(self, step: int) -> dict:
        """Re-hash every file against the manifest. Returns the manifest;
        raises :class:`CheckpointCorrupt` on the first mismatch."""
        manifest = self.read_manifest(step)
        d = self.step_dir(step)
        for fname, rec in manifest["files"].items():
            path = os.path.join(d, fname)
            try:
                with open(path, "rb") as fh:
                    data = fh.read()
            except OSError as exc:
                raise CheckpointCorrupt(
                    f"step {step}: missing file {fname} ({exc})"
                ) from exc
            digest = sha256_bytes(data)
            if digest != rec.get("sha256"):
                raise CheckpointCorrupt(
                    f"step {step}: checksum mismatch on {fname} "
                    f"(manifest {rec.get('sha256')!r:.20}…, file "
                    f"{digest!r:.20}…)"
                )
        return manifest

    def load_step(
        self, step: int, expect_meta: Optional[dict] = None
    ) -> LoadedCheckpoint:
        """Verify + load one step. Config mismatch → loud
        :class:`CheckpointMismatch` (never a skip); integrity failure →
        :class:`CheckpointCorrupt`."""
        manifest = self.verify_step(step)
        meta = manifest.get("meta", {})
        if expect_meta is not None:
            diffs = {
                k: (meta.get(k), v)
                for k, v in expect_meta.items()
                if meta.get(k) != v
            }
            if diffs:
                raise CheckpointMismatch(
                    f"step {step} was written by a different recipe — "
                    "refusing to resume (checkpoint value vs this run): "
                    + ", ".join(
                        f"{k}={got!r} vs {want!r}"
                        for k, (got, want) in sorted(diffs.items())
                    )
                    + " — clear the checkpoint directory (pio ckpt gc "
                    "--all / --no-resume) to train fresh"
                )
        d = self.step_dir(step)
        arrays = {}
        for fname in manifest["files"]:
            try:
                arrays[fname[: -len(".npy")]] = np.load(
                    os.path.join(d, fname)
                )
            except (OSError, ValueError) as exc:
                raise CheckpointCorrupt(
                    f"step {step}: undecodable array {fname} ({exc})"
                ) from exc
        return LoadedCheckpoint(step=int(manifest["step"]), arrays=arrays,
                                meta=meta)

    def load(
        self,
        expect_meta: Optional[dict] = None,
        max_step: Optional[int] = None,
    ) -> Optional[LoadedCheckpoint]:
        """Newest valid checkpoint (≤ ``max_step`` if given), or None.

        Corrupt steps are skipped LOUDLY — logged at ERROR and counted
        in :attr:`corrupt_skipped` — falling back to the previous valid
        step. A config mismatch propagates (loud refusal)."""
        for step in reversed(self.steps()):
            if max_step is not None and step > max_step:
                continue
            try:
                return self.load_step(step, expect_meta=expect_meta)
            except CheckpointCorrupt as exc:
                self.corrupt_skipped += 1
                logger.error(
                    "ckpt: skipping corrupt checkpoint %s (%s); falling "
                    "back to the previous valid step",
                    self.step_dir(step), exc,
                )
        return None

    def verify(self) -> List[dict]:
        """Verification report for every committed step (``pio ckpt
        verify``): ``{"step", "ok", "error"?, "files"?}`` rows."""
        report = []
        for step in self.steps():
            try:
                manifest = self.verify_step(step)
                report.append({
                    "step": step,
                    "ok": True,
                    "files": len(manifest["files"]),
                    "bytes": sum(
                        rec.get("bytes", 0)
                        for rec in manifest["files"].values()
                    ),
                })
            except CheckpointCorrupt as exc:
                report.append({"step": step, "ok": False,
                               "error": str(exc)})
        return report

    # -- retention --------------------------------------------------------

    def retained(self, steps: Optional[Iterable[int]] = None) -> List[int]:
        """The steps the GC policy keeps: the ``keep_last`` newest plus
        every step divisible by ``keep_every`` (when > 0)."""
        all_steps = sorted(self.steps() if steps is None else steps)
        keep = set(all_steps[-self.keep_last:])
        if self.keep_every > 0:
            keep |= {s for s in all_steps if s % self.keep_every == 0}
        return sorted(keep)

    def gc(self, prune_uncommitted: bool = False) -> List[int]:
        """Delete steps outside the retention set; returns what was
        removed. ``prune_uncommitted`` also clears crash garbage
        (manifest-less step dirs) — off by default because the writer
        thread may be mid-commit on one of them."""
        keep = set(self.retained())
        removed = []
        for step in self.steps():
            if step not in keep:
                self.delete_step(step)
                removed.append(step)
        if prune_uncommitted:
            for name in self.uncommitted():
                shutil.rmtree(os.path.join(self.root, name),
                              ignore_errors=True)
        return removed

    def delete_step(self, step: int) -> None:
        """Manifest-first delete: after the unlink the step is garbage by
        protocol, so a crash mid-rmtree can never resurrect a partially
        deleted checkpoint as loadable."""
        d = self.step_dir(step)
        try:
            os.unlink(os.path.join(d, MANIFEST))
        except FileNotFoundError:
            pass
        fsync_dir(d)
        shutil.rmtree(d, ignore_errors=True)

    def clear(self) -> None:
        """Remove every checkpoint (the ``--no-resume`` fresh start)."""
        if os.path.isdir(self.root):
            shutil.rmtree(self.root)
