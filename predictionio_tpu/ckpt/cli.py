"""``pio ckpt`` — inspect, verify and garbage-collect checkpoints.

Forwarded verbatim from the console like ``pio lint``/``pio perf``: pure
filesystem reads plus the store's own GC, so it needs neither jax nor
the storage plane and works on an unconfigured host (the box you ssh
into AFTER the preemption).

    pio ckpt ls     --dir DIR [--json]
    pio ckpt verify --dir DIR [--step N] [--json]
    pio ckpt gc     --dir DIR [--keep-last K] [--keep-every J]
                    [--all] [--json]

``verify`` exits 1 when any committed step fails its checksums — the
CI-able form of the load path's loud skip. ``gc --all`` clears the
store entirely (the manual ``--no-resume``).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional, Sequence

from .settings import resolve_retention
from .store import CheckpointCorrupt, CheckpointStore


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="pio ckpt",
        description="checkpoint store operations (docs/checkpoint.md)",
    )
    sub = p.add_subparsers(dest="ckpt_command", required=True)

    ls = sub.add_parser("ls", help="committed steps, sizes, garbage")
    verify = sub.add_parser(
        "verify",
        help="re-hash every file against its manifest (exit 1 on any "
        "corrupt step)",
    )
    verify.add_argument(
        "--step", type=int, default=None,
        help="verify one step instead of all",
    )
    gc = sub.add_parser(
        "gc", help="apply the keep-last-k / keep-every-j retention policy"
    )
    gc.add_argument("--keep-last", type=int, default=None, metavar="K",
                    help="newest committed steps to keep (default: "
                    "PIO_CKPT_KEEP_LAST, else 3)")
    gc.add_argument("--keep-every", type=int, default=None, metavar="J",
                    help="also keep steps divisible by J (default: "
                    "PIO_CKPT_KEEP_EVERY, else off)")
    gc.add_argument("--all", action="store_true",
                    help="clear the store entirely (train fresh next run)")
    for sp in (ls, verify, gc):
        sp.add_argument("--dir", required=True, metavar="DIR",
                        help="checkpoint root (the trainer's store dir)")
        sp.add_argument("--json", action="store_true",
                        help="machine-readable output")
    return p


def _emit(args, obj: dict, lines) -> None:
    if args.json:
        print(json.dumps(obj, indent=2, sort_keys=True))
    else:
        for line in lines:
            print(line)


def run(args: argparse.Namespace) -> int:
    if not os.path.isdir(args.dir):
        # a typo'd --dir must not read as "no checkpoints": the empty
        # answer and the wrong-path answer are different facts
        raise ValueError(f"checkpoint dir does not exist: {args.dir}")
    keep_last, keep_every = resolve_retention(
        getattr(args, "keep_last", None), getattr(args, "keep_every", None)
    )
    store = CheckpointStore(args.dir, keep_last=keep_last,
                            keep_every=keep_every)

    if args.ckpt_command == "ls":
        report = store.verify()
        garbage = store.uncommitted()
        obj = {"dir": args.dir, "steps": report, "uncommitted": garbage}
        lines = [
            f"{r['step']:>10}  "
            + (f"ok  {r['files']} files  {r['bytes']} bytes"
               if r["ok"] else f"CORRUPT  {r['error']}")
            for r in report
        ] or ["(no committed checkpoints)"]
        lines += [f"{'':>10}  garbage: {g} (no manifest)" for g in garbage]
        _emit(args, obj, lines)
        return 0

    if args.ckpt_command == "verify":
        if args.step is not None:
            try:
                manifest = store.verify_step(args.step)
                report = [{"step": args.step, "ok": True,
                           "files": len(manifest["files"]),
                           "bytes": sum(r.get("bytes", 0) for r in
                                        manifest["files"].values())}]
            except CheckpointCorrupt as exc:
                report = [{"step": args.step, "ok": False,
                           "error": str(exc)}]
        else:
            report = store.verify()
        bad = [r for r in report if not r["ok"]]
        _emit(
            args, {"dir": args.dir, "steps": report, "ok": not bad},
            [
                f"{r['step']:>10}  " + ("ok" if r["ok"]
                                        else f"CORRUPT  {r['error']}")
                for r in report
            ] or ["(no committed checkpoints)"],
        )
        return 1 if bad else 0

    if args.ckpt_command == "gc":
        if args.all:
            before = store.steps()
            store.clear()
            _emit(args, {"dir": args.dir, "removed": before, "kept": []},
                  [f"removed {len(before)} checkpoint(s); store cleared"])
            return 0
        removed = store.gc(prune_uncommitted=True)
        kept = store.steps()
        _emit(
            args,
            {"dir": args.dir, "removed": removed, "kept": kept,
             "keepLast": keep_last, "keepEvery": keep_every},
            [f"removed: {removed or '[]'}", f"kept:    {kept or '[]'}"],
        )
        return 0

    return 2  # unreachable: argparse requires a subcommand


def main(argv: Optional[Sequence[str]] = None) -> int:
    try:
        return run(build_parser().parse_args(argv))
    except (ValueError, OSError) as exc:
        print(json.dumps({"error": str(exc)}), file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
