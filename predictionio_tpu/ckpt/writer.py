"""Background checkpoint writer: the train loop never stalls on disk.

The trainer snapshots device state to host arrays (cheap — one
device-to-host copy per table) and hands the snapshot to
:class:`CheckpointWriter.submit`, which enqueues it on a BOUNDED queue
and returns immediately. A dedicated thread drains the queue through
:meth:`~.store.CheckpointStore.save` (atomic files + manifest-last).

Backpressure policy: when the queue is full — the disk cannot keep up
with ``checkpoint_every`` — the NEW snapshot is dropped and counted
(:attr:`CheckpointWriter.dropped`), never blocked on. A dropped
checkpoint costs recovery granularity; a blocked train loop costs every
step. The drop is loud (WARNING + counter + profile), so a persistently
starved writer shows up in the ledger, not as a mystery slowdown.

Write errors follow the same record-loudly-continue discipline as the
fleet degrade paths (``fleet/sharedcache.py``): the failure is logged at
ERROR, counted, and kept as ``last_error`` — checkpointing is a
durability aid, and a full disk must not kill an otherwise healthy
training run. :meth:`close` drains the queue (the final step's snapshot
is never dropped silently) and joins the thread.
"""

from __future__ import annotations

import logging
import queue
import threading
from typing import Dict, Optional

import numpy as np

from .store import CheckpointStore

logger = logging.getLogger("pio.ckpt")

_STOP = object()


class CheckpointWriter:
    """One writer thread over one :class:`CheckpointStore`."""

    def __init__(self, store: CheckpointStore, queue_depth: int = 2) -> None:
        if queue_depth < 1:
            raise ValueError(
                f"writer queue_depth must be >= 1, got {queue_depth}"
            )
        self.store = store
        self.written = 0
        self.dropped = 0
        self.errors = 0
        self.last_error: Optional[str] = None
        self._queue: "queue.Queue" = queue.Queue(maxsize=queue_depth)
        self._closed = False
        self._thread = threading.Thread(
            target=self._run, name="pio-ckpt-writer", daemon=True
        )
        self._thread.start()

    def submit(
        self, step: int, arrays: Dict[str, np.ndarray], meta: dict
    ) -> bool:
        """Enqueue one snapshot without blocking. False = dropped
        (queue full — counted and logged, training continues)."""
        if self._closed:
            raise RuntimeError("CheckpointWriter is closed")
        try:
            self._queue.put_nowait((step, arrays, meta))
            return True
        except queue.Full:
            self.dropped += 1
            logger.warning(
                "ckpt: writer queue full — dropping snapshot of step %d "
                "(disk is behind checkpoint_every; %d dropped so far)",
                step, self.dropped,
            )
            return False

    def flush_submit(
        self, step: int, arrays: Dict[str, np.ndarray], meta: dict
    ) -> None:
        """Blocking submit for the FINAL snapshot of a run: the one
        checkpoint that must not be dropped waits for a queue slot."""
        if self._closed:
            raise RuntimeError("CheckpointWriter is closed")
        self._queue.put((step, arrays, meta))

    def _run(self) -> None:
        while True:
            item = self._queue.get()
            if item is _STOP:
                return
            step, arrays, meta = item
            try:
                self.store.save(step, arrays, meta)
                self.written += 1
            except Exception as exc:
                self.errors += 1
                self.last_error = f"step {step}: {exc}"
                logger.error(
                    "ckpt: background write of step %d failed (%s) — "
                    "training continues; the previous committed "
                    "checkpoint remains the resume point",
                    step, exc,
                )

    def close(self, timeout: Optional[float] = 60.0) -> dict:
        """Drain pending snapshots, stop the thread, return
        :meth:`stats`. Idempotent."""
        if not self._closed:
            self._closed = True
            self._queue.put(_STOP)
            self._thread.join(timeout)
            if self._thread.is_alive():
                self.errors += 1
                self.last_error = (
                    f"writer thread failed to drain within {timeout}s"
                )
                logger.error("ckpt: %s", self.last_error)
        return self.stats()

    def stats(self) -> dict:
        return {
            "written": self.written,
            "dropped": self.dropped,
            "errors": self.errors,
            "lastError": self.last_error,
        }

    def __enter__(self) -> "CheckpointWriter":
        return self

    def __exit__(self, *exc_info) -> None:
        self.close()
