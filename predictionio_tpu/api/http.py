"""Shared HTTP plumbing for the framework's REST surfaces.

The Event Server (``api/event_server.py``), query server
(``workflow/serving.py``) and dashboard all speak the same dialect: JSON
bodies, keep-alive connections, daemon-threaded stdlib servers. This module
is the single home for that plumbing (the analogue of the spray/akka layer
both reference servers share).
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional

from ..obs import expo
from ..obs.metrics import MetricsRegistry
from ..obs.trace import Tracer

logger = logging.getLogger(__name__)


class JsonHTTPHandler(BaseHTTPRequestHandler):
    """Request handler base: JSON responses, body draining, quiet logs.

    Observability (``docs/observability.md``): every response status is
    counted into the owning server's metrics registry, and
    :meth:`serve_obs` answers the two diagnostic routes all servers
    share — ``GET /metrics`` (Prometheus text) and ``GET /traces.json``
    (the span ring buffer)."""

    protocol_version = "HTTP/1.1"
    # Keep-alive request/response with Nagle on hits the classic
    # delayed-ACK interaction: every small response waits ~40 ms for the
    # peer's ACK before the kernel flushes it. Measured p50 on loopback:
    # 44 ms → 0.3 ms with TCP_NODELAY.
    disable_nagle_algorithm = True

    #: Extra labels every ``pio_http_responses_total`` sample of this
    #: handler class carries (label *names* are schema, pinned per
    #: registry — so a subclass must declare the full closed set here
    #: and may override per-request *values* via ``self.response_labels``).
    #: The query server adds ``{"variant": "-"}`` so canary/shadow
    #: traffic is attributable per variant (docs/rollouts.md).
    response_label_defaults: dict = {}

    def respond(
        self,
        status: int,
        payload: Any,
        content_type: str = "application/json",
        headers: Any = None,
    ) -> None:
        """Send a response. JSON payloads are dumped; raw ``bytes`` (and
        ``str`` only for non-JSON content types, e.g. HTML pages) pass
        through verbatim. ``headers`` adds extra response headers (e.g.
        ``Retry-After`` on a load-shed 503)."""
        if isinstance(payload, bytes):
            body = payload
        elif isinstance(payload, str) and content_type != "application/json":
            body = payload.encode("utf-8")
        else:
            body = json.dumps(payload).encode("utf-8")
        metrics = getattr(self.server, "metrics", None)
        if metrics is not None:
            # HTTP status codes are a small closed set — a safe label;
            # ditto the declared extras (variant is a two-value vocabulary)
            labels = dict(self.response_label_defaults)
            labels.update(getattr(self, "response_labels", None) or {})
            labels["status"] = status
            metrics.counter(
                "pio_http_responses_total",
                "Responses by HTTP status",
                labelnames=tuple(sorted(labels)),
            ).inc(1, **labels)
        self.send_response(status)
        self.send_header("Content-Type", f"{content_type}; charset=UTF-8")
        self.send_header("Content-Length", str(len(body)))
        for key, value in (headers or {}).items():
            self.send_header(key, str(value))
        self.end_headers()
        self.wfile.write(body)

    def serve_obs(self, path: str) -> bool:
        """Answer the diagnostic routes every server shares — ``GET
        /metrics`` (Prometheus text), ``GET /traces.json`` (span ring),
        ``GET /health.json`` (the health plane's SLO/stall summary) and
        ``GET /blackbox.json`` (the flight-recorder ring) — from the
        owning server's registry/tracer/health plane; False when
        ``path`` is none of them (or the server opted out by nulling
        the attributes)."""
        if path == "/metrics":
            metrics = getattr(self.server, "metrics", None)
            if metrics is not None:
                self.respond(
                    200, expo.render(metrics), content_type=expo.CONTENT_TYPE
                )
                return True
        elif path == "/traces.json":
            tracer = getattr(self.server, "tracer", None)
            if tracer is not None:
                self.respond(
                    200,
                    {
                        "service": tracer.service,
                        "spans": tracer.store.dump(),
                    },
                )
                return True
        elif path == "/health.json":
            health = getattr(self.server, "health", None)
            if health is not None:
                self.respond(200, health.health_json())
                return True
        elif path == "/blackbox.json":
            health = getattr(self.server, "health", None)
            flight = health.flight if health is not None else None
            if flight is not None:
                self.respond(
                    200,
                    {
                        "service": type(self.server).__name__,
                        "enabled": flight.enabled,
                        "events": flight.dump(),
                    },
                )
                return True
        return False

    def read_body(self) -> bytes:
        """Drain the request body. Must happen before any error response on a
        keep-alive connection, else leftover body bytes desync the next
        request."""
        length = int(self.headers.get("Content-Length", 0))
        return self.rfile.read(length) if length else b""

    def log_message(self, fmt: str, *args: Any) -> None:
        logger.debug("%s - %s", self.address_string(), fmt % args)


class BackgroundHTTPServer(ThreadingHTTPServer):
    """Threaded server with ephemeral-port introspection and background run.

    Every instance owns a :class:`MetricsRegistry` and a :class:`Tracer`
    (service-named after the concrete class) so ``GET /metrics`` and
    ``GET /traces.json`` work on all servers without per-server wiring;
    subclasses pass their own (e.g. with an injected clock) via the
    ``metrics``/``tracer`` kwargs."""

    daemon_threads = True

    def __init__(
        self,
        *args,
        metrics: Optional[MetricsRegistry] = None,
        tracer: Optional[Tracer] = None,
        health_kind: Optional[str] = None,
        health_config=None,
        **kwargs,
    ):
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        self.tracer = (
            tracer if tracer is not None else Tracer(type(self).__name__)
        )
        # the canonical liveness sample: a fresh server's exposition is
        # never empty, and scrapers key "up" on it
        self.metrics.gauge(
            "pio_up", "1 while the server process is serving"
        ).set(1)
        # Health plane (docs/slo.md): SLO burn-rate engine + stall
        # watchdog + the process flight recorder, one ticker thread per
        # server, read via GET /health.json + /blackbox.json. A server
        # that passes no kind (tests building bare servers) carries no
        # plane and the routes simply 404 through.
        self.health = None
        if health_kind is not None:
            from ..obs.slo import HealthPlane

            self.health = HealthPlane(
                self.metrics,
                health_kind,
                clock=self.metrics.clock,
                config=health_config,
            )
        super().__init__(*args, **kwargs)
        if self.health is not None:
            # AFTER the bind: a failed construction (port in use) must
            # not leave a ticking daemon thread behind
            self.health.start()
        self._live_conns: set = set()
        self._conn_lock = threading.Lock()

    def server_close(self) -> None:
        health = getattr(self, "health", None)
        if health is not None:
            health.stop()
        super().server_close()

    # Track accepted sockets so kill() can sever keep-alive connections:
    # shutdown() only stops the accept loop — handler threads blocked on
    # a persistent connection keep answering, which is not what "the
    # process died" means to a chaos test.
    def get_request(self):
        request, client_address = super().get_request()
        with self._conn_lock:
            self._live_conns.add(request)
        return request, client_address

    def shutdown_request(self, request) -> None:
        with self._conn_lock:
            self._live_conns.discard(request)
        super().shutdown_request(request)

    def serve_forever(self, poll_interval: float = 0.5) -> None:
        self._serving = True  # kill() must not shutdown() a never-run loop
        super().serve_forever(poll_interval)

    def kill(self) -> None:
        """Hard-stop: stop accepting AND sever every live connection —
        the in-process analogue of ``kill -9`` on the server process
        (``tools/loadgen.py --kill-primary-at``, replication chaos
        tests). In-flight requests see a reset, exactly like a real
        crash."""
        if getattr(self, "_serving", False):
            # shutdown() blocks on an event only serve_forever() sets —
            # calling it on a server whose loop never ran hangs forever
            self.shutdown()
        self.server_close()
        import socket as _socket

        with self._conn_lock:
            conns, self._live_conns = list(self._live_conns), set()
        for request in conns:
            try:
                request.shutdown(_socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                request.close()
            except OSError:
                pass

    def handle_error(self, request, client_address) -> None:
        """Client disconnects mid-response (an abandoned streaming scan, a
        killed curl) are normal operation, not stack-trace material."""
        import sys

        exc = sys.exc_info()[1]
        if isinstance(
            exc, (BrokenPipeError, ConnectionResetError, TimeoutError)
        ):
            logger.debug("client %s dropped: %s", client_address, exc)
            return
        super().handle_error(request, client_address)

    @property
    def bound_port(self) -> int:
        return self.server_address[1]

    def start_background(self) -> threading.Thread:
        # tight poll so shutdown() returns in ~50 ms instead of the
        # stdlib's 500 ms — server-heavy test suites pay that latency
        # once per server teardown, which adds up to tens of seconds
        thread = threading.Thread(
            target=lambda: self.serve_forever(poll_interval=0.05),
            daemon=True,
        )
        thread.start()
        return thread

    def stop_async(self) -> None:
        """Shut down from inside a handler thread (``GET /stop``)."""

        def stop() -> None:
            self.shutdown()
            self.server_close()  # release the listening socket

        threading.Thread(target=stop, daemon=True).start()
