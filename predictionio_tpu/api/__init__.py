"""REST data-ingestion plane (the reference's Event Server, SURVEY §1 L2).

Rebuild of ``data/src/main/scala/io/prediction/data/api/EventAPI.scala``:
the ``events.json`` / ``events/<id>.json`` / ``stats.json`` routes with
access-key authentication and hourly/lifetime stats bookkeeping. The spray/
akka actor tree becomes a threaded stdlib HTTP server — the ingestion path is
pure control plane and never touches the TPU.
"""

from .event_server import (
    EventServer,
    EventServerConfig,
    Stats,
    StatsTracker,
    create_event_server,
)

__all__ = [
    "EventServer",
    "EventServerConfig",
    "Stats",
    "StatsTracker",
    "create_event_server",
]
