"""Event Server: REST ingestion API.

Rebuild of the reference Event Server
(``data/src/main/scala/io/prediction/data/api/EventAPI.scala``):

- ``GET /``                      → ``{"status": "alive"}``            (``EventAPI.scala:168-175``)
- ``POST /events.json``          → 201 ``{"eventId": ...}``           (``EventAPI.scala:229-252``)
- ``GET /events.json``           → filtered scan, default limit 20    (``EventAPI.scala:254-325``)
- ``GET /events/<id>.json``      → single event or 404                (``EventAPI.scala:177-200``)
- ``DELETE /events/<id>.json``   → ``{"message": "Found"/"Not Found"}`` (``EventAPI.scala:202-226``)
- ``GET /stats.json``            → hourly + lifetime counters (``--stats`` only)
                                                                      (``EventAPI.scala:327-345``)

``POST /events.json`` (and each element of the batch route) accepts an
optional client-supplied ``idempotencyKey``: duplicate POSTs with the same
key insert exactly one event (the key derives a deterministic ``eventId``
and dedup rides the stores' upsert-by-id path) — the contract that makes
write retries safe for the serving feedback loop and ``storage/remote.py``
(see ``docs/robustness.md``).

Every route authenticates via the ``accessKey`` query parameter resolved to an
``appId`` through the metadata store (``withAccessKey``,
``EventAPI.scala:149-164``); missing or unknown keys get
401 ``{"message": "Invalid accessKey."}``. Defaults: localhost:7070
(``EventServerConfig``, ``EventAPI.scala:422-425``).

The spray actor tree (``EventServerActor``/``EventServiceActor``/
``StatsActor``) collapses into a ``ThreadingHTTPServer`` + a lock-guarded
:class:`StatsTracker` — same observable surface, no actor machinery.
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import json
import logging
import threading
from typing import Dict, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from .http import BackgroundHTTPServer, JsonHTTPHandler

from ..obs.trace import TRACE_HEADER
from ..storage.event import (
    Event,
    EventValidationError,
    format_event_time,
    idempotency_event_id,
    parse_event_time,
    utcnow,
    validate_event,
)
from ..storage.events import EventFilter, EventStore
from ..storage.metadata import MetadataStore
from ..storage.registry import StorageRegistry, get_registry

logger = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# Stats bookkeeping (EventAPI.scala:60-112, 354-395)
# ---------------------------------------------------------------------------


class Stats:
    """Counters for one window: status codes and (entityType, targetEntityType,
    event) triples per app (``class Stats``, ``EventAPI.scala:81-112``)."""

    def __init__(self, start_time: _dt.datetime):
        self.start_time = start_time
        self.end_time: Optional[_dt.datetime] = None
        self.status_code_count: Dict[Tuple[int, int], int] = {}
        self.ete_count: Dict[Tuple[int, Tuple[str, Optional[str], str]], int] = {}

    def cutoff(self, end_time: _dt.datetime) -> None:
        self.end_time = end_time

    def update(self, app_id: int, status_code: int, event: Event) -> None:
        sk = (app_id, status_code)
        self.status_code_count[sk] = self.status_code_count.get(sk, 0) + 1
        ek = (app_id, (event.entity_type, event.target_entity_type, event.event))
        self.ete_count[ek] = self.ete_count.get(ek, 0) + 1

    def snapshot(self, app_id: int) -> dict:
        """``StatsSnapshot`` JSON shape (``EventAPI.scala:73-78``)."""
        return {
            "startTime": format_event_time(self.start_time),
            "endTime": format_event_time(self.end_time) if self.end_time else None,
            "basic": [
                {
                    "key": {
                        "entityType": ete[0],
                        "targetEntityType": ete[1],
                        "event": ete[2],
                    },
                    "value": count,
                }
                for (aid, ete), count in sorted(
                    self.ete_count.items(),
                    key=lambda kv: (kv[0][0], kv[0][1][0], kv[0][1][1] or "", kv[0][1][2]),
                )
                if aid == app_id
            ],
            "statusCode": [
                {"key": code, "value": count}
                for (aid, code), count in sorted(self.status_code_count.items())
                if aid == app_id
            ],
        }


def _current_hour(now: Optional[_dt.datetime] = None) -> _dt.datetime:
    now = now or utcnow()
    return now.replace(minute=0, second=0, microsecond=0)


class StatsTracker:
    """Hourly + lifetime windows with hour rollover
    (``StatsActor``, ``EventAPI.scala:354-395``); thread-safe in place of the
    actor mailbox."""

    def __init__(self):
        self._lock = threading.Lock()
        self.long_live = Stats(utcnow())
        self.hourly = Stats(_current_hour())
        self.prev_hourly = Stats(_current_hour() - _dt.timedelta(hours=1))
        self.prev_hourly.cutoff(self.hourly.start_time)

    def bookkeeping(self, app_id: int, status_code: int, event: Event) -> None:
        with self._lock:
            current = _current_hour()
            if current != self.hourly.start_time:
                self.prev_hourly = self.hourly
                self.prev_hourly.cutoff(current)
                self.hourly = Stats(current)
            self.hourly.update(app_id, status_code, event)
            self.long_live.update(app_id, status_code, event)

    def get(self, app_id: int) -> dict:
        """``GetStats`` reply shape (``EventAPI.scala:383-387``)."""
        with self._lock:
            return {
                "time": format_event_time(utcnow()),
                "currentHour": self.hourly.snapshot(app_id),
                "prevHour": self.prev_hourly.snapshot(app_id),
                "longLive": self.long_live.snapshot(app_id),
            }


# ---------------------------------------------------------------------------
# HTTP plumbing
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class EventServerConfig:
    """``EventServerConfig`` (``EventAPI.scala:422-425``)."""

    ip: str = "localhost"
    port: int = 7070
    stats: bool = False
    #: directory for the ingest quality monitor's durable per-app
    #: event-mix baselines (docs/observability.md#quality); None reads
    #: the ``PIO_QUALITY_DIR`` env (unset = in-memory baselines only)
    quality_dir: Optional[str] = None


class _HTTPError(Exception):
    def __init__(self, status: int, body: dict):
        self.status = status
        self.body = body


def _parse_bool(text: str) -> bool:
    return text.strip().lower() in ("true", "1", "yes")


class _EventServiceHandler(JsonHTTPHandler):
    """One request = one route dispatch (``EventServiceActor.route``,
    ``EventAPI.scala:166-349``)."""

    server: "EventServer"

    # -- helpers ----------------------------------------------------------
    _respond = JsonHTTPHandler.respond

    def _auth(self, query: Dict[str, list]) -> int:
        """accessKey → appId (``withAccessKey``, ``EventAPI.scala:149-164``).
        Missing and invalid keys both yield 401."""
        keys = query.get("accessKey")
        if not keys:
            raise _HTTPError(401, {"message": "Invalid accessKey."})
        ak = self.server.metadata.access_key_get(keys[0])
        if ak is None:
            raise _HTTPError(401, {"message": "Invalid accessKey."})
        return ak.appid

    @staticmethod
    def _route_label(method: str, path: str) -> str:
        """Collapse a request path to its route template — the bounded
        label the latency histogram is keyed on (a per-event-id label
        would be a cardinality explosion; see docs/observability.md)."""
        if path.startswith("/events/") and path.endswith(".json"):
            return f"{method} /events/<id>.json"
        if path in ("/", "/events.json", "/batches/events.json",
                    "/stats.json"):
            return f"{method} {path}"
        return "other"

    # -- dispatch ---------------------------------------------------------
    def _route(self, method: str) -> None:
        parsed = urlparse(self.path)
        path = parsed.path
        query = parse_qs(parsed.query)
        # Drain the request body up front: on keep-alive connections an error
        # response sent before the body is read would desync the next request.
        self._body = self.read_body()
        if method == "GET" and self.serve_obs(path):
            return  # /metrics + /traces.json (docs/observability.md)
        route = self._route_label(method, path)
        started = self.server.metrics.clock()
        try:
            # admission span: joins the caller's X-PIO-Trace (the serving
            # feedback loop forwards its request's id here)
            with self.server.tracer.server_span(
                route, header_value=self.headers.get(TRACE_HEADER)
            ):
                self._dispatch(method, path, query)
        except _HTTPError as err:
            self._respond(err.status, err.body)
        except Exception as exc:  # route-level catch-all (rejectionHandler)
            logger.exception("Event server error on %s %s", method, path)
            self._respond(500, {"message": str(exc)})
        finally:
            self.server.metrics.histogram(
                "pio_http_request_seconds",
                "Event Server request latency by route",
                labelnames=("route",),
            ).observe(self.server.metrics.clock() - started, route=route)

    def _dispatch(self, method: str, path: str, query: Dict[str, list]) -> None:
        if path == "/" and method == "GET":
            self._respond(200, {"status": "alive"})
        elif path == "/replication.json" and method == "GET":
            # the ingest tier's per-partition view of the partitioned
            # event store (docs/storage.md#partitioning): one row per
            # partition, probed client-side — `pio top`'s PARTS column
            self._respond(200, self.server.replication_json())
        elif path == "/events.json" and method == "POST":
            self._post_event(query)
        elif path == "/batches/events.json" and method == "POST":
            self._post_event_batch(query)
        elif path == "/events.json" and method == "GET":
            self._find_events(query)
        elif (
            path.startswith("/events/")
            and path.endswith(".json")
            and method in ("GET", "DELETE")
        ):
            event_id = path[len("/events/") : -len(".json")]
            app_id = self._auth(query)
            if method == "GET":
                self._get_event(event_id, app_id)
            else:
                self._delete_event(event_id, app_id)
        elif path == "/stats.json" and method == "GET":
            self._get_stats(query)
        else:
            self._respond(404, {"message": "Not Found"})

    def do_GET(self) -> None:  # noqa: N802
        self._route("GET")

    def do_POST(self) -> None:  # noqa: N802
        self._route("POST")

    def do_DELETE(self) -> None:  # noqa: N802
        self._route("DELETE")

    @staticmethod
    def _apply_idempotency_key(obj: dict, app_id: int) -> None:
        """``idempotencyKey`` (optional, client-supplied, per event): a
        duplicate POST with the same key must insert exactly once. The
        key maps to a deterministic ``eventId``, so dedup rides the
        stores' upsert-by-id semantics — no extra index, and it works
        identically through the remote storage plane. An explicit
        ``eventId`` wins (the client already controls identity)."""
        key = obj.pop("idempotencyKey", None)
        if key is None:
            return
        if not isinstance(key, str) or not key:
            raise EventValidationError(
                "idempotencyKey must be a non-empty string"
            )
        if not obj.get("eventId"):
            obj["eventId"] = idempotency_event_id(app_id, key)

    def _shed_if_frozen(self) -> bool:
        """During the cutover flip the attached migration briefly holds
        writes (docs/storage.md#live-migration); shed them with the same
        503 + Retry-After contract as a partition outage. Nothing is
        acked, so nothing is lost — just late."""
        after = self.server.migration_frozen_after()
        if after is None:
            return False
        self._respond(
            503,
            {"message": "migration cutover in progress; retry shortly"},
            headers={"Retry-After": after},
        )
        return True

    # -- routes -----------------------------------------------------------
    def _post_event(self, query: Dict[str, list]) -> None:
        """``EventAPI.scala:229-252``."""
        app_id = self._auth(query)
        if self._shed_if_frozen():
            return
        raw = self._body
        try:
            obj = json.loads(raw.decode("utf-8"))
            if not isinstance(obj, dict):
                raise EventValidationError("event body must be a JSON object")
            self._apply_idempotency_key(obj, app_id)
            event = Event.from_json_dict(obj)
            validate_event(event)
        except (
            ValueError,
            KeyError,
            TypeError,
            AttributeError,
            EventValidationError,
        ) as exc:
            # MalformedRequestContentRejection → 400 (EventAPI.scala:135-137)
            self.server._observe_quality(app_id)
            self._respond(400, {"message": str(exc)})
            return
        try:
            event_id = self.server.events.insert(event, app_id)
        except Exception as exc:
            shed = self.server._partition_shed(exc)
            if shed is None:
                raise
            # partial-partition degradation (docs/robustness.md): the
            # partition owning THIS key is down — shed it with 503 +
            # Retry-After so a well-behaved SDK backs off and retries,
            # while every other partition's keys keep acking 201. The
            # event was never acked, so nothing is lost — just late.
            self._respond(
                503,
                {
                    "message": str(exc),
                    "partitions": list(getattr(exc, "partitions", ())),
                },
                headers={"Retry-After": shed},
            )
            return
        if self.server.migration is not None:
            from ..storage.event import with_event_id

            self.server.mirror_events(
                [
                    event
                    if event.event_id is not None
                    else with_event_id(event, event_id)
                ],
                app_id,
            )
        # quality accounting only AFTER the store accepted the event: a
        # storage outage (500s + client retries) must not feed the mix
        # window or auto-pin a baseline from traffic that was never kept
        self.server._observe_quality(app_id, event)
        status = 201
        if self.server.stats_tracker is not None:
            self.server.stats_tracker.bookkeeping(app_id, status, event)
        self._respond(status, {"eventId": event_id})

    def _post_event_batch(self, query: Dict[str, list]) -> None:
        """``POST /batches/events.json`` — bulk ingestion (the official
        SDKs' batch surface; added to PredictionIO after the surveyed
        release, kept wire-compatible with it here). Body is a JSON array
        of events; the response is a per-event array of
        ``{"status": 201, "eventId": ...}`` or ``{"status": 400,
        "message": ...}`` in input order — one bad event does not reject
        the batch. Valid events take the store's batched append path."""
        app_id = self._auth(query)
        if self._shed_if_frozen():
            return
        try:
            objs = json.loads(self._body.decode("utf-8"))
            if not isinstance(objs, list):
                raise ValueError("batch body must be a JSON array")
        except ValueError as exc:
            self._respond(400, {"message": str(exc)})
            return
        results: list = [None] * len(objs)
        valid: list = []  # (position, event)
        for pos, obj in enumerate(objs):
            try:
                if not isinstance(obj, dict):
                    raise EventValidationError(
                        "event must be a JSON object"
                    )
                self._apply_idempotency_key(obj, app_id)
                event = Event.from_json_dict(obj)
                validate_event(event)
                valid.append((pos, event))
            except (
                ValueError,
                KeyError,
                TypeError,
                AttributeError,
                EventValidationError,
            ) as exc:
                self.server._observe_quality(app_id)
                results[pos] = {"status": 400, "message": str(exc)}
        if valid:
            from ..storage.event import with_event_id
            from ..storage.sqlite_events import make_event_id

            fresh = []  # server-minted ids: guaranteed-new batch path
            upserts = []  # client-supplied ids keep upsert semantics
            resolved: Dict[int, Event] = {}  # pos → event with final id
            for pos, event in valid:
                if event.event_id is None:
                    eid = make_event_id(event)
                    # with_event_id, not dataclasses.replace: replace()
                    # re-validates every field per event on this hot path
                    event = with_event_id(event, eid)
                    fresh.append(event)
                else:
                    eid = event.event_id
                    upserts.append(event)
                resolved[pos] = event
                results[pos] = {"status": 201, "eventId": eid}
            # One write per (partition, path): a mixed batch over a
            # partially-down partitioned store lands everything whose
            # partition is up and answers 503 for the rest, per event —
            # never all-or-nothing behind the dead keyspace
            # (docs/storage.md#partitioning). The unpartitioned store is
            # one group, preserving the original two batched writes.
            # Failures are scoped PER WRITER CALL: a partition that died
            # between the fresh and the upsert writes must 503 only the
            # events of the call that actually failed — marking an
            # already-acked event 503 would invite a client retry that
            # duplicates an unkeyed event.
            failed = self._write_groups(app_id, fresh, upserts)
            if failed is not None:
                part_of = self.server.events.partition_for
                for pos, event in valid:
                    call_failed = failed[
                        "fresh" if event.event_id is None else "upserts"
                    ]
                    if part_of(app_id, event.entity_id) in call_failed:
                        results[pos] = {
                            "status": 503,
                            "message": (
                                "event-store partition "
                                f"{part_of(app_id, event.entity_id)} "
                                "unavailable; retry later"
                            ),
                        }
            stored = [
                (pos, event) for pos, event in valid
                if results[pos]["status"] == 201
            ]
            if self.server.migration is not None:
                self.server.mirror_events(
                    [resolved[pos] for pos, _event in stored], app_id
                )
            # quality accounting only AFTER the batched writes landed
            # (same stored-events-only discipline as the single path)
            for _pos, event in stored:
                self.server._observe_quality(app_id, event)
            if self.server.stats_tracker is not None:
                for _pos, event in stored:
                    self.server.stats_tracker.bookkeeping(app_id, 201, event)
        self._respond(200, results)

    def _write_groups(self, app_id: int, fresh: list, upserts: list):
        """Run the batch's two write paths; None = all landed, else a
        dict of failed partition-index sets PER CALL (``fresh`` /
        ``upserts``). The shed counter advances once per shed EVENT
        (not per failed group), so batch-heavy and single-post traffic
        read identically on ``pio_ingest_partition_shed_total``."""
        from ..storage.remote import PartitionUnavailable

        failed = {"fresh": set(), "upserts": set()}
        any_failed = False
        for key, events, writer in (
            ("fresh", fresh, self.server.events.write_new),
            ("upserts", upserts, self.server.events.write),
        ):
            if not events:
                continue
            try:
                writer(events, app_id)
            except PartitionUnavailable as exc:
                # only the partitioned remote store raises this, so the
                # partition_for accessor exists exactly when needed —
                # local stores never take this branch
                part_of = self.server.events.partition_for
                parts = set(exc.partitions)
                failed[key] |= parts
                any_failed = True
                self.server._count_partition_shed(
                    part_of(app_id, e.entity_id)
                    for e in events
                    if part_of(app_id, e.entity_id) in parts
                )
        return failed if any_failed else None

    def _find_events(self, query: Dict[str, list]) -> None:
        """``EventAPI.scala:254-325``; single ``event`` name, limit default 20."""
        app_id = self._auth(query)

        def q(name: str) -> Optional[str]:
            vals = query.get(name)
            return vals[0] if vals else None

        try:
            flt = EventFilter(
                start_time=(
                    parse_event_time(q("startTime")) if q("startTime") else None
                ),
                until_time=(
                    parse_event_time(q("untilTime")) if q("untilTime") else None
                ),
                entity_type=q("entityType"),
                entity_id=q("entityId"),
                event_names=[q("event")] if q("event") else None,
                target_entity_type=q("targetEntityType"),
                target_entity_id=q("targetEntityId"),
                limit=int(q("limit")) if q("limit") else 20,
                reversed=_parse_bool(q("reversed") or "false"),
            )
        except (ValueError, EventValidationError) as exc:
            self._respond(400, {"message": str(exc)})
            return
        events = list(self.server.events.find(app_id, flt))
        if events:
            self._respond(200, [e.to_json_dict() for e in events])
        else:
            self._respond(404, {"message": "Not Found"})

    def _get_event(self, event_id: str, app_id: int) -> None:
        event = self.server.events.get(event_id, app_id)
        if event is None:
            self._respond(404, {"message": "Not Found"})
        else:
            self._respond(200, event.to_json_dict())

    def _delete_event(self, event_id: str, app_id: int) -> None:
        if self._shed_if_frozen():
            return
        found = self.server.events.delete(event_id, app_id)
        if found:
            self.server.mirror_delete(event_id, app_id)
            self._respond(200, {"message": "Found"})
        else:
            self._respond(404, {"message": "Not Found"})

    def _get_stats(self, query: Dict[str, list]) -> None:
        app_id = self._auth(query)
        if self.server.stats_tracker is None:
            self._respond(
                404,
                {
                    "message": "To see stats, launch Event Server with "
                    "--stats argument."
                },
            )
            return
        self._respond(200, self.server.stats_tracker.get(app_id))


class EventServer(BackgroundHTTPServer):
    """Threaded HTTP server bound to the storage plane
    (``EventServer.createEventServer``, ``EventAPI.scala:427-445``)."""

    def __init__(
        self,
        config: EventServerConfig,
        events: EventStore,
        metadata: MetadataStore,
        migration=None,
    ):
        self.config = config
        self._events = events
        self.migration = migration
        self.metadata = metadata
        self.stats_tracker: Optional[StatsTracker] = (
            StatsTracker() if config.stats else None
        )
        from ..obs.trace import Tracer

        super().__init__(
            (config.ip, config.port),
            _EventServiceHandler,
            tracer=Tracer("event-server"),
            health_kind="event",
        )
        # Ingest data-quality plane (docs/observability.md#quality):
        # per-app schema/range/poison counters + event-type mix PSI vs a
        # durable per-app baseline, on this server's /metrics.
        import os as _os

        from ..obs.quality import IngestQualityMonitor

        self.quality = IngestQualityMonitor(
            self.metrics,
            clock=self.metrics.clock,
            baseline_dir=(
                config.quality_dir or _os.environ.get("PIO_QUALITY_DIR")
            ),
        )
        self._observer_errors = self.metrics.counter(
            "pio_observer_errors_total",
            "Swallowed observer/monitor exceptions by site",
            labelnames=("site",),
        )
        # partial-partition degradation accounting
        # (docs/storage.md#partitioning): every 503-shed ingest write,
        # by the partition whose keyspace was unavailable
        self._partition_shed_total = self.metrics.counter(
            "pio_ingest_partition_shed_total",
            "Ingest writes shed 503 because the owning event-store "
            "partition was unavailable",
            labelnames=("partition",),
        )

    @property
    def events(self) -> EventStore:
        """The event store of record. With a live ``PartitionMigration``
        attached this indirects through its active layout, so the cutover
        flip moves every read and write in one swap
        (docs/storage.md#live-migration)."""
        if self.migration is not None:
            return self.migration.active_events()
        return self._events

    def migration_frozen_after(self) -> Optional[int]:
        """Retry-After seconds if the attached migration is holding
        writes for the cutover flip; None = writes may proceed."""
        if self.migration is None:
            return None
        from ..storage.migration import MigrationFrozen

        try:
            self.migration.check_frozen()
        except MigrationFrozen as exc:
            return max(1, int(round(exc.retry_after_s)))
        return None

    def mirror_events(self, events, app_id: int) -> None:
        """Dual-write acked events into the migration's other layout
        (no-op without a migration; never raises — the mirror path is
        queue-backed and failure-isolated by design)."""
        if self.migration is not None and events:
            self.migration.mirror(events, app_id)

    def mirror_delete(self, event_id: str, app_id: int) -> None:
        if self.migration is not None:
            self.migration.mirror_delete(event_id, app_id)

    def _partition_shed(self, exc: Exception) -> Optional[int]:
        """If ``exc`` is a partition outage, count it and return the
        Retry-After seconds for the 503; None = not a shed (re-raise)."""
        from ..storage.remote import PartitionUnavailable

        if not isinstance(exc, PartitionUnavailable):
            return None
        self._count_partition_shed(exc.partitions)
        return max(1, int(round(exc.retry_after_s)))

    def _count_partition_shed(self, partitions) -> None:
        for p in partitions:
            # pio: lint-ok[obs-unbounded-label] partition indices are a closed operator-configured set (0..N-1, N = deployed partition count); the registry cardinality cap bounds the series regardless
            self._partition_shed_total.inc(1, partition=str(p))

    def replication_json(self) -> dict:
        """The ingest tier's ``GET /replication.json``: one probed row
        per event-store partition (empty for a local, unpartitioned
        store — the route answers uniformly so scrapers need no
        store-type knowledge)."""
        status = getattr(self.events, "partition_status", None)
        if status is None:
            return {"partitions": []}
        return {"partitions": status()}

    def _observe_quality(self, app_id: int, event=None) -> None:
        """Quality accounting, swallowed on error: the serving path's
        'observability must never fail a query' discipline — a monitor
        fault after the store committed would turn a stored event into
        a client-visible 500 (and an SDK retry into a duplicate). The
        swallow is COUNTED (docs/slo.md): a monitor that starts failing
        on every event must be visible on /metrics."""
        try:
            if event is None:
                self.quality.record_rejected(app_id)
            else:
                self.quality.record_event(app_id, event)
        except Exception:
            self._observer_errors.inc(1, site="ingest.quality")
            logger.debug("ingest quality accounting failed", exc_info=True)


def create_event_server(
    config: EventServerConfig = EventServerConfig(),
    registry: Optional[StorageRegistry] = None,
    block: bool = True,
) -> EventServer:
    """Wire the server to the configured storage registry and run it
    (``EventServer.createEventServer``, ``EventAPI.scala:427-445``).

    With ``block=False`` the server runs on a daemon thread and is returned
    for programmatic shutdown (used by tests and the deploy feedback loop).
    """
    registry = registry or get_registry()
    server = EventServer(
        config,
        events=registry.get_events(),
        metadata=registry.get_metadata(),
    )
    logger.info(
        "Event Server listening on %s:%d (stats=%s)",
        config.ip,
        server.bound_port,
        config.stats,
    )
    if block:
        try:
            server.serve_forever()
        finally:
            server.server_close()
    else:
        server.start_background()
    return server
