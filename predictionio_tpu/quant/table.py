"""Quantized factor tables: int8 rows + per-row f32 scales, exactness-gated.

The serving memory layer of the bandwidth arc (docs/quantization.md): a
rank-R f32 factor row costs 4R bytes; its int8 twin costs R code bytes
plus one f32 scale — 3.7x smaller at the bench's rank 50, so one host
holds multiples of the catalog. Symmetric absmax quantization per row:

    scale_i = max_j |row_ij| / 127        codes_ij = round(row_ij / scale_i)
    dequant_ij = codes_ij * scale_i

Per-row scales factor OUT of the serving dot product, so the quantized
score kernel reads only the int8 codes (the bandwidth win) and applies
scales to the score matrix — the dequantized f32 table never
materializes (:func:`top_k_quantized`).

Quantization is lossy, so serving from codes is allowed only through
the exactness gate — the bf16 RMSE gate discipline (PR 12) extended
from a scalar drift bound to id identity: the quantized top-k ids must
match the f32 top-k on a probe set, and a mismatch is a loud refusal
(:class:`QuantGateError` + counted metric), never a silent quality
slide. ``fp8`` tables sit behind a capability probe and fall back to
int8 LOUDLY off accelerator.
"""

from __future__ import annotations

import dataclasses
import functools
import os
import threading
import warnings
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .ragged import ragged_gather

#: symmetric int8 grid: codes live in [-127, 127] (-128 unused so the
#: grid negates onto itself and |dequant| <= row absmax exactly)
INT8_QMAX = 127.0

#: fp8 e4m3 finite max — the fp8 grid normalizes row absmax onto it
FP8_QMAX = 448.0


class QuantGateError(ValueError):
    """The exactness gate refused a quantized serving table."""


def resolve_quantized_serving(
    explicit: Optional[bool], env: Optional[str] = None
) -> bool:
    """Resolve the ``quantized_serving`` tri-state lever (PR-12
    discipline): an explicit True/False wins, ``None`` resolves from
    ``PIO_SERVE_QUANT`` ("1"/"0"; what ``pio deploy`` environments
    set), else OFF. An unparseable env value fails loudly — a silently
    ignored flag would corrupt the hardware A/B."""
    if explicit is not None:
        return bool(explicit)
    if env is None:
        env = os.environ.get("PIO_SERVE_QUANT")
    if env is None or env == "":
        return False
    if env not in ("0", "1"):
        raise ValueError(
            f"PIO_SERVE_QUANT must be '0' or '1', got {env!r}"
        )
    return env == "1"


# gate outcome counters ("mismatch = loud refusal + counted metric"):
# module-level so every server surface exports the same truth — the
# query server publishes them as pio_quant_gate_{runs,refusals}_total
# via gauge callbacks (workflow/serving.py) and /status.json echoes them
_GATE_LOCK = threading.Lock()
_GATE_COUNTS = {"runs": 0, "refusals": 0}


def gate_counts() -> dict:
    """Snapshot of exactness-gate outcomes for this process."""
    with _GATE_LOCK:
        return dict(_GATE_COUNTS)


def _gate_tally(key: str) -> None:
    with _GATE_LOCK:
        _GATE_COUNTS[key] += 1


@dataclasses.dataclass(frozen=True)
class QuantizedTable:
    """A factor table quantized for serving: codes + per-row scales.

    Plain numpy arrays (like :class:`models.recommendation.ALSModel`) so
    the table blob-persists and ships across processes; kernels lift to
    device on use.
    """

    codes: np.ndarray  # [N, R] int8 (or fp8-encoded) codes
    scales: np.ndarray  # [N] f32 per-row scales; dequant = codes * scale
    dtype: str = "int8"  # "int8" | "fp8"
    #: set when a requested dtype fell back (capability probe), e.g.
    #: "fp8->int8: no fp8 matmul on cpu" — surfaced at /status.json so
    #: the fallback is visible, never silent
    fallback: Optional[str] = None

    @property
    def n_rows(self) -> int:
        return int(self.codes.shape[0])

    @property
    def rank(self) -> int:
        return int(self.codes.shape[1])

    @property
    def table_bytes(self) -> int:
        """Actual serving footprint: codes + scales."""
        return int(self.codes.nbytes + self.scales.nbytes)

    @property
    def f32_bytes(self) -> int:
        """The f32 twin's footprint (the compression baseline)."""
        return int(self.n_rows * self.rank * 4)

    @property
    def compression_ratio(self) -> float:
        return self.f32_bytes / max(self.table_bytes, 1)

    def status(self) -> dict:
        """The /status.json + profile shape: dtype, bytes, compression."""
        out = {
            "dtype": self.dtype,
            "tableBytes": self.table_bytes,
            "f32Bytes": self.f32_bytes,
            "compression": round(self.compression_ratio, 2),
        }
        if self.fallback:
            out["fallback"] = self.fallback
        return out


def fp8_supported() -> bool:
    """Capability probe for fp8 serving tables.

    fp8 codes only pay off where the matmul units consume them (TPU
    v5+/recent GPUs); on CPU XLA widens element-wise, which is slower
    than both int8 and f32 — a trap, not a lever. The probe keys on the
    active backend, so the same config deploys everywhere and the
    fallback (to int8) is taken — loudly — exactly where fp8 would lose.
    """
    if not hasattr(jnp, "float8_e4m3fn"):  # pragma: no cover - old jaxlib
        return False
    return jax.default_backend() in ("tpu", "gpu")


def _normalized_rows(
    table: np.ndarray, qmax: float
) -> Tuple[np.ndarray, np.ndarray]:
    """Rows scaled onto the [-qmax, qmax] grid + the per-row scales.

    Zero rows get scale 0.0 (their codes are 0; dequant reproduces the
    zero row exactly instead of dividing by zero).
    """
    absmax = np.abs(table).max(axis=1)
    scales = (absmax / qmax).astype(np.float32)
    safe = np.where(scales > 0.0, scales, 1.0).astype(np.float32)
    return table / safe[:, None], scales


def quantize_table(table, dtype: str = "int8") -> QuantizedTable:
    """Quantize an f32 factor table (symmetric absmax, per-row scales).

    The ungated constructor — bench twins and tests use it directly;
    the serve path goes through :func:`quantize_serving_table`, which
    is this plus the exactness gate. ``dtype="fp8"`` requires
    :func:`fp8_supported`; off accelerator it falls back to int8 with a
    warning and a ``fallback`` marker on the table (loud, recorded,
    never silent).
    """
    if dtype not in ("int8", "fp8"):
        raise ValueError(
            f"quantize_table dtype must be 'int8' or 'fp8', got {dtype!r}"
        )
    fallback = None
    if dtype == "fp8" and not fp8_supported():
        fallback = (
            f"fp8->int8: no fp8 matmul on {jax.default_backend()} "
            "(docs/quantization.md#fp8)"
        )
        warnings.warn(fallback, stacklevel=2)
        dtype = "int8"
    table = np.asarray(table, dtype=np.float32)
    if table.ndim != 2:
        raise ValueError(f"factor table must be 2-D, got shape {table.shape}")
    if dtype == "int8":
        normalized, scales = _normalized_rows(table, INT8_QMAX)
        codes = np.rint(np.clip(normalized, -INT8_QMAX, INT8_QMAX)).astype(
            np.int8
        )
    else:
        normalized, scales = _normalized_rows(table, FP8_QMAX)
        codes = np.asarray(jnp.asarray(normalized).astype(jnp.float8_e4m3fn))
    return QuantizedTable(
        codes=codes, scales=scales, dtype=dtype, fallback=fallback
    )


def dequantize_rows(qtable: QuantizedTable, ids):
    """Fused dequant-on-gather: f32 rows for ``ids``, each unique row
    dequantized once.

    The one kernel home for reconstructing f32 factors from a quantized
    table — the ragged idiom applied to dequantization: unique the ids,
    gather + scale each referenced row once, replay duplicates through
    the inverse map. Exact dequantization (codes * scale), so
    ``dequantize_rows(quantize_table(t), ids)`` is bit-identical to
    dequantizing the whole table and indexing it.
    """
    idx = jnp.asarray(ids, jnp.int32)
    flat = idx.reshape(-1)
    rank = int(qtable.codes.shape[1])
    if flat.shape[0] == 0:
        return jnp.zeros(idx.shape + (rank,), jnp.float32)
    uniq, inverse = jnp.unique(
        flat, size=flat.shape[0], return_inverse=True, fill_value=0
    )
    rows = jnp.asarray(qtable.codes)[uniq].astype(jnp.float32)
    rows = rows * jnp.asarray(qtable.scales)[uniq][:, None]
    return rows[inverse.reshape(-1)].reshape(idx.shape + (rank,))


def estimate_table_bytes(n_rows: int, rank: int, dtype: str = "f32") -> float:
    """Serving footprint model for one factor table — the quant member
    of the ``estimate_*_hbm_bytes`` family (honest roofline accounting;
    hardware-day item: validate against measured silicon).

    f32: 4 bytes/element. int8/fp8: 1 byte/element + one f32 scale per
    row. Pinned against actual ``QuantizedTable.table_bytes`` in tests.
    """
    if dtype == "f32":
        return float(n_rows) * rank * 4.0
    if dtype in ("int8", "fp8"):
        return float(n_rows) * (rank * 1.0 + 4.0)
    raise ValueError(f"unknown table dtype {dtype!r}")


def estimate_quant_topk_hbm_bytes(
    b: int, n_items: int, rank: int, k: int, dtype: str = "int8"
) -> float:
    """HBM-traffic model for one quantized top-k dispatch — the
    companion of ``ops.scoring.estimate_topk_hbm_bytes``'s dense leg
    with the item-table read priced at the quantized width (the whole
    point: the score matrix terms are unchanged, the table read
    shrinks ~4x)."""
    queries = float(b) * rank * 4.0
    items = estimate_table_bytes(n_items, rank, dtype)
    results = float(b) * k * 8.0
    score_matrix = float(b) * n_items * 4.0
    return queries + items + 2.0 * score_matrix + results


@functools.partial(jax.jit, static_argnames=("k",))
def _topk_quant(q, codes, scales, k):
    scores = (
        jnp.einsum(
            "br,ir->bi",
            q,
            codes.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        * scales[None, :]
    )
    n_items = codes.shape[0]
    k_eff = min(k, n_items)
    s, i = jax.lax.top_k(scores, k_eff)
    # sentinel contract parity with ops.scoring: -inf slots carry -1
    i = jnp.where(jnp.isneginf(s), -1, i.astype(jnp.int32))
    if k_eff < k:
        neg_inf = float("-inf")
        s = jnp.pad(s, ((0, 0), (0, k - k_eff)), constant_values=neg_inf)
        i = jnp.pad(i, ((0, 0), (0, k - k_eff)), constant_values=-1)
    return s, i


def top_k_quantized(user_factors, qtable: QuantizedTable, user_idx, k: int):
    """Fused quantized score+select: top-k items scored from int8 codes.

    ``scores = (q @ codes^T) * scale`` — per-row scales factor out of
    the dot product, so the kernel reads the narrow codes (the
    bandwidth win) and applies scales to the [B, k-candidate] score
    matrix; the dequantized f32 table never materializes. The user-row
    gather rides :func:`ragged_gather` (duplicate users in a batch cost
    one row read). Same (scores [B, k], ids [B, k]) result contract and
    (-inf, -1) sentinels as ``ops.scoring.top_k_for_users_fused``.
    """
    q = ragged_gather(user_factors, jnp.asarray(user_idx, jnp.int32))
    return _topk_quant(
        q, jnp.asarray(qtable.codes), jnp.asarray(qtable.scales), int(k)
    )


def default_probe_idx(n_rows: int, probes: int = 64) -> np.ndarray:
    """The held-out probe set: evenly spaced user rows, catalog-spanning
    and deterministic (the gate must refuse reproducibly, not
    probabilistically)."""
    if n_rows <= 0:
        return np.zeros(0, dtype=np.int32)
    return np.unique(
        np.linspace(0, n_rows - 1, num=min(int(probes), n_rows))
        .round()
        .astype(np.int32)
    )


def topk_match_gate(
    user_factors, item_factors, qtable: QuantizedTable, probe_idx, k: int
) -> float:
    """Fraction of probe rows whose quantized top-k id set equals the
    f32 top-k id set.

    Id-SET identity, not rank order: quantization noise may reorder
    near-ties *within* the retrieved set, but membership is the serving
    contract (the fleet merge and fold-in equivalence both key on which
    items are returned). 1.0 means every probe user would receive
    exactly the same items quantized as f32.
    """
    from ..ops.scoring import top_k_for_users_fused

    idx = np.asarray(probe_idx, dtype=np.int32)
    if idx.size == 0:
        return 1.0
    k = int(min(k, np.asarray(item_factors).shape[0]))
    _, ref_ids = top_k_for_users_fused(
        user_factors, item_factors, idx, k=k, mode="never"
    )
    _, quant_ids = top_k_quantized(user_factors, qtable, idx, k=k)
    ref = np.sort(np.asarray(ref_ids), axis=1)
    got = np.sort(np.asarray(quant_ids), axis=1)
    return float(np.mean(np.all(ref == got, axis=1)))


def quantize_serving_table(
    item_factors,
    user_factors,
    *,
    dtype: str = "int8",
    probe_idx=None,
    k: int = 10,
    min_match: float = 1.0,
) -> Tuple[QuantizedTable, dict]:
    """Quantize an item table FOR SERVING: quantize + exactness gate.

    The only constructor the serve path may use. Runs at model attach
    (train / fold-in / first serve of a loaded model) and proves the
    quantized top-k ids match the f32 top-k on the probe set before any
    quantized answer is produced. Returns ``(table, gate_status)``;
    raises :class:`QuantGateError` on refusal — loud and counted
    (``pio_quant_gate_refusals_total``), never a silent quality slide.
    """
    item_factors = np.asarray(item_factors, dtype=np.float32)
    if dtype == "int8":
        # int8 encode inlined: the narrowing cast and the gate that
        # licenses it share one scope — the adjacency the lint rule
        # spmd-unguarded-downcast pins (mutation-tested; do not hoist
        # the cast out of this function)
        normalized, scales = _normalized_rows(item_factors, INT8_QMAX)
        codes = np.rint(np.clip(normalized, -INT8_QMAX, INT8_QMAX)).astype(
            np.int8
        )
        qtable = QuantizedTable(codes=codes, scales=scales, dtype="int8")
    else:
        qtable = quantize_table(item_factors, dtype=dtype)
    if probe_idx is None:
        probe_idx = default_probe_idx(np.asarray(user_factors).shape[0])
    probe_idx = np.asarray(probe_idx, dtype=np.int32)
    _gate_tally("runs")
    match_rate = topk_match_gate(
        user_factors, item_factors, qtable, probe_idx, k
    )
    gate_status = dict(qtable.status())
    gate_status.update(
        matchRate=round(match_rate, 4),
        probes=int(probe_idx.size),
        k=int(min(k, item_factors.shape[0])),
    )
    if match_rate < min_match:
        _gate_tally("refusals")
        raise QuantGateError(
            f"quantized serving REFUSED: top-k match rate "
            f"{match_rate:.4f} < required {min_match} (dtype="
            f"{qtable.dtype}, k={gate_status['k']}, probes="
            f"{gate_status['probes']}) — the model serves f32 or not at "
            "all; see docs/quantization.md#gate"
        )
    return qtable, gate_status
