"""Quantization subsystem: the memory-bandwidth layer both the trainer
and the serving tier stand on (docs/quantization.md, ROADMAP "bandwidth
arc").

Three pieces: quantized factor tables (int8 codes + per-row f32 scales,
fp8 behind a capability probe), the shared ragged/deduplicated gather
primitive, and the exactness gate that licenses serving from codes —
quantized top-k ids must match the f32 top-k on a probe set, mismatch
is a loud counted refusal.
"""

from .ragged import ragged_gather
from .table import (
    FP8_QMAX,
    INT8_QMAX,
    QuantGateError,
    QuantizedTable,
    default_probe_idx,
    dequantize_rows,
    estimate_quant_topk_hbm_bytes,
    estimate_table_bytes,
    fp8_supported,
    gate_counts,
    quantize_serving_table,
    quantize_table,
    resolve_quantized_serving,
    top_k_quantized,
    topk_match_gate,
)

__all__ = [
    "FP8_QMAX",
    "INT8_QMAX",
    "QuantGateError",
    "QuantizedTable",
    "default_probe_idx",
    "dequantize_rows",
    "estimate_quant_topk_hbm_bytes",
    "estimate_table_bytes",
    "fp8_supported",
    "gate_counts",
    "quantize_serving_table",
    "quantize_table",
    "ragged_gather",
    "resolve_quantized_serving",
    "top_k_quantized",
    "topk_match_gate",
]
