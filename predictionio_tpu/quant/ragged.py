"""Ragged/deduplicated gather — one home for "touch only referenced rows".

Both ends of the data plane gather factor rows by id lists that repeat:
the sharded trainer's per-bucket solve blocks reference the same hot
counterpart rows across a block (power-law catalogs guarantee it), and a
serving batch names the same user many times under load. A dense
``table[ids]`` pays the row read once per *reference*; the ragged gather
pays it once per *unique row* and replays duplicates through an inverse
map — the ALX §4.2 "fetch only the rows each bucket actually references"
idiom, shared between ``ops/als_sharded.py`` and the fused serve-side
top-k (``ops/scoring.py``) so there is exactly one implementation to
price on hardware.

The result is bit-identical to ``table[ids]`` (it is the same rows,
reassembled), so adoption sites need no tolerance: equivalence is pinned
exactly in ``tests/test_quant.py``.
"""

from __future__ import annotations

import jax.numpy as jnp


def ragged_gather(table, ids):
    """``table[ids]`` touching each unique referenced row once.

    ``ids`` may be any integer shape (a serving batch ``[B]``, a solve
    block ``[B, K]``); the result is ``ids.shape + table.shape[1:]``.
    Deduplication uses the size-bounded ``jnp.unique`` (static output
    shape = ``ids.size``, surplus slots filled with row 0), so the
    primitive traces inside ``jit``/``shard_map`` bodies — the unique
    row set is computed on device, never a host round trip.
    """
    table = jnp.asarray(table)
    idx = jnp.asarray(ids, jnp.int32)
    flat = idx.reshape(-1)
    if flat.shape[0] == 0:
        return jnp.zeros(idx.shape + table.shape[1:], table.dtype)
    uniq, inverse = jnp.unique(
        flat, size=flat.shape[0], return_inverse=True, fill_value=0
    )
    rows = table[uniq]
    return rows[inverse.reshape(-1)].reshape(idx.shape + table.shape[1:])
